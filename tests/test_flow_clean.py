"""Tier-1 gate: the tree must stay graftflow-clean, and the CLI's JSON
output contract must hold (mirrors ``test_lint_clean.py`` — same schema
assertions, so a report regression fails the suite rather than the CI
consumer).

A true finding is fixed (two were, in this PR: the per-host ``aligned``
decision in ``core/communication.py`` and the wall-clock checkpoint
cadence in ``resilience/supervisor.py``); an intentional exception is
waived in place with a ``# graftflow: <tag>`` comment that documents WHY
(see docs/ANALYSIS.md). Either way the gate stays green — what it
forbids is silent drift.
"""
import json
import os
import subprocess
import sys

import pytest

from heat_tpu.analysis import graftflow as gf
from heat_tpu.analysis import graftlint as gl

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# same gated surface as the graftlint gate
GATED_PATHS = ["heat_tpu", "tools", "bench.py", "examples"]

CLEAN_LINE_BUDGET = 2048

REQUIRED_KEYS = (
    "tool", "schema_version", "paths", "files_checked", "rules",
    "findings", "counts", "total", "exit_code",
)


def test_tree_is_flow_clean():
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, p) for p in GATED_PATHS]
    )
    assert files_checked > 90  # the walker actually saw the tree
    assert not findings, "graftflow found unwaived violations:\n" + "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_lazy_package_is_flow_clean():
    """Explicit gate over the lazy-fusion subsystem: graph signatures are
    exactly the rank-divergence surface graftflow taints (lcounts/layout
    data flowing into cache keys), so its waivers must stay justified and
    everything else clean."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "core", "lazy")]
    )
    assert files_checked >= 4  # __init__, graph, capture, evaluate
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_stream_package_is_flow_clean():
    """Explicit gate over the out-of-core streaming layer: chunk shapes
    and validity counts flow into jitted per-chunk programs, which is the
    rank-divergence surface graftflow taints."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "stream")]
    )
    assert files_checked >= 5  # __init__, _stats, chunked, estimators, prefetch
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_sketch_package_is_flow_clean():
    """Explicit gate over the sketch layer: sketch states are merged over
    the tree_merge butterfly, so every value feeding a fold or combine
    must be replicated-identical across ranks — a rank-divergent count or
    geometry here corrupts the merged estimate silently."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "stream", "sketch")]
    )
    assert files_checked >= 4  # __init__, kll, hll, countmin
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_kernels_package_is_flow_clean():
    """Explicit gate over the fused-kernel layer: the sharded wrappers
    derive per-shard validity windows from axis_index inside shard_map —
    exactly the rank-divergence surface graftflow taints — and the
    dispatch decisions must stay rank-uniform."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "core", "kernels")]
    )
    # __init__, _dispatch, topk_distance, lloyd, moments, panel_update
    assert files_checked >= 6
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_frame_package_is_flow_clean():
    """Explicit gate over the shuffle/frame layer: partition decisions
    (splitter election, destination matrices, received-row counts) must
    be REPLICATED values — exactly the rank-divergence surface graftflow
    taints. A per-process branch on any of them deadlocks the exchange."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "frame")]
    )
    assert files_checked >= 4  # __init__, _shuffle, frame, groupby
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_testing_package_is_flow_clean():
    """Explicit gate over the fault-tolerant suite runner: the worker
    drives real collectives from a persistent process, so a laundered
    per-process branch around its deadline/reset paths would diverge the
    very groups the runner exists to keep in lockstep."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "testing")]
    )
    assert files_checked >= 5  # __init__, protocol, quarantine, runner, worker
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_suite_runner_cli_is_flow_clean():
    """tools/mpirun.py rides the ``tools`` tree walk; gate it by name so
    moving it out of tools/ cannot silently un-gate it."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "tools", "mpirun.py")]
    )
    assert files_checked == 1
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_health_monitor_is_flow_clean():
    """Explicit gate over the health monitor: the EWMA frame and the
    cadence decision are collectives, so flow-laundering a per-rank
    value (a local clock, a local failure set) into either would
    desynchronize the very verdicts the monitor exists to replicate."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "resilience", "monitor.py")]
    )
    assert files_checked == 1
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_autoscaler_is_flow_clean():
    """Explicit gate over the autoscale policy: queue depth is
    rank-divergent by nature, so every path from it to a mesh rebuild
    must pass through the replicated grow decision — a laundered branch
    here grows a mesh on one rank only."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "serve", "autoscale.py")]
    )
    assert files_checked == 1
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_serve_tick_is_flow_clean():
    """Explicit gate over the replicated dispatch tick plan module: it
    must stay a PURE function of the gathered frames — any rank-local
    source (a clock, a local queue view, rank identity) flowing into
    the plan re-creates the exact divergent-dispatch hazard the tick
    exists to dodge (see tests/lint_fixtures/tick_dispatch_pos.py for
    the flagged shape)."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "serve", "tick.py")]
    )
    assert files_checked == 1
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_serve_service_is_flow_clean():
    """Explicit gate over the dispatcher: the tick loop's collective
    pairing (one replicated_decision per iteration, one
    replicated_frame per agreed tick) is exactly the discipline F001/
    F003 police — a rank-local value gating either collective is the
    disarmed-triggers deadlock come back."""
    findings, files_checked = gf.analyze_paths(
        [os.path.join(REPO, "heat_tpu", "serve", "service.py")]
    )
    assert files_checked == 1
    assert not findings, "\n".join(
        f"  {f.path}:{f.line}:{f.col}: {f.rule} {f.message}" for f in findings
    )


def test_collective_vocabulary_matches_graftlint():
    """graftflow keeps its own copy of the collective-name set (both
    halves must stay importable without the other); the copies must not
    drift."""
    assert gf.COLLECTIVE_NAMES == gl.COLLECTIVE_NAMES


def _run_cli(*args):
    return subprocess.run(
        [sys.executable, os.path.join("tools", "graftflow.py"), *args],
        capture_output=True, text=True, cwd=REPO,
    )


def test_cli_clean_exit_zero():
    proc = _run_cli(*GATED_PATHS)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_cli_json_contract():
    proc = _run_cli(*GATED_PATHS, "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, "JSON mode must emit exactly one line"
    line = lines[0]
    assert len(line) <= CLEAN_LINE_BUDGET
    obj = json.loads(line)
    missing = [k for k in REQUIRED_KEYS if k not in obj]
    assert not missing, f"report missing keys: {missing}"
    assert obj["tool"] == "graftflow"
    assert obj["schema_version"] == gf.SCHEMA_VERSION
    assert obj["total"] == 0 and obj["exit_code"] == 0
    # PR 19: the DRIFT hand-table diagnostic reports alongside the rules
    assert sorted(obj["counts"]) == sorted(list(gf.RULES) + ["DRIFT"])
    assert all(v == 0 for v in obj["counts"].values())
    assert isinstance(obj["files_checked"], int) and obj["files_checked"] > 90
    assert {r["id"] for r in obj["rules"]} == set(gf.RULES) | {"DRIFT"}
    for r in obj["rules"]:
        assert set(r) == {"id", "tag", "bit", "summary"}
    # the round trip itself: re-serialization must be lossless
    assert json.loads(json.dumps(obj)) == obj


def test_cli_github_format_clean_tree():
    """A clean tree emits no ::error annotation, just the summary line;
    a seeded finding emits the workflow-annotation shape."""
    proc = _run_cli("heat_tpu", "--format", "github")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "::error" not in proc.stdout
    assert "graftflow:" in proc.stdout
    report = gf.build_report(
        ["x.py"],
        gf.analyze_source(
            "import jax\n"
            "def f(xs):\n"
            "    if jax.process_index() == 0:\n"
            "        jax.experimental.multihost_utils.process_allgather(xs)\n",
            "x.py",
        ),
        1,
    )
    out = gf.render_github(report)
    assert out.startswith("::error file=x.py,line=")
    assert "title=graftflow F001" in out


def test_cli_report_matches_api():
    """The CLI is a thin shell over the library: same findings, same code."""
    proc = _run_cli("heat_tpu", "--format", "json")
    obj = json.loads(proc.stdout.strip().splitlines()[-1])
    findings, files_checked = gf.analyze_paths([os.path.join(REPO, "heat_tpu")])
    assert obj["total"] == len(findings)
    assert obj["files_checked"] == files_checked
    assert proc.returncode == gf.exit_code_for(findings)


def test_cli_runs_without_jax():
    """Flow analysis must work on machines with no accelerator runtime:
    the CLI pulls the analyzer in by file path and never imports
    heat_tpu/jax."""
    proc = subprocess.run(
        [
            sys.executable, "-c",
            "import sys; sys.argv = ['graftflow', 'heat_tpu/analysis'];\n"
            "import tools.graftflow as cli\n"
            "rc = cli.main(['heat_tpu/analysis'])\n"
            "assert 'jax' not in sys.modules, 'flow analysis imported jax!'\n"
            "assert 'heat_tpu' not in sys.modules, 'flow analysis imported heat_tpu!'\n"
            "sys.exit(rc)",
        ],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ------------------------------------------------------ graftcheck (unified)
def _run_graftcheck(*args):
    return subprocess.run(
        [sys.executable, os.path.join("tools", "graftcheck.py"), *args],
        capture_output=True, text=True, cwd=REPO,
    )


def test_graftcheck_clean_exit_zero():
    """The PR 19 acceptance gate: one graftcheck invocation over the
    gated surface is clean at head."""
    proc = _run_graftcheck(*GATED_PATHS)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_graftcheck_merged_json_contract():
    """One process, one line, both analyzers: the merged report carries
    the union rule table and counts, per-tool sub-reports with each
    tool's own bitmask, and the combined exit code."""
    proc = _run_graftcheck(*GATED_PATHS, "--format", "json")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    lines = [ln for ln in proc.stdout.strip().splitlines() if ln.strip()]
    assert len(lines) == 1, "JSON mode must emit exactly one line"
    obj = json.loads(lines[0])
    missing = [k for k in REQUIRED_KEYS if k not in obj]
    assert not missing, f"report missing keys: {missing}"
    assert obj["tool"] == "graftcheck"
    assert obj["total"] == 0 and obj["exit_code"] == 0
    union = set(gf.RULES) | set(gl.RULES) | {"DRIFT"}
    assert sorted(obj["counts"]) == sorted(union)
    assert all(v == 0 for v in obj["counts"].values())
    assert {r["id"] for r in obj["rules"]} == union
    assert set(obj["tools"]) == {"graftlint", "graftflow"}
    for sub in obj["tools"].values():
        assert sub["total"] == 0 and sub["exit_code"] == 0
    assert json.loads(json.dumps(obj)) == obj


def test_graftcheck_combined_bitmask_and_select():
    """The fixture corpus trips both analyzers: bit 1 (graftlint) and
    bit 2 (graftflow) combine to 3; selecting one tool's rules silences
    the other entirely."""
    fixtures = os.path.join("tests", "lint_fixtures")
    proc = _run_graftcheck(fixtures, "--format", "json")
    obj = json.loads(proc.stdout.strip().splitlines()[-1])
    assert proc.returncode == 3
    assert obj["exit_code"] == 3
    assert {f["tool"] for f in obj["findings"]} == {"graftlint", "graftflow"}
    # findings arrive merged in (path, line, col, rule) order
    keys = [(f["path"], f["line"], f["col"], f["rule"]) for f in obj["findings"]]
    assert keys == sorted(keys)
    lint_only = _run_graftcheck(fixtures, "--select", "G003", "--format", "json")
    lint_obj = json.loads(lint_only.stdout.strip().splitlines()[-1])
    assert lint_only.returncode == 1
    assert {f["rule"] for f in lint_obj["findings"]} == {"G003"}
    flow_only = _run_graftcheck(fixtures, "--select", "F001", "--format", "json")
    flow_obj = json.loads(flow_only.stdout.strip().splitlines()[-1])
    assert flow_only.returncode == 2
    assert {f["rule"] for f in flow_obj["findings"]} == {"F001"}


# The SARIF 2.1.0 members GitHub code scanning actually rejects uploads
# over — a structural subset of the official schema, validated offline.
_SARIF_SUBSET_SCHEMA = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "maxItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {
                            "driver": {
                                "type": "object",
                                "required": ["name", "rules"],
                                "properties": {
                                    "rules": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": ["id"],
                                        },
                                    },
                                },
                            },
                        },
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["ruleId", "message", "locations"],
                            "properties": {
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                },
                                "locations": {
                                    "type": "array",
                                    "minItems": 1,
                                    "items": {
                                        "type": "object",
                                        "required": ["physicalLocation"],
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "required": ["artifactLocation",
                                                             "region"],
                                                "properties": {
                                                    "region": {
                                                        "type": "object",
                                                        "required": ["startLine"],
                                                        "properties": {
                                                            "startLine": {
                                                                "type": "integer",
                                                                "minimum": 1,
                                                            },
                                                            "startColumn": {
                                                                "type": "integer",
                                                                "minimum": 1,
                                                            },
                                                        },
                                                    },
                                                },
                                            },
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


def test_graftcheck_sarif_is_schema_valid():
    """SARIF output validates against the structural schema subset, on
    both a clean tree (empty results) and the fixture corpus (every rule
    id resolvable against the driver's rule table)."""
    jsonschema = pytest.importorskip("jsonschema")
    for paths, want_rc in ((GATED_PATHS, 0), (["tests/lint_fixtures"], 3)):
        proc = _run_graftcheck(*paths, "--format", "sarif")
        assert proc.returncode == want_rc, proc.stdout + proc.stderr
        sarif = json.loads(proc.stdout)
        jsonschema.validate(sarif, _SARIF_SUBSET_SCHEMA)
        driver = sarif["runs"][0]["tool"]["driver"]
        assert driver["name"] == "graftcheck"
        rule_ids = {r["id"] for r in driver["rules"]}
        assert rule_ids == set(gf.RULES) | set(gl.RULES) | {"DRIFT"}
        for res in sarif["runs"][0]["results"]:
            assert res["ruleId"] in rule_ids
        if want_rc == 0:
            assert sarif["runs"][0]["results"] == []


def test_graftcheck_github_format():
    proc = _run_graftcheck("heat_tpu", "--format", "github")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "::error" not in proc.stdout
    dirty = _run_graftcheck(os.path.join("tests", "lint_fixtures"),
                            "--select", "F001", "--format", "github")
    assert dirty.returncode == 2
    for line in dirty.stdout.strip().splitlines():
        assert line.startswith("::error file="), line
        assert "title=graftflow F001" in line


def test_graftcheck_runs_without_jax():
    """The unified gate must be runnable on a machine with no
    accelerator runtime at all: both analyzers load by file path, and
    neither jax nor heat_tpu may appear in sys.modules afterwards."""
    proc = subprocess.run(
        [
            sys.executable, "-c",
            "import sys\n"
            "import tools.graftcheck as cli\n"
            "rc = cli.main(['heat_tpu/analysis', '--format', 'json'])\n"
            "assert 'jax' not in sys.modules, 'graftcheck imported jax!'\n"
            "assert 'heat_tpu' not in sys.modules, 'graftcheck imported heat_tpu!'\n"
            "sys.exit(rc)",
        ],
        capture_output=True, text=True, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
