"""Random and I/O tests (reference ``test_random.py``, ``test_io.py``)."""
import os
import tempfile

import numpy as np
import pytest

import heat_tpu as ht

from .base import TestCase


class TestRandom(TestCase):
    def test_reproducible_after_seed(self):
        ht.random.seed(42)
        a = ht.random.rand(16, split=0).numpy()
        ht.random.seed(42)
        b = ht.random.rand(16, split=0).numpy()
        np.testing.assert_array_equal(a, b)

    def test_split_invariant_stream(self):
        """The reference's core guarantee (``random.py:55-201``): same
        global stream for every split."""
        # (9, 5)/(3, 7, 5): non-divisible on EVERY axis — regression for
        # padded-shape generation shifting the threefry counters when a
        # non-leading dim was padded
        for shape in [(16,), (8, 8), (13,), (9, 5), (3, 7, 5)]:
            ht.random.seed(7)
            ref = ht.random.rand(*shape, split=None).numpy()
            for split in range(len(shape)):
                ht.random.seed(7)
                got = ht.random.rand(*shape, split=split).numpy()
                np.testing.assert_array_equal(ref, got)
            ht.random.seed(11)
            iref = ht.random.randint(0, 100, size=shape, split=None).numpy()
            ht.random.seed(11)
            igot = ht.random.randint(0, 100, size=shape, split=len(shape) - 1).numpy()
            np.testing.assert_array_equal(iref, igot)

    def test_state_roundtrip(self):
        ht.random.seed(1)
        ht.random.rand(4)
        state = ht.random.get_state()
        a = ht.random.rand(8).numpy()
        ht.random.set_state(state)
        b = ht.random.rand(8).numpy()
        np.testing.assert_array_equal(a, b)
        assert state[0] == "Threefry"

    def test_distributions(self):
        ht.random.seed(0)
        u = ht.random.rand(10000, split=0)
        assert 0.0 <= float(u.min().item()) and float(u.max().item()) < 1.0
        assert abs(float(u.mean().item()) - 0.5) < 0.02
        n = ht.random.randn(10000, split=0)
        assert abs(float(n.mean().item())) < 0.05
        assert abs(float(n.std().item()) - 1.0) < 0.05

    def test_randint(self):
        ht.random.seed(3)
        r = ht.random.randint(0, 10, size=(100,), split=0)
        vals = r.numpy()
        assert vals.min() >= 0 and vals.max() < 10
        assert r.dtype == ht.int32
        with pytest.raises(ValueError):
            ht.random.randint(5, 5)

    def test_normal_uniform(self):
        ht.random.seed(4)
        n = ht.random.normal(5.0, 2.0, (5000,), split=0)
        assert abs(float(n.mean().item()) - 5.0) < 0.15
        u = ht.random.uniform(-2.0, 2.0, (5000,))
        assert -2.0 <= float(u.min().item()) and float(u.max().item()) < 2.0

    def test_randperm_permutation(self):
        ht.random.seed(5)
        p = ht.random.randperm(20)
        np.testing.assert_array_equal(np.sort(p.numpy()), np.arange(20))
        x = ht.arange(10, split=0)
        shuffled = ht.random.permutation(x)
        np.testing.assert_array_equal(np.sort(shuffled.numpy()), np.arange(10))

    def test_dtype_checks(self):
        with pytest.raises(ValueError):
            ht.random.rand(4, dtype=ht.int32)


class TestIO(TestCase):
    def test_hdf5_roundtrip(self):
        x = ht.random.randn(32, 4, split=0)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "data.h5")
            ht.save_hdf5(x, path, "data")
            for split in (None, 0, 1):
                back = ht.load_hdf5(path, "data", split=split)
                assert back.split == split
                np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)
            via_load = ht.load(path, dataset="data", split=0)
            np.testing.assert_allclose(via_load.numpy(), x.numpy(), rtol=1e-6)

    def test_hdf5_roundtrip_dtypes(self):
        """float64/int32/int64/bool survive the chunked save/load path
        bit-exactly at every split (including the non-divisible dim)."""
        rng = np.random.default_rng(12)
        cases = {
            "f64": rng.normal(size=(9, 5)),
            "i32": rng.integers(-1000, 1000, size=(9, 5)).astype(np.int32),
            "i64": rng.integers(-(2**40), 2**40, size=(9, 5)).astype(np.int64),
            "bool": rng.random(size=(9, 5)) > 0.5,
        }
        with tempfile.TemporaryDirectory() as d:
            for name, arr in cases.items():
                path = os.path.join(d, f"{name}.h5")
                ht.save_hdf5(ht.array(arr, split=0), path, "data")
                for split in (None, 0, 1):
                    back = ht.load_hdf5(path, "data", dtype=arr.dtype, split=split)
                    np.testing.assert_array_equal(back.numpy(), arr, err_msg=name)

    def test_hdf5_load_multi_axis_mesh(self):
        """Chunked loads on a 2-D (nodes x split) mesh: a device's shard
        rank is its coordinate along the split axis, and devices sharing a
        split coordinate replicate the same block (regression: ravel
        position was used as the rank, zero-filling the second row)."""
        import jax
        from jax.sharding import Mesh

        import h5py

        if ht.get_comm().size != 8:
            pytest.skip("needs 8 devices for the 2x4 topology")
        devs = np.array(jax.devices()).reshape(2, 4)
        comm = ht.MPICommunication(mesh=Mesh(devs, ("nodes", "split")))
        x = np.arange(24, dtype=np.float32).reshape(12, 2)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "ma.h5")
            with h5py.File(path, "w") as f:
                f.create_dataset("d", data=x)
            a = ht.load_hdf5(path, "d", split=0, comm=comm)
        sums = [float(np.asarray(s.data).sum()) for s in a.larray.addressable_shards]
        assert sums[:4] == sums[4:], f"nodes-axis replicas differ: {sums}"
        np.testing.assert_array_equal(np.asarray(a._logical()), x)

    def test_csv_roundtrip(self):
        x = ht.arange(24, dtype=ht.float32).reshape((6, 4))
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "data.csv")
            ht.save(x, path)
            back = ht.load_csv(path, split=0)
            np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-5)

    def test_csv_header(self):
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "h.csv")
            with open(path, "w") as f:
                f.write("a,b\n1.0,2.0\n3.0,4.0\n")
            back = ht.load_csv(path, header_lines=1)
            np.testing.assert_allclose(back.numpy(), [[1, 2], [3, 4]])

    def test_netcdf_roundtrip(self):
        """netCDF-4 via the h5py fallback (netCDF-4 files ARE HDF5): save
        writes dimension scales like the real library; load routes through
        the chunked parallel reader."""
        assert ht.io.supports_netcdf()
        x = ht.random.randn(9, 5, split=0)
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "data.nc")
            ht.save_netcdf(x, path, "var")
            for split in (None, 0, 1):
                back = ht.load_netcdf(path, "var", split=split)
                assert back.split == split
                np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)
            # extension dispatch through save/load
            ht.save(x, os.path.join(d, "data2.nc"), "var")
            via = ht.load(os.path.join(d, "data2.nc"), variable="var", split=0)
            np.testing.assert_allclose(via.numpy(), x.numpy(), rtol=1e-6)
            # the file is valid HDF5 with netCDF-4 dimension-scale structure
            import h5py

            with h5py.File(path, "r") as f:
                assert f["var"].dims[0]  # dimension scales attached
            # asking for a dimension dataset as a variable errors
            with pytest.raises(KeyError):
                ht.load_netcdf(path, "dim_0")
            with pytest.raises(KeyError):
                ht.load_netcdf(path, "missing")

    def test_netcdf3_classic_roundtrip(self):
        """Classic CDF-1/2 via the dependency-free parser (VERDICT r3
        missing item 5; reference reads classic files through the netCDF4
        C lib, ``io.py:268``): save format='NETCDF3*', chunked load on
        every split, scipy cross-validation of the written bytes."""
        x = ht.array(
            np.arange(11 * 6, dtype=np.float32).reshape(11, 6) / 7.0, split=0
        )
        with tempfile.TemporaryDirectory() as d:
            for fmt, version in (("NETCDF3_CLASSIC", 1), ("NETCDF3_64BIT", 2)):
                path = os.path.join(d, f"classic_v{version}.nc")
                ht.save_netcdf(x, path, "var", format=fmt)
                with open(path, "rb") as f:
                    assert f.read(4) == b"CDF" + bytes([version])
                for split in (None, 0, 1):
                    back = ht.load_netcdf(path, "var", split=split)
                    assert back.split == split
                    np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-6)
                # independent implementation reads our bytes
                from scipy.io import netcdf_file

                with netcdf_file(path, "r", version=version) as g:
                    np.testing.assert_allclose(
                        np.asarray(g.variables["var"][:]), x.numpy(), rtol=1e-6
                    )
            # scipy writes (incl. record variables) -> we load chunked
            p2 = os.path.join(d, "scipy.nc")
            from scipy.io import netcdf_file

            f = netcdf_file(p2, "w")
            f.createDimension("time", None)
            f.createDimension("x", 4)
            v = f.createVariable("temp", np.float64, ("time", "x"))
            data = np.arange(9 * 4, dtype=np.float64).reshape(9, 4)
            v[:] = data
            f.close()
            for split in (None, 0, 1):
                back = ht.load_netcdf(p2, "temp", dtype=ht.float64, split=split)
                np.testing.assert_allclose(back.numpy(), data)
            with pytest.raises(KeyError):
                ht.load_netcdf(p2, "missing")
            # int16 data and dtype conversion
            p3 = os.path.join(d, "ints.nc")
            xi = ht.array(np.arange(23, dtype=np.int16), split=0)
            ht.save_netcdf(xi.astype(ht.int32), p3, "n", format="NETCDF3_CLASSIC")
            bi = ht.load_netcdf(p3, "n", dtype=ht.int32, split=0)
            np.testing.assert_array_equal(bi.numpy(), np.arange(23))

    def test_unsupported_extension(self):
        # a missing path now raises FileNotFoundError BEFORE extension
        # dispatch; the unsupported-extension ValueError needs a real file
        with pytest.raises(FileNotFoundError):
            ht.load("/tmp/file.xyz")
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "file.xyz")
            open(p, "w").close()
            with pytest.raises(ValueError):
                ht.load(p)
        with pytest.raises(ValueError):
            ht.save(ht.zeros(3), "/tmp/file.xyz")

    def test_save_load_validation(self):
        with pytest.raises(TypeError):
            ht.load(123)
        with pytest.raises(TypeError):
            ht.save_hdf5(np.zeros(3), "/tmp/x.h5", "data")


class TestMatrixGallery(TestCase):
    def test_parter(self):
        p = ht.utils.data.matrixgallery.parter(8)
        # reference orientation (matrixgallery.py:49-61): II varies along
        # columns, so A[i, j] = 1 / (j - i + 0.5)
        expected = 1.0 / (np.arange(8)[None, :] - np.arange(8)[:, None] + 0.5)
        np.testing.assert_allclose(p.numpy(), expected, rtol=1e-6)

    def test_hermitian(self):
        h = ht.utils.data.matrixgallery.hermitian(6)
        hn = h.numpy()
        np.testing.assert_allclose(hn, hn.conj().T, rtol=1e-6)
