"""Autoscaler policy tests (PR 17 tentpole, part 3): the queue-depth
band, hysteresis, cooldown, heal-driven grows, and the ServeService
integration (proactive shrink on a flap, elastic re-grow on heal, warm
caches invalidated, every response correct).

The full seeded storm — two degrade -> shrink -> heal -> re-grow cycles
under continuous traffic with the zero-lost/zero-duplicated proof — is
``tools/chaos_soak.py --autoscale`` (tier-1 via test_chaos_soak.py).
"""
from __future__ import annotations

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import resilience as rz
from heat_tpu import serve as serve_mod
from heat_tpu.core import communication as comm_mod
from heat_tpu.resilience.monitor import HealthMonitor, reset_health_stats
from heat_tpu.serve import SERVE_STATS, Autoscaler, reset_serve_stats
from tests.base import TestCase


def _monitor(**kw):
    kw.setdefault("interval_s", 0.0)
    return HealthMonitor(**kw)


class AutoscaleBase(TestCase):
    def setUp(self):
        reset_health_stats()

    def tearDown(self):
        comm_mod.use_comm(None)
        rz.clear_unhealthy()


class TestPolicy(AutoscaleBase):
    def test_param_validation(self):
        mon = _monitor()
        with pytest.raises(ValueError):
            Autoscaler(mon, high_depth=0)
        with pytest.raises(ValueError):
            Autoscaler(mon, high_depth=4, low_depth=5)
        with pytest.raises(ValueError):
            Autoscaler(mon, low_depth=-1)
        with pytest.raises(ValueError):
            Autoscaler(mon, hysteresis=0)
        with pytest.raises(ValueError):
            Autoscaler(mon, cooldown_s=-1.0)

    def test_idle_consult_is_none(self):
        scaler = Autoscaler(_monitor())
        self.assertIsNone(scaler.consult(queue_depth=0))

    def test_off_tick_consults_do_nothing(self):
        clock = [0.0]
        scaler = Autoscaler(_monitor(interval_s=100.0, clock=lambda: clock[0]))
        self.assertIsNone(scaler.consult(0))   # first tick always due
        # off the cadence even maximal pressure cannot arm the streak
        for _ in range(5):
            self.assertIsNone(scaler.consult(10_000))
        self.assertEqual(scaler._pressure, 0)

    def test_degrade_verdict_shrinks_immediately(self):
        p = self.comm.size
        scaler = Autoscaler(_monitor())
        sched = rz.FaultSchedule(
            events=[("monitor.probe", 1, "device_flap")],
        )
        with sched:
            self.assertEqual(scaler.consult(0), "shrink")
        self.assertEqual(len(rz.unhealthy_devices()), 1)

    def test_heal_triggers_grow_when_capacity_below_base(self):
        p = self.comm.size
        if p < 2:
            pytest.skip("needs a shrinkable mesh")
        scaler = Autoscaler(_monitor(heal_after=1))
        sched = rz.FaultSchedule(events=[("monitor.probe", 1, "device_flap")])
        with sched:
            self.assertEqual(scaler.consult(0), "shrink")
        # apply the shrink so capacity actually drops below base
        small, _ = rz.shrink_to_healthy(None, set_default=True)
        self.assertEqual(small.size, p - 1)
        # next tick: clean probe heals (heal_after=1) -> grow verdict
        self.assertEqual(scaler.consult(0), "grow")

    def test_heal_without_missing_capacity_is_none(self):
        """A device healing while the mesh is already full (e.g. an
        external mark cleared before any shrink) must not grow."""
        scaler = Autoscaler(_monitor(heal_after=1))
        sched = rz.FaultSchedule(events=[("monitor.probe", 1, "device_flap")])
        with sched:
            self.assertEqual(scaler.consult(0), "shrink")
        # mesh was never shrunk: capacity == base even after the heal
        self.assertIsNone(scaler.consult(0))
        self.assertEqual(rz.unhealthy_devices(), frozenset())

    def test_pressure_band_hysteresis(self):
        p = self.comm.size
        if p < 2:
            pytest.skip("needs a shrinkable mesh")
        # free capacity without any heal events: shrink the default mesh
        # while the base stays fully healthy.  The monitor must capture the
        # FULL world as its base, so build it before swapping the default.
        world = comm_mod.sanitize_comm(None)
        scaler = Autoscaler(
            _monitor(heal_after=100), high_depth=8, low_depth=2, hysteresis=3,
        )
        sub = comm_mod.MeshCommunication(
            devices=world.mesh.devices.ravel().tolist()[:-1]
        )
        comm_mod.use_comm(sub)
        # two over-pressure ticks: streak at 2 < hysteresis -> no grow
        self.assertIsNone(scaler.consult(20))
        self.assertIsNone(scaler.consult(20))
        # depth back inside the band (> low, <= high): streak holds
        self.assertIsNone(scaler.consult(5))
        self.assertEqual(scaler._pressure, 2)
        # depth at the low edge: streak resets
        self.assertIsNone(scaler.consult(2))
        self.assertEqual(scaler._pressure, 0)
        # three consecutive over-pressure ticks arm the grow
        self.assertIsNone(scaler.consult(20))
        self.assertIsNone(scaler.consult(20))
        self.assertEqual(scaler.consult(20), "grow")
        self.assertEqual(scaler._pressure, 0)  # verdict consumed the streak

    def test_pressure_never_grows_at_full_capacity(self):
        scaler = Autoscaler(_monitor(heal_after=100), hysteresis=1)
        for _ in range(4):
            self.assertIsNone(scaler.consult(10_000))

    def test_cooldown_defers_heal_grow(self):
        p = self.comm.size
        if p < 2:
            pytest.skip("needs a shrinkable mesh")
        clock = [0.0]
        scaler = Autoscaler(
            _monitor(heal_after=1), cooldown_s=100.0, clock=lambda: clock[0],
        )
        sched = rz.FaultSchedule(events=[("monitor.probe", 1, "device_flap")])
        with sched:
            self.assertEqual(scaler.consult(0), "shrink")
        rz.shrink_to_healthy(None, set_default=True)
        # first grow is never cooldown-blocked (no prior grow)
        self.assertEqual(scaler.consult(0), "grow")
        comm_mod.use_comm(None)  # "apply" it: back to the full mesh

        # second cycle: degrade + shrink again, then heal INSIDE the
        # cooldown window -> deferred, fires once the window elapses
        sched = rz.FaultSchedule(events=[("monitor.probe", 1, "device_flap")])
        with sched:
            self.assertEqual(scaler.consult(0), "shrink")
        rz.shrink_to_healthy(None, set_default=True)
        clock[0] = 50.0                      # heal tick, still cooling
        self.assertIsNone(scaler.consult(0))
        self.assertTrue(scaler._deferred_heal)
        clock[0] = 90.0                      # later tick, still cooling
        self.assertIsNone(scaler.consult(0))
        clock[0] = 101.0                     # window elapsed
        self.assertEqual(scaler.consult(0), "grow")
        self.assertFalse(scaler._deferred_heal)


class TestServeIntegration(AutoscaleBase):
    def test_flap_shrink_heal_grow_under_traffic(self):
        """End to end on a live service: a flapping device proactively
        shrinks the mesh between batches, the heal re-grows it, the
        warm-bucket cache is invalidated on both scale events, and every
        response stays oracle-equal throughout."""
        p = self.comm.size
        if p < 2:
            pytest.skip("needs a shrinkable mesh")
        cols = 4
        w_np = np.arange(cols, dtype=np.float32) + 1.0

        class _Lin:
            """Minimal resident model: relocatable via state_dict, so the
            service can land its weight on each re-scaled mesh."""

            def __init__(self):
                self.w = ht.array(w_np)

            def predict(self, x):
                return x @ self.w

            def state_dict(self):
                return {"w": self.w}

            def load_state_dict(self, state):
                # relocation hands back host arrays; land on the current mesh
                self.w = ht.array(np.asarray(state["w"]))

        reset_serve_stats()
        before = dict(SERVE_STATS)
        # heal_after=2: the dispatcher consults twice per submit+drain
        # round (after the batch and after the drain sentinel), so with a
        # 1-tick heal the mesh would re-grow inside the shrink round and
        # no batch would ever dispatch on the shrunken mesh.
        monitor = _monitor(heal_after=2)
        svc = serve_mod.ServeService(
            serve_mod.BucketPolicy(max_latency_ms=60_000.0, max_batch=16),
            autoscaler=Autoscaler(monitor),
        )
        orig = comm_mod.sanitize_comm(None)
        try:
            svc.register_model("lin", _Lin(), methods=("predict",))
            rng = np.random.default_rng(17)

            def one_round():
                x = rng.normal(size=(2, cols)).astype(np.float32)
                r = svc.submit("lin.predict", x)
                svc.drain(timeout=300)
                np.testing.assert_allclose(
                    np.asarray(r.result(0)), x @ w_np, atol=1e-4
                )

            one_round()  # warm on the full mesh
            sched = rz.FaultSchedule(events=[("monitor.probe", 1, "device_flap")])
            with sched:
                # the flap tick happens at the dispatcher's next consult
                for _ in range(4):
                    one_round()
                    if comm_mod.sanitize_comm(None).size == p - 1:
                        break
            self.assertEqual(sched.pending(), [])
            self.assertEqual(comm_mod.sanitize_comm(None).size, p - 1)
            # clean ticks heal (heal_after=1) and grow back
            for _ in range(6):
                one_round()
                if comm_mod.sanitize_comm(None).size == p:
                    break
            self.assertEqual(comm_mod.sanitize_comm(None).size, p)
            one_round()  # traffic still flows on the re-grown mesh
            svc.close(timeout=60)
        finally:
            comm_mod.use_comm(orig)
            rz.clear_unhealthy()
        delta = {k: SERVE_STATS[k] - before[k]
                 for k in ("shrinks", "grows", "scale_events", "errors")}
        self.assertEqual(delta["shrinks"], 1, delta)
        self.assertEqual(delta["grows"], 1, delta)
        self.assertEqual(delta["scale_events"], 2, delta)
        self.assertEqual(delta["errors"], 0, delta)
        # cache-invalidation contract: cold start + one re-warm per scale
        self.assertGreaterEqual(SERVE_STATS["bucket_misses"] - before["bucket_misses"], 3)

    def test_queue_depth_gauge_fresh_after_drain(self):
        """The PR 17 gauge fix: queue_depth must read 0 after a drain,
        not the high-water depth of the last enqueue."""
        cols = 3
        w = ht.array(np.ones(cols, np.float32))
        reset_serve_stats()
        with serve_mod.ServeService(
            serve_mod.BucketPolicy(max_latency_ms=60_000.0, max_batch=16)
        ) as svc:
            svc.register_endpoint("dot", lambda x: x @ w)
            reqs = [
                svc.submit("dot", np.ones((1, cols), np.float32))
                for _ in range(4)
            ]
            svc.drain(timeout=300)
            for r in reqs:
                r.result(0)
        self.assertEqual(SERVE_STATS["queue_depth"], 0, SERVE_STATS)
        self.assertGreaterEqual(SERVE_STATS["max_queue_depth"], 1)
