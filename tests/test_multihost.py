"""Real multi-process execution: 2 CPU processes over jax.distributed.

The reference's whole multi-node story is "run the same suite under
``mpirun -n N``" (``Jenkinsfile:24-27``). The analogue here launches two
actual OS processes, each with 4 virtual CPU devices, connected through
``jax.distributed.initialize`` — then drives init -> is_split assembly ->
chunked load -> global reduce -> rank-serialized save through the public
API. This executes the code paths that the single-process suite cannot:
``assemble_local_shards``'s process_allgather, ``load_hdf5``'s
per-process chunk reads, and ``save_hdf5``'s barrier-serialized writes.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

# each test here boots 2-4 real OS processes joined by jax.distributed and
# drives whole sub-suites inside them — minutes of wall clock on a small
# CPU box, so the file sits outside the tier-1 gate (-m 'not slow')
pytestmark = pytest.mark.slow

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]; tmp = sys.argv[4]

import heat_tpu as ht
ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.process_count() == nproc
assert jax.device_count() == 8 and jax.local_device_count() == 4
comm = ht.get_comm()
assert comm.size == 8

# --- is_split, aligned path: equal extents, divisible by local devices ---
full = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
local = full[pid * 8 : (pid + 1) * 8]
a = ht.array(local, is_split=0)
assert a.shape == (16, 3), a.shape
assert a.split == 0
# global reduce crosses the process boundary
total = float(a.sum().item())
assert total == float(full.sum()), (total, full.sum())

# --- is_split, uneven path: different extents per process ---
cut = 7  # process 0: 7 rows, process 1: 9 rows
local_u = full[:cut] if pid == 0 else full[cut:]
b = ht.array(local_u, is_split=0)
assert b.shape == (16, 3), b.shape
assert float(b.sum().item()) == float(full.sum())
col = b.mean(axis=0)
np.testing.assert_allclose(np.asarray(col._logical()), full.mean(axis=0), rtol=1e-6)

# --- non-split-dim mismatch must raise (reference consistency check) ---
try:
    ht.array(np.zeros((4, 2 + pid), np.float32), is_split=0)
    raise AssertionError("expected ValueError for mismatched non-split dims")
except ValueError:
    pass

# --- replicated-input constructor: same global np array on every process ---
g = ht.array(full, split=0)
assert float(g.sum().item()) == float(full.sum())
gn = ht.array(full[:5])  # replicated
assert float((g[:5] * gn).sum().item()) == float((full[:5] ** 2).sum())

# --- chunked load: every process reads only its slice ---
path = os.path.join(tmp, "mh_2proc.h5")
x = ht.load(path, dataset="data", split=0)
ref = np.arange(37 * 5, dtype=np.float32).reshape(37, 5)
assert x.shape == (37, 5)
assert float(x.sum().item()) == float(ref.sum())

# --- rank-serialized save of a distributed result ---
y = x * 2.0
ht.save(y, os.path.join(tmp, "mh_out.h5"), "doubled")

# --- RNG: both processes see the same global stream ---
ht.random.seed(123)
d = ht.random.rand(13, 4, split=0)
s = float(d.sum().item())

# --- chunked CSV: neither process parses the whole file (VERDICT r2 #6) ---
from heat_tpu import native as hnative
import heat_tpu.core.io as hio
csv_rows = 101
_range_calls = []
_orig_range = hnative.csv_parse_range
def _spy_range(path, off, per, *a, **k):
    r = _orig_range(path, off, per, *a, **k)
    _range_calls.append(None if r is None else r.shape[0])
    return r
hnative.csv_parse_range = _spy_range
_py_calls = []
_orig_py = hio._py_csv_range
def _spy_py(*a, **k):
    r = _orig_py(*a, **k)
    _py_calls.append(r.shape[0])
    return r
hio._py_csv_range = _spy_py
csv = ht.load_csv(os.path.join(tmp, "mh_rows.csv"), header_lines=1, split=0)
assert csv.shape == (csv_rows, 3), csv.shape
parsed = [n for n in _range_calls + _py_calls if n is not None]
assert parsed and all(n < csv_rows for n in parsed), parsed
csv_ref = np.loadtxt(os.path.join(tmp, "mh_rows.csv"), delimiter=",", skiprows=1, dtype=np.float64, ndmin=2).astype(np.float32)
assert abs(float(csv.sum().item()) - float(csv_ref.sum())) < 1e-3
w = np.arange(1, csv_rows * 3 + 1, dtype=np.float32).reshape(csv_rows, 3)
chk = float((csv * ht.array(w, split=0)).sum().item())
assert abs(chk - float((csv_ref * w).sum())) < 0.5, (chk, float((csv_ref * w).sum()))

# --- distributed sort across the process boundary (shard_map ppermute) ---
rng_l = np.random.default_rng(7)
xs = rng_l.normal(size=37).astype(np.float32)
sv, si = ht.sort(ht.array(xs, split=0))
ev = np.sort(xs)
wgt = np.arange(1, 38, dtype=np.float32)
got_chk = float((sv * ht.array(wgt, split=0)).sum().item())
assert abs(got_chk - float((ev * wgt).sum())) < 1e-2, (got_chk, float((ev * wgt).sum()))
gi = float((si.astype(ht.float32) * ht.array(wgt, split=0)).sum().item())
ei = float((np.argsort(xs, kind="stable") * wgt).sum())
assert abs(gi - ei) < 1e-2, (gi, ei)

# --- TSQR across processes + residual ---
A = rng_l.normal(size=(33, 4)).astype(np.float32)
a_q = ht.array(A, split=0)
q, r = ht.linalg.qr(a_q)
err = float(ht.linalg.norm(ht.matmul(q, r) - a_q).item())
assert err < 1e-3, err

# --- KMeans.fit: bit-identical centroids on both processes ---
blobs = np.concatenate([
    rng_l.normal(loc=-4, size=(40, 3)), rng_l.normal(loc=4, size=(40, 3))
]).astype(np.float32)
km = ht.cluster.KMeans(n_clusters=2, init="random", max_iter=10, random_state=5)
km.fit(ht.array(blobs, split=0))
cent = np.asarray(km.cluster_centers_._logical() if hasattr(km.cluster_centers_, "_logical") else km.cluster_centers_)
import hashlib
cent_hash = hashlib.sha256(np.ascontiguousarray(cent).tobytes()).hexdigest()[:16]

# --- unique: candidate exchange across processes ---
uvals = ht.unique(ht.array(np.tile(np.arange(9, dtype=np.int64), 5), split=0))
got_u = np.sort(np.asarray(uvals._logical()))
np.testing.assert_array_equal(got_u, np.arange(9))

# --- nonzero: per-shard scan + ordered cross-process coordinate concat ---
nz_x = np.zeros(45, np.float32); nz_x[::7] = 1.0
nz = ht.nonzero(ht.array(nz_x, split=0))
np.testing.assert_array_equal(np.asarray(nz._logical()), np.nonzero(nz_x)[0])

# --- DASO step on the process-spanning 2x4 mesh ---
import optax, jax.numpy as jnp
from heat_tpu.parallel import make_hierarchical_mesh
hmesh = make_hierarchical_mesh(n_slow=2)
daso = ht.optim.DASO(optax.sgd(0.1), total_epochs=4, warmup_epochs=0, cooldown_epochs=0)
dparams = daso.init({"w": jnp.zeros((3,), jnp.float32)}, hmesh)
daso.global_skip = 2; daso.batches_to_wait = 0
xb = jnp.asarray(blobs)
yb = jnp.asarray(np.sign(blobs.sum(1)).astype(np.float32))
def lg(p, xb, yb):
    import jax as _jax
    return _jax.value_and_grad(lambda p: jnp.mean((xb @ p["w"] - yb) ** 2))(p)
gaps = []
for b in range(4):
    dparams, dloss = daso.step(lg, dparams, xb, yb)
    gaps.append(float(jnp.max(jnp.abs(dparams["w"][0] - dparams["w"][1]))))
assert gaps[0] < 1e-6 and gaps[1] > 1e-7, gaps  # sync at 0, diverge at 1
daso_final = daso.consolidated_params(dparams)
daso_hash = hashlib.sha256(np.ascontiguousarray(np.asarray(daso_final["w"], dtype=np.float32)).tobytes()).hexdigest()[:16]

print(f"WORKER{pid} OK {s:.6f} kmeans={cent_hash} daso={daso_hash}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_end_to_end(tmp_path):
    import h5py

    ref = np.arange(37 * 5, dtype=np.float32).reshape(37, 5)
    with h5py.File(tmp_path / "mh_2proc.h5", "w") as f:
        f.create_dataset("data", data=ref)

    rng = np.random.default_rng(11)
    csv_data = rng.normal(size=(101, 3)).astype(np.float64)
    with open(tmp_path / "mh_rows.csv", "w") as f:
        f.write("a,b,c\n")
        for row in csv_data:
            f.write(",".join(f"{v:.17g}" for v in row) + "\n")

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} OK" in out, out

    # same RNG stream, bit-identical KMeans centroids, identical DASO
    # consolidated params on both processes
    finals = [out.strip().splitlines()[-1].split() for out in outs]
    assert finals[0][2:] == finals[1][2:], finals

    # the saved file carries the full doubled dataset
    with h5py.File(tmp_path / "mh_out.h5", "r") as f:
        np.testing.assert_allclose(f["doubled"][...], ref * 2.0)


_GUARD_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu import resilience as rz

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

# --- fingerprint: each process digests only its addressable shards ---
full = np.arange(24 * 3, dtype=np.float32).reshape(24, 3)
x = ht.array(full, split=0)
fp = rz.check_divergence(x, check_layout=True)  # healthy: no divergence
assert fp.split == 0
assert len(fp.groups) == 4, fp.groups  # 4 local shards of the 8 global

# --- guarded() across a process-spanning reduce ---
with rz.guarded(x) as g:
    total = float(x.sum().item())
    assert total == float(full.sum()), (total, full.sum())

# --- watchdog: injected stall inside resplit_ -> CollectiveTimeout on
# every rank (the fault fires host-side, symmetrically: same seed) ---
y = ht.array(full, split=0)
with rz.deadlines(30.0):
    with rz.chaos(seed=0, timeout=1.0, targets=("collective",)):
        try:
            y.resplit_(1)
            raise AssertionError("expected CollectiveTimeout")
        except rz.CollectiveTimeout as e:
            assert e.label == "collective.resplit", e.label

# --- shrink-to-healthy: drop one device of process 1's four; the
# surviving 7-device mesh still spans both processes and the values
# survive the redistribution bit-identically ---
rz.mark_unhealthy(7)
new_comm, (z,) = rz.shrink_to_healthy(arrays=[x])
assert new_comm.size == 7, new_comm.size
assert 7 not in [int(d.id) for d in new_comm.mesh.devices.ravel()]
assert float(z.sum().item()) == float(full.sum())
zcol = float((z * ht.array(full, split=0, comm=new_comm)).sum().item())
assert abs(zcol - float((full * full).sum())) < 1e-2, zcol
rz.clear_unhealthy()

print(f"WORKER{pid} GUARD OK {total:.3f}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_guard_layer(tmp_path):
    """Runtime guards under real multi-process execution: divergence
    check over addressable shards, watchdog-bounded resplit, and an
    elastic shrink whose surviving mesh still spans both processes."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "guard_worker.py"
    worker.write_text(_GUARD_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} GUARD OK" in out, out


_RAGGED_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu.core.dndarray import LAYOUT_STATS
from heat_tpu.parallel.flatmove import MOVE_STATS

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

p = ht.get_comm().size
rows = 3 * p + 2
full = np.arange(rows * 4, dtype=np.float32).reshape(rows, 4)

# everything on the LAST shard: maximally skewed, spans the process split
counts = [0] * p
counts[-1] = rows
target = np.tile([rows, 4], (p, 1))
target[:, 0] = counts

x = ht.array(full, split=0)
r0, m0 = LAYOUT_STATS["rebalances"], MOVE_STATS["ragged_moves"]
x.redistribute_(target_map=target)        # the ONE exchange
z = (x + 1.0) * 2.0                       # computes in place on the ragged map
s = float(x.sum().item())
mx = float(ht.max(x).item())
z.redistribute_(target_map=target)        # already there: no-op
moves = MOVE_STATS["ragged_moves"] - m0
rebalances = LAYOUT_STATS["rebalances"] - r0
assert moves == 1, moves
assert rebalances == 0, rebalances
assert z.lcounts == tuple(counts), z.lcounts
assert s == float(full.sum()), (s, full.sum())
assert mx == float(full.max()), (mx, full.max())
np.testing.assert_array_equal(z.numpy(), (full + 1.0) * 2.0)

print(f"WORKER{pid} RAGGED OK {s:.3f} {mx:.3f} {moves} {rebalances}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_ragged_compute(tmp_path):
    """Ragged compute under real multi-process execution (PR 3 tentpole):
    redistribute -> elementwise/reduce -> redistribute on a maximally
    skewed process-spanning layout costs exactly ONE exchange, zero
    rebalances, and matches numpy on both ranks."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "ragged_worker.py"
    worker.write_text(_RAGGED_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} RAGGED OK" in out, out
    # both ranks computed identical global results and counters
    finals = [out.strip().splitlines()[-1].split()[2:] for out in outs]
    assert finals[0] == finals[1], finals


_FACTOR_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu.core.dndarray import LAYOUT_STATS
from heat_tpu.parallel.flatmove import MOVE_STATS

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

n = 21  # non-divisible by the 8-device process-spanning mesh
rng = np.random.default_rng(3)
A = (np.eye(n) + rng.standard_normal((n, n)) / (2.0 * np.sqrt(n))).astype(np.float32)
spd = (A @ A.T + np.eye(n)).astype(np.float32)
b = rng.standard_normal((n, 2)).astype(np.float32)

a0 = ht.array(A, split=0)
b0 = ht.array(b, split=0)
s0 = ht.array(spd, split=0)

# warm the programs, then counter-assert the compute is gather-free
# across the REAL process boundary
ht.linalg.det(a0); ht.linalg.inv(a0); ht.linalg.solve(a0, b0); ht.linalg.cholesky(s0)
m0, r0 = MOVE_STATS["ragged_moves"], LAYOUT_STATS["rebalances"]
d = ht.linalg.det(a0)
inv = ht.linalg.inv(a0)
x = ht.linalg.solve(a0, b0)
L = ht.linalg.cholesky(s0)
moves = MOVE_STATS["ragged_moves"] - m0
rebalances = LAYOUT_STATS["rebalances"] - r0
assert moves == 0, moves
assert rebalances == 0, rebalances

dv = float(d.larray)
assert abs(dv - np.linalg.det(A.astype(np.float64))) < 5e-3 * abs(dv), dv
np.testing.assert_allclose(np.asarray(inv._logical()), np.linalg.inv(A), atol=5e-3)
np.testing.assert_allclose(np.asarray(x._logical()), np.linalg.solve(A, b), atol=5e-3)
np.testing.assert_allclose(np.asarray(L._logical()), np.linalg.cholesky(spd), atol=5e-3)

print(f"WORKER{pid} FACTOR OK {dv:.6f} {moves} {rebalances}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_factorizations(tmp_path):
    """Distributed dense factorizations under real multi-process execution
    (PR 5 tentpole): det/inv/solve/cholesky on a split-0 operand spanning
    two OS processes match numpy, with zero layout exchanges and zero
    rebalances during compute, and identical results on both ranks."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "factor_worker.py"
    worker.write_text(_FACTOR_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} FACTOR OK" in out, out
    # both ranks computed the identical determinant and counters
    finals = [out.strip().splitlines()[-1].split()[3:] for out in outs]
    assert finals[0] == finals[1], finals


_SUPERVISOR_WORKER = r"""
import os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]; tmp = sys.argv[4]

import heat_tpu as ht
from heat_tpu import resilience as rz

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

NOSLEEP = rz.RetryPolicy(max_attempts=4, base_delay=0.001, seed=0, sleep=lambda s: None)

state = {"x": ht.array(np.arange(16, dtype=np.float32), split=0), "n": 0}

# mid-fit, ALL of process 1's accelerators die: mark them unhealthy on
# every process (the marks are what probe() reads back in simulation)
# and raise the RuntimeError a real died accelerator would surface.
fired = []
victims = [int(d.id) for d in jax.devices() if d.process_index == 1]

def step(st, data, i):
    if i == 3 and not fired:
        fired.append(i)
        for dev_id in victims:
            rz.mark_unhealthy(dev_id)
        raise RuntimeError("simulated: process 1's accelerators died mid-step")
    return {"x": st["x"] + 1.0, "n": st["n"] + 1}, False

sup = rz.Supervisor(
    os.path.join(tmp, "sup-ckpt"),
    rz.CheckpointSchedule(every_steps=1, keep_last=5),
    retry=NOSLEEP, checkpoint_retry=NOSLEEP,
)
res = sup.run(step, state, n_steps=6)

done_marker = os.path.join(tmp, "sup_done_0")
if pid == 1:
    # every local device died: this process detaches from the run and the
    # survivor finishes without it. Hold the distributed runtime open
    # until the survivor reports done, then exit cleanly.
    assert res.detached, "process with no surviving devices must detach"
    assert res.state is None
    assert res.counters["shrinks"] == 1, res.counters
    deadline = time.time() + 300
    while not os.path.exists(done_marker):
        assert time.time() < deadline, "survivor never finished"
        time.sleep(0.2)
    print(f"WORKER{pid} SUP OK detached shrinks={res.counters['shrinks']}")
else:
    # the survivor restores the last pre-fault checkpoint onto its own
    # 4-device mesh and completes the full run alone
    assert not res.detached
    assert res.steps == 6 and res.state["n"] == 6, (res.steps, res.state["n"])
    np.testing.assert_array_equal(
        res.state["x"].numpy(), np.arange(16, dtype=np.float32) + 6.0
    )
    assert res.comm.size == 4, res.comm.size
    procs = {int(d.process_index) for d in res.comm.mesh.devices.ravel()}
    assert procs == {0}, procs
    assert res.counters["shrinks"] == 1, res.counters
    assert res.counters["checkpoints"] >= 4, res.counters  # baseline + steps 1-3
    with open(done_marker, "w") as fh:
        fh.write("ok")
    print(f"WORKER{pid} SUP OK n={res.state['n']} mesh={res.comm.size} "
          f"shrinks={res.counters['shrinks']}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_supervisor_survives_process_loss(tmp_path):
    """Self-healing supervised execution across a REAL process boundary
    (PR 6 tentpole): chaos kills every device of process 1 mid-run; the
    supervisor probes, shrinks to the surviving process-0 mesh, restores
    the last good checkpoint onto it, and finishes — while the deviceless
    process detaches cleanly instead of hanging in a collective."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "supervisor_worker.py"
    worker.write_text(_SUPERVISOR_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} SUP OK" in out, out
    assert "detached" in outs[1]
    assert "n=6 mesh=4" in outs[0]


_LOCKSTEP_WORKER = r"""
import contextlib, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu import analysis, resilience as rz
from heat_tpu.core import communication

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

# --- healthy: identical dispatch on every rank -> clean cross-check ---
with analysis.lockstep(check_at_exit=False, deadline=60.0) as ls:
    for i in range(3):
        communication.ragged_process_allgather(np.arange(i + 1))
    ls.check("healthy")
assert ls.events == 3, ls.events
assert ht.LOCKSTEP_STATS["divergences"] == 0

# --- seeded divergence: chaos drops rank 1's SECOND recorded allgather,
# so its digest reads exactly like a rank whose control flow skipped that
# collective. The real collectives still all run (the mesh never wedges:
# the detector, not the hang, is under test) and the explicit check at a
# shared program point must convert the skip into a LockstepError on
# EVERY rank, within the watchdog budget, naming the divergent site. ---
sched = (
    rz.FaultSchedule(events=[("collective.allgather", 2, "lockstep_divergence")])
    if pid == 1
    else contextlib.nullcontext()
)
t0 = time.monotonic()
err = None
with sched:
    with analysis.lockstep(check_at_exit=False, deadline=60.0) as ls:
        for i in range(3):
            communication.ragged_process_allgather(np.arange(i + 1))
        try:
            ls.check("step-boundary")
            raise AssertionError("expected LockstepError")
        except rz.LockstepError as e:
            err = e
elapsed = time.monotonic() - t0
assert elapsed < 60.0, elapsed

# dropping seq 1 shifts rank 1's remaining event down, so BOTH ranks hold
# an entry at seq 1 with different fingerprints: the first divergent call
# site is named on both sides, not just on the long rank
assert err.seq == 1, err.seq
assert err.site == "collective.allgather", err.site
assert tuple(err.counts) == (3, 2), err.counts
assert err.label == "step-boundary", err.label
assert "collective.allgather" in str(err), err
assert err.process_index == pid, (err.process_index, pid)
assert ht.LOCKSTEP_STATS["divergences"] == 1
if pid == 1:
    assert ht.LOCKSTEP_STATS["dropped"] == 1
    assert ls.events == 2, ls.events
else:
    assert ht.LOCKSTEP_STATS["dropped"] == 0
    assert ls.events == 3, ls.events

print(f"WORKER{pid} LOCKSTEP OK seq={err.seq} counts={tuple(err.counts)} "
      f"elapsed={elapsed:.1f}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_lockstep_divergence(tmp_path):
    """Cross-process collective-lockstep sanitizer under real
    multi-process execution (PR 7 tentpole): a chaos ``lockstep_divergence``
    fault makes rank 1's digest skip one allgather; the explicit
    ``check()`` at a shared program point raises ``LockstepError`` on both
    ranks — naming the first divergent seq, site, and per-rank counts —
    instead of the silent mesh-wide hang a real skipped collective causes."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "lockstep_worker.py"
    worker.write_text(_LOCKSTEP_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} LOCKSTEP OK" in out, out
    # both ranks named the SAME divergence point
    finals = [out.strip().splitlines()[-1].split()[3:6] for out in outs]
    assert finals[0] == finals[1] == ["seq=1", "counts=(3,", "2)"], finals


_SERVE_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu import analysis
from heat_tpu.analysis.sanitizer import Region
from heat_tpu.serve import BucketPolicy, ServeService, reset_serve_stats

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

cols, classes = 8, 4
rng = np.random.default_rng(21)
w_np = rng.normal(size=(cols, classes)).astype(np.float32)
mu_np = rng.normal(size=(classes,)).astype(np.float32)
# model weights SPLIT across the process boundary: every batch dispatch
# contracts x @ w over the sharded axis, a cross-process collective
w = ht.array(w_np, split=0)
mu = ht.array(mu_np)

def linear(x):
    return x @ w + mu

def score(x):
    return ht.argmax(x @ w + mu, axis=1)

with analysis.lockstep():
    svc = ServeService(policy=BucketPolicy(edges=(1, 2, 4, 8), max_batch=8))
    svc.register_endpoint("linear", linear)
    svc.register_endpoint("score", score)
    # async (timer/count) triggers fire at rank-divergent moments and
    # must be disarmed under multiple controllers: barrier-driven only
    assert svc._async_triggers is False

    # cold pass: one dispatch per (endpoint, bucket), each draining alone
    for name in ("linear", "score"):
        for b in (1, 2, 4, 8):
            r = svc.submit(name, rng.normal(size=(b, cols)).astype(np.float32))
            svc.flush()
            r.result(300)

    # the SPMD serving contract: both ranks submit the SAME interleaved
    # multi-tenant trace in the same order, then one flush barrier; many
    # collective-bearing requests are outstanding concurrently and the
    # dispatcher must form identical batches in identical order on both
    # ranks (or the x @ w collectives cross-rendezvous and deadlock)
    trace = [
        (("linear", "score")[i % 2],
         rng.normal(size=(1 + i % 4, cols)).astype(np.float32))
        for i in range(24)
    ]
    reset_serve_stats()
    region = Region("ws2 warm serve")
    requests = [svc.submit(name, p) for name, p in trace]
    svc.flush()
    results = [r.result(300) for r in requests]
    warm = region.compiles + region.traces
    stats = svc.stats()
    svc.close(300)
div = int(analysis.LOCKSTEP_STATS["divergences"])
assert warm == 0, warm
assert div == 0, div
assert stats["errors"] == 0, stats
assert stats["bucket_misses"] == 0, stats

acc = 0.0
for (name, p), out in zip(trace, results):
    ref = p @ w_np + mu_np
    if name == "score":
        assert np.array_equal(np.asarray(out), np.argmax(ref, axis=1)), name
    else:
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)
    acc += float(np.asarray(out, dtype=np.float64).sum())

print(f"WORKER{pid} SERVE OK {acc:.4f} {warm} {div} {stats['batches']}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_serving(tmp_path):
    """Resident serving under real multi-process execution (PR 13
    tentpole): two endpoints over process-spanning sharded weights serve
    24 concurrent outstanding requests; batches form identically on both
    ranks (no lockstep divergence, no deadlock), the warm phase neither
    traces nor compiles, and both ranks scatter identical results."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "serve_worker.py"
    worker.write_text(_SERVE_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} SERVE OK" in out, out
    # identical result checksum, batch count, and zero counters per rank
    finals = [out.strip().splitlines()[-1].split()[3:] for out in outs]
    assert finals[0] == finals[1], finals


_SERVE_SHRINK_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]; shared = sys.argv[4]

import heat_tpu as ht
from heat_tpu import resilience as rz
from heat_tpu.core import communication as comm_mod
from heat_tpu.serve import BucketPolicy, ServeService, reset_serve_stats

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

cols, classes = 8, 4
rng = np.random.default_rng(31)
w_np = rng.normal(size=(cols, classes)).astype(np.float32)


class Linear:
    # snapshot-protocol model whose split-0 weight SPANS the process
    # boundary; load_state_dict re-places it on the CURRENT default
    # mesh, which is what makes the elastic relocate land on survivors
    def __init__(self, w_host):
        self.load_state_dict({"w": w_host})

    def state_dict(self):
        return {"w": self.w}

    def load_state_dict(self, state):
        self.w = ht.array(np.asarray(state["w"], dtype=np.float32), split=0)

    def predict(self, x):
        return x @ self.w


reset_serve_stats()
svc = ServeService(
    policy=BucketPolicy(edges=(2, 4), max_batch=8),
    snapshot_dir=shared,
    snapshot_every=1,
)
svc.register_model("lin", Linear(w_np))
assert svc._async_triggers is False

xs = [rng.normal(size=(2, cols)).astype(np.float32) for _ in range(3)]
# warm pass: the (2-row) bucket compiles and the first snapshot commits
r = svc.submit("lin.predict", xs[0])
svc.flush()
np.testing.assert_allclose(np.asarray(r.result(300)), xs[0] @ w_np, atol=1e-4)

# one chaos device loss at the next dispatch: same seed on both ranks,
# so both mark the SAME global device and classify/probe/shrink in
# lockstep (the replicated_ids union + one replicated go/no-go)
sched = rz.FaultSchedule(events=[("serve.dispatch", 1, "device_loss")], seed=7)
with sched:
    reqs = [svc.submit("lin.predict", x) for x in xs[1:]]
    svc.flush()
    outs = [np.asarray(q.result(300)) for q in reqs]
assert sched.pending() == [], sched.pending()

stats = svc.stats()
svc.close(300)
new_comm = comm_mod.sanitize_comm(None)
assert new_comm.size == 7, new_comm.size
# the survivor mesh still spans BOTH processes
procs = {int(d.process_index) for d in new_comm.mesh.devices.ravel()}
assert procs == {0, 1}, procs
for x, out in zip(xs[1:], outs):
    np.testing.assert_allclose(out, x @ w_np, atol=1e-4)
assert stats["shrinks"] == 1, stats
assert stats["redispatched"] == 2, stats
assert stats["restores"] == 1, stats  # the shrink-relocate restore
acc = float(sum(abs(o).sum() for o in outs))
rz.clear_unhealthy()
print(f"WORKER{pid} SHRINK OK {new_comm.size} {stats['shrinks']} "
      f"{stats['redispatched']} {acc:.4f}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_serve_shrink_redispatch(tmp_path):
    """PR 16 tentpole, end to end at real world size 2: a chaos device
    loss mid-dispatch makes both ranks probe, agree on the casualty via
    the replicated-ids union, shrink to the 7 survivors (still spanning
    both processes), elastically restore the registry's process-spanning
    sharded weights from the snapshot, and redispatch the in-flight
    batch — every request answered exactly once with oracle-equal rows."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "serve_shrink_worker.py"
    worker.write_text(_SERVE_SHRINK_WORKER)
    shared = tmp_path / "snap"
    shared.mkdir()
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port), str(shared)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} SHRINK OK" in out, out
    # identical survivor mesh, counters, and result checksum on each rank
    finals = [out.strip().splitlines()[-1].split()[3:] for out in outs]
    assert finals[0] == finals[1], finals


_TICK_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu import analysis
from heat_tpu.analysis.sanitizer import Region
from heat_tpu.serve import BucketPolicy, ServeService, reset_serve_stats

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

cols, classes = 8, 4
rng = np.random.default_rng(43)
w_np = rng.normal(size=(cols, classes)).astype(np.float32)
mu_np = rng.normal(size=(classes,)).astype(np.float32)
# weights SPLIT across the process boundary: every dispatch contracts
# x @ w over the sharded axis, a cross-process collective — any
# rank-divergent batch formation deadlocks the rendezvous
w = ht.array(w_np, split=0)
mu = ht.array(mu_np)

def linear(x):
    return x @ w + mu

def score(x):
    return ht.argmax(x @ w + mu, axis=1)

with analysis.lockstep():
    # DEFAULT construction at ws2: the replicated dispatch tick is
    # armed (cadence = max_latency_ms) and the rank-local async
    # triggers stay off — NO flush()/drain() anywhere in this worker;
    # every dispatch below is tick-decided
    svc = ServeService(
        policy=BucketPolicy(edges=(1, 2, 4, 8), max_batch=8,
                            max_latency_ms=20.0)
    )
    svc.register_endpoint("linear", linear)
    svc.register_endpoint("score", score)
    assert svc._tick_armed is True
    assert svc._async_triggers is False

    # cold pass: the latency trigger alone must dispatch each
    # (endpoint, bucket) — result() blocks until a tick decides it
    for name in ("linear", "score"):
        for b in (1, 2, 4, 8):
            r = svc.submit(name, rng.normal(size=(b, cols)).astype(np.float32))
            r.result(300)

    # warm phase: both ranks submit the SAME interleaved multi-tenant
    # trace with no barrier at all; ticks re-arm the timer/count
    # triggers and every rank forms the identical batch sequence from
    # the gathered frames (or the x @ w collectives cross-rendezvous
    # and deadlock)
    trace = [
        (("linear", "score")[i % 2],
         rng.normal(size=(1 + i % 4, cols)).astype(np.float32))
        for i in range(24)
    ]
    reset_serve_stats()
    region = Region("ws2 tick serve")
    requests = [svc.submit(name, p) for name, p in trace]
    results = [r.result(300) for r in requests]
    warm = region.compiles + region.traces
    # close() joins the dispatcher, so the counters are quiescent —
    # every agreed tick fully applied and counted — before the read
    svc.close(300)
    stats = svc.stats()
div = int(analysis.LOCKSTEP_STATS["divergences"])
assert warm == 0, warm
assert div == 0, div
assert stats["ticks"] > 0, stats
assert stats["tick_batches"] == stats["batches"] > 0, stats
assert stats["errors"] == 0, stats
assert stats["bucket_misses"] == 0, stats
assert stats["shed"] == 0 and stats["rejected"] == 0, stats

acc = 0.0
for (name, p), out in zip(trace, results):
    ref = p @ w_np + mu_np
    if name == "score":
        assert np.array_equal(np.asarray(out), np.argmax(ref, axis=1)), name
    else:
        np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)
    acc += float(np.asarray(out, dtype=np.float64).sum())

# every deterministic SERVE_STATS counter must agree across ranks —
# plans are pure, so both ranks form the same batches from the same
# requests. The raw `ticks` count is asserted >0 but NOT compared:
# the mid-worker reset_serve_stats() lands at a rank-local wall-clock
# moment, so an EMPTY heartbeat tick can fall on either side of it on
# different ranks (batch-bearing ticks can't — their dispatches are
# ordered against the results the trace waits on).
counters = " ".join(
    f"{k}={stats[k]}" for k in (
        "requests", "batches", "tick_batches", "batched_rows",
        "shed", "rejected", "errors", "bucket_misses",
    )
)
print(f"WORKER{pid} TICK OK {acc:.4f} warm={warm} div={div} {counters}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_tick_dispatch(tmp_path):
    """ISSUE 18 tentpole, end to end at real world size 2: with the
    replicated dispatch tick armed (the ws>1 default) and NO flush()
    calls anywhere, the timer/count triggers dispatch 24 concurrent
    outstanding requests across two endpoints over process-spanning
    sharded weights — batches form identically on both ranks from the
    gathered tick frames (zero lockstep divergences, zero deadlocks),
    the warm phase neither traces nor compiles, responses are
    oracle-equal, and every deterministic SERVE_STATS counter is
    identical on both ranks."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "tick_worker.py"
    worker.write_text(_TICK_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} TICK OK" in out, out
    # identical checksum, warm/divergence zeros, and counters per rank
    finals = [out.strip().splitlines()[-1].split()[3:] for out in outs]
    assert finals[0] == finals[1], finals


_GROW_WORKER = r"""
import contextlib
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu import resilience as rz
from heat_tpu.core import communication as comm_mod
from heat_tpu.resilience.monitor import HEALTH_STATS

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

world = comm_mod.sanitize_comm(None)
x_np = np.arange(64, dtype=np.float32).reshape(16, 4)
x = ht.array(x_np, split=0)

mon = rz.HealthMonitor(world, heal_after=3, degrade_after=2)

# --- 1) symmetric no-error barrier: a clean tick runs the same
# collectives on every rank (probe-failure union + EWMA frame) and
# degrades nobody
rep = mon.tick()
assert rep.degraded == [] and rep.failed == frozenset(), rep

# --- 2) a probe failure injected on ONE rank only (rank 1's first
# addressable device) must surface the SAME degraded verdict on every
# rank through the replicated-ids union. Rank 1 probes its 4 local
# devices per tick, so hits 1 and 9 are ticks 1 and 3 of the scope:
# degrade, then a mid-heal flap inside the heal_after=3 window.
ravel = list(world.mesh.devices.ravel())
flap_dev = [int(d.id) for d in ravel if int(d.process_index) == 1][0]
sched = (
    rz.FaultSchedule(
        events=[("monitor.probe", 1, "device_flap"),
                ("monitor.probe", 9, "device_flap")],
        seed=5,
    )
    if pid == 1 else contextlib.nullcontext()
)
with sched:
    rep = mon.tick()
    assert rep.degraded == [flap_dev], (pid, rep)
    assert mon.ledger[flap_dev].state == "unhealthy"

    # proactive shrink off the degraded device: survivors still span
    # BOTH processes, and the split-0 array lands on them intact
    small, (xs,) = rz.shrink_to_healthy(world, [x], set_default=True)
    assert small.size == 7, small.size
    assert {int(d.process_index) for d in small.mesh.devices.ravel()} == {0, 1}
    np.testing.assert_array_equal(xs.numpy(), x_np)

    rep = mon.tick()   # clean: healing streak 1 on every rank
    assert mon.ledger[flap_dev].state == "healing", mon.ledger[flap_dev]
    rep = mon.tick()   # scheduled mid-heal flap: damped on every rank
    assert rep.flapped == [flap_dev], (pid, rep)
    assert mon.ledger[flap_dev].state == "unhealthy"
if pid == 1:
    assert sched.pending() == [], sched.report()

# --- 3) flap damping restarts the streak: exactly heal_after=3 clean
# ticks re-admit the device, with identical counters on every rank
for _ in range(3):
    rep = mon.tick()
assert rep.healed == [flap_dev], (pid, rep)
assert mon.ledger[flap_dev].state == "healthy"
assert rz.unhealthy_devices() == frozenset(), rz.unhealthy_devices()

# --- 4) elastic re-grow onto the healed base: full mesh, both
# processes, values preserved through shrink AND grow
grown, (xg,) = rz.grow_to_healthy(small, [xs], base=world, set_default=True)
assert grown.size == 8, grown.size
assert {int(d.process_index) for d in grown.mesh.devices.ravel()} == {0, 1}
np.testing.assert_array_equal(xg.numpy(), x_np)

entry = mon.ledger[flap_dev]
acc = float(abs(xg.numpy()).sum())
print(f"WORKER{pid} GROW OK {small.size}->{grown.size} dev{flap_dev} "
      f"{entry.state} streak{entry.streak} flaps{entry.flaps} "
      f"H{HEALTH_STATS['degraded']}/{HEALTH_STATS['healed']}"
      f"/{HEALTH_STATS['flaps_damped']} {acc:.4f}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_grow_after_shrink(tmp_path):
    """PR 17 tentpole at real world size 2: a probe failure injected on
    ONE rank surfaces the same degraded verdict on every rank (the
    replicated-ids union), the mesh shrinks to 7 survivors spanning
    both processes, a mid-heal flap is damped with rank-identical
    streak counters (the quantized EWMA frame keeps verdicts
    bit-equal), and after heal_after clean ticks grow_to_healthy
    rebuilds the full 8-device mesh with array values preserved."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "grow_worker.py"
    worker.write_text(_GROW_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} GROW OK" in out, out
    # identical mesh trajectory, ledger state, streaks, flap counters,
    # health counters, and array checksum on each rank
    finals = [out.strip().splitlines()[-1].split()[2:] for out in outs]
    assert finals[0] == finals[1], finals


_FRAME_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu.analysis.sanitizer import Region
from heat_tpu.parallel.flatmove import MOVE_STATS

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

# identical rows on every process (the host-boundary contract)
rng = np.random.default_rng(17)
keys = rng.integers(0, 23, size=301).astype(np.int32)
vals = rng.normal(size=301).astype(np.float32)
f = ht.Frame({"k": keys, "x": vals})

f.groupby("k").mean()  # cold: compile plan+merge, elect splitters
before = MOVE_STATS["bucket_moves"]
region = Region("warm 2-process groupby")
out = f.groupby("k").mean()
moves = MOVE_STATS["bucket_moves"] - before
warm = region.compiles + region.traces
assert moves == 3, moves   # keys + fsum + count, ONE exchange each
assert warm == 0, region.stats()

d = {n: np.asarray(c._logical()) for n, c in zip(out.columns, (out["k"], out["x"]))}
order = np.argsort(d["k"], kind="stable")
uk = np.unique(keys)
want = np.array([vals[keys == u].mean() for u in uk], np.float64)
np.testing.assert_array_equal(d["k"][order], uk)
np.testing.assert_allclose(d["x"][order], want, rtol=1e-4, atol=1e-5)

# join across the process split: small unique-keyed right side
small = ht.Frame({"k": np.arange(23, dtype=np.int32),
                  "y": np.arange(23, dtype=np.float32)})
j = f.join(small, on="k")
dj = j.to_dict()
assert len(dj["k"]) == len(keys)
np.testing.assert_allclose(np.sort(dj["y"]), np.sort(keys.astype(np.float32)))

payload = " ".join(f"{v:.5f}" for v in d["x"][order][:8])
print(f"WORKER{pid} FRAME OK {moves} {warm} {payload}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_frame_groupby_join(tmp_path):
    """The shuffle engine under real multi-process execution (PR 14
    tentpole): splitter election, destination matrices, and received-row
    counts are replicated, so both ranks run the same bounded exchange
    schedule — warm groupby is 0-trace/0-compile with exactly one
    bucket exchange per operand, and groupby+join match numpy on both
    ranks with identical payloads."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "frame_worker.py"
    worker.write_text(_FRAME_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} FRAME OK" in out, out
    # identical move/compile counters and identical group means per rank
    finals = [out.strip().splitlines()[-1].split()[3:] for out in outs]
    assert finals[0] == finals[1], finals


_PYTEST_DRIVER = r"""
import os, sys
import jax

jax.config.update("jax_platforms", "cpu")
pid, nproc, port, tmp, repo = sys.argv[1:6]

import heat_tpu as ht

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=int(nproc), process_id=int(pid)
)
assert jax.process_count() == int(nproc)

import pytest

sys.exit(
    pytest.main(
        [
            "-m", "multihost", "-q", "--no-header", "-p", "no:cacheprovider",
            f"--junitxml={tmp}/rank{pid}.xml",
            os.path.join(repo, "tests"),
        ]
    )
)
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed total-8 topology matrix is enough",
)
@pytest.mark.parametrize("nproc", [2, 4])
def test_multi_process_pytest_subset(tmp_path, nproc):
    """Run the ENTIRE ``-m multihost`` pytest subset inside ``nproc`` real
    OS processes joined by jax.distributed (VERDICT r3 item 3 — the
    reference's mpirun'd suite at several world sizes,
    ``Jenkinsfile:24-27``; here 2x4 and 4x2 process-x-device topologies).
    Per-test junit results are aggregated across ranks: all ranks must
    execute the SAME >= 50 test ids, every one passing on every rank."""
    import xml.etree.ElementTree as ET

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    driver = tmp_path / "mh_pytest_driver.py"
    driver.write_text(_PYTEST_DRIVER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={8 // nproc}"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = repo
    env["HEAT_TPU_MH_TMP"] = str(tmp_path)
    from concurrent.futures import ThreadPoolExecutor

    procs = [
        subprocess.Popen(
            [sys.executable, str(driver), str(i), str(nproc), str(port), str(tmp_path), repo],
            env=env,
            cwd=repo,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nproc)
    ]
    try:
        # drain ALL pipes concurrently (a failing subset prints more than
        # a pipe buffer; sequential communicate() would deadlock the ranks)
        with ThreadPoolExecutor(nproc) as pool:
            outs = list(pool.map(lambda p: p.communicate(timeout=900)[0], procs))
    finally:
        for p in procs:  # one rank dying blocks the others in a barrier
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {i} pytest run failed:\n{out[-8000:]}"

    results = []
    for i in range(nproc):
        tree = ET.parse(tmp_path / f"rank{i}.xml")
        cases = {}
        for tc in tree.iter("testcase"):
            name = f"{tc.get('classname')}::{tc.get('name')}"
            if tc.find("failure") is not None or tc.find("error") is not None:
                cases[name] = "failed"
            elif tc.find("skipped") is not None:
                cases[name] = "skipped"
            else:
                cases[name] = "passed"
        results.append(cases)
    for r in results[1:]:
        assert set(r) == set(results[0]), "ranks executed different test sets"
    passed = [
        n for n in results[0] if all(r[n] == "passed" for r in results)
    ]
    failed = [n for n in results[0] if any(r[n] == "failed" for r in results)]
    # a rank-dependent outcome (ran on one rank, skipped on another)
    # breaks 'every test on every rank' just as much as a failure
    uneven = [n for n in results[0] if len({r[n] for r in results}) > 1]
    # >= 50 tests really executed under jax.distributed on every rank
    assert len(passed) >= 50, f"only {len(passed)} multihost tests passed"
    assert not failed, f"multihost subset failures: {failed}"
    assert not uneven, f"rank-dependent outcomes: {uneven}"


_TREE_MERGE_WORKER = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]

import heat_tpu as ht
from heat_tpu.core import communication
from heat_tpu.core.communication import tree_merge, tree_merge_rounds
from heat_tpu.parallel.flatmove import MOVE_STATS
from heat_tpu.stream import (
    ChunkIterator, CountMinTopK, HyperLogLog, KLLSketch, StreamingMoments,
)

ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.device_count() == 8 and jax.local_device_count() == 4

rounds_expected = tree_merge_rounds(nproc)
assert rounds_expected == 1, rounds_expected  # ceil(log2 2)

# --- tree path vs flat path: bit-identical replicated result -------------
import jax.numpy as jnp
rng = np.random.default_rng(100 + pid)
state = (jnp.int32(pid + 1), jnp.asarray(rng.normal(size=(5,)).astype(np.float32)))

def comb(a, b):
    return a[0] + b[0], a[1] + b[1] * 2.0  # deliberately non-commutative

flat = communication._flat_state_merge(
    [np.asarray(x) for x in state],
    jax.tree_util.tree_structure(state), comb, nproc,
)
t0 = dict(MOVE_STATS)
merged = tree_merge(state, comb)
assert MOVE_STATS["tree_merges"] == t0["tree_merges"] + 1
assert MOVE_STATS["tree_merge_rounds"] == t0["tree_merge_rounds"] + rounds_expected
assert int(merged[0]) == int(flat[0]) == 3, (int(merged[0]), int(flat[0]))
np.testing.assert_array_equal(np.asarray(merged[1]), np.asarray(flat[1]))

# --- estimator retrofit: merge_processes == flat whole-data answer -------
full = np.random.default_rng(7).normal(size=(240, 3)).astype(np.float32)
local_rows = full[pid * 120 : (pid + 1) * 120]
mom = StreamingMoments()
for c in ChunkIterator(local_rows, 32, split=None):  # per-process pipeline
    mom.update(c)
t0 = dict(MOVE_STATS)
mom.merge_processes()
assert MOVE_STATS["tree_merges"] == t0["tree_merges"] + 1
assert MOVE_STATS["tree_merge_rounds"] == t0["tree_merge_rounds"] + rounds_expected
assert mom.n == 240, mom.n
np.testing.assert_allclose(mom.mean.numpy(), full.mean(axis=0), rtol=1e-4, atol=1e-5)
np.testing.assert_allclose(mom.var.numpy(), full.var(axis=0), rtol=1e-3, atol=1e-4)

# --- sketches over the tree: oracle bounds hold at ws2 -------------------
big = np.random.default_rng(9).normal(size=(8000, 2)).astype(np.float32)
mine = big[pid * 4000 : (pid + 1) * 4000]
sk = KLLSketch(k=256, levels=10)
for c in ChunkIterator(mine, 512, split=None):
    sk.update(c)
sk.merge_processes()
assert sk.n == big.shape[0], sk.n  # both halves merged back
med = float(np.asarray(sk.median()._logical()))
flat_sorted = np.sort(big.ravel())
rank_err = abs(np.searchsorted(flat_sorted, med) / flat_sorted.size - 0.5)
assert rank_err <= sk.eps + 1.0 / (2 * sk.k), (rank_err, sk.eps)

ints = np.random.default_rng(11).integers(0, 3000, size=(6000, 1)).astype(np.float32)
hll = HyperLogLog(p=12)
for c in ChunkIterator(ints[pid * 3000 : (pid + 1) * 3000], 1024, split=None):
    hll.update(c)
hll.merge_processes()
true_d = len(np.unique(ints))
est = hll.distinct()
assert abs(est - true_d) / true_d <= 4 * hll.rel_error, (est, true_d)

zipf = np.minimum(np.random.default_rng(13).zipf(1.5, size=8000), 500).astype(
    np.float32
)[:, None]
cm = CountMinTopK(width=1024, depth=4, k=16)
for c in ChunkIterator(zipf[pid * 4000 : (pid + 1) * 4000], 1024, split=None):
    cm.update(c)
cm.merge_processes()
tv, tc = cm.topk(5)
tv = np.asarray(tv._logical())
uniq, cnt = np.unique(zipf, return_counts=True)
true_top3 = set(uniq[np.argsort(-cnt)[:3]].tolist())
assert true_top3.issubset(set(tv.tolist())), (true_top3, tv)

# --- groupby quantile: no shuffle, matches exact within the KLL bound ----
keys = np.repeat(np.arange(4, dtype=np.int32), 500)
vals = (np.random.default_rng(17).normal(size=2000) + keys).astype(np.float32)
f = ht.Frame({"k": ht.array(keys, split=0), "v": ht.array(vals, split=0)})
b0 = MOVE_STATS["bucket_moves"]
res = f.groupby("k").quantile(0.5)
assert MOVE_STATS["bucket_moves"] == b0, "groupby quantile shuffled"
rk = np.asarray(res["k"]._logical()); rv = np.asarray(res["v"]._logical())
for i, g in enumerate(rk):
    grp = np.sort(vals[keys == g])
    r_err = abs(np.searchsorted(grp, rv[i]) / grp.size - 0.5)
    assert r_err <= (3 + 1) / (2 * 256) + 1e-3, (g, r_err)

assert ht.LOCKSTEP_STATS["divergences"] == 0

fp = float(np.sum(np.asarray(merged[1])))
print(f"WORKER{pid} OK tree rounds={MOVE_STATS['tree_merge_rounds']} "
      f"fp={fp:.6f} med={med:.6f} est={est:.1f}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_tree_merge(tmp_path):
    """Log-depth ``tree_merge`` under real 2-process execution (PR 20
    tentpole): the butterfly path must (a) complete in exactly
    ``ceil(log2 P)`` ppermute rounds (MOVE_STATS counter), (b) produce the
    bit-identical replicated state the flat allgather+fold path produces,
    (c) carry the retrofitted estimator ``merge_processes`` and every
    sketch's cross-process merge within their oracle bounds, and (d) run
    ``Frame.groupby(...).quantile`` with ``bucket_moves == 0`` while
    matching the exact per-group quantile within the KLL rank bound —
    all with ``LOCKSTEP_STATS['divergences'] == 0``."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "tree_merge_worker.py"
    worker.write_text(_TREE_MERGE_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=600)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} OK" in out, out
    # replicated results are identical across ranks: same merged payload,
    # same sketch answers, same round counters
    finals = [out.strip().splitlines()[-1].split()[2:] for out in outs]
    assert finals[0] == finals[1], finals
