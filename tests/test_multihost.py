"""Real multi-process execution: 2 CPU processes over jax.distributed.

The reference's whole multi-node story is "run the same suite under
``mpirun -n N``" (``Jenkinsfile:24-27``). The analogue here launches two
actual OS processes, each with 4 virtual CPU devices, connected through
``jax.distributed.initialize`` — then drives init -> is_split assembly ->
chunked load -> global reduce -> rank-serialized save through the public
API. This executes the code paths that the single-process suite cannot:
``assemble_local_shards``'s process_allgather, ``load_hdf5``'s
per-process chunk reads, and ``save_hdf5``'s barrier-serialized writes.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np

pid = int(sys.argv[1]); nproc = int(sys.argv[2]); port = sys.argv[3]; tmp = sys.argv[4]

import heat_tpu as ht
ht.init_distributed(
    coordinator_address=f"localhost:{port}", num_processes=nproc, process_id=pid
)
assert jax.process_count() == nproc
assert jax.device_count() == 8 and jax.local_device_count() == 4
comm = ht.get_comm()
assert comm.size == 8

# --- is_split, aligned path: equal extents, divisible by local devices ---
full = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
local = full[pid * 8 : (pid + 1) * 8]
a = ht.array(local, is_split=0)
assert a.shape == (16, 3), a.shape
assert a.split == 0
# global reduce crosses the process boundary
total = float(a.sum().item())
assert total == float(full.sum()), (total, full.sum())

# --- is_split, uneven path: different extents per process ---
cut = 7  # process 0: 7 rows, process 1: 9 rows
local_u = full[:cut] if pid == 0 else full[cut:]
b = ht.array(local_u, is_split=0)
assert b.shape == (16, 3), b.shape
assert float(b.sum().item()) == float(full.sum())
col = b.mean(axis=0)
np.testing.assert_allclose(np.asarray(col._logical()), full.mean(axis=0), rtol=1e-6)

# --- non-split-dim mismatch must raise (reference consistency check) ---
try:
    ht.array(np.zeros((4, 2 + pid), np.float32), is_split=0)
    raise AssertionError("expected ValueError for mismatched non-split dims")
except ValueError:
    pass

# --- replicated-input constructor: same global np array on every process ---
g = ht.array(full, split=0)
assert float(g.sum().item()) == float(full.sum())
gn = ht.array(full[:5])  # replicated
assert float((g[:5] * gn).sum().item()) == float((full[:5] ** 2).sum())

# --- chunked load: every process reads only its slice ---
path = os.path.join(tmp, "mh_2proc.h5")
x = ht.load(path, dataset="data", split=0)
ref = np.arange(37 * 5, dtype=np.float32).reshape(37, 5)
assert x.shape == (37, 5)
assert float(x.sum().item()) == float(ref.sum())

# --- rank-serialized save of a distributed result ---
y = x * 2.0
ht.save(y, os.path.join(tmp, "mh_out.h5"), "doubled")

# --- RNG: both processes see the same global stream ---
ht.random.seed(123)
d = ht.random.rand(13, 4, split=0)
s = float(d.sum().item())
print(f"WORKER{pid} OK {s:.6f}")
"""


@pytest.mark.skipif(
    os.environ.get("HEAT_TPU_TEST_DEVICES", "8") != "8",
    reason="one fixed 2x4 topology is enough for the matrix",
)
def test_two_process_end_to_end(tmp_path):
    import h5py

    ref = np.arange(37 * 5, dtype=np.float32).reshape(37, 5)
    with h5py.File(tmp_path / "mh_2proc.h5", "w") as f:
        f.create_dataset("data", data=ref)

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]

    worker = tmp_path / "worker.py"
    worker.write_text(_WORKER)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env.pop("HEAT_TPU_TEST_DEVICES", None)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = [
        subprocess.Popen(
            [sys.executable, str(worker), str(i), "2", str(port), str(tmp_path)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=420)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"WORKER{i} OK" in out, out

    # both processes drew the same global stream
    sums = [out.strip().splitlines()[-1].split()[-1] for out in outs]
    assert sums[0] == sums[1], sums

    # the saved file carries the full doubled dataset
    with h5py.File(tmp_path / "mh_out.h5", "r") as f:
        np.testing.assert_allclose(f["doubled"][...], ref * 2.0)
