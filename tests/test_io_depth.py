"""I/O depth tests (VERDICT r3 item 6 — test mass for ``core/io.py``,
647 LoC + ``core/_netcdf3.py``; reference guard: ``test_io.py``).

CSV dialects (headers, separators, decimals, truncate semantics), HDF5
modes and error contracts, classic netCDF-3 edge battery (multi-variable
record files via scipy, CDF-2, all six classic types, corrupt-file
errors, the 2 GiB vsize ceiling), extension dispatch, and the
chunked-load split matrix for every format.
"""
from __future__ import annotations

import os
import struct
import tempfile

import numpy as np
import pytest

import heat_tpu as ht
from tests.base import TestCase


class TestCSVDepth(TestCase):
    def _write(self, d, text, name="t.csv"):
        p = os.path.join(d, name)
        with open(p, "w") as f:
            f.write(text)
        return p

    def test_separator_variants(self):
        with tempfile.TemporaryDirectory() as d:
            for sep in (",", ";", "\t"):
                p = self._write(d, sep.join(["1", "2"]) + "\n" + sep.join(["3", "4"]) + "\n")
                back = ht.load_csv(p, sep=sep)
                np.testing.assert_allclose(back.numpy(), [[1, 2], [3, 4]])

    def test_header_lines(self):
        with tempfile.TemporaryDirectory() as d:
            p = self._write(d, "# c1,c2\n# more\n1.5,2.5\n3.5,4.5\n")
            back = ht.load_csv(p, header_lines=2)
            np.testing.assert_allclose(back.numpy(), [[1.5, 2.5], [3.5, 4.5]])

    def test_dtype_and_splits(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(23, 4)).astype(np.float64)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "m.csv")
            with open(p, "w") as f:
                for row in x:
                    f.write(",".join(f"{v:.17g}" for v in row) + "\n")
            for split in (None, 0, 1):
                back = ht.load_csv(p, split=split, dtype=ht.float64)
                assert back.split == split
                np.testing.assert_allclose(back.numpy(), x, rtol=1e-12)

    def test_save_decimals_and_roundtrip(self):
        x = ht.array(np.asarray([[1.23456, 2.5], [3.0, 4.125]], np.float32), split=0)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "o.csv")
            ht.save_csv(x, p, decimals=3)
            txt = open(p).read()
            assert "1.235" in txt or "1.234" in txt
            back = ht.load_csv(p)
            np.testing.assert_allclose(back.numpy(), x.numpy(), atol=5e-4)

    def test_save_truncate_false_overwrites_in_place(self):
        """Reference semantics (io.py:926): no truncation -> the file is
        overwritten from offset 0 but never shortened."""
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "t.csv")
            ht.save_csv(ht.array(np.arange(8, dtype=np.float32).reshape(4, 2)), p)
            long_size = os.path.getsize(p)
            ht.save_csv(ht.array(np.zeros((1, 2), np.float32)), p, truncate=False)
            assert os.path.getsize(p) == long_size  # stale tail survives
            ht.save_csv(ht.array(np.zeros((1, 2), np.float32)), p, truncate=True)
            assert os.path.getsize(p) < long_size

    def test_int_format(self):
        x = ht.array(np.arange(6, dtype=np.int64).reshape(3, 2), split=0)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "i.csv")
            ht.save_csv(x, p)
            rows = [ln.split(",") for ln in open(p).read().strip().splitlines()]
            assert rows[0][0] == "0" and "." not in rows[0][0]

    def test_header_write(self):
        x = ht.array(np.ones((2, 2), np.float32))
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "h.csv")
            ht.save_csv(x, p, header_lines=["alpha", "beta"])
            lines = open(p).read().splitlines()
            assert lines[0] == "alpha" and lines[1] == "beta"
            back = ht.load_csv(p, header_lines=2)
            np.testing.assert_allclose(back.numpy(), np.ones((2, 2)))


class TestHDF5Depth(TestCase):
    def test_modes_append_and_overwrite(self):
        import h5py

        a = ht.array(np.arange(10, dtype=np.float32), split=0)
        b = ht.array(np.arange(6, dtype=np.float32).reshape(2, 3), split=0)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "m.h5")
            ht.save_hdf5(a, p, "first", mode="w")
            ht.save_hdf5(b, p, "second", mode="a")
            with h5py.File(p, "r") as f:
                assert set(f.keys()) == {"first", "second"}
            np.testing.assert_allclose(ht.load_hdf5(p, "first").numpy(), a.numpy())
            np.testing.assert_allclose(ht.load_hdf5(p, "second", split=1).numpy(), b.numpy())

    def test_missing_dataset_and_bad_args(self):
        a = ht.array(np.zeros(4, np.float32))
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "e.h5")
            ht.save_hdf5(a, p, "data")
            with pytest.raises(KeyError):
                ht.load_hdf5(p, "nope")
            with pytest.raises(TypeError):
                ht.load_hdf5(123, "data")
            with pytest.raises(TypeError):
                ht.load_hdf5(p, 3.5)
            with pytest.raises(TypeError):
                ht.save_hdf5(np.zeros(3), p, "x")

    def test_dtype_conversion_on_load(self):
        x = np.arange(12, dtype=np.int64).reshape(3, 4)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "c.h5")
            ht.save_hdf5(ht.array(x), p, "ints")
            back = ht.load_hdf5(p, "ints", dtype=ht.float64, split=0)
            assert back.dtype is ht.float64
            np.testing.assert_allclose(back.numpy(), x.astype(np.float64))

    def test_every_split_chunked(self):
        rng = np.random.default_rng(5)
        x = rng.normal(size=(13, 7, 3)).astype(np.float32)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "s.h5")
            ht.save(ht.array(x), p, "cube")
            for split in (None, 0, 1, 2):
                back = ht.load(p, dataset="cube", split=split)
                assert back.split == split
                np.testing.assert_allclose(back.numpy(), x, rtol=1e-6)


class TestNetCDF3Depth(TestCase):
    def test_all_classic_types_roundtrip(self):
        from heat_tpu.core._netcdf3 import NetCDF3File, write_netcdf3

        rng = np.random.default_rng(1)
        with tempfile.TemporaryDirectory() as d:
            for dt in (np.int8, np.int16, np.int32, np.float32, np.float64):
                x = (rng.normal(size=(7, 3)) * 40).astype(dt)
                p = os.path.join(d, f"t_{np.dtype(dt).name}.nc")
                write_netcdf3(p, "v", x)
                r = NetCDF3File(p)
                np.testing.assert_array_equal(r.read("v").astype(dt), x)

    def test_widening_unrepresentable_dtypes(self):
        """int64/bool/f16 have no classic representation — the writer
        widens like the netCDF4 library's default conversions."""
        from heat_tpu.core._netcdf3 import NetCDF3File, write_netcdf3

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "w.nc")
            x = np.asarray([1, 2, 3], np.int64)
            write_netcdf3(p, "v", x)
            r = NetCDF3File(p)
            np.testing.assert_array_equal(r.read("v").astype(np.int64), x)
            p2 = os.path.join(d, "w2.nc")
            xb = np.asarray([True, False, True])
            write_netcdf3(p2, "v", xb)
            np.testing.assert_array_equal(
                NetCDF3File(p2).read("v").astype(np.int32), [1, 0, 1]
            )

    def test_multi_record_var_file(self):
        """Two record variables interleave per record; strides must honor
        both (scipy writes, we read every variable chunked)."""
        from scipy.io import netcdf_file

        from heat_tpu.core._netcdf3 import NetCDF3File

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "multi.nc")
            f = netcdf_file(p, "w")
            f.createDimension("t", None)
            f.createDimension("x", 3)
            v1 = f.createVariable("a", np.float32, ("t", "x"))
            v2 = f.createVariable("b", np.int32, ("t",))
            a = np.arange(18, dtype=np.float32).reshape(6, 3)
            b = np.arange(6, dtype=np.int32) * 10
            v1[:] = a
            v2[:] = b
            f.close()
            r = NetCDF3File(p)
            np.testing.assert_array_equal(r.read("a").astype(np.float32), a)
            np.testing.assert_array_equal(r.read("b").astype(np.int32), b)
            np.testing.assert_array_equal(r.read("a", 2, 5).astype(np.float32), a[2:5])
            np.testing.assert_array_equal(r.read("b", 4, 6).astype(np.int32), b[4:6])
            # chunked public load of a record variable, every split
            for split in (None, 0, 1):
                back = ht.load_netcdf(p, "a", split=split)
                np.testing.assert_allclose(back.numpy(), a, rtol=1e-6)

    def test_scalar_and_0d(self):
        # (scipy's writer has its own 0-d assignValue quirk, so the
        # round trip uses our writer + our reader)
        from heat_tpu.core._netcdf3 import NetCDF3File, write_netcdf3

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "s.nc")
            write_netcdf3(p, "s", np.float64(3.25))
            r = NetCDF3File(p)
            assert r.shape("s") == ()
            assert float(r.read("s")) == 3.25

    def test_corrupt_files_error_clearly(self):
        from heat_tpu.core._netcdf3 import NetCDF3File, is_classic_netcdf

        with tempfile.TemporaryDirectory() as d:
            bad = os.path.join(d, "bad.nc")
            with open(bad, "wb") as f:
                f.write(b"CDF\x01" + struct.pack(">i", 0) + b"\x00\x00")  # truncated
            assert is_classic_netcdf(bad)
            with pytest.raises(ValueError, match="truncated"):
                NetCDF3File(bad)
            notnc = os.path.join(d, "not.nc")
            with open(notnc, "wb") as f:
                f.write(b"HELLO WORLD PADPAD")
            assert not is_classic_netcdf(notnc)
            with pytest.raises(ValueError, match="not a classic"):
                NetCDF3File(notnc)

    def test_oversized_variable_rejected(self):
        from unittest import mock

        from heat_tpu.core import _netcdf3

        data = np.zeros((4, 2), np.float64)  # 64 B >= the patched ceiling
        with mock.patch.object(_netcdf3, "_MAX_VSIZE", 32):
            with tempfile.TemporaryDirectory() as d:
                with pytest.raises(ValueError, match="2 GiB"):
                    _netcdf3.write_netcdf3(os.path.join(d, "x.nc"), "v", data)

    def test_save_mode_and_format_validation(self):
        a = ht.array(np.zeros(4, np.float32))
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "v.nc")
            with pytest.raises(ValueError, match="mode"):
                ht.save_netcdf(a, p, "v", mode="a", format="NETCDF3_CLASSIC")

    def test_extension_dispatch(self):
        x = ht.array(np.arange(8, dtype=np.float32), split=0)
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "e.nc")
            ht.save(x, p, "var", format="NETCDF3_CLASSIC")
            back = ht.load(p, variable="var", split=0)
            np.testing.assert_allclose(back.numpy(), x.numpy())

    def test_attrs_parsed_not_applied(self):
        from scipy.io import netcdf_file

        from heat_tpu.core._netcdf3 import NetCDF3File

        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "a.nc")
            f = netcdf_file(p, "w")
            f.history = b"made by tests"
            f.createDimension("x", 3)
            v = f.createVariable("v", np.float32, ("x",))
            v[:] = np.asarray([1, 2, 3], np.float32)
            v.scale_factor = 2.0
            f.close()
            r = NetCDF3File(p)
            assert "history" in r.attrs
            # raw values (no auto mask/scale — same as the h5py fallback)
            np.testing.assert_array_equal(r.read("v").astype(np.float32), [1, 2, 3])
