"""graftflow unit tests: fixture corpus, taint engine, waivers,
schedules, exit codes.

The fixture corpus under ``tests/lint_fixtures/`` is shared with
graftlint: every file carries TWO headers — line 1
``# graftlint-fixture: Gxxx=N`` (consumed by ``test_graftlint.py``) and
line 2 ``# graftflow-fixture: Fxxx=N`` (consumed here). Each
parametrized check asserts the analyzer produces EXACTLY the declared
counts — every unlisted finding id must report zero, so a fixture that
trips a neighboring rule fails loudly instead of silently inflating
coverage. The ``f001_neg`` fixture is the measured false-positive
reduction over the syntactic G003 (its dual header pins G003=2, F001=0).
"""
import os
import re
import subprocess
import sys

import pytest

from heat_tpu.analysis import graftflow as gf

FIXTURE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lint_fixtures")
FIXTURES = sorted(f for f in os.listdir(FIXTURE_DIR) if f.endswith(".py"))

_HEADER_RE = re.compile(r"#\s*graftflow-fixture:\s*(.+)")


def _expected_counts(path):
    with open(path, encoding="utf-8") as fh:
        head = fh.readline() + fh.readline()  # dual headers: lines 1-2
    m = _HEADER_RE.search(head)
    assert m, f"{path}: missing '# graftflow-fixture: Fxxx=N' header"
    expected = {rid: 0 for rid in gf.RULES}
    for token in m.group(1).split():
        rid, _, n = token.partition("=")
        assert rid in gf.RULES and n.isdigit(), f"bad fixture token {token!r}"
        expected[rid] = int(n)
    return expected


def test_fixture_corpus_is_complete():
    """Every finding id has at least one positive and one negative
    fixture, and EVERY corpus file (g-rules included) declares its
    expected graftflow counts."""
    for rid in gf.RULES:
        stem = rid.lower()
        assert f"{stem}_pos.py" in FIXTURES, f"missing positive fixture for {rid}"
        assert f"{stem}_neg.py" in FIXTURES, f"missing negative fixture for {rid}"
        pos = _expected_counts(os.path.join(FIXTURE_DIR, f"{stem}_pos.py"))
        neg = _expected_counts(os.path.join(FIXTURE_DIR, f"{stem}_neg.py"))
        assert pos[rid] > 0, f"{rid} positive fixture expects no findings?"
        assert neg[rid] == 0, f"{rid} negative fixture expects findings?"
    for name in FIXTURES:
        _expected_counts(os.path.join(FIXTURE_DIR, name))  # header present


@pytest.mark.parametrize("name", FIXTURES)
def test_fixture(name):
    path = os.path.join(FIXTURE_DIR, name)
    expected = _expected_counts(path)
    findings = gf.analyze_file(path)
    got = {rid: 0 for rid in gf.RULES}
    for f in findings:
        got[f.rule] += 1
    assert got == expected, "\n".join(
        [f"{name}: finding counts diverge (got vs expected above)"]
        + [f"  {f.path}:{f.line}: {f.rule} {f.message}" for f in findings]
    )


def test_flow_upgrade_over_g003_is_measured():
    """The acceptance evidence: the near-miss file G003 flags twice is
    flow-clean, and the assignment-hidden positives G003 misses are all
    caught. Read the counts from the dual headers so the claim cannot
    drift from what the corpus actually pins."""
    from heat_tpu.analysis import graftlint as gl

    neg = os.path.join(FIXTURE_DIR, "f001_neg.py")
    pos = os.path.join(FIXTURE_DIR, "f001_pos.py")
    assert sum(1 for f in gl.lint_file(neg) if f.rule == "G003") == 2
    assert not [f for f in gf.analyze_file(neg) if f.rule == "F001"]
    assert not [f for f in gl.lint_file(pos) if f.rule == "G003"]
    assert len([f for f in gf.analyze_file(pos) if f.rule == "F001"]) == 3


# ----------------------------------------------------------------- waivers
_DIV_SNIPPET = (
    "import jax\n"
    "def f(xs):\n"
    "    if jax.process_index() == 0:{}\n"
    "        return process_allgather(xs)\n"
    "    return xs\n"
)


def test_waiver_same_line():
    dirty = gf.analyze_source(_DIV_SNIPPET.format(""))
    assert [f.rule for f in dirty] == ["F001"]
    assert not gf.analyze_source(_DIV_SNIPPET.format("  # graftflow: F001"))
    # tag spelling works too
    assert not gf.analyze_source(
        _DIV_SNIPPET.format("  # graftflow: divergent-collective")
    )
    # 'all' waives any finding
    assert not gf.analyze_source(_DIV_SNIPPET.format("  # graftflow: all"))


def test_waiver_comment_block_above():
    src = (
        "import jax\n"
        "def f(xs):\n"
        "    # leader-only aggregation is this helper's documented\n"
        "    # graftflow: F001 - contract; callers broadcast the result\n"
        "    if jax.process_index() == 0:\n"
        "        return process_allgather(xs)\n"
        "    return xs\n"
    )
    assert not gf.analyze_source(src)


def test_waiver_wrong_id_does_not_apply():
    assert gf.analyze_source(_DIV_SNIPPET.format("  # graftflow: F002"))


def test_skip_file_pragma():
    src = "# graftflow: skip-file\n" + _DIV_SNIPPET.format("")
    assert not gf.analyze_source(src)


def test_graftlint_spelling_shares_the_grammar():
    """The waiver grammar is shared: '# graftlint: F001' waives too (one
    comment can carry waivers for both tools on a dual-flagged line)."""
    assert not gf.analyze_source(_DIV_SNIPPET.format("  # graftlint: F001"))


def test_fixture_header_is_not_a_waiver():
    """'# graftflow-fixture:' must NOT parse as a waiver — the hyphen
    breaks the token — or every corpus file would self-waive."""
    src = "# graftflow-fixture: all\n" + _DIV_SNIPPET.format("")
    assert gf.analyze_source(src)


# ------------------------------------------------------------ taint engine
def test_taint_survives_reassignment_chains():
    src = (
        "import jax\n"
        "def f(xs):\n"
        "    a = jax.process_index()\n"
        "    b = a + 1\n"
        "    c = (b, 2)\n"
        "    if c[0]:\n"
        "        psum(xs)\n"
    )
    assert [f.rule for f in gf.analyze_source(src)] == ["F001"]


def test_launder_through_allgather_clears_taint():
    src = (
        "import jax\n"
        "def f(xs):\n"
        "    n = jax.process_index()\n"
        "    total = psum(n)\n"
        "    if total:\n"
        "        psum(xs)\n"
    )
    assert not gf.analyze_source(src)


def test_replicated_attrs_clean_even_on_tainted_base():
    src = (
        "def f(x, xs):\n"
        "    shard = x.larray\n"
        "    if shard.shape[0] > 2:\n"
        "        psum(xs)\n"
    )
    assert not gf.analyze_source(src)


def test_unseeded_rng_taints_seeded_does_not():
    tainted = (
        "import random\n"
        "def f(xs):\n"
        "    if random.random() > 0.5:\n"
        "        psum(xs)\n"
    )
    assert [f.rule for f in gf.analyze_source(tainted)] == ["F001"]
    seeded = (
        "import random\n"
        "def f(xs):\n"
        "    rng = random.Random(0)\n"
        "    if rng.random() > 0.5:\n"
        "        psum(xs)\n"
    )
    assert not gf.analyze_source(seeded)


def test_symmetric_arms_are_clean_but_asymmetric_orders_are_not():
    sym = (
        "def f(comm, x):\n"
        "    if comm.rank == 0:\n"
        "        a = psum(x)\n"
        "        b = process_allgather(a)\n"
        "    else:\n"
        "        a = psum(x)\n"
        "        b = process_allgather(a)\n"
        "    return b\n"
    )
    assert not gf.analyze_source(sym)
    # same multiset of collectives, DIFFERENT order: still a deadlock
    swapped = (
        "def f(comm, x):\n"
        "    if comm.rank == 0:\n"
        "        a = psum(x)\n"
        "        b = process_allgather(a)\n"
        "    else:\n"
        "        b = process_allgather(x)\n"
        "        a = psum(b)\n"
        "    return a\n"
    )
    assert [f.rule for f in gf.analyze_source(swapped)] == ["F001"]


# -------------------------------------------------------------- schedules
def test_collective_schedules_extraction():
    src = (
        "def step(x):\n"
        "    a = psum(x)\n"
        "    b = process_allgather(a)\n"
        "    return b\n"
        "def quiet(y):\n"
        "    return y + 1\n"
    )
    sched = gf.collective_schedules(src)
    assert [name for name, _ in sched["step"]] == ["psum", "process_allgather"]
    assert sched["quiet"] == []


def test_collective_wrappers_count_as_schedule_events():
    src = (
        "def f(x, path):\n"
        "    save_checkpoint(path, x)\n"
        "    check_divergence(x)\n"
    )
    sched = gf.collective_schedules(src)
    assert [name for name, _ in sched["f"]] == ["save_checkpoint", "check_divergence"]


# ------------------------------------------------------------- exit codes
def test_exit_code_bitmask():
    mk = lambda rule: gf.Finding(rule, "x.py", 1, 0, "m")
    assert gf.exit_code_for([]) == 0
    assert gf.exit_code_for([mk("F001")]) == 1
    assert gf.exit_code_for([mk("F002"), mk("F002")]) == 2
    assert gf.exit_code_for([mk("F001"), mk("F004")]) == 9
    # the PR 19 pack (F005-F009) shares bit 16; DRIFT has its own bit 32
    assert gf.exit_code_for([mk("F005")]) == 16
    assert gf.exit_code_for([mk("F006"), mk("F009")]) == 16
    assert gf.exit_code_for([mk(r) for r in gf.RULES]) == 31
    assert gf.exit_code_for([mk("DRIFT")]) == 32
    assert gf.exit_code_for([mk("SYNTAX")]) == 128


def test_syntax_error_reported_not_raised():
    findings = gf.analyze_source("def f(:\n")
    assert [f.rule for f in findings] == ["SYNTAX"]
    assert gf.exit_code_for(findings) == 128


def test_select_subset():
    path = os.path.join(FIXTURE_DIR, "f001_pos.py")
    assert not gf.analyze_file(path, select={"F002"})
    assert gf.analyze_file(path, select={"F001"})


# ------------------------------------------------- interprocedural summaries
def test_two_deep_chain_needs_no_hand_entry():
    """The PR 19 acceptance pin: a caller -> helper -> collective chain
    two hops deep is flagged by F001 purely from COMPUTED summaries —
    neither helper appears in any hand table."""
    from heat_tpu.analysis import summaries as S

    for helper in ("_mid", "_leaf"):
        assert helper not in S.INTERNAL_LAUNDER
        assert helper not in S.EXTERNAL_LAUNDER
        assert helper not in S.COLLECTIVE_WRAPPERS
    findings = gf.analyze_file(os.path.join(FIXTURE_DIR, "summary_chain_pos.py"))
    assert [f.rule for f in findings] == ["F001"]
    # and the computed-schedule SYMMETRY works at the same depth: two
    # different helpers with identical [psum] schedules stay clean
    assert not gf.analyze_file(os.path.join(FIXTURE_DIR, "summary_chain_neg.py"))


def _whole_tree_table():
    import ast as _ast

    from heat_tpu.analysis import summaries as S

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    trees = {}
    for path in gf.iter_python_files([os.path.join(root, "heat_tpu")]):
        with open(path, encoding="utf-8") as fh:
            trees[path] = _ast.parse(fh.read(), filename=path)
    return S, S.compute_summaries(trees)


def test_hand_table_is_live():
    """Satellite 1, the drift audit: every hand COLLECTIVE_WRAPPERS entry
    names a real in-tree definition whose COMPUTED summary still carries
    collectives, every INTERNAL_LAUNDER contract names an in-tree
    definition, and the whole tree is DRIFT-clean at head."""
    S, table = _whole_tree_table()
    for name in sorted(S.COLLECTIVE_WRAPPERS):
        cands = table.candidates.get(name)
        assert cands, f"hand wrapper {name!r} no longer defined in heat_tpu/"
        assert any(c.schedule for c in cands), (
            f"hand wrapper {name!r} computed collective-free: stale entry"
        )
    for name in sorted(S.INTERNAL_LAUNDER):
        assert table.candidates.get(name), (
            f"internal launder contract {name!r} no longer defined in heat_tpu/"
        )
    # DRIFT-clean at head: every raw contradiction the diagnostic raises
    # must be waived IN PLACE by a reviewed ``# graftflow: DRIFT`` comment
    # documenting why the contract outranks the derivation (monitor.py's
    # tick/apply_gathered clock-feeding reports are the reviewed cases)
    leftover = []
    for f in gf._drift_findings(table):
        with open(f.path, encoding="utf-8") as fh:
            src = fh.read()
        waivers, _pragmas = gf._parse_waivers(src)
        leftover += gf._apply_waivers([f], src, waivers, None)
    assert not leftover, "\n".join(
        f"{f.path}:{f.line}: {f.message}" for f in leftover
    )


# ------------------------------------------------------------------- CLI
def test_cli_on_fixture_corpus():
    """The CLI over the whole corpus reports exactly the summed header
    counts and encodes every finding id in its exit bitmask."""
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "graftflow.py"), FIXTURE_DIR,
         "--format", "json"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    import json

    report = json.loads(proc.stdout.strip().splitlines()[-1])
    want = {rid: 0 for rid in gf.RULES}
    want["DRIFT"] = 0  # hand-table drift: whole-corpus diagnostic, none here
    for name in FIXTURES:
        for rid, n in _expected_counts(os.path.join(FIXTURE_DIR, name)).items():
            want[rid] += n
    assert report["counts"] == want
    assert proc.returncode == 31  # every finding bit set by its positive fixture
    assert report["exit_code"] == 31
