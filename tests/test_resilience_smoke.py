"""Tier-1 wrapper for tools/chaos_smoke.py: the full fault-mix sweep."""
import sys
import os
import unittest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


class TestChaosSmoke(unittest.TestCase):
    def test_all_scenarios_pass(self):
        import chaos_smoke

        self.assertEqual(chaos_smoke.main(), 0)


if __name__ == "__main__":
    unittest.main()
