"""The bench stdout contract (PR 3 satellite): ``python bench.py`` ends
with ONE parseable, budget-sized JSON line.

r5's output was a single ~8 KB JSON dump; the harness's log-tail capture
truncated it and the round recorded ``"parsed": null``. The fix splits
the output — compact summary on stdout, full dict in BENCH_DETAIL.json —
and these tests round-trip the summary builder through
``tools/bench_check.py`` in tier-1, so the contract regresses in the
suite rather than on the next hardware run.
"""
from __future__ import annotations

import json

import pytest

import bench
from tools import bench_check


def _synthetic_out():
    """A full bench result dict shaped like a real run's."""
    out = {
        "metric": "kmeans_iters_per_sec",
        "value": 1234.5,
        "smoke_ok": True,
        "bench_reps": 3,
        "bench_protocol": bench.PROTOCOL,
        "suite_seconds": 321.4,
        "ragged_elementwise_speedup": 2.7,
        "ragged_new_moves_per_trip": 0,
        "ragged_seed_moves_per_trip": 2,
        "fused_pipeline_speedup": 2.1,
        "fused_warm_compiles": 0,
        "fused_warm_dispatches": 1,
        "stream_speedup": 1.42,
        "stream_gbps": 0.51,
        "stream_sync_gbps": 0.36,
        "stream_prefetch_hits": 5,
        "stream_warm_compiles": 0,
        "stream_divergences": 0,
        "stream_unit": "u" * 60,
        "sketch_gbps": 0.0004,
        "sketch_exact_gbps": 0.018,
        "sketch_warm_compiles": 0,
        "sketch_divergences": 0,
        "sketch_kll_rank_err": 0.0005,
        "sketch_kll_eps": 0.0117,
        "sketch_hll_rel_err": 0.0007,
        "sketch_hll_bound": 0.065,
        "sketch_topk_recall": 1.0,
        "sketch_unit": "u" * 60,
        "lockstep_events": 42,
        "lockstep_divergences": 0,
        "kmeans_fused_ratio": 8.87,
        "moments_onepass_warm_compiles": 0,
        "api_over_kernel": {},
        "vs_best": {},
        "vs_best_median": {},
        "vs_trailing_median": {},
        "best_of_reps": {},
        "roofline": {k: {"model": "x" * 200} for k in bench.HEADLINE},
    }
    for k in bench.HEADLINE[1:] + bench.KERNEL_TRACKED:
        out[k] = 99.9
        out["vs_trailing_median"][k] = 1.01
        out["api_over_kernel"][k.replace("kernel_", "")] = 0.97
        out[k.split("_")[0] + "_unit"] = "u" * 60
    return out


class TestCompactSummary:
    def test_round_trip_and_budget(self):
        out = _synthetic_out()
        line = json.dumps(bench._compact_summary(out, "/x/BENCH_DETAIL.json"))
        obj = bench_check.check("warmup noise\nmore noise\n" + line + "\n")
        assert obj["metric"] == "kmeans_iters_per_sec"
        assert obj["value"] == 1234.5
        assert obj["detail"] == "BENCH_DETAIL.json"
        assert obj["suite_seconds"] == 321.4
        assert obj["ragged_elementwise_speedup"] == 2.7
        assert obj["fused_pipeline_speedup"] == 2.1
        assert obj["fused_warm_compiles"] == 0
        assert obj["fused_warm_dispatches"] == 1
        assert obj["stream_speedup"] == 1.42
        assert obj["stream_gbps"] == 0.51
        assert obj["stream_warm_compiles"] == 0
        assert obj["stream_divergences"] == 0
        assert obj["lockstep_events"] == 42
        assert obj["lockstep_divergences"] == 0
        # every headline metric made it into the line
        for k in bench.HEADLINE[1:]:
            assert obj[k] == 99.9
        assert len(line) < bench_check.LINE_BUDGET

    def test_floor_violations_ride_along(self):
        out = _synthetic_out()
        out["floor_violations"] = {"cdist_gbps": 0.6}
        obj = bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        assert obj["floor_violations"] == {"cdist_gbps": 0.6}

    def test_ragged_error_degrades_gracefully(self):
        out = _synthetic_out()
        del out["ragged_elementwise_speedup"]
        out["ragged_error"] = "x" * 400
        line = json.dumps(bench._compact_summary(out, "d.json"))
        obj = bench_check.check(line)
        assert "ragged_error" in obj
        assert len(line) < bench_check.LINE_BUDGET

    def test_summary_is_much_smaller_than_full_dict(self):
        out = _synthetic_out()
        full = len(json.dumps(out))
        compact = len(json.dumps(bench._compact_summary(out, "d.json")))
        assert compact < full / 3


class TestBenchCheck:
    def test_rejects_oversized_line(self):
        obj = {"metric": "m", "value": 1.0, "smoke_ok": True, "bench_reps": 3,
               "detail": "d.json", "pad": "x" * bench_check.LINE_BUDGET}
        with pytest.raises(ValueError, match="budget"):
            bench_check.check(json.dumps(obj))

    def test_rejects_exactly_budget_sized_line(self):
        # the budget is exclusive: a line of exactly LINE_BUDGET bytes is
        # already truncation-prone under the harness's log-tail capture
        base = {"metric": "m", "value": 1.0, "smoke_ok": True, "bench_reps": 3,
                "detail": "d.json", "pad": ""}
        pad = bench_check.LINE_BUDGET - len(json.dumps(base))
        base["pad"] = "x" * pad
        line = json.dumps(base)
        assert len(line) == bench_check.LINE_BUDGET
        with pytest.raises(ValueError, match="budget"):
            bench_check.check(line)
        # one byte under the budget passes
        base["pad"] = "x" * (pad - 1)
        assert bench_check.check(json.dumps(base))["value"] == 1.0

    def test_rejects_lockstep_divergences(self):
        # a bench whose sanitizer caught ranks out of lockstep produced
        # numbers under a broken mesh: the whole run is invalid
        out = _synthetic_out()
        out["lockstep_divergences"] = 2
        with pytest.raises(ValueError, match="lockstep"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out["lockstep_divergences"] = "2"
        with pytest.raises(ValueError, match="must be an int"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_rejects_fused_regression(self):
        # a fused/eager ratio below 1.0 means ht.lazy() made the chain
        # SLOWER than eager dispatch — the perf feature is regressing
        out = _synthetic_out()
        out["fused_pipeline_speedup"] = 0.8
        with pytest.raises(ValueError, match="SLOWER than eager"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out["fused_pipeline_speedup"] = "2.0"
        with pytest.raises(ValueError, match="must be numeric"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_rejects_broken_warm_counters(self):
        # warm fused trips must be 1 cached dispatch, 0 compiles: the
        # worker asserts it, and the summary carries the proof
        out = _synthetic_out()
        out["fused_warm_compiles"] = 3
        with pytest.raises(ValueError, match="recompiled"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["fused_warm_dispatches"] = 2
        with pytest.raises(ValueError, match="one program execution"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_fused_error_degrades_gracefully(self):
        out = _synthetic_out()
        for k in ("fused_pipeline_speedup", "fused_warm_compiles", "fused_warm_dispatches"):
            del out[k]
        out["fused_error"] = "x" * 400
        line = json.dumps(bench._compact_summary(out, "d.json"))
        obj = bench_check.check(line)
        assert "fused_error" in obj
        assert len(line) < bench_check.LINE_BUDGET

    def test_rejects_serve_fault_ladder_activity_on_warm_path(self):
        # a fault-free warm serving run must never shed a deadline or
        # roll the registry back — nonzero means the r16 ladder fires on
        # the healthy path; absence (pre-r16 records) is tolerated
        def serve_out(**over):
            out = _synthetic_out()
            out.update(
                serve_requests_per_sec=800.0,
                serve_batched_speedup=3.5,
                serve_warm_compiles=0,
                serve_lockstep_divergences=0,
                serve_shed=0,
                serve_restores=0,
            )
            out.update(over)
            return out

        line = json.dumps(bench._compact_summary(serve_out(), "d.json"))
        assert bench_check.check(line)["serve_shed"] == 0
        with pytest.raises(ValueError, match="shed deadline requests"):
            bench_check.check(json.dumps(
                bench._compact_summary(serve_out(serve_shed=2), "d.json")
            ))
        with pytest.raises(ValueError, match="rolled the registry back"):
            bench_check.check(json.dumps(
                bench._compact_summary(serve_out(serve_restores=1), "d.json")
            ))

    def test_rejects_autoscale_activity_on_warm_path(self):
        # r17: a healthy idle mesh must never scale, and steady-state
        # health probe ticks must be trace-free; absence (pre-r17
        # records) is tolerated
        def serve_out(**over):
            out = _synthetic_out()
            out.update(
                serve_requests_per_sec=800.0,
                serve_batched_speedup=3.5,
                serve_warm_compiles=0,
                serve_lockstep_divergences=0,
                serve_shed=0,
                serve_restores=0,
                serve_scale_events=0,
                health_probe_ms=0.9,
                health_probe_warm_compiles=0,
            )
            out.update(over)
            return out

        line = json.dumps(bench._compact_summary(serve_out(), "d.json"))
        obj = bench_check.check(line)
        assert obj["serve_scale_events"] == 0
        assert obj["health_probe_warm_compiles"] == 0
        with pytest.raises(ValueError, match="scaled a healthy"):
            bench_check.check(json.dumps(
                bench._compact_summary(serve_out(serve_scale_events=2), "d.json")
            ))
        with pytest.raises(ValueError, match="no longer free"):
            bench_check.check(json.dumps(bench._compact_summary(
                serve_out(health_probe_warm_compiles=1), "d.json"
            )))
        with pytest.raises(ValueError, match="non-negative number"):
            bench_check.check(json.dumps(bench._compact_summary(
                serve_out(health_probe_ms=-1.0), "d.json"
            )))

    def test_rejects_ws2_tick_gate_violations(self):
        # r18: the replicated dispatch tick must beat the barrier-per-
        # request discipline >= 2x at world size 2, with zero lockstep
        # divergences, zero warm compiles, and at least one agreed tick;
        # absence (pre-r18 records / failed subprocess) is tolerated
        def ws2_out(**over):
            out = _synthetic_out()
            out.update(
                serve_ws2_speedup=3.1,
                serve_ws2_requests_per_sec=190.0,
                serve_ws2_p99_ms=340.0,
                serve_ws2_warm_compiles=0,
                serve_ws2_lockstep_divergences=0,
                serve_ws2_ticks=2,
            )
            out.update(over)
            return out

        line = json.dumps(bench._compact_summary(ws2_out(), "d.json"))
        obj = bench_check.check(line)
        assert obj["serve_ws2_speedup"] == 3.1
        assert obj["serve_ws2_ticks"] == 2
        assert len(line) < bench_check.LINE_BUDGET
        with pytest.raises(ValueError, match="bought nothing"):
            bench_check.check(json.dumps(
                bench._compact_summary(ws2_out(serve_ws2_speedup=1.6), "d.json")
            ))
        with pytest.raises(ValueError, match="out of lockstep across ranks"):
            bench_check.check(json.dumps(bench._compact_summary(
                ws2_out(serve_ws2_lockstep_divergences=1), "d.json"
            )))
        with pytest.raises(ValueError, match="traced or compiled at world"):
            bench_check.check(json.dumps(bench._compact_summary(
                ws2_out(serve_ws2_warm_compiles=3), "d.json"
            )))
        with pytest.raises(ValueError, match="never agreed on a dispatch tick"):
            bench_check.check(json.dumps(bench._compact_summary(
                ws2_out(serve_ws2_ticks=0), "d.json"
            )))

    def test_serve_ws2_error_degrades_gracefully(self):
        # a failed 2-process run folds an error note instead of the
        # gated numbers; the summary stays valid and under budget
        out = _synthetic_out()
        out["serve_ws2_error"] = "x" * 400
        line = json.dumps(bench._compact_summary(out, "d.json"))
        obj = bench_check.check(line)
        assert "serve_ws2_error" in obj
        assert len(line) < bench_check.LINE_BUDGET

    def test_rejects_stream_no_overlap(self):
        # prefetch-on barely different from synchronous means the double
        # buffer bought nothing — the pipeline feature is regressing
        out = _synthetic_out()
        out["stream_speedup"] = 1.05
        with pytest.raises(ValueError, match="not overlapping"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out["stream_speedup"] = "1.4"
        with pytest.raises(ValueError, match="must be numeric"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_rejects_stream_divergence_and_recompiles(self):
        out = _synthetic_out()
        out["stream_divergences"] = 1
        with pytest.raises(ValueError, match="in-memory oracle"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["stream_warm_compiles"] = 2
        with pytest.raises(ValueError, match="warm chunk loop"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["stream_gbps"] = 0.0
        with pytest.raises(ValueError, match="moved no data"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_stream_single_core_omits_comparator(self):
        # on a 1-CPU host the worker reports throughput/correctness but no
        # prefetch-vs-sync ratio (both legs share the core) — absent key,
        # no gate, the line still validates
        out = _synthetic_out()
        del out["stream_speedup"]
        del out["stream_sync_gbps"]
        obj = bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        assert "stream_speedup" not in obj
        assert obj["stream_gbps"] == 0.51

    def test_stream_error_degrades_gracefully(self):
        out = _synthetic_out()
        for k in list(out):
            if k.startswith("stream_"):
                del out[k]
        out["stream_error"] = "x" * 400
        line = json.dumps(bench._compact_summary(out, "d.json"))
        obj = bench_check.check(line)
        assert "stream_error" in obj
        assert len(line) < bench_check.LINE_BUDGET

    def test_sketch_keys_round_trip(self):
        out = _synthetic_out()
        obj = bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        assert obj["sketch_gbps"] == 0.0004
        assert obj["sketch_warm_compiles"] == 0
        assert obj["sketch_divergences"] == 0
        assert obj["sketch_kll_rank_err"] == 0.0005
        assert obj["sketch_topk_recall"] == 1.0

    def test_rejects_sketch_divergence_recompile_and_no_data(self):
        out = _synthetic_out()
        out["sketch_divergences"] = 1
        with pytest.raises(ValueError, match="promised bound"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["sketch_warm_compiles"] = 3
        with pytest.raises(ValueError, match="warm sketch fold"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["sketch_gbps"] = 0.0
        with pytest.raises(ValueError, match="moved no data"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_rejects_sketch_error_beyond_bound_and_orphan_column(self):
        # an observed error larger than the sketch's own promise fails
        # even if the worker's divergence counter missed it
        out = _synthetic_out()
        out["sketch_kll_rank_err"] = 0.05
        with pytest.raises(ValueError, match="exceeds promised bound"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["sketch_hll_rel_err"] = 0.2
        with pytest.raises(ValueError, match="exceeds promised bound"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["sketch_topk_recall"] = 0.875
        with pytest.raises(ValueError, match="heavy hitter"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        # error column without its bound is unjudgeable
        out = _synthetic_out()
        del out["sketch_hll_bound"]
        with pytest.raises(ValueError, match="must appear together"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_sketch_error_degrades_gracefully(self):
        out = _synthetic_out()
        for k in list(out):
            if k.startswith("sketch_"):
                del out[k]
        out["sketch_error"] = "x" * 400
        line = json.dumps(bench._compact_summary(out, "d.json"))
        obj = bench_check.check(line)
        assert "sketch_error" in obj
        assert len(line) < bench_check.LINE_BUDGET

    def test_rejects_fused_kmeans_slower_than_components(self):
        # the fused Lloyd iteration landing below the unfused floor probe
        # means the kernel layer made the iteration slower than its parts
        out = _synthetic_out()
        out["kmeans_fused_ratio"] = 0.93
        with pytest.raises(ValueError, match="SLOWER than its own unfused"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out["kmeans_fused_ratio"] = "1.2"
        with pytest.raises(ValueError, match="must be numeric"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_rejects_onepass_moments_outside_fused_band(self):
        # the public mean+std pair must sit within the 1.2x DMA-overlap
        # band of the unexpressible fused probe — both are one data read
        out = _synthetic_out()
        out["kernel_moments_onepass_gbps"] = 50.0  # fused is 99.9
        with pytest.raises(ValueError, match="more than once"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        out = _synthetic_out()
        out["moments_onepass_warm_compiles"] = 2
        with pytest.raises(ValueError, match="one-pass moments sweep recompiled"):
            bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))

    def test_fused_kernel_rows_degrade_gracefully(self):
        # a CPU/fallback bench emits no fused-kernel rows: absent keys are
        # not violations (pallas-unavailable degradation)
        out = _synthetic_out()
        for k in ("kmeans_fused_ratio", "kernel_moments_onepass_gbps",
                  "moments_onepass_warm_compiles"):
            del out[k]
        obj = bench_check.check(json.dumps(bench._compact_summary(out, "d.json")))
        assert "kmeans_fused_ratio" not in obj

    def test_rejects_missing_keys(self):
        with pytest.raises(ValueError, match="missing required keys"):
            bench_check.check('{"metric": "m", "value": 1.0}')

    def test_rejects_non_json_tail(self):
        with pytest.raises(ValueError, match="not JSON"):
            bench_check.check('{"metric": 1}\nTraceback (most recent call last):')

    def test_rejects_empty_output(self):
        with pytest.raises(ValueError, match="empty"):
            bench_check.check("\n\n")

    def test_cli_ok_and_fail(self, tmp_path, capsys):
        good = tmp_path / "good.txt"
        good.write_text(json.dumps(bench._compact_summary(_synthetic_out(), "d.json")))
        assert bench_check.main(["bench_check.py", str(good)]) == 0
        bad = tmp_path / "bad.txt"
        bad.write_text("not json at all")
        assert bench_check.main(["bench_check.py", str(bad)]) == 1

    def test_suite_seconds_reader(self, tmp_path, monkeypatch):
        # bench._suite_seconds reads the conftest-written sidecar
        monkeypatch.setattr(bench, "__file__", str(tmp_path / "bench.py"))
        assert bench._suite_seconds() is None
        (tmp_path / "SUITE_SECONDS.json").write_text(
            json.dumps({"suite_seconds": 123.456, "tests_collected": 900})
        )
        assert bench._suite_seconds() == 123.5
