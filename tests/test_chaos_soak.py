"""Tier-1 wrapper for tools/chaos_soak.py --quick: the bounded recovery
soak (>=1 device loss + >=1 divergence + >=1 torn write per workload,
recovered models equivalent to the fault-free fits)."""
import os
import sys
import unittest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


class TestChaosSoak(unittest.TestCase):
    def test_quick_soak_passes(self):
        import chaos_soak

        self.assertEqual(chaos_soak.main(["--quick"]), 0)

    def test_quick_serve_soak_passes(self):
        """The r16 serving soak: seeded faults on every dispatch rung
        (retry, bisect, restore, shrink, shed, reject) with the zero
        lost / zero duplicated / oracle-equal survival proof — plus the
        r18 tick-armed leg (replicated dispatch tick forced on via
        tick_ms > 0, device_flap + straggler_probe faults fired during
        agreed ticks, every batch/shed tick-decided)."""
        import chaos_soak

        self.assertEqual(chaos_soak.main(["--serve", "--quick"]), 0)

    def test_quick_autoscale_soak_passes(self):
        """The r17 autoscale soak: HealthMonitor + Autoscaler drive a
        live service through two full degrade -> proactive shrink ->
        heal -> elastic re-grow cycles (a flapping device with a damped
        mid-heal flap, then an EWMA-detected straggler) under request
        traffic, with the zero lost / zero duplicated / oracle-equal
        proof and the final mesh back at the full device count."""
        import chaos_soak

        self.assertEqual(chaos_soak.main(["--autoscale", "--quick"]), 0)


if __name__ == "__main__":
    unittest.main()
