"""Serving-layer counters riding the :mod:`heat_tpu.core._hooks`
observer slot, beside LAYOUT/MOVE/COMPILE/FUSE/STREAM/KERNEL_STATS.

The service emits passive ``serve.*`` events (see
:func:`heat_tpu.core._hooks.observe`):

- ``serve.request`` (``depth``) — a request was enqueued; ``depth`` is
  the queue depth right after the append (gauge + high-water mark);
- ``serve.batch`` (``requests``, ``rows``, ``bucket``, ``hit``) — one
  shape-bucketed batch was dispatched: ``rows`` real rows padded up to
  ``bucket``; ``hit`` says this (endpoint, bucket) was dispatched
  before, i.e. every program it runs is warm;
- ``serve.latency`` (``ms``) — one request completed, measured from
  enqueue to result-ready (the client-visible number);
- ``serve.error`` — a dispatch raised; the fault ladder takes over and
  the service lives on;
- ``serve.retry`` (``attempt``) — a transiently-failed batch is being
  re-run under the RetryPolicy backoff schedule;
- ``serve.bisect`` (``requests``) — retry exhausted (or a poison-class
  failure): the batch is being bisected to isolate the poison request(s);
- ``serve.restore`` (``cause``) — resident models were rolled back to
  the last registry snapshot;
- ``serve.shrink`` (``old``, ``new``) — the mesh was shrunk to its
  healthy devices and the registry elastically restored onto it;
- ``serve.grow`` (``old``, ``new``) — the mesh was grown back over
  healed devices and the registry elastically restored onto it;
- ``serve.scale`` (``direction``, ``old``, ``new``) — one
  autoscaler-initiated scale event (proactive shrink or grow), as
  opposed to the reactive fault-ladder shrink;
- ``serve.depth`` (``depth``) — the dispatcher finished a unit of work;
  ``depth`` is the request queue depth it left behind (keeps the
  ``queue_depth`` gauge fresh across drains — enqueue-only updates left
  it stale at the pre-drain value);
- ``serve.redispatch`` (``requests``) — in-flight requests were
  re-dispatched after a restore/shrink recovery;
- ``serve.shed`` (``endpoint``, ``waited_ms``) — a request's deadline
  expired in the queue; it was answered with ``ServeDeadlineError``
  before padding a batch;
- ``serve.rejected`` (``depth``) — admission control fast-rejected a
  submit past the high-water queue depth (``ServeOverloadError``);
- ``serve.tick`` (``batches``, ``shed``, ``call``, ``monitor``) — one
  AGREED replicated dispatch tick was applied (every rank counts the
  same ticks — the rank-local due checks and declined rendezvous are
  not events): ``batches``/``shed`` say what the tick's plan dispatched
  and expired, ``call``/``monitor`` whether it released a control call
  or carried a piggybacked health-monitor tick.

One module-level observer folds them into :data:`SERVE_STATS`; the
percentile gauges are recomputed from a bounded latency ring on
:func:`refresh_latency_stats` (called by ``ServeService.stats()``), not
per event. All writers take the module lock — events arrive from client
threads and the dispatcher thread concurrently.
"""
from __future__ import annotations

import threading
from collections import deque

from ..core import _hooks

__all__ = ["SERVE_STATS", "reset_serve_stats", "refresh_latency_stats"]

SERVE_STATS = {
    "requests": 0,
    "batches": 0,
    "batched_rows": 0,      # real rows dispatched inside batches
    "padded_rows": 0,       # bucket padding overhead (dead rows)
    "bucket_hits": 0,       # batches whose (endpoint, bucket) was warm
    "bucket_misses": 0,
    "errors": 0,
    "retries": 0,           # fault ladder: transient batch re-runs
    "bisections": 0,        # fault ladder: poison-isolation episodes
    "restores": 0,          # fault ladder: registry snapshot rollbacks
    "shrinks": 0,           # fault ladder / autoscaler: elastic mesh shrinks
    "grows": 0,             # autoscaler: elastic re-grows onto healed devices
    "scale_events": 0,      # autoscaler-initiated scale actions (both ways)
    "redispatched": 0,      # requests re-dispatched after a recovery
    "shed": 0,              # requests shed on an expired deadline
    "rejected": 0,          # submits fast-rejected by admission control
    "ticks": 0,             # agreed replicated dispatch ticks applied
    "tick_batches": 0,      # batches dispatched by tick plans
    "tick_sheds": 0,        # deadline sheds decided by tick plans
    "queue_depth": 0,       # gauge: depth at the last enqueue OR dispatch
    "max_queue_depth": 0,
    "p50_latency_ms": 0.0,  # gauges: refreshed from the latency ring
    "p99_latency_ms": 0.0,
}

_LOCK = threading.Lock()
_LATENCIES: "deque" = deque(maxlen=4096)


def reset_serve_stats() -> None:
    """Zero :data:`SERVE_STATS` and the latency ring (test/bench
    isolation)."""
    with _LOCK:
        for k in SERVE_STATS:
            SERVE_STATS[k] = 0.0 if k.endswith("_ms") else 0
        _LATENCIES.clear()


def refresh_latency_stats() -> None:
    """Recompute the p50/p99 gauges from the latency ring."""
    with _LOCK:
        if not _LATENCIES:
            return
        xs = sorted(_LATENCIES)
        n = len(xs)
        SERVE_STATS["p50_latency_ms"] = xs[min(n - 1, int(0.50 * n))]
        SERVE_STATS["p99_latency_ms"] = xs[min(n - 1, int(0.99 * n))]


def _observer(event: str, ctx: dict) -> None:
    if not event.startswith("serve."):
        return
    with _LOCK:
        if event == "serve.request":
            SERVE_STATS["requests"] += 1
            depth = int(ctx.get("depth", 0))
            SERVE_STATS["queue_depth"] = depth
            if depth > SERVE_STATS["max_queue_depth"]:
                SERVE_STATS["max_queue_depth"] = depth
        elif event == "serve.batch":
            SERVE_STATS["batches"] += 1
            rows = int(ctx.get("rows", 0))
            bucket = int(ctx.get("bucket", rows))
            SERVE_STATS["batched_rows"] += rows
            SERVE_STATS["padded_rows"] += max(0, bucket - rows)
            if ctx.get("hit"):
                SERVE_STATS["bucket_hits"] += 1
            else:
                SERVE_STATS["bucket_misses"] += 1
        elif event == "serve.latency":
            _LATENCIES.append(float(ctx.get("ms", 0.0)))
        elif event == "serve.error":
            SERVE_STATS["errors"] += 1
        elif event == "serve.retry":
            SERVE_STATS["retries"] += 1
        elif event == "serve.bisect":
            SERVE_STATS["bisections"] += 1
        elif event == "serve.restore":
            SERVE_STATS["restores"] += 1
        elif event == "serve.shrink":
            SERVE_STATS["shrinks"] += 1
        elif event == "serve.grow":
            SERVE_STATS["grows"] += 1
        elif event == "serve.scale":
            SERVE_STATS["scale_events"] += 1
        elif event == "serve.depth":
            # dispatch/drain-side gauge refresh: without it the gauge
            # stays at the depth of the LAST ENQUEUE after a drain
            SERVE_STATS["queue_depth"] = int(ctx.get("depth", 0))
        elif event == "serve.redispatch":
            SERVE_STATS["redispatched"] += int(ctx.get("requests", 1))
        elif event == "serve.shed":
            SERVE_STATS["shed"] += 1
        elif event == "serve.rejected":
            SERVE_STATS["rejected"] += 1
        elif event == "serve.tick":
            SERVE_STATS["ticks"] += 1
            SERVE_STATS["tick_batches"] += int(ctx.get("batches", 0))
            SERVE_STATS["tick_sheds"] += int(ctx.get("shed", 0))


_hooks.add_observer(_observer)
