"""The resident SPMD service: event loop + single dispatch thread.

One :class:`ServeService` owns the mesh for its lifetime. Client threads
``submit()`` requests (numpy rows + an endpoint name) and block on
:meth:`Request.result`; ONE dispatcher thread drains the queue, forms
shape-bucketed batches (:mod:`heat_tpu.serve.batching`), runs each batch
through its endpoint on-device, and scatters result rows back to the
waiting requests. All device work happens on the dispatcher thread —
the PR 9 lesson: concurrent dispatch from multiple threads interleaves
cross-process collectives differently per process and deadlocks the
rendezvous — and every batch is pinned under ``collective_lockstep``
before the next one launches, so multi-controller execution keeps one
total order of collective-bearing programs.

Dispatch triggers, and the multi-controller contract
----------------------------------------------------
A pending batch dispatches when (a) it reaches ``policy.max_batch``
rows, (b) its oldest request has waited ``policy.max_latency_ms``, or
(c) a barrier forces it: ``flush()``, ``drain()``, ``close()``, or any
``submit_call`` (control calls act as barriers so model mutations are
ordered against traffic). ``flush()`` enqueues a no-op control call, so
the barrier has a deterministic POSITION in the queue: exactly the
requests submitted before it are forced, never a racing later submit
the dispatcher happened to observe.

As LOCAL checks, (a) and (b) are rank-divergent under multiple
controllers: wall clocks drift, and the count trigger fires at whatever
queue prefix each rank's dispatcher happens to observe — with two
pending endpoint groups, rank A can see only the younger group full
(dispatching it first) while rank B sees both (dispatching the older
first), and the collective-bearing batch programs then interleave in
different orders across ranks, which is exactly the deadlock
``collective_lockstep`` exists to prevent (and why PR 13 disarmed them
at ws>1). The REPLICATED DISPATCH TICK (:mod:`heat_tpu.serve.tick`)
re-arms both without that hazard: the dispatcher loop takes exactly one
``replicated_decision`` per iteration on whether any rank is due, and
on an agreed tick every rank exchanges one tiny fixed-width frame of
queue metadata (accepted high-water, per-bucket pending prefix lengths
and rows, µs-quantized oldest ages, expired deadlines) and runs the
same PURE plan function over the gathered frames — so which buckets
dispatch, at what prefix length, which requests shed, and when a
control call runs are decided identically on every rank. Deadline
shedding thereby rides the tick (promoted from its former ws1-only
arming), and the same frame piggybacks the health monitor's probe
exports and the autoscaler's grow votes: one heartbeat carries all
three decisions instead of three allgathers. With ``tick_ms=0`` the
service falls back to barrier-driven SPMD (the PR 13 contract): batches
between barriers form from identical queue segments by identical rules.
See docs/SERVING.md.

The request-survival contract
-----------------------------
Every ACCEPTED request is eventually answered — result rows or a typed
error — never lost and never answered twice, under device loss, poison
payloads, snapshot corruption, and overload. A failed batch dispatch
rides a fault ladder borrowed from the Supervisor's classification
policy (:mod:`heat_tpu.resilience.supervisor`):

- transient ``OSError``/``TimeoutError`` — re-run the batch under the
  :class:`~heat_tpu.resilience.RetryPolicy` backoff schedule; exhausted
  retries escalate to bisection;
- payload-class failures (``ValueError``/``TypeError``/... , or
  exhausted retries) — BISECT the batch: halves re-run until the poison
  request(s) are isolated and answered with
  :class:`~heat_tpu.resilience.PoisonRequestError` while their former
  neighbors get their rows;
- ``CollectiveTimeout``/``DivergenceError`` (resident state suspect) —
  restore the registry from its last snapshot and replay the in-flight
  batch once;
- ``RuntimeError`` (a died device surfaces as an XLA runtime error) —
  ``probe`` + cross-rank consensus on the unhealthy set
  (:func:`~heat_tpu.core.communication.replicated_ids`, so every rank
  builds the SAME survivor mesh), ``shrink_to_healthy``, elastic-restore
  the registry onto the survivors, and re-dispatch the in-flight batch;
- ``NoHealthyDevicesError`` — nothing to run on: the batch is answered
  with the error and the dispatcher lives to reject further work.

Admission control bounds the other end: ``max_queue_depth`` fast-rejects
submits past the high-water mark
(:class:`~heat_tpu.resilience.ServeOverloadError`, raised in the client
thread before enqueue), and per-request deadlines shed expired requests
with :class:`~heat_tpu.resilience.ServeDeadlineError` before they pad a
batch (tick-decided at ws>1: a deadline any rank's clock saw expire is
shed on every rank). Overload rejection is a client-thread decision and
must be trace-invariant: with one controller the live queue depth is
the yardstick; in barrier-driven multi-controller mode it counts
requests accepted since the last barrier — a rank-invariant number —
and with the tick armed at ws>1 there is no barrier to anchor a count
to and the instantaneous depth races rank-divergently, so depth
admission stands down and tick-decided deadline shedding is the
overload mechanism.
Recovery activity is counted in ``SERVE_STATS``
(``retries/bisections/restores/shrinks/shed/rejected/redispatched``);
the recovery-free warm path is byte-identical to PR 13's.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core import _hooks
from ..core import factories
from ..resilience.errors import (
    NoHealthyDevicesError,
    PoisonRequestError,
    ResilienceError,
    ServeDeadlineError,
    ServeOverloadError,
)
from ..resilience.retry import RetryPolicy
from ..core.communication import (
    collective_lockstep,
    replicated_decision,
    replicated_frame,
    replicated_ids,
    sanitize_comm,
)
from ..core.dndarray import DNDarray
from . import tick as _tick
from .batching import BucketPolicy, PendingBatch, form_plan_batches
from .session import ModelRegistry
from ._stats import SERVE_STATS, refresh_latency_stats

__all__ = ["Request", "ServeService", "DEFAULT_DISPATCH_POLICY"]

# backoff for transient dispatch errors: fast, deterministic (seeded,
# zero jitter — every rank must sleep the same schedule), bounded
DEFAULT_DISPATCH_POLICY = RetryPolicy(
    max_attempts=3, base_delay=0.02, max_delay=0.5, multiplier=2.0,
    jitter=0.0, seed=0, max_elapsed=10.0,
)


def _classify_dispatch(exc: BaseException) -> str:
    """Map a dispatch exception to a ladder rung. The Supervisor's
    policy table with one serving-specific refinement: an exception that
    is none of the known infrastructure classes (``ValueError``,
    ``TypeError``, ...) is a PAYLOAD problem — bisect, don't die."""
    if isinstance(exc, NoHealthyDevicesError):
        return "fatal"
    if isinstance(exc, ResilienceError):
        # checked BEFORE OSError/TimeoutError: CollectiveTimeout
        # subclasses TimeoutError and must not be retried in place
        return "restore"
    if isinstance(exc, (OSError, TimeoutError)):
        return "retry"
    if isinstance(exc, RuntimeError):
        return "probe"
    return "bisect"


class Request:
    """One client request: ``payload`` rows bound for ``endpoint``.

    ``payload`` is host data shaped ``(rows, *row_shape)``; the result
    (set by the dispatcher) is the matching slice of the batch output.
    ``deadline_ms`` bounds the time the request may wait in the queue
    before it is shed with :class:`ServeDeadlineError` (None: no bound).
    ``answers`` counts ``_finish`` calls — the survival contract says it
    ends at exactly 1, and the chaos soak asserts it.
    """

    __slots__ = ("endpoint", "payload", "rows", "enqueue_t", "seq",
                 "deadline_ms", "deadline_t", "answers",
                 "_done", "_result", "_error")

    def __init__(self, endpoint: str, payload: np.ndarray,
                 deadline_ms: Optional[float] = None):
        self.endpoint = endpoint
        self.payload = payload
        self.rows = int(payload.shape[0])
        self.enqueue_t = time.monotonic()
        # admission order within the service (set under the queue lock
        # at accept time): the trace-invariant identity the replicated
        # tick plans speak in — identical for the same request on every
        # rank, unlike id() or enqueue wall time
        self.seq = -1
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self.deadline_t = (
            None if deadline_ms is None
            else self.enqueue_t + float(deadline_ms) / 1e3
        )
        self.answers = 0
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def _finish(self, result=None, error: Optional[BaseException] = None) -> None:
        self.answers += 1
        if self._done.is_set():
            # first answer wins; extra calls are only COUNTED so the
            # never-answered-twice contract stays provable
            return
        self._result = result
        self._error = error
        _hooks.observe(
            "serve.latency", ms=(time.monotonic() - self.enqueue_t) * 1e3
        )
        self._done.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the dispatcher answered; returns the result rows
        or re-raises the dispatch error."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request to {self.endpoint!r} still pending")
        if self._error is not None:
            raise self._error
        return self._result


class _Call:
    """A control item: a closure executed on the dispatcher thread (the
    only thread allowed to do device work). Acts as a flush barrier."""

    __slots__ = ("fn", "_done", "_result", "_error")

    def __init__(self, fn: Callable):
        self.fn = fn
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("control call still pending")
        if self._error is not None:
            raise self._error
        return self._result


class ServeService:
    """Persistent multi-tenant serving loop over the resident mesh.

    Parameters
    ----------
    policy : BucketPolicy
        Batching policy (bucket menu, max-batch, max-latency).
    registry : ModelRegistry
        Resident model registry; a fresh one when omitted.
    snapshot_dir : str, optional
        When set, the registry is snapshotted here every
        ``snapshot_every`` successful batches (on the dispatcher thread,
        so snapshots are ordered against traffic), and a dispatch error
        triggers a best-effort restore from the last snapshot before the
        service carries on — the supervised-service loop.
    snapshot_every : int
        Snapshot cadence in batches (0 disables periodic snapshots).
    max_queue_depth : int, optional
        Admission high-water mark: a ``submit`` that would push the
        queue past this depth is fast-rejected with
        :class:`ServeOverloadError` (None: unbounded, the PR 13
        behavior).
    retry : RetryPolicy, optional
        Backoff schedule for transiently-failed batch dispatches
        (default :data:`DEFAULT_DISPATCH_POLICY`).
    autoscaler : Autoscaler, optional
        A :class:`~heat_tpu.serve.autoscale.Autoscaler` the dispatcher
        consults BETWEEN work units — never mid-batch, so in-flight
        requests are never dropped. A ``"shrink"`` verdict (the
        autoscaler's HealthMonitor degraded a device) or ``"grow"``
        verdict (a device healed, or sustained queue pressure with
        healed capacity available) rebuilds the default mesh,
        elastically relocates the resident registry, and invalidates
        the warm-bucket program cache — exactly the fault ladder's
        shrink rung, but proactive. With the tick armed, the monitor's
        probe exports and the grow votes ride the dispatch frame (one
        heartbeat, not three allgathers).
    tick_ms : float, optional
        Replicated dispatch tick cadence (module docstring). ``None``
        (default): armed at ``jax.process_count() > 1`` with the
        ``policy.max_latency_ms`` cadence, while a single controller
        keeps the direct async triggers. ``0``: ticks disabled — ws>1
        falls back to barrier-driven dispatch (the PR 13 contract).
        ``> 0``: explicit cadence; forces tick mode even at ws==1
        (the replicated primitives pass through), which is how the
        unit tests and the chaos soak drive the tick machinery in one
        process.
    """

    def __init__(
        self,
        policy: Optional[BucketPolicy] = None,
        registry: Optional[ModelRegistry] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_every: int = 0,
        max_queue_depth: Optional[int] = None,
        retry: Optional[RetryPolicy] = None,
        autoscaler=None,
        tick_ms: Optional[float] = None,
    ):
        if max_queue_depth is not None and max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1, got {max_queue_depth}"
            )
        if tick_ms is not None and tick_ms < 0:
            raise ValueError(f"tick_ms must be >= 0, got {tick_ms}")
        self.policy = policy or BucketPolicy()
        self.registry = registry or ModelRegistry()
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = int(snapshot_every)
        self.max_queue_depth = max_queue_depth
        self.retry = retry or DEFAULT_DISPATCH_POLICY
        self.autoscaler = autoscaler
        self._endpoints: Dict[str, Callable] = {}
        self._cond = threading.Condition()
        self._queue: List = []
        self._closed = False
        self._seen_buckets = set()
        self._have_snapshot = False
        self._batches_since_snapshot = 0
        # requests accepted since the last barrier: the rank-invariant
        # depth admission control uses under multiple controllers (the
        # instantaneous queue length races the dispatcher's pops at
        # rank-divergent moments)
        self._since_barrier = 0
        self._single = jax.process_count() == 1
        if tick_ms is None:
            self._tick_armed = not self._single
            self._tick_s = self.policy.max_latency_ms / 1e3
        else:
            self._tick_armed = tick_ms > 0
            self._tick_s = float(tick_ms) / 1e3
        # the DIRECT latency timer and max-batch count trigger consult
        # rank-local state and fire at rank-divergent moments (see the
        # module docstring); arm them only when there is no other rank
        # to diverge from AND the replicated tick is not driving
        self._async_triggers = self._single and not self._tick_armed
        # trace-invariant admission order; plans identify requests by it
        self._next_seq = 0
        self._last_tick = -1.0
        # the health monitor's local probe export, parked between the
        # rank-local probe and the agreed tick that applies the gathered
        # union: (fail_ids, ewma_export, probes, autoscale votes)
        self._mon_stash = None
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-dispatch"
        )
        self._thread.start()

    # ------------------------------------------------------------ endpoints
    def register_endpoint(self, name: str, fn: Callable) -> None:
        """Install a row-wise endpoint: ``fn(x: DNDarray) -> DNDarray``
        where output row ``i`` depends only on input row ``i`` (plus
        resident state) — the contract that makes bucket padding and
        result scattering safe."""
        if self._closed:
            raise RuntimeError("service is closed")
        self._endpoints[name] = fn

    def register_model(self, name: str, model, methods: Sequence[str] = ("predict",)):
        """Register ``model`` in the resident registry and expose one
        endpoint per method as ``"<name>.<method>"``. Endpoints resolve
        the model through the registry AT DISPATCH TIME, so a later
        ``registry.register(name, refreshed)`` swaps the model without
        touching endpoints or compiled programs."""
        self.registry.register(name, model)
        for method in methods:
            if not callable(getattr(model, method, None)):
                raise TypeError(f"{name!r} model has no callable {method!r}")
            self._endpoints[f"{name}.{method}"] = _model_endpoint(
                self.registry, name, method
            )

    def endpoints(self) -> List[str]:
        return sorted(self._endpoints)

    # ------------------------------------------------------------- clients
    def submit(self, endpoint: str, payload,
               deadline_ms: Optional[float] = None) -> Request:
        """Enqueue ``payload`` rows for ``endpoint``; returns a
        :class:`Request` future. ``payload`` is host data shaped
        ``(rows, *row_shape)`` (one sample: shape ``(1, ...)``).
        ``deadline_ms`` bounds queue wait: a request still undispatched
        past it is answered with :class:`ServeDeadlineError` instead of
        padding a batch (single-controller only — wall clocks are
        rank-divergent; see the module docstring). A submit past
        ``max_queue_depth`` raises :class:`ServeOverloadError` without
        enqueueing — a rejected request was never accepted."""
        if endpoint not in self._endpoints:
            raise KeyError(
                f"unknown endpoint {endpoint!r}; known: {self.endpoints()}"
            )
        payload = np.asarray(payload)
        if payload.ndim < 1 or payload.shape[0] < 1:
            raise ValueError("payload must be (rows, ...) with rows >= 1")
        request = Request(endpoint, payload, deadline_ms=deadline_ms)
        reject = None
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            if self.max_queue_depth is not None:
                # the admission verdict must be trace-invariant (every
                # rank accepts/rejects the same submits). ws==1: the
                # live queue depth. Barrier-driven ws>1: accepts since
                # the last barrier (every rank submits the same trace,
                # so the count is identical everywhere). Tick-armed
                # ws>1: neither works — no barrier to anchor a count
                # to, and the live depth races the tick's pops at
                # rank-divergent moments — so depth admission stands
                # down and tick-decided deadline shedding bounds the
                # queue instead (module docstring). Control calls
                # (flush/drain sentinels, submit_call work) never
                # consume admission budget — only requests do.
                if self._single:
                    depth_now = sum(
                        1 for x in self._queue if not isinstance(x, _Call)
                    )
                elif not self._tick_armed:
                    depth_now = self._since_barrier
                else:
                    depth_now = None
                if depth_now is not None and depth_now >= self.max_queue_depth:
                    reject = depth_now
            if reject is None:
                request.seq = self._next_seq
                self._next_seq += 1
                self._queue.append(request)
                self._since_barrier += 1
                depth = len(self._queue)
                self._cond.notify()
        if reject is not None:
            _hooks.observe("serve.rejected", depth=reject)
            raise ServeOverloadError(reject, self.max_queue_depth)
        _hooks.observe("serve.request", depth=depth)
        return request

    def predict(self, name: str, payload, timeout: Optional[float] = None):
        """Synchronous convenience: submit to ``"<name>.predict"`` and
        wait for the rows."""
        return self.submit(f"{name}.predict", payload).result(timeout)

    def submit_call(self, fn: Callable) -> _Call:
        """Run ``fn()`` on the dispatcher thread, ordered after every
        currently pending request (a barrier). This is the door for
        anything that is NOT a row-wise map: ``fit``, ``partial_fit``,
        registry snapshots, model refreshes."""
        call = _Call(fn)
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            self._queue.append(call)
            self._since_barrier = 0
            self._cond.notify()
        return call

    def feed(
        self,
        name: str,
        chunks,
        method: str = "partial_fit",
        depth: int = 2,
        timeout: Optional[float] = None,
    ) -> int:
        """Stream chunks into a resident model's incremental update
        (``partial_fit`` / ``update``), overlapping chunk production with
        device compute: the PR 10 Prefetcher runs the chunk source
        ``depth`` ahead on its producer thread while each update executes
        on the DISPATCHER thread (via :meth:`submit_call`, so updates are
        ordered against concurrent predict traffic). Tuple chunks splat
        into positional args — ``(x, y)`` feeds ``partial_fit(x, y)``.
        Returns the number of chunks applied."""
        from ..stream import Prefetcher

        registry = self.registry
        applied = 0
        pending: List[_Call] = []
        for chunk in Prefetcher(chunks, depth=depth):
            pending.append(self.submit_call(_feed_step(registry, name, method, chunk)))
            applied += 1
            # stay at most ``depth`` updates ahead of the dispatcher so
            # the chunk source is throttled by compute, not read whole
            while len(pending) > max(1, depth):
                pending.pop(0).result(timeout)
        for call in pending:
            call.result(timeout)
        return applied

    def flush(self) -> None:
        """Force-dispatch everything submitted before this call
        (non-blocking). Implemented as a no-op control call so the
        barrier sits at a deterministic queue position — requests
        submitted AFTER the flush stay pending, on every rank."""
        call = _Call(lambda: None)
        with self._cond:
            if self._closed:
                return
            self._queue.append(call)
            self._since_barrier = 0
            self._cond.notify()

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every request submitted before this call has been
        dispatched and answered. Safe to call mid-recovery: the fault
        ladder always terminates with every in-flight request answered,
        so the barrier behind it is reached regardless of which rung the
        dispatcher is currently climbing."""
        self.submit_call(lambda: None).result(timeout)

    def stats(self) -> dict:
        """Snapshot of SERVE_STATS with the latency percentiles
        refreshed."""
        refresh_latency_stats()
        snap = dict(SERVE_STATS)
        with self._cond:
            snap["queue_depth"] = len(self._queue)
        return snap

    def close(self, timeout: Optional[float] = None) -> None:
        """Flush outstanding work and stop the dispatcher thread."""
        with self._cond:
            if self._closed and not self._thread.is_alive():
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)

    def __enter__(self) -> "ServeService":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False

    # ----------------------------------------------------------- dispatcher
    def _loop(self) -> None:
        if self._tick_armed:
            self._tick_loop()
            return
        while True:
            with self._cond:
                work = self._pick_work()
                if work is None:
                    if self._closed and not self._queue:
                        return
                    self._cond.wait(self._wait_timeout())
                    continue
            kind, item = work
            if kind == "batch":
                self._dispatch_batch(item)
            elif kind == "shed":
                self._shed(item)
            else:
                self._run_call(item)
            # between work units — never mid-batch: refresh the depth
            # gauge (enqueue-only updates go stale across drains) and
            # give the autoscaler its consultation point
            with self._cond:
                depth = sum(
                    1 for x in self._queue if not isinstance(x, _Call)
                )
            _hooks.observe("serve.depth", depth=depth)
            if self.autoscaler is not None:
                self._autoscale(depth)

    # ------------------------------------------------- replicated tick mode
    def _tick_loop(self) -> None:
        """The tick-armed dispatcher (module docstring). Collective
        pairing invariant, the thing graftflow exists to check: every
        iteration makes exactly ONE ``replicated_decision`` (am I — or
        anyone — due?), and an agreed True is followed by exactly one
        ``replicated_frame`` exchange; the plan derived from it is a
        pure function of the gathered array, so the batch/shed/call
        programs it triggers run in one total order on every rank. The
        rank-local due check and the bounded waits never touch a
        collective, so clock drift only costs latency (a rank blocks in
        the rendezvous until the slowest peer's wait expires — at most
        one cadence), never divergence."""
        multi = not self._single
        while True:
            with self._cond:
                if not self._tick_due_locked():
                    self._cond.wait(self._tick_wait_locked())
                due = self._tick_due_locked()
            if not replicated_decision(due, active=multi):
                continue
            plan = self._tick_exchange()
            if self._tick_apply(plan):
                return

    def _tick_due_locked(self) -> bool:
        """Rank-local: is there a reason to ask for a tick? Caller holds
        the lock. True on close (the drain/quit path needs frames), when
        the heartbeat interval elapsed (keeps the piggybacked health
        monitor ticking through idle traffic), or when locally
        actionable work should hurry the rendezvous: a pending control
        call, a full group, an over-age group, an expired deadline."""
        if self._closed:
            return True
        now = time.monotonic()
        if self._last_tick < 0 or (now - self._last_tick) >= self._tick_s:
            return True
        rows: Dict[tuple, int] = {}
        oldest = None
        for item in self._queue:
            if isinstance(item, _Call):
                return True
            key = (item.endpoint, item.payload.shape[1:], item.payload.dtype.str)
            rows[key] = rows.get(key, 0) + item.rows
            if rows[key] >= self.policy.max_batch:
                return True
            if oldest is None:
                oldest = item.enqueue_t
            if item.deadline_t is not None and now >= item.deadline_t:
                return True
        if oldest is not None:
            return (now - oldest) * 1e3 >= self.policy.max_latency_ms
        return False

    def _tick_wait_locked(self) -> float:
        """Seconds until this rank next turns due (interval remainder,
        oldest group's latency trigger, or nearest deadline — whichever
        lands first). Always finite: every rank re-enters the due
        rendezvous at least once per cadence, which bounds how long a
        peer can block in it."""
        now = time.monotonic()
        if self._last_tick < 0:
            return 1e-4
        remaining = self._tick_s - (now - self._last_tick)
        for item in self._queue:
            if isinstance(item, _Call):
                break
            remaining = min(
                remaining,
                self.policy.max_latency_ms / 1e3 - (now - item.enqueue_t),
            )
            if item.deadline_t is not None:
                remaining = min(remaining, item.deadline_t - now)
        return max(1e-4, remaining)

    def _tick_exchange(self) -> "_tick.TickPlan":
        """One agreed tick: snapshot the local queue view under the
        lock, bolt on the health monitor's probe export and the
        autoscaler's grow votes, exchange ONE replicated frame, and
        derive the pure plan every rank will apply identically."""
        with self._cond:
            self._last_tick = time.monotonic()
            now = self._last_tick
            call_at = len(self._queue)
            for i, item in enumerate(self._queue):
                if isinstance(item, _Call):
                    call_at = i
                    break
            buckets: Dict[tuple, list] = {}
            expired = []
            for item in self._queue[:call_at]:
                key = (
                    item.endpoint, item.payload.shape[1:], item.payload.dtype.str
                )
                record = buckets.get(key)
                if record is None:
                    buckets[key] = record = [0, 0, int(item.seq)]
                record[0] += 1
                record[1] += item.rows
                if item.deadline_t is not None and now >= item.deadline_t:
                    expired.append(int(item.seq))
            view = dict(
                seq=self._next_seq,
                closed=self._closed,
                qlen=len(self._queue),
                npending=call_at,
                have_call=call_at < len(self._queue),
                depth=sum(
                    1 for x in self._queue if not isinstance(x, _Call)
                ),
            )
            first_age_us: Dict[tuple, int] = {}
            for item in self._queue[:call_at]:
                key = (
                    item.endpoint, item.payload.shape[1:], item.payload.dtype.str
                )
                if key not in first_age_us:
                    first_age_us[key] = int((now - item.enqueue_t) * 1e6)
            frame_buckets = [
                (_tick.bucket_token(key), count, rows, first_age_us[key], first_seq)
                for key, (count, rows, first_seq) in buckets.items()
            ]
        mon = getattr(self.autoscaler, "monitor", None)
        mon_due = None
        mon_failed: list = []
        mon_ewmas_us: list = []
        votes = None
        if mon is not None:
            mon_due = False
            # advisory path (same contract as _autoscale): a failed
            # probe must never take down the dispatcher — this rank
            # just reports not-due and the piggybacked monitor tick
            # waits for a cleaner heartbeat
            try:
                if self._mon_stash is None and mon.local_due():
                    fail_ids, export, probes = mon.probe_local()
                    self._mon_stash = (
                        list(fail_ids), dict(export), int(probes),
                        self.autoscaler.pre_vote(view["depth"]),
                    )
            # graftlint: G006 - advisory: probe/vote failures are
            # absorbed; the reactive fault ladder owns hard faults
            except Exception:  # noqa: BLE001
                _hooks.observe("serve.error", endpoint="<autoscale>")
            if self._mon_stash is not None:
                fail_ids, export, _, votes = self._mon_stash
                mon_due = True
                mon_failed = [int(d) for d in fail_ids]
                # µs·1000-free: quantization matches the monitor's own
                # health frame, int(round(ms * 1000.0)) microseconds
                mon_ewmas_us = [
                    (int(d), int(round(ms * 1000.0)))
                    for d, ms in export.items()
                ]
        frame = _tick.encode_frame(
            seq=view["seq"],
            closed=view["closed"],
            qlen=view["qlen"],
            npending=view["npending"],
            have_call=view["have_call"],
            buckets=frame_buckets,
            shed=expired,
            mon_due=mon_due,
            mon_failed=mon_failed,
            mon_ewmas_us=mon_ewmas_us,
            votes=votes,
        )
        gathered = replicated_frame(
            frame, label="collective.serve_tick", active=not self._single
        )
        return _tick.plan_dispatch(
            gathered,
            max_batch_rows=self.policy.max_batch,
            max_latency_us=int(self.policy.max_latency_ms * 1000),
        )

    def _tick_apply(self, plan: "_tick.TickPlan") -> bool:
        """Apply one replicated plan: pull the plan-selected requests
        and call out of the queue under the lock, then shed / dispatch /
        run them outside it, in the plan's (hence every rank's) order.
        Returns True when the plan says quit (all ranks closed and
        drained)."""
        with self._cond:
            call_at = len(self._queue)
            for i, item in enumerate(self._queue):
                if isinstance(item, _Call):
                    call_at = i
                    break
            by_token: Dict[int, list] = {}
            for item in self._queue[:call_at]:
                key = (
                    item.endpoint, item.payload.shape[1:], item.payload.dtype.str
                )
                by_token.setdefault(
                    _tick.bucket_token(key), (key, [])
                )[1].append(item)
            taken = set()
            shed_items: List[Request] = []
            batches: List[PendingBatch] = []
            for token, n in plan.dispatch:
                entry = by_token.get(token)
                if entry is None:
                    continue
                key, members = entry
                prefix = members[:n]
                taken.update(id(r) for r in prefix)
                live = [r for r in prefix if r.seq not in plan.shed]
                batches.extend(
                    form_plan_batches(key, live, self.policy.max_batch)
                )
            for item in self._queue[:call_at]:
                if item.seq in plan.shed:
                    shed_items.append(item)
                    taken.add(id(item))
            if taken:
                self._queue = [
                    x for x in self._queue if id(x) not in taken
                ]
            call = None
            if plan.run_call and self._queue and isinstance(
                self._queue[0], _Call
            ):
                call = self._queue.pop(0)
        # count the tick BEFORE its effects land: a client that has seen
        # a result (or a stats reader racing the dispatcher) then always
        # sees the tick that produced it already counted — the ordering
        # tests and the bench rely on when comparing tick_batches to
        # batches at quiescence points
        _hooks.observe(
            "serve.tick",
            batches=len(batches),
            shed=len(shed_items),
            call=int(call is not None),
            monitor=int(plan.monitor_tick),
        )
        if shed_items:
            self._shed(shed_items)
        for group in batches:
            self._dispatch_batch(group)
        if call is not None:
            self._run_call(call)
        if plan.monitor_tick and self._mon_stash is not None:
            fail_ids, _, probes, _ = self._mon_stash
            self._mon_stash = None
            mon = self.autoscaler.monitor
            # advisory, like _autoscale: a failed scale is absorbed
            try:
                report = mon.apply_gathered(
                    plan.mon_failed,
                    {int(d): us / 1000.0 for d, us in plan.mon_ewmas_us},
                    probes=probes,
                    failures=len(fail_ids),
                )
                want_grow = plan.grow_pressure or (
                    bool(report.healed) and plan.grow_ready
                )
                action = self.autoscaler.resolve(bool(want_grow), report)
                if action is not None:
                    self._scale(action)
            # graftlint: G006 - advisory path: a failed scale must never
            # take down the dispatcher; the ladder owns hard faults
            except Exception:  # noqa: BLE001
                _hooks.observe("serve.error", endpoint="<autoscale>")
        with self._cond:
            depth = sum(1 for x in self._queue if not isinstance(x, _Call))
        _hooks.observe("serve.depth", depth=depth)
        return plan.quit

    def _pick_work(self):
        """Choose the next unit of work, FIFO by oldest member. Caller
        holds the lock; device work happens outside it."""
        if not self._queue:
            return None
        # the segment before the first control call; the call is a
        # barrier, so requests behind it stay out of this round's groups
        call_at = len(self._queue)
        for i, item in enumerate(self._queue):
            if isinstance(item, _Call):
                call_at = i
                break
        if self._async_triggers:
            # deadline shedding: expired requests are answered with the
            # typed error BEFORE they can pad a batch. Wall-clock driven,
            # hence single-controller only (same arming as the triggers)
            now = time.monotonic()
            expired = [
                item for item in self._queue[:call_at]
                if item.deadline_t is not None and now >= item.deadline_t
            ]
            if expired:
                doomed = set(map(id, expired))
                self._queue = [x for x in self._queue if id(x) not in doomed]
                return ("shed", expired)
        groups: Dict[tuple, PendingBatch] = {}
        for item in self._queue[:call_at]:
            key = (item.endpoint, item.payload.shape[1:], item.payload.dtype.str)
            if key not in groups:
                groups[key] = PendingBatch(key)
            groups[key].add(item)
        force = self._closed or call_at < len(self._queue)
        now = time.monotonic()
        for group in groups.values():  # insertion order = oldest first
            if (
                force
                or (
                    self._async_triggers
                    and (
                        group.rows >= self.policy.max_batch
                        or group.age_ms(now) >= self.policy.max_latency_ms
                    )
                )
            ):
                # cap each dispatch at max_batch rows: a burst then
                # becomes several batches in the SAME warm bucket rather
                # than one batch in a novel (cold) oversized bucket; a
                # single over-large request still dispatches alone
                chosen = PendingBatch(group.key)
                for request in group.requests:
                    if chosen.rows and chosen.rows + request.rows > self.policy.max_batch:
                        break
                    chosen.add(request)
                members = set(map(id, chosen.requests))
                self._queue = [x for x in self._queue if id(x) not in members]
                return ("batch", chosen)
        if call_at == 0:
            return ("call", self._queue.pop(0))
        return None

    def _wait_timeout(self) -> Optional[float]:
        """Seconds until the oldest pending group hits the latency
        trigger or the nearest request deadline expires (None: sleep
        until notified)."""
        if not self._async_triggers or not self._queue:
            return None
        oldest = None
        deadline = None
        for item in self._queue:
            if isinstance(item, _Call):
                break
            if oldest is None or item.enqueue_t < oldest:
                oldest = item.enqueue_t
            if item.deadline_t is not None and (
                deadline is None or item.deadline_t < deadline
            ):
                deadline = item.deadline_t
        if oldest is None:
            return None
        now = time.monotonic()
        remaining = self.policy.max_latency_ms / 1e3 - (now - oldest)
        if deadline is not None:
            remaining = min(remaining, deadline - now)
        return max(1e-4, remaining)

    def _shed(self, expired: List[Request]) -> None:
        """Answer deadline-expired requests with the typed error (off
        the lock — finishing wakes client threads and fires observers)."""
        now = time.monotonic()
        for request in expired:
            waited = (now - request.enqueue_t) * 1e3
            _hooks.observe(
                "serve.shed", endpoint=request.endpoint, waited_ms=waited
            )
            request._finish(error=ServeDeadlineError(
                request.endpoint, waited, request.deadline_ms
            ))

    def _dispatch_batch(self, group: PendingBatch) -> None:
        """Run one batch through the fault ladder (module docstring):
        retry -> bisect for payload faults, snapshot-restore + replay for
        suspect state, probe + lockstep shrink + redispatch for device
        loss. Terminates with EVERY request in ``group`` answered —
        result rows or a typed error — no matter which rungs fire."""
        endpoint = group.key[0]
        attempt = 0
        delays = None
        restored = False
        shrunk = False
        while True:
            try:
                self._execute(group)
                self._maybe_snapshot()
                return
            except Exception as exc:  # noqa: BLE001 - classified, never ignored
                _hooks.observe("serve.error", endpoint=endpoint)
                action = _classify_dispatch(exc)
                if action == "retry":
                    if delays is None:
                        delays = self.retry.delays()
                    if attempt < len(delays):
                        _hooks.observe(
                            "serve.retry", attempt=attempt + 1, endpoint=endpoint
                        )
                        self.retry.sleep(delays[attempt])
                        attempt += 1
                        continue
                    action = "bisect"  # retries exhausted: suspect a payload
                if action == "restore":
                    # resident state is suspect (divergence / deserted
                    # collective): roll back to the snapshot, replay once
                    if not restored and self._restore_registry(exc):
                        restored = True
                        _hooks.observe(
                            "serve.redispatch", requests=len(group.requests)
                        )
                        continue
                    self._fail_group(group, exc)
                    return
                if action == "probe":
                    # a died device surfaces as an XLA RuntimeError
                    try:
                        handled = not shrunk and self._shrink_and_restore(exc)
                    except Exception as shrink_exc:  # noqa: BLE001 - e.g. nothing survives
                        self._fail_group(group, shrink_exc)
                        return
                    if handled:
                        shrunk = True
                        _hooks.observe(
                            "serve.redispatch", requests=len(group.requests)
                        )
                        continue
                    # probe found a healthy mesh: not a device problem
                    action = "bisect"
                if action == "bisect":
                    self._bisect(group, exc)
                    return
                # fatal (NoHealthyDevicesError, ...): answer and live on
                self._fail_group(group, exc)
                return

    def _execute(self, group: PendingBatch) -> None:
        """One batch attempt: stack, dispatch, scatter. Raises on any
        failure WITHOUT finishing requests — that is the ladder's call."""
        endpoint, row_shape, dtype_str = group.key
        stacked = group.stack(self.policy)
        bucket = int(stacked.shape[0])
        bucket_key = (endpoint, bucket, row_shape, dtype_str)
        hit = bucket_key in self._seen_buckets
        _hooks.fault_point(
            "serve.dispatch", endpoint=endpoint, bucket=bucket, rows=group.rows
        )
        x = factories.array(stacked, split=0)
        out = self._endpoints[endpoint](x)
        # pin this program to completion before the next independent
        # one launches: multi-controller collective order stays total
        collective_lockstep(out._raw if isinstance(out, DNDarray) else out)
        host = out.numpy() if isinstance(out, DNDarray) else np.asarray(out)
        self._seen_buckets.add(bucket_key)
        _hooks.observe(
            "serve.batch",
            requests=len(group.requests),
            rows=group.rows,
            bucket=bucket,
            hit=hit,
        )
        offset = 0
        for request in group.requests:
            request._finish(result=host[offset:offset + request.rows])
            offset += request.rows

    def _fail_group(self, group: PendingBatch, exc: BaseException) -> None:
        for request in group.requests:
            request._finish(error=exc)

    def _bisect(self, group: PendingBatch, cause: BaseException) -> None:
        """Isolate the poison request(s): re-run halves of the failed
        batch until every still-failing singleton is answered with
        :class:`PoisonRequestError` — its former batch neighbors get
        their rows from the succeeding halves."""
        endpoint = group.key[0]
        requests = list(group.requests)
        found: List[Request] = []

        def fail_one(request: Request, exc: BaseException) -> None:
            found.append(request)
            request._finish(error=PoisonRequestError(endpoint, exc))

        def run(part: List[Request], exc: BaseException) -> None:
            if len(part) == 1:
                fail_one(part[0], exc)
                return
            mid = len(part) // 2
            for half in (part[:mid], part[mid:]):
                sub = PendingBatch(group.key)
                for request in half:
                    sub.add(request)
                try:
                    self._execute(sub)
                except Exception as sub_exc:  # noqa: BLE001 - recurse to isolate
                    _hooks.observe("serve.error", endpoint=endpoint)
                    run(half, sub_exc)

        if len(requests) == 1:
            fail_one(requests[0], cause)
        else:
            _hooks.observe("serve.bisect", requests=len(requests))
            run(requests, cause)
        if found:
            # a poison payload may have corrupted resident state before
            # raising: the old supervised-service rollback still applies
            self._maybe_restore(cause)

    def _shrink_and_restore(self, exc: BaseException) -> bool:
        """Device-loss recovery: probe, reach cross-rank consensus on
        the unhealthy set, shrink the mesh onto the survivors, and land
        the resident registry on the new mesh. Returns False when the
        probe (on every rank) found a healthy mesh — the failure was not
        a device problem. Raises :class:`NoHealthyDevicesError` through
        when nothing survives."""
        from ..resilience import degrade

        comm = sanitize_comm(None)
        multi = jax.process_count() > 1
        try:
            degrade.probe(comm)
        except ResilienceError:
            raise
        except Exception:  # noqa: BLE001 - a dead probe proves nothing new
            pass
        # every rank must build the SAME survivor mesh: probe only sees
        # this process's addressable devices, so union the per-rank sets
        # and take one replicated go/no-go — ranks shrink in lockstep
        bad = replicated_ids(degrade.unhealthy_devices(), active=multi)
        for dev in bad:
            degrade.mark_unhealthy(dev)
        if not replicated_decision(bool(bad), active=multi):
            return False
        old = comm.size
        new_comm, _ = degrade.shrink_to_healthy(comm, set_default=True)
        self._relocate_registry()
        # programs compiled for the old mesh are dead; buckets re-warm
        self._seen_buckets.clear()
        _hooks.observe(
            "serve.shrink", old=old, new=new_comm.size, cause=type(exc).__name__
        )
        return True

    def _relocate_registry(self) -> None:
        """Land every resident model's state on the (new) default mesh:
        elastic-restore from the last snapshot when there is one
        (``load_checkpoint`` reassembles shards onto the current mesh),
        or round-trip live state through host memory otherwise."""
        if self.snapshot_dir and self._have_snapshot:
            try:
                self.registry.restore(self.snapshot_dir)
                _hooks.observe("serve.restore", cause="shrink")
                return
            # graftlint: G006 - best-effort: a failed elastic restore falls
            # through to the live state_dict move below, never silent loss
            except Exception:  # noqa: BLE001
                _hooks.observe("serve.error", endpoint="<restore>")
        for name in self.registry.names():
            model = self.registry.get(name)
            state_fn = getattr(model, "state_dict", None)
            load_fn = getattr(model, "load_state_dict", None)
            if state_fn is None or load_fn is None:
                continue
            state = {
                k: (v.numpy() if isinstance(v, DNDarray) else v)
                for k, v in state_fn().items()
            }
            load_fn(state)

    # ---------------------------------------------------------- autoscaling
    def _autoscale(self, depth: int) -> None:
        """Consult the autoscaler between work units and apply its
        verdict. Advisory by contract: a scaling failure is absorbed
        (counted as a serve error) and the service lives on — hard
        device failures still ride the reactive fault ladder."""
        try:
            action = self.autoscaler.consult(depth)
            if action is not None:
                self._scale(action)
        # graftlint: G006 - advisory path: a failed scale must never
        # take down the dispatcher; the reactive ladder owns hard faults
        except Exception:  # noqa: BLE001
            _hooks.observe("serve.error", endpoint="<autoscale>")

    def _scale(self, direction: str) -> None:
        """Apply one scale verdict on the dispatcher thread (the only
        thread allowed to do device work): rebuild the default mesh,
        land the resident registry on it, and invalidate the warm-bucket
        program cache — the PR 16 shrink-rung contract, both ways."""
        from ..resilience import degrade

        comm = sanitize_comm(None)
        old = comm.size
        if direction == "shrink":
            new_comm, _ = degrade.shrink_to_healthy(comm, set_default=True)
        else:
            new_comm, _ = degrade.grow_to_healthy(
                comm, base=self.autoscaler.monitor.base, set_default=True
            )
        if new_comm.size == old:
            return  # nothing to do (verdict already satisfied)
        self._relocate_registry()
        # programs compiled for the old mesh are dead; buckets re-warm
        self._seen_buckets.clear()
        _hooks.observe(
            "serve.scale", direction=direction, old=old, new=new_comm.size
        )
        _hooks.observe(
            "serve.shrink" if direction == "shrink" else "serve.grow",
            old=old, new=new_comm.size, cause="autoscale",
        )

    def _run_call(self, call: _Call) -> None:
        try:
            call._result = call.fn()
        except Exception as exc:  # noqa: BLE001 - delivered to the caller
            call._error = exc
            _hooks.observe("serve.error", endpoint="<call>")
        call._done.set()

    # ------------------------------------------------- supervised snapshots
    def _maybe_snapshot(self) -> None:
        if not self.snapshot_dir or self.snapshot_every <= 0:
            return
        self._batches_since_snapshot += 1
        if self._batches_since_snapshot < self.snapshot_every:
            return
        self._batches_since_snapshot = 0
        try:
            _hooks.fault_point("serve.snapshot", directory=self.snapshot_dir)
            self.registry.snapshot(self.snapshot_dir)
            self._have_snapshot = True
        # graftlint: G006 - snapshots are best-effort; the checkpoint
        # layer's _replicated_raise discipline makes any multi-process
        # failure (ResilienceError included) symmetric, so every rank
        # absorbs it together and the NEXT cadence hit retries (the
        # previous good snapshot, if any, still stands)
        except Exception:  # noqa: BLE001
            _hooks.observe("serve.error", endpoint="<snapshot>")

    def _restore_registry(self, exc: BaseException) -> bool:
        """Roll resident models back to the last snapshot ahead of a
        batch replay; False when there is nothing to restore from (or
        the restore itself failed, symmetrically on every rank)."""
        if not self.snapshot_dir or not self._have_snapshot:
            return False
        try:
            self.registry.restore(self.snapshot_dir)
        # graftlint: G006 - symmetric absorb (see _maybe_snapshot); the
        # False return escalates the ladder, nothing is lost silently
        except Exception:  # noqa: BLE001
            _hooks.observe("serve.error", endpoint="<restore>")
            return False
        _hooks.observe("serve.restore", cause=type(exc).__name__)
        return True

    def _maybe_restore(self, exc: BaseException) -> None:
        """After a batch ultimately failed, roll the resident models back
        to the last good snapshot (best-effort — the supervised-service
        loop; the failing requests already carry their error)."""
        if not self.snapshot_dir or not self._have_snapshot:
            return
        try:
            self.registry.restore(self.snapshot_dir)
            _hooks.observe("serve.restore", cause=type(exc).__name__)
        # graftlint: G006 - symmetric absorb (see _maybe_snapshot); the
        # failing requests already carry their typed error
        except Exception:  # noqa: BLE001
            _hooks.observe("serve.error", endpoint="<restore>")


def _model_endpoint(registry: ModelRegistry, name: str, method: str) -> Callable:
    def endpoint(x: DNDarray):
        return getattr(registry.get(name), method)(x)

    endpoint._cache_stable = True  # module-level factory, one per registration
    return endpoint


def _feed_step(registry: ModelRegistry, name: str, method: str, chunk) -> Callable:
    def step():
        bound = getattr(registry.get(name), method)
        return bound(*chunk) if isinstance(chunk, tuple) else bound(chunk)

    return step
