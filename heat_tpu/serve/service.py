"""The resident SPMD service: event loop + single dispatch thread.

One :class:`ServeService` owns the mesh for its lifetime. Client threads
``submit()`` requests (numpy rows + an endpoint name) and block on
:meth:`Request.result`; ONE dispatcher thread drains the queue, forms
shape-bucketed batches (:mod:`heat_tpu.serve.batching`), runs each batch
through its endpoint on-device, and scatters result rows back to the
waiting requests. All device work happens on the dispatcher thread —
the PR 9 lesson: concurrent dispatch from multiple threads interleaves
cross-process collectives differently per process and deadlocks the
rendezvous — and every batch is pinned under ``collective_lockstep``
before the next one launches, so multi-controller execution keeps one
total order of collective-bearing programs.

Flush triggers, and the multi-controller contract
-------------------------------------------------
A pending batch dispatches when (a) it reaches ``policy.max_batch``
rows, (b) its oldest request has waited ``policy.max_latency_ms``, or
(c) a barrier forces it: ``flush()``, ``drain()``, ``close()``, or any
``submit_call`` (control calls act as barriers so model mutations are
ordered against traffic). ``flush()`` enqueues a no-op control call, so
the barrier has a deterministic POSITION in the queue: exactly the
requests submitted before it are forced, never a racing later submit
the dispatcher happened to observe.

Triggers (a) and (b) are armed with a single controller only. Both are
rank-divergent under multiple controllers: wall clocks drift, and the
count trigger fires at whatever queue prefix each rank's dispatcher
happens to observe — with two pending endpoint groups, rank A can see
only the younger group full (dispatching it first) while rank B sees
both (dispatching the older first), and the collective-bearing batch
programs then interleave in different orders across ranks, which is
exactly the deadlock ``collective_lockstep`` exists to prevent. So at
``jax.process_count() > 1`` the service is barrier-driven SPMD like
everything else in this tree: every process submits the same requests
in the same order and calls the same barriers; batches between barriers
form from identical queue segments by identical rules, and lockstep
pinning keeps one total order of programs. See docs/SERVING.md.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

import jax
import numpy as np

from ..core import _hooks
from ..core import factories
from ..resilience.errors import ResilienceError
from ..core.communication import collective_lockstep
from ..core.dndarray import DNDarray
from .batching import BucketPolicy, PendingBatch
from .session import ModelRegistry
from ._stats import SERVE_STATS, refresh_latency_stats

__all__ = ["Request", "ServeService"]


class Request:
    """One client request: ``payload`` rows bound for ``endpoint``.

    ``payload`` is host data shaped ``(rows, *row_shape)``; the result
    (set by the dispatcher) is the matching slice of the batch output.
    """

    __slots__ = ("endpoint", "payload", "rows", "enqueue_t",
                 "_done", "_result", "_error")

    def __init__(self, endpoint: str, payload: np.ndarray):
        self.endpoint = endpoint
        self.payload = payload
        self.rows = int(payload.shape[0])
        self.enqueue_t = time.monotonic()
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def _finish(self, result=None, error: Optional[BaseException] = None) -> None:
        self._result = result
        self._error = error
        _hooks.observe(
            "serve.latency", ms=(time.monotonic() - self.enqueue_t) * 1e3
        )
        self._done.set()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until the dispatcher answered; returns the result rows
        or re-raises the dispatch error."""
        if not self._done.wait(timeout):
            raise TimeoutError(f"request to {self.endpoint!r} still pending")
        if self._error is not None:
            raise self._error
        return self._result


class _Call:
    """A control item: a closure executed on the dispatcher thread (the
    only thread allowed to do device work). Acts as a flush barrier."""

    __slots__ = ("fn", "_done", "_result", "_error")

    def __init__(self, fn: Callable):
        self.fn = fn
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError("control call still pending")
        if self._error is not None:
            raise self._error
        return self._result


class ServeService:
    """Persistent multi-tenant serving loop over the resident mesh.

    Parameters
    ----------
    policy : BucketPolicy
        Batching policy (bucket menu, max-batch, max-latency).
    registry : ModelRegistry
        Resident model registry; a fresh one when omitted.
    snapshot_dir : str, optional
        When set, the registry is snapshotted here every
        ``snapshot_every`` successful batches (on the dispatcher thread,
        so snapshots are ordered against traffic), and a dispatch error
        triggers a best-effort restore from the last snapshot before the
        service carries on — the supervised-service loop.
    snapshot_every : int
        Snapshot cadence in batches (0 disables periodic snapshots).
    """

    def __init__(
        self,
        policy: Optional[BucketPolicy] = None,
        registry: Optional[ModelRegistry] = None,
        snapshot_dir: Optional[str] = None,
        snapshot_every: int = 0,
    ):
        self.policy = policy or BucketPolicy()
        self.registry = registry or ModelRegistry()
        self.snapshot_dir = snapshot_dir
        self.snapshot_every = int(snapshot_every)
        self._endpoints: Dict[str, Callable] = {}
        self._cond = threading.Condition()
        self._queue: List = []
        self._closed = False
        self._seen_buckets = set()
        self._have_snapshot = False
        self._batches_since_snapshot = 0
        # the latency timer and the max-batch count trigger both fire at
        # rank-divergent moments (see the module docstring); arm them
        # only when there is no other rank to diverge from
        self._async_triggers = jax.process_count() == 1
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="serve-dispatch"
        )
        self._thread.start()

    # ------------------------------------------------------------ endpoints
    def register_endpoint(self, name: str, fn: Callable) -> None:
        """Install a row-wise endpoint: ``fn(x: DNDarray) -> DNDarray``
        where output row ``i`` depends only on input row ``i`` (plus
        resident state) — the contract that makes bucket padding and
        result scattering safe."""
        if self._closed:
            raise RuntimeError("service is closed")
        self._endpoints[name] = fn

    def register_model(self, name: str, model, methods: Sequence[str] = ("predict",)):
        """Register ``model`` in the resident registry and expose one
        endpoint per method as ``"<name>.<method>"``. Endpoints resolve
        the model through the registry AT DISPATCH TIME, so a later
        ``registry.register(name, refreshed)`` swaps the model without
        touching endpoints or compiled programs."""
        self.registry.register(name, model)
        for method in methods:
            if not callable(getattr(model, method, None)):
                raise TypeError(f"{name!r} model has no callable {method!r}")
            self._endpoints[f"{name}.{method}"] = _model_endpoint(
                self.registry, name, method
            )

    def endpoints(self) -> List[str]:
        return sorted(self._endpoints)

    # ------------------------------------------------------------- clients
    def submit(self, endpoint: str, payload) -> Request:
        """Enqueue ``payload`` rows for ``endpoint``; returns a
        :class:`Request` future. ``payload`` is host data shaped
        ``(rows, *row_shape)`` (one sample: shape ``(1, ...)``)."""
        if endpoint not in self._endpoints:
            raise KeyError(
                f"unknown endpoint {endpoint!r}; known: {self.endpoints()}"
            )
        payload = np.asarray(payload)
        if payload.ndim < 1 or payload.shape[0] < 1:
            raise ValueError("payload must be (rows, ...) with rows >= 1")
        request = Request(endpoint, payload)
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            self._queue.append(request)
            depth = len(self._queue)
            self._cond.notify()
        _hooks.observe("serve.request", depth=depth)
        return request

    def predict(self, name: str, payload, timeout: Optional[float] = None):
        """Synchronous convenience: submit to ``"<name>.predict"`` and
        wait for the rows."""
        return self.submit(f"{name}.predict", payload).result(timeout)

    def submit_call(self, fn: Callable) -> _Call:
        """Run ``fn()`` on the dispatcher thread, ordered after every
        currently pending request (a barrier). This is the door for
        anything that is NOT a row-wise map: ``fit``, ``partial_fit``,
        registry snapshots, model refreshes."""
        call = _Call(fn)
        with self._cond:
            if self._closed:
                raise RuntimeError("service is closed")
            self._queue.append(call)
            self._cond.notify()
        return call

    def feed(
        self,
        name: str,
        chunks,
        method: str = "partial_fit",
        depth: int = 2,
        timeout: Optional[float] = None,
    ) -> int:
        """Stream chunks into a resident model's incremental update
        (``partial_fit`` / ``update``), overlapping chunk production with
        device compute: the PR 10 Prefetcher runs the chunk source
        ``depth`` ahead on its producer thread while each update executes
        on the DISPATCHER thread (via :meth:`submit_call`, so updates are
        ordered against concurrent predict traffic). Tuple chunks splat
        into positional args — ``(x, y)`` feeds ``partial_fit(x, y)``.
        Returns the number of chunks applied."""
        from ..stream import Prefetcher

        registry = self.registry
        applied = 0
        pending: List[_Call] = []
        for chunk in Prefetcher(chunks, depth=depth):
            pending.append(self.submit_call(_feed_step(registry, name, method, chunk)))
            applied += 1
            # stay at most ``depth`` updates ahead of the dispatcher so
            # the chunk source is throttled by compute, not read whole
            while len(pending) > max(1, depth):
                pending.pop(0).result(timeout)
        for call in pending:
            call.result(timeout)
        return applied

    def flush(self) -> None:
        """Force-dispatch everything submitted before this call
        (non-blocking). Implemented as a no-op control call so the
        barrier sits at a deterministic queue position — requests
        submitted AFTER the flush stay pending, on every rank."""
        call = _Call(lambda: None)
        with self._cond:
            if self._closed:
                return
            self._queue.append(call)
            self._cond.notify()

    def drain(self, timeout: Optional[float] = None) -> None:
        """Block until every request submitted before this call has been
        dispatched and answered."""
        self.submit_call(lambda: None).result(timeout)

    def stats(self) -> dict:
        """Snapshot of SERVE_STATS with the latency percentiles
        refreshed."""
        refresh_latency_stats()
        snap = dict(SERVE_STATS)
        with self._cond:
            snap["queue_depth"] = len(self._queue)
        return snap

    def close(self, timeout: Optional[float] = None) -> None:
        """Flush outstanding work and stop the dispatcher thread."""
        with self._cond:
            if self._closed and not self._thread.is_alive():
                return
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)

    def __enter__(self) -> "ServeService":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False

    # ----------------------------------------------------------- dispatcher
    def _loop(self) -> None:
        while True:
            with self._cond:
                work = self._pick_work()
                if work is None:
                    if self._closed and not self._queue:
                        return
                    self._cond.wait(self._wait_timeout())
                    continue
            kind, item = work
            if kind == "batch":
                self._dispatch_batch(item)
            else:
                self._run_call(item)

    def _pick_work(self):
        """Choose the next unit of work, FIFO by oldest member. Caller
        holds the lock; device work happens outside it."""
        if not self._queue:
            return None
        # the segment before the first control call; the call is a
        # barrier, so requests behind it stay out of this round's groups
        call_at = len(self._queue)
        for i, item in enumerate(self._queue):
            if isinstance(item, _Call):
                call_at = i
                break
        groups: Dict[tuple, PendingBatch] = {}
        for item in self._queue[:call_at]:
            key = (item.endpoint, item.payload.shape[1:], item.payload.dtype.str)
            if key not in groups:
                groups[key] = PendingBatch(key)
            groups[key].add(item)
        force = self._closed or call_at < len(self._queue)
        now = time.monotonic()
        for group in groups.values():  # insertion order = oldest first
            if (
                force
                or (
                    self._async_triggers
                    and (
                        group.rows >= self.policy.max_batch
                        or group.age_ms(now) >= self.policy.max_latency_ms
                    )
                )
            ):
                # cap each dispatch at max_batch rows: a burst then
                # becomes several batches in the SAME warm bucket rather
                # than one batch in a novel (cold) oversized bucket; a
                # single over-large request still dispatches alone
                chosen = PendingBatch(group.key)
                for request in group.requests:
                    if chosen.rows and chosen.rows + request.rows > self.policy.max_batch:
                        break
                    chosen.add(request)
                members = set(map(id, chosen.requests))
                self._queue = [x for x in self._queue if id(x) not in members]
                return ("batch", chosen)
        if call_at == 0:
            return ("call", self._queue.pop(0))
        return None

    def _wait_timeout(self) -> Optional[float]:
        """Seconds until the oldest pending group hits the latency
        trigger (None: sleep until notified)."""
        if not self._async_triggers or not self._queue:
            return None
        oldest = None
        for item in self._queue:
            if isinstance(item, _Call):
                break
            if oldest is None or item.enqueue_t < oldest:
                oldest = item.enqueue_t
        if oldest is None:
            return None
        remaining = self.policy.max_latency_ms / 1e3 - (time.monotonic() - oldest)
        return max(1e-4, remaining)

    def _dispatch_batch(self, group: PendingBatch) -> None:
        endpoint, row_shape, dtype_str = group.key
        try:
            stacked = group.stack(self.policy)
            bucket = int(stacked.shape[0])
            bucket_key = (endpoint, bucket, row_shape, dtype_str)
            hit = bucket_key in self._seen_buckets
            x = factories.array(stacked, split=0)
            out = self._endpoints[endpoint](x)
            # pin this program to completion before the next independent
            # one launches: multi-controller collective order stays total
            collective_lockstep(out._raw if isinstance(out, DNDarray) else out)
            host = out.numpy() if isinstance(out, DNDarray) else np.asarray(out)
            self._seen_buckets.add(bucket_key)
        except Exception as exc:  # noqa: BLE001 - delivered to the clients
            _hooks.observe("serve.error", endpoint=endpoint)
            for request in group.requests:
                request._finish(error=exc)
            self._maybe_restore(exc)
            return
        _hooks.observe(
            "serve.batch",
            requests=len(group.requests),
            rows=group.rows,
            bucket=bucket,
            hit=hit,
        )
        offset = 0
        for request in group.requests:
            request._finish(result=host[offset:offset + request.rows])
            offset += request.rows
        self._maybe_snapshot()

    def _run_call(self, call: _Call) -> None:
        try:
            call._result = call.fn()
        except Exception as exc:  # noqa: BLE001 - delivered to the caller
            call._error = exc
            _hooks.observe("serve.error", endpoint="<call>")
        call._done.set()

    # ------------------------------------------------- supervised snapshots
    def _maybe_snapshot(self) -> None:
        if not self.snapshot_dir or self.snapshot_every <= 0:
            return
        self._batches_since_snapshot += 1
        if self._batches_since_snapshot < self.snapshot_every:
            return
        self._batches_since_snapshot = 0
        try:
            self.registry.snapshot(self.snapshot_dir)
            self._have_snapshot = True
        except ResilienceError:
            # a deserted collective / divergence is never "best-effort" —
            # swallowing it here would wedge the other ranks
            raise
        except Exception:  # noqa: BLE001 - snapshots are best-effort
            _hooks.observe("serve.error", endpoint="<snapshot>")

    def _maybe_restore(self, exc: BaseException) -> None:
        """After a dispatch error, roll the resident models back to the
        last good snapshot (best-effort — the supervised-service loop).
        """
        if not self.snapshot_dir or not self._have_snapshot:
            return
        try:
            self.registry.restore(self.snapshot_dir)
            _hooks.observe("serve.restore", cause=type(exc).__name__)
        except ResilienceError:
            raise
        except Exception:  # noqa: BLE001 - the original error already went out
            _hooks.observe("serve.error", endpoint="<restore>")


def _model_endpoint(registry: ModelRegistry, name: str, method: str) -> Callable:
    def endpoint(x: DNDarray):
        return getattr(registry.get(name), method)(x)

    endpoint._cache_stable = True  # module-level factory, one per registration
    return endpoint


def _feed_step(registry: ModelRegistry, name: str, method: str, chunk) -> Callable:
    def step():
        bound = getattr(registry.get(name), method)
        return bound(*chunk) if isinstance(chunk, tuple) else bound(chunk)

    return step
