"""The replicated dispatch tick: frame codec + pure plan function.

This module is the heart of re-arming the timer and max-batch-count
triggers at ``jax.process_count() > 1``. Both triggers are rank-divergent
when each rank consults its OWN queue (wall clocks drift; the count
trigger fires at whatever queue prefix each rank's dispatcher happens to
observe), which is the F001 deadlock class that forced PR 13 to disarm
them. The fix shape (GSPMD's, see PAPERS.md): make every rank derive the
*same* decision from *replicated* metadata.

At an agreed cadence every rank encodes its local queue view into one
fixed-width int64 frame (:func:`encode_frame`), exchanges it with
:func:`heat_tpu.core.communication.replicated_frame` (one allgather —
every rank receives the identical ``(nproc, FRAME_WIDTH)`` array), and
runs :func:`plan_dispatch` over the gathered frames. ``plan_dispatch``
is a PURE function of the gathered array plus static policy numbers —
no clocks, no queue access, no randomness — so its
:class:`TickPlan` is byte-identical on every rank, and applying it is
rank-divergence-free by construction.

Why min-over-ranks prefix lengths are safe
------------------------------------------
The SPMD contract (docs/SERVING.md): every process submits the same
requests in the same order, and every resolution (dispatch, shed, call)
is tick-decided, hence applied identically everywhere. So at any moment
each rank's pending queue is a CONTIGUOUS PREFIX WINDOW of the same
global submit sequence — ranks differ only in how much of the tail they
have observed. For a bucket key ``k`` it follows that one rank's pending
``k``-requests are a prefix of another's, so dispatching the first
``min-over-ranks count(k)`` requests of ``k`` selects the SAME request
set on every rank; a key some rank has not seen yet simply contributes
count 0 and waits a tick. The frame's per-key ``first_seq`` values agree
wherever the key is reported, giving one global FIFO order, and keys
beyond the ``BUCKET_CAP`` report window all carry larger ``first_seq``
than every reported key (they first appear in some rank's unobserved
tail), so capped reporting stays consistent across ranks and makes
progress oldest-first.

The same frame piggybacks two more decisions (ISSUE 18: one heartbeat,
not three allgathers): the health monitor's probe exports (fail ids +
EWMA samples, applied via ``HealthMonitor.apply_gathered`` when ALL
ranks report due) and the autoscaler's grow votes
(``Autoscaler.pre_vote`` pairs, resolved against the freshly applied
health report).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "FRAME_WIDTH",
    "BUCKET_CAP",
    "SHED_CAP",
    "TickPlan",
    "bucket_token",
    "encode_frame",
    "plan_dispatch",
]

# ---------------------------------------------------------------- layout
# header cells
H_SEQ = 0         # data requests accepted ever (the next seq to assign)
H_CLOSED = 1      # 1 once close() ran
H_QLEN = 2        # pending items, requests AND calls (0 = drained)
H_NPEND = 3       # pending requests BEFORE the first pending call
H_HAVE_CALL = 4   # 1 if a control call is pending
H_MON_DUE = 5     # 1 monitor locally due, 0 not due, -1 no monitor
H_VOTE_PRESSURE = 6  # autoscale pre_vote()[0]; -1 no autoscaler
H_VOTE_READY = 7     # autoscale pre_vote()[1]; -1 no autoscaler
_HDR = 8

# per-bucket records: (token, pending requests, pending rows,
# oldest-member age µs, first member's seq)
BUCKET_CAP = 16
_B_CELLS = 5
_B_OFF = _HDR

# deadline-expired pending seqs, -1 padded
SHED_CAP = 32
_S_OFF = _B_OFF + BUCKET_CAP * _B_CELLS

# piggybacked health-monitor probe export: locally-failed device ids
# (-1 padded) and (device id, EWMA µs) pairs — quantization matches
# HealthMonitor's health frame: int(round(ms * 1000.0))
MON_FAIL_CAP = 64
_F_OFF = _S_OFF + SHED_CAP
MON_EWMA_CAP = 64
_E_OFF = _F_OFF + MON_FAIL_CAP

FRAME_WIDTH = _E_OFF + MON_EWMA_CAP * 2


def bucket_token(key) -> int:
    """Deterministic cross-process token for a bucket key (endpoint,
    per-row shape, dtype str). Python's builtin ``hash`` is salted per
    process (PYTHONHASHSEED), so it would diverge across ranks; a
    truncated blake2b of the key's repr is stable everywhere."""
    digest = hashlib.blake2b(repr(key).encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(digest, "big") >> 1  # non-negative, fits int64


def encode_frame(
    *,
    seq: int,
    closed: bool,
    qlen: int,
    npending: int,
    have_call: bool,
    buckets: Sequence[Tuple[int, int, int, int, int]],
    shed: Sequence[int] = (),
    mon_due: Optional[bool] = None,
    mon_failed: Sequence[int] = (),
    mon_ewmas_us: Sequence[Tuple[int, int]] = (),
    votes: Optional[Tuple[bool, bool]] = None,
) -> np.ndarray:
    """Pack one rank's queue view into the fixed-width int64 frame.

    ``buckets`` holds ``(token, count, rows, age_us, first_seq)``
    records; past :data:`BUCKET_CAP` the caller must keep the
    smallest-``first_seq`` records (oldest keys first — see the module
    docstring for why that stays rank-consistent). ``shed`` holds the
    seqs of deadline-expired pending requests (oldest first, capped at
    :data:`SHED_CAP`); ``mon_due``/``mon_failed``/``mon_ewmas_us`` carry
    the health monitor's local probe export when it is due, and
    ``votes`` the autoscaler's ``pre_vote`` pair."""
    frame = np.full(FRAME_WIDTH, -1, dtype=np.int64)
    frame[H_SEQ] = int(seq)
    frame[H_CLOSED] = int(bool(closed))
    frame[H_QLEN] = int(qlen)
    frame[H_NPEND] = int(npending)
    frame[H_HAVE_CALL] = int(bool(have_call))
    frame[H_MON_DUE] = -1 if mon_due is None else int(bool(mon_due))
    if votes is not None:
        frame[H_VOTE_PRESSURE] = int(bool(votes[0]))
        frame[H_VOTE_READY] = int(bool(votes[1]))
    records = sorted(buckets, key=lambda r: r[4])[:BUCKET_CAP]
    for i, (token, count, rows, age_us, first_seq) in enumerate(records):
        base = _B_OFF + i * _B_CELLS
        frame[base:base + _B_CELLS] = (
            int(token), int(count), int(rows), int(age_us), int(first_seq)
        )
    for i, s in enumerate(sorted(shed)[:SHED_CAP]):
        frame[_S_OFF + i] = int(s)
    for i, dev in enumerate(sorted(mon_failed)[:MON_FAIL_CAP]):
        frame[_F_OFF + i] = int(dev)
    for i, (dev, us) in enumerate(sorted(mon_ewmas_us)[:MON_EWMA_CAP]):
        base = _E_OFF + i * 2
        frame[base] = int(dev)
        frame[base + 1] = int(us)
    return frame


@dataclass(frozen=True)
class TickPlan:
    """One tick's replicated verdict — a pure function of the gathered
    frames, identical on every rank.

    ``dispatch`` lists ``(token, n_requests)`` in global FIFO order:
    each rank takes the first ``n_requests`` pending requests of that
    bucket key (counted BEFORE shed removal), drops the ``shed``
    members, and dispatches the rest in ``max_batch``-row chunks.
    ``shed`` seqs are answered with ``ServeDeadlineError`` everywhere —
    tick-decided deadline shedding, the promotion from ws1-only.
    ``run_call`` fires only when every rank's pre-call segment empties
    under this plan, so the call executes at the same queue position on
    every rank. ``quit`` means every rank is closed and drained.
    ``monitor_tick`` + ``mon_failed``/``mon_ewmas_us`` and the two grow
    flags carry the piggybacked health/autoscale decisions."""

    dispatch: Tuple[Tuple[int, int], ...]
    shed: frozenset
    run_call: bool
    quit: bool
    monitor_tick: bool
    mon_failed: Tuple[int, ...]
    mon_ewmas_us: Tuple[Tuple[int, int], ...]
    grow_pressure: bool
    grow_ready: bool


def plan_dispatch(
    gathered: np.ndarray,
    *,
    max_batch_rows: int,
    max_latency_us: int,
) -> TickPlan:
    """Derive the tick's plan from the gathered ``(nproc, FRAME_WIDTH)``
    frames. Pure: no clocks, no queue access — every rank computes the
    identical plan, which is the whole point.

    Trigger rules per bucket key (mirroring the ws1 async triggers, but
    over replicated numbers): dispatch ``min``-over-ranks pending count
    when that min is >= 1 AND (forced, or the ``max``-over-ranks oldest
    age reached the latency bound, or the ``min``-over-ranks pending
    rows reached ``max_batch_rows``). Forced means a control call is
    pending somewhere (hurry the segment out so the barrier can run) or
    every rank closed (drain)."""
    frames = np.asarray(gathered, dtype=np.int64)
    if frames.ndim != 2 or frames.shape[1] != FRAME_WIDTH:
        raise ValueError(f"expected (nproc, {FRAME_WIDTH}), got {frames.shape}")
    closed_all = bool((frames[:, H_CLOSED] == 1).all())
    have_call_any = bool((frames[:, H_HAVE_CALL] == 1).any())
    have_call_all = bool((frames[:, H_HAVE_CALL] == 1).all())
    min_seq = int(frames[:, H_SEQ].min())
    force = closed_all or have_call_any

    # shed: any rank's clock says expired, every rank has accepted it
    shed = frozenset(
        int(s) for s in frames[:, _S_OFF:_S_OFF + SHED_CAP].ravel()
        if 0 <= s < min_seq
    )

    # bucket records per rank, keyed by token
    per_rank: List[Dict[int, Tuple[int, int, int, int]]] = []
    for frame in frames:
        records: Dict[int, Tuple[int, int, int, int]] = {}
        for i in range(BUCKET_CAP):
            base = _B_OFF + i * _B_CELLS
            token = int(frame[base])
            if token < 0:
                continue
            records[token] = (
                int(frame[base + 1]), int(frame[base + 2]),
                int(frame[base + 3]), int(frame[base + 4]),
            )
        per_rank.append(records)
    tokens = set()
    for records in per_rank:
        tokens.update(records)
    chosen: List[Tuple[int, int, int]] = []  # (first_seq, token, n)
    planned_total = 0
    for token in tokens:
        hits = [records[token] for records in per_rank if token in records]
        n = min(
            (records[token][0] if token in records else 0)
            for records in per_rank
        )
        if n < 1:
            continue
        rows_min = min(h[1] for h in hits)
        age_max = max(h[2] for h in hits)
        first_seq = min(h[3] for h in hits)
        if force or age_max >= max_latency_us or rows_min >= max_batch_rows:
            chosen.append((first_seq, token, n))
            planned_total += n
    chosen.sort()  # global FIFO: oldest first_seq dispatches first

    # the call runs only when this plan empties EVERY rank's pre-call
    # segment (identical segments when all ranks hold the call; the
    # equality check catches BUCKET_CAP overflow, which defers the call
    # one tick while the oldest keys drain)
    run_call = have_call_all and bool(
        (frames[:, H_NPEND] == planned_total).all()
    )
    quit_ = closed_all and bool((frames[:, H_QLEN] == 0).all())

    monitor_tick = bool((frames[:, H_MON_DUE] == 1).all())
    mon_failed: Tuple[int, ...] = ()
    mon_ewmas: Tuple[Tuple[int, int], ...] = ()
    if monitor_tick:
        mon_failed = tuple(sorted({
            int(d) for d in frames[:, _F_OFF:_F_OFF + MON_FAIL_CAP].ravel()
            if d >= 0
        }))
        merged: Dict[int, int] = {}
        for frame in frames:  # rank order, matching the health frame's merge
            pairs = frame[_E_OFF:].reshape(MON_EWMA_CAP, 2)
            for dev, us in pairs:
                if dev >= 0:
                    merged[int(dev)] = int(us)
        mon_ewmas = tuple(sorted(merged.items()))
    grow_pressure = monitor_tick and bool(
        (frames[:, H_VOTE_PRESSURE] == 1).any()
    )
    grow_ready = monitor_tick and bool((frames[:, H_VOTE_READY] == 1).any())

    return TickPlan(
        dispatch=tuple((token, n) for _, token, n in chosen),
        shed=shed,
        run_call=run_call,
        quit=quit_,
        monitor_tick=monitor_tick,
        mon_failed=mon_failed,
        mon_ewmas_us=mon_ewmas,
        grow_pressure=grow_pressure,
        grow_ready=grow_ready,
    )
