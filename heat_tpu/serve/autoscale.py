"""Queue-driven elastic autoscaling policy for the resident service.

ROADMAP item 1's missing serving piece: grow/shrink the mesh as
``queue_depth`` moves, without dropping in-flight requests. The policy
lives here; the *mechanism* is the PR 16/17 degrade machinery
(:func:`~heat_tpu.resilience.degrade.shrink_to_healthy` /
:func:`~heat_tpu.resilience.degrade.grow_to_healthy`) applied by the
``ServeService`` dispatcher — which consults :meth:`Autoscaler.consult`
strictly BETWEEN batches, never mid-batch, so a scale event can
invalidate compiled-program caches but never a request.

Decision ladder, evaluated once per monitor tick (the
:class:`~heat_tpu.resilience.monitor.HealthMonitor` owns the cadence,
replicated at ws>1, so every rank decides together):

1. the tick **degraded** a device → ``"shrink"``, immediately — a
   proactive shrink beats waiting for the device to poison a dispatch;
   safety ignores hysteresis and cooldown;
2. the tick **healed** a device (it survived flap damping) → ``"grow"``
   when capacity is actually below the base set; cooldown applies, and
   a grow deferred by cooldown fires at a later tick;
3. **queue pressure** — ``queue_depth`` above ``high_depth`` for
   ``hysteresis`` consecutive ticks (the streak resets only when depth
   falls back to ``low_depth``: the classic band, so depth oscillating
   inside the band neither arms nor disarms) → ``"grow"`` when healed
   capacity is available and cooldown has elapsed.

Under multiple controllers the instantaneous queue depth is
rank-divergent (each rank's clients race its dispatcher differently),
so the final grow verdict is laundered through ONE
:func:`~heat_tpu.core.communication.replicated_decision` per tick —
every rank grows together or not at all; shrink needs no extra
collective because the monitor's degrade verdicts are already
replicated.

The cache-invalidation contract (docs/SERVING.md): any scale event
rebuilds the default mesh, so every program compiled for the old mesh
is dead — the dispatcher clears its warm-bucket set and elastically
relocates the resident registry, exactly like the PR 16 shrink rung.
Scale activity is counted in ``SERVE_STATS``
(``grows``/``shrinks``/``scale_events``); the steady-state warm path
performs zero scale events and zero compiles (``bench.py`` gates both).
"""
from __future__ import annotations

import time
from typing import Optional

import jax

from ..core.communication import replicated_decision, sanitize_comm
from ..resilience import degrade
from ..resilience.monitor import HealthMonitor

__all__ = ["Autoscaler"]


class Autoscaler:
    """Target queue-depth band + hysteresis + cooldown scaling policy.

    Parameters
    ----------
    monitor : HealthMonitor
        Owns the probe cadence and the health verdicts; its ``base``
        communicator defines full capacity.
    high_depth : int
        Upper edge of the target queue-depth band: depth above this
        arms the pressure streak.
    low_depth : int
        Lower edge: depth at or below this resets the streak.
    hysteresis : int
        Consecutive over-pressure ticks required before a pressure grow
        (damping, so one burst never scales).
    cooldown_s : float
        Minimum seconds between grows (scale-up storms); shrinks are
        safety-driven and never wait.
    clock : callable
        Monotonic time source (injectable for tests).
    """

    def __init__(
        self,
        monitor: HealthMonitor,
        *,
        high_depth: int = 8,
        low_depth: int = 2,
        hysteresis: int = 2,
        cooldown_s: float = 0.0,
        clock=time.monotonic,
    ):
        if high_depth < 1:
            raise ValueError(f"high_depth must be >= 1, got {high_depth}")
        if not 0 <= low_depth <= high_depth:
            raise ValueError(
                f"need 0 <= low_depth <= high_depth, got "
                f"low={low_depth} high={high_depth}"
            )
        if hysteresis < 1:
            raise ValueError(f"hysteresis must be >= 1, got {hysteresis}")
        if cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {cooldown_s}")
        self.monitor = monitor
        self.high_depth = int(high_depth)
        self.low_depth = int(low_depth)
        self.hysteresis = int(hysteresis)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._pressure = 0            # consecutive over-high-watermark ticks
        self._deferred_heal = False   # a heal grow blocked by cooldown
        self._last_grow: float = -1.0

    # ------------------------------------------------------------- policy
    def consult(self, queue_depth: int) -> Optional[str]:
        """One dispatcher consultation (between batches): runs the
        monitor's ``maybe_tick`` and returns ``"shrink"``, ``"grow"`` or
        ``None``. Off tick boundaries this is a single replicated bool
        at ws>1 and pure arithmetic at ws==1."""
        report = self.monitor.maybe_tick()
        if report is None:
            return None
        if report.degraded:
            # replicated fact: every rank shrinks with no extra rendezvous
            return self.resolve(False, report)
        want_grow = self.vote(queue_depth, report)
        # ONE symmetric rendezvous per tick: pressure streaks and
        # cooldown clocks are rank-local, the executed action must not be
        want_grow = replicated_decision(
            want_grow, active=jax.process_count() > 1
        )
        return self.resolve(want_grow, report)

    def vote(self, queue_depth: int, report) -> bool:
        """The rank-local half of a tick consultation: fold this tick's
        queue depth into the pressure streak and return this rank's grow
        vote — NO collective. ``consult`` composes this with one
        ``replicated_decision`` and :meth:`resolve`."""
        if report.degraded:
            return False  # resolve() shrinks regardless of votes
        pressure, ready = self.pre_vote(queue_depth)
        return pressure or (bool(report.healed) and ready)

    def pre_vote(self, queue_depth: int) -> tuple:
        """The report-FREE rank-local half, for piggybacking on a frame
        exchanged before this tick's health report exists (the serve
        dispatch tick). Folds ``queue_depth`` into the pressure streak
        and returns ``(pressure_vote, capacity_ready)``:

        - ``pressure_vote`` — this rank wants a grow on its own merits
          (pressure streak armed, or a deferred heal pending), capacity
          and cooldown permitting;
        - ``capacity_ready`` — capacity is below base and cooldown has
          elapsed, so a *heal* reported by the gathered frames should
          grow.

        The gathered verdict ``OR(pressure_vote) or (healed and
        OR(capacity_ready))`` equals ``OR`` over ranks of :meth:`vote`
        because heal/degrade facts are rank-uniform."""
        if queue_depth > self.high_depth:
            self._pressure += 1
        elif queue_depth <= self.low_depth:
            self._pressure = 0
        cooled = (
            self._last_grow < 0
            or (self._clock() - self._last_grow) >= self.cooldown_s
        )
        ready = self._capacity_below_base() and cooled
        pressure = ready and (
            self._deferred_heal or self._pressure >= self.hysteresis
        )
        return (pressure, ready)

    def resolve(self, want_grow: bool, report) -> Optional[str]:
        """The replicated half: apply an already-rendezvoused grow
        verdict (identical on every rank by the caller's contract) plus
        the tick report's degrade/heal facts, and return the action."""
        if report.degraded:
            # safety first: reset pressure so the post-shrink queue
            # build-up must re-arm the band from scratch
            self._pressure = 0
            return "shrink"
        if want_grow:
            self._pressure = 0
            self._deferred_heal = False
            self._last_grow = self._clock()
            return "grow"
        if report.healed and self._capacity_below_base():
            self._deferred_heal = True  # cooldown blocked it; retry later
        return None

    def _capacity_below_base(self) -> bool:
        """Is the current default mesh smaller than the healthy subset
        of the monitored base set (i.e. is there anything to grow onto)?
        Derived from replicated state, hence rank-identical."""
        comm = sanitize_comm(None)
        return comm.size < len(degrade.healthy_devices(self.monitor.base))
