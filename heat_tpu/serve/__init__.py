"""heat_tpu.serve — resident multi-tenant serving over the SPMD mesh.

The rest of this tree is script-shaped: a program owns the mesh, runs,
and exits, paying trace/compile cost every launch. This package keeps
the mesh (and every compiled program) RESIDENT: one
:class:`~heat_tpu.serve.service.ServeService` holds named fitted
estimators on-device (:class:`~heat_tpu.serve.session.ModelRegistry`),
routes concurrent client requests through an async queue, and batches
them by shape bucket (:mod:`~heat_tpu.serve.batching`) so unrelated
clients share one sharded dispatch — warm requests replay cached
programs only: 1 dispatch / 0 traces / 0 compiles.

Counters live in :data:`SERVE_STATS` (re-exported as
``heat_tpu.SERVE_STATS``), fed through the same
:mod:`heat_tpu.core._hooks` observer slot as LAYOUT/MOVE/COMPILE/FUSE/
STREAM/KERNEL_STATS. See docs/SERVING.md for the architecture, the
bucket-policy latency/throughput model, and the multi-controller
lockstep contract.
"""
from ..resilience.errors import (
    PoisonRequestError,
    ServeDeadlineError,
    ServeError,
    ServeOverloadError,
)
from ._stats import SERVE_STATS, refresh_latency_stats, reset_serve_stats
from .autoscale import Autoscaler
from .batching import BucketPolicy, PendingBatch
from .service import DEFAULT_DISPATCH_POLICY, Request, ServeService
from .session import ModelRegistry
from .tick import TickPlan, plan_dispatch

__all__ = [
    "SERVE_STATS",
    "refresh_latency_stats",
    "reset_serve_stats",
    "Autoscaler",
    "BucketPolicy",
    "PendingBatch",
    "TickPlan",
    "plan_dispatch",
    "Request",
    "ServeService",
    "ModelRegistry",
    "DEFAULT_DISPATCH_POLICY",
    "ServeError",
    "ServeOverloadError",
    "ServeDeadlineError",
    "PoisonRequestError",
]
