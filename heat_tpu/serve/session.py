"""Resident model registry: named fitted estimators held on-device.

A service keeps one :class:`ModelRegistry` alive for its lifetime. Every
estimator in this tree that can serve (``KMeans``, ``Lasso``, the
streaming accumulators, anything with sklearn-style methods) registers
under a name; endpoints then close over the registry entry, so a
re-``register`` (model refresh) swaps what subsequent batches see
without touching compiled programs — bucketed input shapes, not model
identity, key the caches.

Snapshots ride the PR 6 checkpoint layer: each ``state_dict()`` array
entry becomes a sharded checkpoint directory written by
:func:`heat_tpu.resilience.save_checkpoint` (checksummed shards, atomic
manifest commit, multi-process correct), and the scalar remainder goes
into one JSON manifest committed via the single-writer + barrier pattern
from :mod:`heat_tpu.core.io`. Restore is the mirror image and lands on
the CURRENT mesh, so a snapshot taken before an elastic shrink restores
onto whatever the supervisor left healthy.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Iterable, List, Optional

import numpy as np

from ..core import io as core_io
from ..core.dndarray import DNDarray
from ..resilience import load_checkpoint, save_checkpoint
from ..resilience.checkpoint import _replicated_raise

__all__ = ["ModelRegistry"]

_MANIFEST = "registry.json"


class ModelRegistry:
    """Thread-safe name -> estimator map with checkpoint snapshots."""

    def __init__(self):
        self._models: Dict[str, object] = {}
        self._lock = threading.RLock()

    # ------------------------------------------------------------- registry
    def register(self, name: str, model) -> None:
        """Install (or replace) ``model`` under ``name``."""
        if not name or "/" in name:
            raise ValueError(f"invalid model name: {name!r}")
        with self._lock:
            self._models[name] = model

    def get(self, name: str):
        with self._lock:
            try:
                return self._models[name]
            except KeyError:
                raise KeyError(
                    f"no model registered under {name!r}; "
                    f"known: {sorted(self._models)}"
                ) from None

    def remove(self, name: str) -> None:
        with self._lock:
            self._models.pop(name, None)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._models)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._models

    # ------------------------------------------------------------ snapshots
    def snapshot(self, directory: str) -> str:
        """Write every registered model's ``state_dict`` under
        ``directory`` (one subdirectory per model, one checkpoint per
        array entry). Models without a ``state_dict`` are skipped —
        they are listed in the manifest so ``restore`` can report them.
        Returns the manifest path."""
        with self._lock:
            items = list(self._models.items())
        manifest: Dict[str, dict] = {}
        for name, model in items:
            state_fn = getattr(model, "state_dict", None)
            if state_fn is None:
                manifest[name] = {"skipped": "no state_dict"}
                continue
            state = state_fn()
            scalars, arrays = {}, []
            for key, value in state.items():
                if isinstance(value, DNDarray):
                    value = value.numpy()
                if isinstance(value, np.ndarray):
                    save_checkpoint(
                        DNDarray(value, split=None),
                        os.path.join(directory, name, key),
                    )
                    arrays.append(key)
                else:
                    scalars[key] = value
            manifest[name] = {"scalars": scalars, "arrays": arrays}
        path = os.path.join(directory, _MANIFEST)

        def write():
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True, default=str)
            os.replace(tmp, path)

        core_io._single_writer_commit("serve.registry_manifest", write)
        return path

    def restore(self, directory: str, names: Optional[Iterable[str]] = None) -> List[str]:
        """Load a :meth:`snapshot` back into the CURRENTLY registered
        models (each must already be registered — the snapshot stores
        state, not code). Returns the list of restored names."""
        path = os.path.join(directory, _MANIFEST)
        # the manifest read is rank-LOCAL (plain open on a shared path):
        # if it fails on one process only, that process must not desert
        # the load_checkpoint collectives below — gather the per-rank
        # status first and raise on EVERY rank together (the failing
        # rank its real error, peers a CheckpointError naming it)
        manifest, err = None, None
        try:
            core_io._check_path_visible(path)
            with open(path) as f:
                manifest = json.load(f)
        except Exception as exc:  # noqa: BLE001 - re-raised symmetrically
            err = exc
        _replicated_raise("registry restore", err)
        wanted = set(names) if names is not None else None
        restored: List[str] = []
        # graftflow: F003 - manifest is the single-writer-committed shared
        # snapshot (visibility barriered above), identical on every rank
        for name, entry in manifest.items():
            if wanted is not None and name not in wanted:
                continue
            if "skipped" in entry or name not in self:
                continue
            state = dict(entry["scalars"])
            # graftflow: F003 - same shared manifest, replicated iterable
            for key in entry["arrays"]:
                state[key] = load_checkpoint(
                    os.path.join(directory, name, key)
                ).numpy()
            self.get(name).load_state_dict(state)
            restored.append(name)
        return restored
