"""Shape-bucketed batching policy for the resident service.

The entire warm-replay story hangs on SHAPE STABILITY: every compiled
program in this tree — eager op chains, fused lazy programs, kernel-layer
panels — is cached by the physical shapes of its inputs, so a service
that dispatched each request at its natural row count would retrace on
every novel batch size and never go warm. The bucket policy rounds every
batch up to a small fixed menu of row counts (powers of two by default):
after one cold pass per (endpoint, bucket) the service replays cached
programs only — 1 dispatch / 0 traces / 0 compiles, Region-asserted in
the tests and the bench worker.

The padding contract: endpoints must be ROW-WISE maps (output row ``i``
depends only on input row ``i`` plus resident model state — predict,
transform, kNN queries, captured pipelines all qualify). Dead padded
rows then produce dead output rows, which the service slices away when
scattering results back to requests; no endpoint ever sees which rows
were padding. Row-coupled programs (a global ``fit``, a reduction over
the batch) must go through ``submit_call``, which runs them unbatched.
"""
from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["BucketPolicy", "PendingBatch", "form_plan_batches"]

# power-of-two menu: small enough that a handful of cold dispatches
# covers all of it, dense enough that padding waste stays under 2x
DEFAULT_EDGES: Tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


class BucketPolicy:
    """Pad-to-bucket policy with max-batch and max-latency triggers.

    Parameters
    ----------
    edges : sequence of int
        Sorted menu of batch row counts; a batch of ``n`` real rows pads
        up to the smallest edge >= ``n`` (beyond the last edge: the next
        power of two, so oversized batches stay shape-stable too).
    max_batch : int
        Flush a pending group as soon as it holds this many real rows.
    max_latency_ms : float
        Flush a non-full group once its oldest request has waited this
        long. Both the timer and the count trigger consult rank-local
        state (a wall clock; this rank's queue view), so with multiple
        controllers they are never evaluated directly — the replicated
        dispatch tick (:mod:`heat_tpu.serve.tick`) exchanges the
        underlying numbers in a fixed-width frame and re-derives both
        triggers from the gathered, rank-identical view (max-over-ranks
        age, min-over-ranks rows). ``max_latency_ms`` also sets the
        default tick cadence (see docs/SERVING.md).
    """

    def __init__(
        self,
        edges: Sequence[int] = DEFAULT_EDGES,
        max_batch: int = 32,
        max_latency_ms: float = 2.0,
    ):
        if not edges:
            raise ValueError("edges must be non-empty")
        self.edges = tuple(sorted(int(e) for e in edges))
        if self.edges[0] < 1:
            raise ValueError("edges must be >= 1")
        self.max_batch = int(max_batch)
        if self.max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.max_latency_ms = float(max_latency_ms)

    def bucket_rows(self, rows: int) -> int:
        """Padded row count for a batch of ``rows`` real rows."""
        if rows < 1:
            raise ValueError("a batch needs at least one row")
        for e in self.edges:
            if rows <= e:
                return e
        bucket = self.edges[-1]
        while bucket < rows:
            bucket *= 2
        return bucket

    def pad(self, stacked: np.ndarray) -> np.ndarray:
        """Zero-pad ``stacked`` along axis 0 up to its bucket."""
        bucket = self.bucket_rows(stacked.shape[0])
        if bucket == stacked.shape[0]:
            return stacked
        pad = [(0, bucket - stacked.shape[0])] + [(0, 0)] * (stacked.ndim - 1)
        return np.pad(stacked, pad)


class PendingBatch:
    """Requests for one (endpoint, row signature) awaiting dispatch.

    ``key`` is ``(endpoint, per-row shape, dtype)`` — only requests whose
    rows stack into one array share a batch. ``born`` is the enqueue time
    of the OLDEST member (the latency trigger watches it)."""

    __slots__ = ("key", "requests", "rows", "born")

    def __init__(self, key):
        self.key = key
        self.requests: List = []
        self.rows = 0
        self.born: Optional[float] = None

    def add(self, request) -> None:
        if self.born is None:
            self.born = request.enqueue_t
        self.requests.append(request)
        self.rows += request.rows

    def age_ms(self, now: Optional[float] = None) -> float:
        if self.born is None:
            return 0.0
        return ((now if now is not None else time.monotonic()) - self.born) * 1e3

    def stack(self, policy: BucketPolicy) -> np.ndarray:
        """One bucket-padded array holding every member's rows in
        request order."""
        stacked = np.concatenate([r.payload for r in self.requests], axis=0)
        return policy.pad(stacked)


def form_plan_batches(key, requests, max_batch: int) -> List[PendingBatch]:
    """Split a tick plan's request prefix for one bucket key into
    dispatchable batches, capped at ``max_batch`` real rows each — a
    burst becomes several batches in the SAME warm bucket rather than
    one batch in a novel (cold) oversized bucket; a single over-large
    request still dispatches alone. Pure request-order arithmetic over
    plan-selected inputs, so every rank forms the identical batch
    sequence."""
    batches: List[PendingBatch] = []
    current: Optional[PendingBatch] = None
    for request in requests:
        if (
            current is None
            or (current.rows and current.rows + request.rows > max_batch)
        ):
            current = PendingBatch(key)
            batches.append(current)
        current.add(request)
    return batches
