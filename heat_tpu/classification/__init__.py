"""Classification (reference ``heat/classification/``)."""
from .kneighborsclassifier import KNeighborsClassifier
