"""k-nearest-neighbors classifier (reference
``heat/classification/kneighborsclassifier.py``).

fit stores the training set; predict is a fused sharded program: distance
matrix on the MXU -> ``lax.top_k`` of the negated distances -> one-hot
vote (reference ``kneighborsclassifier.py:10-136``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray
from ..spatial.distance import _quadratic_expand

__all__ = ["KNeighborsClassifier"]


def one_hot_encoding(y: jnp.ndarray, classes: jnp.ndarray) -> jnp.ndarray:
    """One-hot over an arbitrary class alphabet (reference
    ``kneighborsclassifier.py:45``)."""
    return (y[:, None] == classes[None, :]).astype(jnp.float32)


class KNeighborsClassifier(BaseEstimator, ClassificationMixin):
    """reference ``kneighborsclassifier.py:10``"""

    def __init__(self, n_neighbors: int = 5):
        self.n_neighbors = n_neighbors
        self.x = None
        self.y = None
        self.classes_ = None

    def fit(self, x: DNDarray, y: DNDarray) -> "KNeighborsClassifier":
        """Store the training set (reference ``kneighborsclassifier.py``)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError(f"input needs to be DNDarrays, but were {type(x)}, {type(y)}")
        self.x = x
        self.y = y
        self.classes_ = jnp.unique(y._logical().ravel())
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """reference ``kneighborsclassifier.py:predict``"""
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        yt = self.y._logical().ravel()
        nq, nt = x.shape[0], self.x.shape[0]
        from ..core.kernels import pallas_supported
        from ..spatial.distance import nearest_neighbors

        # the fused kernel's merge is O(k*(k+tile_m)) per tile — past k~64
        # the materializing cdist+top_k path wins, so gate on k as well
        if (
            pallas_supported()
            and nq * nt > 1 << 22
            and x.split in (None, 0)
            and self.n_neighbors <= 64
        ):
            # fused pallas path: never materializes the (nq, nt) matrix
            _, idx_nd = nearest_neighbors(x, self.x, self.n_neighbors)
            idx = idx_nd._logical()
        else:
            from ..core.kernels import record_dispatch

            record_dispatch("topk_distance", "fallback")
            Xq = x._logical().astype(jnp.float32)
            Xt = self.x._logical().astype(jnp.float32)
            d2 = _quadratic_expand(Xq, Xt)  # (nq, nt)
            _, idx = jax.lax.top_k(-d2, self.n_neighbors)  # (nq, k) nearest
        neigh_labels = jnp.take(yt, idx)  # (nq, k)
        votes = jnp.sum(
            one_hot_encoding(neigh_labels.ravel(), self.classes_).reshape(
                idx.shape[0], self.n_neighbors, -1
            ),
            axis=1,
        )  # (nq, n_classes)
        pred = jnp.take(self.classes_, jnp.argmax(votes, axis=1))
        return DNDarray(pred, split=x.split, device=x.device, comm=x.comm)
