// Native IDX (MNIST) binary reader for heat_tpu.
//
// The reference loads MNIST through torchvision's Python IDX reader
// (reference heat/utils/data/mnist.py:16 builds on
// torchvision.datasets.MNIST).  This native reader parses the IDX
// header (magic: two zero bytes, a dtype code, and ndims, followed by
// big-endian uint32 dims) and bulk-copies the payload, byte-swapping
// multi-byte types to little-endian host order.
//
// dtype codes (IDX spec): 0x08 u8, 0x09 i8, 0x0B i16, 0x0C i32,
// 0x0D f32, 0x0E f64.
#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

int type_size(int32_t code) {
  switch (code) {
    case 0x08:
    case 0x09:
      return 1;
    case 0x0B:
      return 2;
    case 0x0C:
    case 0x0D:
      return 4;
    case 0x0E:
      return 8;
    default:
      return 0;
  }
}

uint32_t be32(const unsigned char *p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

void byteswap(void *buf, int64_t count, int width) {
  unsigned char *p = static_cast<unsigned char *>(buf);
  for (int64_t i = 0; i < count; ++i, p += width)
    for (int j = 0; j < width / 2; ++j) {
      unsigned char t = p[j];
      p[j] = p[width - 1 - j];
      p[width - 1 - j] = t;
    }
}

}  // namespace

extern "C" {

// Fills dims[0..7], *ndims, *dtype_code. Returns 0 or negative error.
int64_t ht_idx_header(const char *path, int64_t *dims, int64_t *ndims,
                      int32_t *dtype_code) {
  if (!path || !dims || !ndims || !dtype_code) return -4;
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  unsigned char hdr[4];
  if (fread(hdr, 1, 4, f) != 4 || hdr[0] != 0 || hdr[1] != 0) {
    fclose(f);
    return -2;
  }
  int32_t code = hdr[2];
  int nd = hdr[3];
  if (type_size(code) == 0 || nd <= 0 || nd > 8) {
    fclose(f);
    return -2;
  }
  for (int i = 0; i < nd; ++i) {
    unsigned char d[4];
    if (fread(d, 1, 4, f) != 4) {
      fclose(f);
      return -2;
    }
    dims[i] = be32(d);
  }
  *ndims = nd;
  *dtype_code = code;
  fclose(f);
  return 0;
}

// Reads the payload into out (host little-endian order). out_bytes must
// equal prod(dims) * type_size. Returns 0 or negative error.
int64_t ht_idx_read(const char *path, void *out, int64_t out_bytes) {
  if (!path || !out || out_bytes < 0) return -4;
  int64_t dims[8];
  int64_t nd;
  int32_t code;
  int64_t rc = ht_idx_header(path, dims, &nd, &code);
  if (rc != 0) return rc;
  int width = type_size(code);
  int64_t count = 1;
  for (int64_t i = 0; i < nd; ++i) count *= dims[i];
  if (count * width != out_bytes) return -3;
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  if (fseek(f, 4 + 4 * static_cast<long>(nd), SEEK_SET) != 0) {
    fclose(f);
    return -1;
  }
  if (static_cast<int64_t>(fread(out, 1, out_bytes, f)) != out_bytes) {
    fclose(f);
    return -2;
  }
  fclose(f);
  if (width > 1) byteswap(out, count, width);
  return 0;
}

}  // extern "C"
