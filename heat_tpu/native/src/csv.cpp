// Native CSV parser for heat_tpu.
//
// TPU-native replacement for the reference's per-rank Python byte-range CSV
// parser (reference heat/core/io.py:713 `load_csv`, which splits the file by
// byte offsets and parses lines with Python `float()`).  Here the whole file
// is mmap'ed once, row boundaries are found with memchr, and rows are parsed
// in parallel with std::from_chars into a caller-provided numeric buffer.
//
// C ABI (ctypes-friendly), all functions return 0 on success or a negative
// error code:
//   -1 open/stat/mmap failure        -2 malformed number
//   -3 inconsistent column count     -4 bad arguments
#include <atomic>
#include <charconv>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Mapped {
  const char *data = nullptr;
  size_t size = 0;
  int fd = -1;
};

bool map_file(const char *path, Mapped &m) {
  m.fd = ::open(path, O_RDONLY);
  if (m.fd < 0) return false;
  struct stat st;
  if (::fstat(m.fd, &st) != 0) {
    ::close(m.fd);
    return false;
  }
  m.size = static_cast<size_t>(st.st_size);
  if (m.size == 0) {
    m.data = nullptr;
    return true;
  }
  void *p = ::mmap(nullptr, m.size, PROT_READ, MAP_PRIVATE, m.fd, 0);
  if (p == MAP_FAILED) {
    ::close(m.fd);
    return false;
  }
  m.data = static_cast<const char *>(p);
  return true;
}

void unmap_file(Mapped &m) {
  if (m.data) ::munmap(const_cast<char *>(m.data), m.size);
  if (m.fd >= 0) ::close(m.fd);
}

struct Line {
  const char *begin;
  const char *end;  // exclusive, '\r' already trimmed
};

// Collect non-empty data lines after skipping `header_lines`.
void collect_lines(const char *data, size_t size, int64_t header_lines,
                   std::vector<Line> &lines) {
  const char *p = data;
  const char *limit = data + size;
  for (int64_t h = 0; h < header_lines && p < limit; ++h) {
    const char *nl = static_cast<const char *>(memchr(p, '\n', limit - p));
    p = nl ? nl + 1 : limit;
  }
  while (p < limit) {
    const char *nl = static_cast<const char *>(memchr(p, '\n', limit - p));
    const char *end = nl ? nl : limit;
    const char *trimmed = end;
    while (trimmed > p && (trimmed[-1] == '\r' || trimmed[-1] == ' '))
      --trimmed;
    if (trimmed > p) lines.push_back({p, trimmed});
    p = nl ? nl + 1 : limit;
  }
}

// Collect the data lines OWNED by the byte range [offset, offset+length)
// (file-absolute offsets; pass length < 0 for "to EOF").  Header lines are
// skipped first.  Ownership follows the reference's per-rank byte-range
// convention (reference heat/core/io.py:713-924): a line belongs to the
// range containing its FIRST byte, and its owner parses it to the end even
// when it straddles the range boundary — so ranges that partition the file
// yield disjoint, covering row sets.
void collect_lines_range(const char *data, size_t size, int64_t header_lines,
                         int64_t offset, int64_t length,
                         std::vector<Line> &lines) {
  const char *p = data;
  const char *limit = data + size;
  for (int64_t h = 0; h < header_lines && p < limit; ++h) {
    const char *nl = static_cast<const char *>(memchr(p, '\n', limit - p));
    p = nl ? nl + 1 : limit;
  }
  if (offset < 0) offset = 0;
  const char *lo = data + (static_cast<size_t>(offset) > size
                               ? size
                               : static_cast<size_t>(offset));
  const char *hi = limit;
  if (length >= 0 && static_cast<size_t>(offset) + static_cast<size_t>(length) < size)
    hi = data + offset + length;
  if (p < lo) {
    // first owned line begins at the first byte after a '\n' at or past
    // lo-1 (data[lo-1]=='\n' means a line starts exactly at lo)
    const char *scan = lo - 1;
    const char *nl = static_cast<const char *>(memchr(scan, '\n', limit - scan));
    p = nl ? nl + 1 : limit;
  }
  while (p < limit && p < hi) {
    const char *nl = static_cast<const char *>(memchr(p, '\n', limit - p));
    const char *end = nl ? nl : limit;
    const char *trimmed = end;
    while (trimmed > p && (trimmed[-1] == '\r' || trimmed[-1] == ' '))
      --trimmed;
    if (trimmed > p) lines.push_back({p, trimmed});
    p = nl ? nl + 1 : limit;
  }
}

int64_t count_fields(const Line &ln, char sep) {
  int64_t n = 1;
  for (const char *p = ln.begin; p < ln.end; ++p)
    if (*p == sep) ++n;
  return n;
}

// Parse one row into out[0..cols); returns 0, -2 or -3.
template <typename T>
int parse_row(const Line &ln, char sep, T *out, int64_t cols) {
  const char *p = ln.begin;
  for (int64_t c = 0; c < cols; ++c) {
    const char *fend = static_cast<const char *>(
        memchr(p, sep, ln.end - p));
    if (!fend) fend = ln.end;
    if (c == cols - 1 && fend != ln.end) return -3;  // too many fields
    if (c < cols - 1 && fend == ln.end) return -3;   // too few fields
    while (p < fend && (*p == ' ' || *p == '\t')) ++p;
    const char *vend = fend;
    while (vend > p && (vend[-1] == ' ' || vend[-1] == '\t')) --vend;
    // std::from_chars rejects an explicit leading '+', which Python's
    // float() (the reference parser, heat/core/io.py:800) accepts; skip it.
    // Underscore numerals ("1_5") still return -2 here and reach the
    // Python fallback, whose last-resort per-field float() pass
    // (core/io.py load_csv) parses them like the reference
    if (p + 1 < vend && *p == '+' && *(p + 1) != '-') ++p;
    double v;
    auto res = std::from_chars(p, vend, v);
    if (res.ec != std::errc() || res.ptr != vend) return -2;
    if (v != v) {
      // from_chars accepts "nan(123)" but Python float() raises on the
      // parenthesized form; divert it so native never parses what the
      // reference rejects (bare "nan" stays accepted — float() takes it)
      for (const char *q = p; q < vend; ++q)
        if (*q == '(') return -2;
    }
    out[c] = static_cast<T>(v);
    p = fend + 1;
  }
  return 0;
}

template <typename T>
int64_t parse_all(const std::vector<Line> &lines, char sep, T *out,
                  int64_t rows, int64_t cols, int32_t nthreads) {
  if (static_cast<int64_t>(lines.size()) != rows) return -3;
  if (nthreads < 1) nthreads = 1;
  int64_t hw = static_cast<int64_t>(std::thread::hardware_concurrency());
  if (hw > 0 && nthreads > hw) nthreads = static_cast<int32_t>(hw);
  if (nthreads > rows) nthreads = rows > 0 ? static_cast<int32_t>(rows) : 1;
  std::atomic<int> err{0};
  auto work = [&](int64_t r0, int64_t r1) {
    for (int64_t r = r0; r < r1 && err.load(std::memory_order_relaxed) == 0;
         ++r) {
      int rc = parse_row(lines[r], sep, out + r * cols, cols);
      if (rc != 0) err.store(rc, std::memory_order_relaxed);
    }
  };
  if (nthreads == 1) {
    work(0, rows);
  } else {
    std::vector<std::thread> ts;
    int64_t per = (rows + nthreads - 1) / nthreads;
    for (int32_t t = 0; t < nthreads; ++t) {
      int64_t r0 = t * per;
      int64_t r1 = std::min(rows, r0 + per);
      if (r0 >= r1) break;
      ts.emplace_back(work, r0, r1);
    }
    for (auto &t : ts) t.join();
  }
  return err.load();
}

}  // namespace

extern "C" {

int64_t ht_csv_dims(const char *path, int64_t header_lines, char sep,
                    int64_t *rows, int64_t *cols) {
  if (!path || !rows || !cols) return -4;
  Mapped m;
  if (!map_file(path, m)) return -1;
  std::vector<Line> lines;
  if (m.data) collect_lines(m.data, m.size, header_lines, lines);
  *rows = static_cast<int64_t>(lines.size());
  *cols = lines.empty() ? 0 : count_fields(lines.front(), sep);
  unmap_file(m);
  return 0;
}

// Handle-based one-pass API: mmap + line index built once, reused by the
// parse call so large files are not scanned twice for dims then data.
struct CsvHandle {
  Mapped m;
  std::vector<Line> lines;
  int64_t cols = 0;
};

void *ht_csv_open(const char *path, int64_t header_lines, char sep,
                  int64_t *rows, int64_t *cols) {
  if (!path || !rows || !cols) return nullptr;
  CsvHandle *h = new CsvHandle();
  if (!map_file(path, h->m)) {
    delete h;
    return nullptr;
  }
  if (h->m.data) collect_lines(h->m.data, h->m.size, header_lines, h->lines);
  h->cols = h->lines.empty() ? 0 : count_fields(h->lines.front(), sep);
  *rows = static_cast<int64_t>(h->lines.size());
  *cols = h->cols;
  return h;
}

// Range variant of ht_csv_open: only the lines owned by byte range
// [offset, offset+length) are indexed (length < 0 -> to EOF).  The handle
// feeds the same ht_csv_parse_h / ht_csv_close.
void *ht_csv_open_range(const char *path, int64_t header_lines, char sep,
                        int64_t offset, int64_t length, int64_t *rows,
                        int64_t *cols) {
  if (!path || !rows || !cols) return nullptr;
  CsvHandle *h = new CsvHandle();
  if (!map_file(path, h->m)) {
    delete h;
    return nullptr;
  }
  if (h->m.data)
    collect_lines_range(h->m.data, h->m.size, header_lines, offset, length,
                        h->lines);
  h->cols = h->lines.empty() ? 0 : count_fields(h->lines.front(), sep);
  *rows = static_cast<int64_t>(h->lines.size());
  *cols = h->cols;
  return h;
}

int64_t ht_csv_parse_h(void *handle, char sep, int32_t dtype, void *out,
                       int64_t rows, int64_t cols, int32_t nthreads) {
  if (!handle || !out || rows < 0 || cols <= 0) return -4;
  CsvHandle *h = static_cast<CsvHandle *>(handle);
  if (dtype == 0)
    return parse_all(h->lines, sep, static_cast<float *>(out), rows, cols,
                     nthreads);
  if (dtype == 1)
    return parse_all(h->lines, sep, static_cast<double *>(out), rows, cols,
                     nthreads);
  return -4;
}

void ht_csv_close(void *handle) {
  if (!handle) return;
  CsvHandle *h = static_cast<CsvHandle *>(handle);
  unmap_file(h->m);
  delete h;
}

}  // extern "C"
