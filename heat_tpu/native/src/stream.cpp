// Native prefetching file-stream for heat_tpu's data pipeline.
//
// TPU-native counterpart of the reference's background-thread slab loader
// (reference heat/utils/data/partial_dataset.py:20 `queue_thread` +
// PartialH5DataLoaderIter:224, which overlap HDF5 reads with training in
// Python threads).  Here the producer is a real OS thread doing pread(2)
// into a ring of `depth` slab buffers while the consumer (Python, via
// ctypes) drains them — IO overlaps compute without holding the GIL.
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Slab {
  std::vector<char> buf;
  int64_t len = 0;
};

struct Stream {
  int fd = -1;
  int64_t chunk = 0;
  int64_t remaining = 0;
  int64_t offset = 0;
  std::vector<Slab> ring;
  size_t head = 0, tail = 0, filled = 0;
  bool eof = false, stop = false;
  int64_t err = 0;
  std::mutex mu;
  std::condition_variable cv_prod, cv_cons;
  std::thread worker;

  void produce() {
    for (;;) {
      std::unique_lock<std::mutex> lk(mu);
      cv_prod.wait(lk, [&] { return stop || filled < ring.size(); });
      if (stop) return;
      if (remaining <= 0) {
        eof = true;
        cv_cons.notify_all();
        return;
      }
      Slab &s = ring[head];
      int64_t want = std::min(chunk, remaining);
      lk.unlock();
      int64_t got = 0;
      while (got < want) {
        ssize_t n = ::pread(fd, s.buf.data() + got, want - got, offset + got);
        if (n < 0) {
          std::lock_guard<std::mutex> lg(mu);
          err = -1;
          eof = true;
          cv_cons.notify_all();
          return;
        }
        if (n == 0) break;  // short file
        got += n;
      }
      lk.lock();
      s.len = got;
      offset += got;
      remaining = (got < want) ? 0 : remaining - got;
      head = (head + 1) % ring.size();
      ++filled;
      if (got == 0) eof = true;
      cv_cons.notify_all();
      if (eof) return;
    }
  }
};

}  // namespace

extern "C" {

// Opens a background-prefetched stream over [offset, offset+length) of path.
// chunk_bytes: slab size; depth: number of slabs read ahead.
// Returns an opaque handle or nullptr on failure.
void *ht_stream_open(const char *path, int64_t offset, int64_t length,
                     int64_t chunk_bytes, int32_t depth) {
  if (!path || offset < 0 || length < 0 || chunk_bytes <= 0 || depth <= 0)
    return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  Stream *s = new Stream();
  s->fd = fd;
  s->chunk = chunk_bytes;
  s->remaining = length;
  s->offset = offset;
  s->ring.resize(depth);
  for (auto &sl : s->ring) sl.buf.resize(chunk_bytes);
  s->worker = std::thread([s] { s->produce(); });
  return s;
}

// Copies the next slab into out (cap bytes available). Returns the number of
// bytes copied, 0 at end-of-stream, or a negative error code.
int64_t ht_stream_next(void *h, void *out, int64_t cap) {
  if (!h || !out) return -4;
  Stream *s = static_cast<Stream *>(h);
  std::unique_lock<std::mutex> lk(s->mu);
  s->cv_cons.wait(lk, [&] { return s->filled > 0 || s->eof; });
  // drain successfully-read slabs before surfacing a late pread error
  if (s->filled == 0) return s->err != 0 ? s->err : 0;
  Slab &sl = s->ring[s->tail];
  if (sl.len > cap) return -3;
  int64_t n = sl.len;
  memcpy(out, sl.buf.data(), n);
  s->tail = (s->tail + 1) % s->ring.size();
  --s->filled;
  s->cv_prod.notify_one();
  return n;
}

void ht_stream_close(void *h) {
  if (!h) return;
  Stream *s = static_cast<Stream *>(h);
  {
    std::lock_guard<std::mutex> lg(s->mu);
    s->stop = true;
  }
  s->cv_prod.notify_all();
  if (s->worker.joinable()) s->worker.join();
  ::close(s->fd);
  delete s;
}

}  // extern "C"
