"""Native (C++) runtime components for heat_tpu.

The reference delegates all native performance to libtorch kernels and the
MPI C library (SURVEY §2: pure-Python repo).  In the TPU-native rebuild the
compute path is XLA; this package supplies the *runtime* native layer around
it — parallel file parsing and background IO prefetch — compiled from
``src/*.cpp`` with g++ at first use and bound through :mod:`ctypes`.

Every entry point degrades gracefully: if the toolchain or the build is
unavailable (``HEAT_TPU_NO_NATIVE=1`` disables it outright), callers fall
back to their pure-Python paths.

Components
----------
- CSV parser (``src/csv.cpp``): mmap + multithreaded ``std::from_chars``,
  replacing the reference's Python byte-range parser
  (reference ``heat/core/io.py:713``).
- IDX reader (``src/idx.cpp``): MNIST-format binary loader
  (reference ``heat/utils/data/mnist.py:16``).
- Prefetch stream (``src/stream.cpp``): background pread(2) ring buffer,
  the native analogue of the reference's ``queue_thread`` slab loader
  (reference ``heat/utils/data/partial_dataset.py:20,224``).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

__all__ = [
    "available",
    "csv_dims",
    "csv_parse",
    "csv_parse_range",
    "idx_read",
    "FileStream",
]

_SRC_DIR = os.path.join(os.path.dirname(__file__), "src")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "_heat_native.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        names = os.listdir(_SRC_DIR)
    except OSError:
        # non-editable installs may ship without src/ — degrade to Python paths
        return os.path.exists(_LIB_PATH)
    sources = sorted(os.path.join(_SRC_DIR, f) for f in names if f.endswith(".cpp"))
    if not sources:
        return False
    newest_src = max(os.path.getmtime(s) for s in sources)
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= newest_src:
        return True
    # compile to a per-process temp name, then atomically rename: a
    # concurrent process must never dlopen a half-written library
    tmp = f"{_LIB_PATH}.tmp{os.getpid()}"
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-shared",
        "-fPIC",
        "-pthread",
        *sources,
        "-o",
        tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB_PATH)
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def _load() -> Optional[ctypes.CDLL]:
    """Build (if stale) and dlopen the native library; None on any failure."""
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        return None
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("HEAT_TPU_NO_NATIVE"):
            return None
        if not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        try:
            _bind_symbols(lib)
        except AttributeError:
            # stale prebuilt .so missing current symbols — degrade to Python
            return None
        _lib = lib
        return _lib


def _bind_symbols(lib: ctypes.CDLL) -> None:
    lib.ht_csv_dims.restype = ctypes.c_int64
    lib.ht_csv_dims.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.ht_csv_open.restype = ctypes.c_void_p
    lib.ht_csv_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.ht_csv_open_range.restype = ctypes.c_void_p
    lib.ht_csv_open_range.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.ht_csv_parse_h.restype = ctypes.c_int64
    lib.ht_csv_parse_h.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char,
        ctypes.c_int32,
        ctypes.c_void_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    lib.ht_csv_close.restype = None
    lib.ht_csv_close.argtypes = [ctypes.c_void_p]
    lib.ht_idx_header.restype = ctypes.c_int64
    lib.ht_idx_header.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int32),
    ]
    lib.ht_idx_read.restype = ctypes.c_int64
    lib.ht_idx_read.argtypes = [ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64]
    lib.ht_stream_open.restype = ctypes.c_void_p
    lib.ht_stream_open.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int32,
    ]
    lib.ht_stream_next.restype = ctypes.c_int64
    lib.ht_stream_next.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.ht_stream_close.restype = None
    lib.ht_stream_close.argtypes = [ctypes.c_void_p]


def available() -> bool:
    """True when the native library is (or can be) built and loaded."""
    return _load() is not None


def csv_dims(path: str, header_lines: int = 0, sep: str = ",") -> Optional[Tuple[int, int]]:
    """(rows, cols) of the CSV data region, or None if native is unavailable."""
    lib = _load()
    if lib is None or len(sep) != 1:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.ht_csv_dims(
        path.encode(), header_lines, sep.encode(), ctypes.byref(rows), ctypes.byref(cols)
    )
    if rc != 0:
        return None
    return rows.value, cols.value


def _csv_dtype_code(dtype: np.dtype):
    np_dtype = np.dtype(dtype)
    if np_dtype == np.float32:
        return 0, np_dtype, None
    if np_dtype == np.float64:
        return 1, np_dtype, None
    # ints etc.: parse as f64 then cast — matching the reference, which
    # parses every field with Python float() before the dtype cast
    # (reference heat/core/io.py:800-806), including its >2**53
    # rounding behavior
    return 1, np.dtype(np.float64), np_dtype


def _csv_parse_handle(lib, handle, sep, rows, cols, code, np_dtype, cast_to, nthreads):
    try:
        if rows == 0 or cols == 0:
            return np.empty((rows, cols), dtype=cast_to or np_dtype)
        out = np.empty((rows, cols), dtype=np_dtype)
        if nthreads <= 0:
            nthreads = min(16, os.cpu_count() or 1)
        rc = lib.ht_csv_parse_h(
            handle,
            sep.encode(),
            code,
            out.ctypes.data_as(ctypes.c_void_p),
            rows,
            cols,
            nthreads,
        )
    finally:
        lib.ht_csv_close(handle)
    if rc != 0:
        return None
    return out if cast_to is None else out.astype(cast_to)


def csv_parse(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype: np.dtype = np.float32,
    nthreads: int = 0,
) -> Optional[np.ndarray]:
    """Parse a numeric CSV into a numpy array; None → caller falls back."""
    lib = _load()
    if lib is None or len(sep) != 1:
        return None
    code, np_dtype, cast_to = _csv_dtype_code(dtype)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    handle = lib.ht_csv_open(
        path.encode(), header_lines, sep.encode(), ctypes.byref(rows), ctypes.byref(cols)
    )
    if not handle:
        return None
    return _csv_parse_handle(
        lib, handle, sep, rows.value, cols.value, code, np_dtype, cast_to, nthreads
    )


def csv_parse_range(
    path: str,
    offset: int,
    length: int,
    header_lines: int = 0,
    sep: str = ",",
    dtype: np.dtype = np.float32,
    nthreads: int = 0,
) -> Optional[np.ndarray]:
    """Parse only the rows OWNED by byte range [offset, offset+length) —
    a row belongs to the range containing its first byte and is parsed to
    its end even across the boundary, so ranges partitioning the file give
    disjoint covering row sets (the reference's per-rank convention,
    ``heat/core/io.py:713-924``). ``length < 0`` means to EOF.
    None → caller falls back to the Python range parser."""
    lib = _load()
    if lib is None or len(sep) != 1:
        return None
    code, np_dtype, cast_to = _csv_dtype_code(dtype)
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    handle = lib.ht_csv_open_range(
        path.encode(), header_lines, sep.encode(), offset, length,
        ctypes.byref(rows), ctypes.byref(cols),
    )
    if not handle:
        return None
    return _csv_parse_handle(
        lib, handle, sep, rows.value, cols.value, code, np_dtype, cast_to, nthreads
    )


_IDX_DTYPES = {
    0x08: np.uint8,
    0x09: np.int8,
    0x0B: np.int16,
    0x0C: np.int32,
    0x0D: np.float32,
    0x0E: np.float64,
}


def idx_read(path: str) -> Optional[np.ndarray]:
    """Read an (uncompressed) IDX file into a numpy array; None → fallback."""
    lib = _load()
    if lib is None:
        return None
    dims = (ctypes.c_int64 * 8)()
    ndims = ctypes.c_int64()
    code = ctypes.c_int32()
    rc = lib.ht_idx_header(path.encode(), dims, ctypes.byref(ndims), ctypes.byref(code))
    if rc != 0 or code.value not in _IDX_DTYPES:
        return None
    shape = tuple(dims[i] for i in range(ndims.value))
    out = np.empty(shape, dtype=_IDX_DTYPES[code.value])
    rc = lib.ht_idx_read(path.encode(), out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
    if rc != 0:
        return None
    return out


class FileStream:
    """Background-prefetched sequential reader over a byte range of a file.

    A native OS thread preads slabs of ``chunk_bytes`` into a ring of
    ``depth`` buffers ahead of the consumer, so disk IO overlaps Python-side
    compute without the GIL (native analogue of reference
    ``heat/utils/data/partial_dataset.py:20`` ``queue_thread``).

    Iterating yields ``numpy.uint8`` arrays of at most ``chunk_bytes``.
    Usable as a context manager.
    """

    def __init__(
        self,
        path: str,
        offset: int = 0,
        length: Optional[int] = None,
        chunk_bytes: int = 1 << 20,
        depth: int = 4,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("heat_tpu native library unavailable")
        if length is None:
            length = max(0, os.path.getsize(path) - offset)
        self._lib = lib
        self._chunk = chunk_bytes
        self._handle = lib.ht_stream_open(path.encode(), offset, length, chunk_bytes, depth)
        if not self._handle:
            raise OSError(f"cannot open stream on {path!r}")

    def read_next(self) -> Optional[np.ndarray]:
        """Next slab as a uint8 array, or None at end of stream."""
        if self._handle is None:
            return None
        buf = np.empty(self._chunk, dtype=np.uint8)
        n = self._lib.ht_stream_next(
            self._handle, buf.ctypes.data_as(ctypes.c_void_p), self._chunk
        )
        if n < 0:
            raise OSError(f"native stream read failed (code {n})")
        if n == 0:
            return None
        return buf[:n]

    def __iter__(self):
        while True:
            slab = self.read_next()
            if slab is None:
                return
            yield slab

    def close(self) -> None:
        if self._handle is not None:
            self._lib.ht_stream_close(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except (OSError, AttributeError):
            # close() only touches the ctypes handle; never mask anything
            # wider (e.g. ResilienceError) from interpreter teardown
            pass
