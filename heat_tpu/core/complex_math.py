"""Complex number operations (reference ``heat/core/complex_math.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import _local_op
from .dndarray import DNDarray

__all__ = ["angle", "conj", "conjugate", "imag", "real"]


def angle(x, deg: bool = False, out=None) -> DNDarray:
    """Phase angle of a complex array (reference ``complex_math.py``)."""
    return _local_op(lambda t: jnp.angle(t, deg=deg), x, out=out, no_cast=True)


def conjugate(x, out=None) -> DNDarray:
    """Complex conjugate."""
    return _local_op(jnp.conjugate, x, out=out, no_cast=True)


conj = conjugate


def imag(x, out=None) -> DNDarray:
    """Imaginary part; zeros for real input."""
    return _local_op(jnp.imag, x, out=out, no_cast=True)


def real(x, out=None) -> DNDarray:
    """Real part."""
    if isinstance(x, DNDarray) and not types.heat_type_is_complexfloating(x.dtype):
        return x
    return _local_op(jnp.real, x, out=out, no_cast=True)
