"""Dispatch fencing for per-batch training loops.

The CPU backend's in-process collectives (the virtual multi-device test
mesh) deadlock when more than one SPMD execution is in flight: each
device drains its own execution queue independently, so device X can
finish program N and block in program N+1's all-reduce rendezvous while
device Y still sits in program N's — both wait forever and XLA aborts
the process from ``xla::internal::AwaitAndLogIfStuck`` after ~40 s.
(``jax_cpu_enable_async_dispatch`` does not help; it "only applies to
non-parallel computations".)

Training steps used to be implicitly serialized by fetching the loss to
host every batch — a ~100 ms RPC floor per step on a tunneled TPU, which
round 2's verdict flagged. The loss now stays on device, so the step
paths that dispatch collective programs back-to-back fence explicitly on
the PREVIOUS step's result before dispatching the next — but only on the
``cpu`` platform, where it is the supported mode; on TPU the hardware
runtime orders its own queue and dispatch stays fully asynchronous.
"""
from __future__ import annotations

import jax

__all__ = ["fence_cpu_collectives"]


def fence_cpu_collectives(prev) -> None:
    """Block on ``prev`` (any array/pytree or None) iff it lives on the
    CPU backend. Call with the previous step's output before dispatching
    the next collective program."""
    if prev is None:
        return
    leaves = jax.tree_util.tree_leaves(prev)
    if not leaves:
        return
    first = leaves[0]
    devs = getattr(first, "devices", None)
    if devs is None:
        return
    ds = devs() if callable(devs) else devs
    try:
        platform = next(iter(ds)).platform
    except (StopIteration, TypeError):  # pragma: no cover - defensive
        return
    if platform == "cpu":
        # graftlint: host-sync - deliberate fence: CPU collectives deadlock
        # without draining in-flight work (see module docstring)
        jax.block_until_ready(leaves)
