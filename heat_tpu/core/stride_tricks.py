"""Shape/axis sanitation helpers (reference ``heat/core/stride_tricks.py``)."""
from __future__ import annotations

from typing import Optional, Tuple, Union

import numpy as np

__all__ = ["broadcast_shape", "broadcast_shapes", "sanitize_axis", "sanitize_shape", "sanitize_slice"]


def broadcast_shape(shape_a: Tuple[int, ...], shape_b: Tuple[int, ...]) -> Tuple[int, ...]:
    """NumPy-broadcast two shapes, raising ValueError on mismatch
    (reference ``stride_tricks.py:12``)."""
    try:
        return tuple(np.broadcast_shapes(tuple(shape_a), tuple(shape_b)))
    except ValueError:
        raise ValueError(
            f"operands could not be broadcast, input shapes {tuple(shape_a)} {tuple(shape_b)}"
        )


def broadcast_shapes(*shapes) -> Tuple[int, ...]:
    try:
        return tuple(np.broadcast_shapes(*[tuple(s) for s in shapes]))
    except ValueError:
        raise ValueError(f"operands could not be broadcast, input shapes {shapes}")


def sanitize_axis(
    shape: Tuple[int, ...], axis: Union[int, Tuple[int, ...], None]
) -> Union[int, Tuple[int, ...], None]:
    """Normalize (possibly negative / tuple) axis against ``shape``
    (reference ``stride_tricks.py:72``)."""
    if axis is None:
        return None
    ndim = len(shape)
    if isinstance(axis, (list, tuple)):
        axes = tuple(sanitize_axis(shape, a) for a in axis)
        if len(set(axes)) != len(axes):
            raise ValueError("duplicate value in axis")
        return axes
    if not isinstance(axis, (int, np.integer)):
        raise TypeError(f"axis must be None or int or tuple of ints, got {type(axis)}")
    axis = int(axis)
    if ndim == 0:
        if axis in (0, -1):
            return 0 if axis == -1 else axis
        raise ValueError(f"axis {axis} out of bounds for 0-dimensional array")
    if axis < 0:
        axis += ndim
    if not 0 <= axis < ndim:
        raise ValueError(f"axis {axis - ndim if axis >= ndim else axis} out of bounds for {ndim}-dimensional array")
    return axis


def sanitize_shape(shape, lval: int = 0) -> Tuple[int, ...]:
    """Normalize a shape argument to a tuple of non-negative ints
    (reference ``stride_tricks.py:135``)."""
    if isinstance(shape, (int, np.integer)):
        shape = (int(shape),)
    shape = tuple(shape)
    out = []
    for dim in shape:
        if not isinstance(dim, (int, np.integer)):
            raise TypeError(f"expected sequence object with length >= 0 or a single integer, got {type(dim)}")
        dim = int(dim)
        if dim < lval:
            raise ValueError(f"negative dimensions are not allowed, got {dim}")
        out.append(dim)
    return tuple(out)


def sanitize_slice(sl: slice, max_dim: int) -> slice:
    """Resolve a slice to concrete non-negative start/stop/step
    (reference ``stride_tricks.py:180``)."""
    if not isinstance(sl, slice):
        raise TypeError("This function is only for slices!")
    return slice(*sl.indices(max_dim))
