"""Pure-Python classic netCDF (CDF-1/CDF-2) reader/writer.

The reference reads both classic and netCDF-4 files through the netCDF4
C library (``/root/reference/heat/core/io.py:268-351``). That library is
not in this image; netCDF-4 files are HDF5 and go through h5py, and this
module closes the remaining gap: the classic on-disk format
(https://docs.unidata.ucar.edu/netcdf-c/current/file_format_specifications.html)
is a few hundred bytes of big-endian header plus flat row-major data, so
a dependency-free parser feeds the same chunked multi-host assembly
(:func:`heat_tpu.core.communication._assemble_from_chunks`) the HDF5
path uses — byte-range reads per device chunk, never the whole file.

Scope: CDF-1 (32-bit offsets) and CDF-2 (64-bit offsets), all six
classic types, fixed and record variables, attributes parsed and
skipped (no automatic scale/offset application — same behavior as the
h5py fallback). The writer emits a minimal CDF-1/2 file: the dimension
list, one data variable, no attributes — enough for reference-parity
round trips.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["NetCDF3File", "write_netcdf3", "is_classic_netcdf"]

_NC_DIMENSION = 0x0A
_NC_VARIABLE = 0x0B
_NC_ATTRIBUTE = 0x0C

_TYPES = {
    1: np.dtype(">i1"),  # NC_BYTE
    2: np.dtype("S1"),   # NC_CHAR
    3: np.dtype(">i2"),  # NC_SHORT
    4: np.dtype(">i4"),  # NC_INT
    5: np.dtype(">f4"),  # NC_FLOAT
    6: np.dtype(">f8"),  # NC_DOUBLE
}
_TYPE_CODES = {
    np.dtype(np.int8): 1,
    np.dtype("S1"): 2,
    np.dtype(np.int16): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.float32): 5,
    np.dtype(np.float64): 6,
}


# the classic header stores vsize as a signed 32-bit int; even CDF-2
# only widens the begin offset
_MAX_VSIZE = 2**31


def is_classic_netcdf(path: str) -> bool:
    with open(path, "rb") as f:
        head = f.read(4)
    return head[:3] == b"CDF" and head[3:4] in (b"\x01", b"\x02")


class _Var:
    __slots__ = ("name", "dimids", "dtype", "vsize", "begin", "is_record", "shape")

    def __init__(self, name, dimids, dtype, vsize, begin):
        self.name = name
        self.dimids = dimids
        self.dtype = dtype
        self.vsize = vsize
        self.begin = begin
        self.is_record = False
        self.shape: Tuple[int, ...] = ()


class NetCDF3File:
    """Parsed classic-format header with byte-range reads."""

    def __init__(self, path: str):
        self.path = path
        # the header is streamed from the open handle — never the whole
        # file (a 50 GB classic file has a few-KB header)
        with open(path, "rb") as f:
            self._f = f
            magic = f.read(4)
            if magic[:3] != b"CDF" or magic[3] not in (1, 2):
                raise ValueError(f"{path} is not a classic netCDF file")
            self.version = magic[3]
            self._off_t = ">q" if self.version == 2 else ">i"
            self.numrecs = self._i4()
            self.dims: List[Tuple[str, int]] = []
            self.attrs: Dict[str, object] = {}
            self.vars: Dict[str, _Var] = {}
            self._dim_list()
            self.attrs = self._att_list()
            self._var_list()
        del self._f
        self._finalize()

    # -- primitive readers ---------------------------------------------------
    def _take(self, n: int) -> bytes:
        b = self._f.read(n)
        if len(b) != n:
            raise ValueError(f"{self.path}: truncated classic netCDF header")
        return b

    def _i4(self) -> int:
        return struct.unpack(">i", self._take(4))[0]

    def _name(self) -> str:
        n = self._i4()
        s = self._take(n).decode("utf-8")
        self._take((-n) % 4)  # padded to 4
        return s

    # -- header sections -----------------------------------------------------
    def _tagged_count(self, expect: int) -> int:
        tag = self._i4()
        count = self._i4()
        if tag == 0 and count == 0:
            return 0
        if tag != expect:
            raise ValueError(f"corrupt header: tag {tag:#x}, expected {expect:#x}")
        return count

    def _dim_list(self) -> None:
        for _ in range(self._tagged_count(_NC_DIMENSION)):
            name = self._name()
            size = self._i4()
            self.dims.append((name, size))

    def _att_list(self) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for _ in range(self._tagged_count(_NC_ATTRIBUTE)):
            name = self._name()
            nc_type = self._i4()
            nelems = self._i4()
            dt = _TYPES[nc_type]
            nbytes = dt.itemsize * nelems
            raw = self._take(nbytes)
            self._take((-nbytes) % 4)
            if nc_type == 2:
                out[name] = raw.decode("utf-8", "replace")
            else:
                out[name] = np.frombuffer(raw, dtype=dt)
        return out

    def _var_list(self) -> None:
        for _ in range(self._tagged_count(_NC_VARIABLE)):
            name = self._name()
            ndims = self._i4()
            dimids = [self._i4() for _ in range(ndims)]
            self._att_list()  # variable attributes: parsed, not applied
            nc_type = self._i4()
            vsize = self._i4()
            begin = struct.unpack(self._off_t, self._take(struct.calcsize(self._off_t)))[0]
            self.vars[name] = _Var(name, dimids, _TYPES[nc_type], vsize, begin)

    def _finalize(self) -> None:
        rec_vars = []
        for v in self.vars.values():
            shape = []
            for i, d in enumerate(v.dimids):
                dname, dsize = self.dims[d]
                if dsize == 0 and i == 0:
                    v.is_record = True
                    shape.append(self.numrecs)
                else:
                    shape.append(dsize)
            v.shape = tuple(shape)
            if v.is_record:
                rec_vars.append(v)
        # each record var's `begin` already points at its slot inside
        # record 0; the per-record stride is the sum of all record vsizes.
        # Spec special case: a SINGLE record variable of byte/char/short
        # stores its record slabs UNPADDED (vsize is still rounded up),
        # so the stride is the raw one-record size.
        if len(rec_vars) == 1 and rec_vars[0].dtype.itemsize < 4:
            v = rec_vars[0]
            rest = [self.dims[d][1] for d in v.dimids[1:]]
            self.recsize = int(np.prod(rest, dtype=np.int64)) * v.dtype.itemsize
        else:
            self.recsize = sum(v.vsize for v in rec_vars)
        if self.numrecs == -1 and rec_vars:  # STREAMING sentinel
            import os

            first = min(v.begin for v in rec_vars)
            self.numrecs = (os.path.getsize(self.path) - first) // max(self.recsize, 1)
            for v in rec_vars:
                v.shape = (self.numrecs,) + v.shape[1:]

    # -- data ----------------------------------------------------------------
    def shape(self, variable: str) -> Tuple[int, ...]:
        return self.vars[variable].shape

    def read(self, variable: str, start: int = 0, stop: Optional[int] = None) -> np.ndarray:
        """Rows ``[start, stop)`` of the first dimension (the whole
        variable when it is 0-d), reading only the covered byte range."""
        v = self.vars[variable]
        if not v.shape:
            with open(self.path, "rb") as f:
                f.seek(v.begin)
                return np.frombuffer(f.read(v.dtype.itemsize), dtype=v.dtype)[0]
        n = v.shape[0]
        stop = n if stop is None else min(stop, n)
        start = max(0, start)
        rows = max(0, stop - start)
        rest = v.shape[1:]
        row_elems = int(np.prod(rest, dtype=np.int64)) if rest else 1
        row_bytes = row_elems * v.dtype.itemsize
        out = np.empty((rows, row_elems), dtype=v.dtype)
        with open(self.path, "rb") as f:
            if v.is_record:
                for i in range(rows):
                    f.seek(v.begin + (start + i) * self.recsize)
                    out[i] = np.frombuffer(f.read(row_bytes), dtype=v.dtype)
            else:
                f.seek(v.begin + start * row_bytes)
                out[:] = np.frombuffer(f.read(rows * row_bytes), dtype=v.dtype).reshape(
                    rows, row_elems
                )
        return out.reshape((rows,) + rest)


def write_netcdf3(
    path: str,
    variable: str,
    data: np.ndarray,
    dim_names: Optional[List[str]] = None,
    version: int = 1,
) -> None:
    """Write ``data`` as a single fixed variable in CDF-1/2 format."""
    data = np.asarray(data)
    if data.ndim:  # ascontiguousarray would promote 0-d to 1-d
        data = np.ascontiguousarray(data)
    code = _TYPE_CODES.get(
        np.dtype("S1") if data.dtype.kind == "S" else np.dtype(data.dtype)
    )
    if code is None:
        # classic format has no 64-bit ints / f16 / bool: widen to a
        # representable type the way the netCDF4 library's default does
        if data.dtype.kind in "iub":
            data = data.astype(np.int32)
            code = 4
        else:
            data = data.astype(np.float64)
            code = 6
    be = data.astype(_TYPES[code], copy=False)
    if be.nbytes >= _MAX_VSIZE:
        # the classic header stores vsize as a signed 32-bit int (CDF-2
        # only widens the begin offset); fail clearly instead of a cryptic
        # struct.error after a partial header write
        raise ValueError(
            f"variable too large for classic netCDF ({be.nbytes} bytes >= 2 GiB); "
            "use the netCDF-4 path (format='NETCDF4')"
        )
    if dim_names is None:
        dim_names = [f"{variable}_dim_{i}" for i in range(data.ndim)]

    def name_bytes(s: str) -> bytes:
        b = s.encode("utf-8")
        return struct.pack(">i", len(b)) + b + b"\x00" * ((-len(b)) % 4)

    off_t = ">q" if version == 2 else ">i"
    head = [b"CDF", bytes([version]), struct.pack(">i", 0)]  # numrecs=0
    if data.ndim:
        head.append(struct.pack(">ii", _NC_DIMENSION, data.ndim))
        for nm, sz in zip(dim_names, data.shape):
            head.append(name_bytes(nm) + struct.pack(">i", sz))
    else:
        head.append(struct.pack(">ii", 0, 0))
    head.append(struct.pack(">ii", 0, 0))  # no global attributes
    head.append(struct.pack(">ii", _NC_VARIABLE, 1))
    vsize = (be.nbytes + 3) & ~3
    var_head = (
        name_bytes(variable)
        + struct.pack(">i", data.ndim)
        + b"".join(struct.pack(">i", i) for i in range(data.ndim))
        + struct.pack(">ii", 0, 0)  # no variable attributes
        + struct.pack(">ii", code, vsize)
    )
    begin_field = struct.calcsize(off_t)
    begin = sum(len(b) for b in head) + len(var_head) + begin_field
    head.append(var_head + struct.pack(off_t, begin))
    with open(path, "wb") as f:
        for b in head:
            f.write(b)
        f.write(be.tobytes())
        f.write(b"\x00" * ((-be.nbytes) % 4))
