"""Parallel random number generation (reference ``heat/core/random.py``).

The reference implements a counter-based Threefry-2x32/2x64 generator *in
torch ops* (``random.py:876-1057``) and maps each rank's global element
offsets onto counter values so that any split produces the same global
stream (``__counter_sequence``, ``random.py:55-201``).

JAX's native PRNG **is** counter-based Threefry, and with partitionable
keys (``jax_threefry_partitionable``, enabled here) a draw of a given
global shape produces the *same global stream for every sharding* — the
reference's core guarantee, for free, generated shard-locally on device.
State is (seed, counter); each draw folds the counter into the key and
advances it, so call sequences are reproducible after ``seed()``.
"""
from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import devices, types
from ._cache import ExecutableCache
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_shape

jax.config.update("jax_threefry_partitionable", True)

__all__ = [
    "get_state",
    "normal",
    "permutation",
    "rand",
    "randint",
    "randn",
    "random_integer",
    "random_sample",
    "randperm",
    "ranf",
    "sample",
    "seed",
    "set_state",
    "standard_normal",
    "uniform",
]

# global (seed, counter) state, reference ``random.py:40-43``
__seed: int = 0
__counter: int = 0


def seed(seed: Optional[int] = None) -> None:
    """Reset the generator (reference ``random.py:772``)."""
    global __seed, __counter
    if seed is None:
        seed = int(np.random.SeedSequence().entropy % (2**63))
    __seed = int(seed)
    __counter = 0


def get_state() -> Tuple[str, int, int, int, float]:
    """Generator state tuple (reference ``random.py:203``)."""
    return ("Threefry", __seed, __counter, 0, 0.0)


def set_state(state: Tuple) -> None:
    """Restore generator state (reference ``random.py:790``)."""
    global __seed, __counter
    if not isinstance(state, tuple) or len(state) not in (3, 5):
        raise TypeError("state needs to be a 3- or 5-tuple")
    if state[0] != "Threefry":
        raise ValueError("algorithm must be 'Threefry'")
    __seed = int(state[1])
    __counter = int(state[2])


def _next_key(nelem: int) -> jax.Array:
    """Derive the key for the next draw and advance the counter."""
    global __counter
    key = jax.random.fold_in(jax.random.PRNGKey(__seed), __counter & 0x7FFFFFFF)
    __counter += max(int(nelem), 1)
    return key


def _finalize(data: jax.Array, dtype, split, device, comm) -> DNDarray:
    comm = sanitize_comm(comm)
    return DNDarray(
        data,
        dtype=dtype,
        split=split,
        device=devices.sanitize_device(device),
        comm=comm,
    )


# compiled generator programs keyed by (gen statics, shapes, sharding): the
# gen lambdas below are rebuilt per call, so jitting them directly would key
# the pjit cache by fresh closure identity and retrace on every draw; with
# the token key a repeated same-shape draw reuses one executable (the PRNG
# key enters as a traced operand, so new keys are cache hits too)
_GEN_CACHE = ExecutableCache()


def _sharded_fill(gen, gen_key, key, shape, dtype, split, device, comm) -> DNDarray:
    """Generate at the LOGICAL shape and zero-pad to the physical buffer,
    all inside one jitted program born in its final even sharding.

    With ``jax_threefry_partitionable`` an element's value depends on its
    index *within the generated shape*, so generation must happen at the
    logical extent: generating at the padded shape would shift the
    row-major counters whenever a non-leading dim is padded and break the
    reference's split-invariant-stream guarantee (``random.py:55-201``).
    GSPMD partitions the generation itself, so each device still produces
    only (about) its own region; the pad is deterministic zeros, masked at
    every consumption point like any other buffer padding."""
    pshape = comm.padded_shape(shape, split)
    sharding = comm.array_sharding(pshape, split)
    cache_key = (gen_key, tuple(shape), tuple(pshape), sharding)
    fn = _GEN_CACHE.get(cache_key)
    if fn is None:

        def fill(k):
            x = gen(k, tuple(shape))
            if tuple(pshape) != tuple(shape):
                x = jnp.pad(x, [(0, p - s) for p, s in zip(pshape, shape)])
            return x

        fn = _GEN_CACHE[cache_key] = jax.jit(fill, out_shardings=sharding)
    data = fn(key)
    return DNDarray._from_buffer(
        data, shape, dtype, split, devices.sanitize_device(device), comm
    )


def _float_jt(dtype):
    dtype = types.canonical_heat_type(dtype) if dtype is not None else types.float32
    if dtype not in (types.float16, types.bfloat16, types.float32, types.float64):
        raise ValueError(f"Unsupported dtype {dtype} for random floats")
    return dtype, dtype.jax_type()


def rand(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0, 1) samples (reference ``random.py:404``)."""
    shape = sanitize_shape(d if len(d) else (1,))
    if len(d) == 0:
        shape = ()
    dtype, jt = _float_jt(dtype)
    comm_ = sanitize_comm(comm)
    key = _next_key(int(np.prod(shape)) if shape else 1)
    return _sharded_fill(
        lambda k, ps: jax.random.uniform(k, ps, dtype=jt),
        ("uniform", jt),
        key, shape, dtype, split if shape else None, device, comm_,
    )


def randn(*d, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Standard-normal samples (reference ``random.py:592``; the reference
    used the Kundu transform ``random.py:248-266``, JAX uses inverse-erf —
    moments match, bitstreams differ by construction)."""
    shape = sanitize_shape(d if len(d) else (1,))
    if len(d) == 0:
        shape = ()
    dtype, jt = _float_jt(dtype)
    comm_ = sanitize_comm(comm)
    key = _next_key(int(np.prod(shape)) if shape else 1)
    return _sharded_fill(
        lambda k, ps: jax.random.normal(k, ps, dtype=jt),
        ("normal", jt),
        key, shape, dtype, split if shape else None, device, comm_,
    )


def randint(
    low: int,
    high: Optional[int] = None,
    size=None,
    dtype=types.int32,
    split=None,
    device=None,
    comm=None,
) -> DNDarray:
    """Uniform integers in [low, high) (reference ``random.py:481``)."""
    if high is None:
        low, high = 0, low
    if size is None:
        size = ()
    shape = sanitize_shape(size) if size != () else ()
    if high <= low:
        raise ValueError("low >= high")
    dtype = types.canonical_heat_type(dtype)
    comm_ = sanitize_comm(comm)
    key = _next_key(int(np.prod(shape)) if shape else 1)
    split_ = split if shape else None
    return _sharded_fill(
        lambda k, ps: jax.random.randint(k, ps, low, high, dtype=jnp.int64).astype(dtype.jax_type()),
        ("randint", dtype.jax_type(), int(low), int(high)),
        key, shape, dtype, split_, device, comm_,
    )


random_integer = randint


def random_sample(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [0,1) with a shape tuple argument (reference ``random.py``)."""
    if shape is None:
        shape = ()
    shape = sanitize_shape(shape) if shape != () else ()
    return rand(*shape, dtype=dtype, split=split, device=device, comm=comm) if shape else rand(dtype=dtype)


random = random_sample
ranf = random_sample
sample = random_sample


def normal(mean=0.0, std=1.0, shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Normal with given mean/std (reference ``random.py:268``)."""
    if shape is None:
        shape = ()
    shape = sanitize_shape(shape) if shape != () else ()
    base = randn(*shape, dtype=dtype, split=split, device=device, comm=comm)
    # DNDarray arithmetic keeps padding/broadcast alignment correct
    return (base * std + mean).astype(base.dtype)


def standard_normal(shape=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """reference ``random.py``"""
    if shape is None:
        shape = ()
    shape = sanitize_shape(shape) if shape != () else ()
    return randn(*shape, dtype=dtype, split=split, device=device, comm=comm)


def uniform(low=0.0, high=1.0, size=None, dtype=types.float32, split=None, device=None, comm=None) -> DNDarray:
    """Uniform [low, high) (reference ``random.py``)."""
    if size is None:
        size = ()
    shape = sanitize_shape(size) if size != () else ()
    base = rand(*shape, dtype=dtype, split=split, device=device, comm=comm)
    return (base * (high - low) + low).astype(base.dtype)


def randperm(n: int, dtype=types.int64, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation of arange(n) (reference ``random.py:649``)."""
    dtype = types.canonical_heat_type(dtype)
    comm_ = sanitize_comm(comm)
    key = _next_key(int(n))
    data = jax.random.permutation(key, int(n)).astype(dtype.jax_type())
    return _finalize(data, dtype, split, device, comm_)


def permutation(x, split=None, device=None, comm=None) -> DNDarray:
    """Random permutation / shuffle of the first axis (reference
    ``random.py:326``)."""
    if isinstance(x, (int, np.integer)):
        return randperm(int(x), split=split, device=device, comm=comm)
    if not isinstance(x, DNDarray):
        raise TypeError(f"x must be int or DNDarray, got {type(x)}")
    key = _next_key(x.shape[0])
    perm = jax.random.permutation(key, x.shape[0])
    result = jnp.take(x._logical(), perm, axis=0)
    return DNDarray(result, dtype=x.dtype, split=x.split, device=x.device, comm=x.comm)
