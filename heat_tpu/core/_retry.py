"""Retry / timeout / backoff policy for I/O and collective entry points.

The public face is :mod:`heat_tpu.resilience.retry` (which re-exports
these names); the implementation lives in ``core`` so that
:mod:`heat_tpu.core.io` can wire retries into its load/save paths without
a core -> resilience import cycle.

Design: exponential backoff with a deterministic jitter cap. Determinism
matters here the same way it matters for the chaos layer — a seeded
policy produces the same delay sequence on every run, so tests (and
multi-process SPMD programs, where divergent sleeps skew barriers) are
reproducible. The terminal failure is a single :class:`RetryError`
carrying the full attempt history, not the bare last exception.
"""
from __future__ import annotations

import random as _random
import time as _time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple, Type

__all__ = ["RetryPolicy", "RetryError", "NO_RETRY"]


class RetryError(OSError):
    """Terminal retry failure: every attempt allowed by the policy failed.

    Subclasses :class:`OSError` so callers that guard an I/O path with
    ``except OSError`` see the terminal failure the same way whether a
    retry policy was in force or not.

    Attributes
    ----------
    attempts : list of (attempt_index, exception, delay_before_next)
        Full history; ``delay_before_next`` is None for the last attempt.
    last : BaseException
        The exception of the final attempt (also the ``__cause__``).
    """

    def __init__(self, label: str, attempts: List[Tuple[int, BaseException, Optional[float]]]):
        self.attempts = attempts
        self.last = attempts[-1][1] if attempts else None
        lines = [
            f"{label}: failed after {len(attempts)} attempt(s):"
        ]
        for i, exc, delay in attempts:
            suffix = "giving up" if delay is None else f"retried after {delay:.3f}s"
            lines.append(f"  attempt {i + 1}: {type(exc).__name__}: {exc} ({suffix})")
        super().__init__("\n".join(lines))


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter (capped), applied to transient errors.

    Parameters
    ----------
    max_attempts : int
        Total attempts (1 = no retry).
    base_delay : float
        Delay before the 2nd attempt, in seconds.
    max_delay : float
        Hard cap on any single delay (backoff + jitter never exceeds it).
    multiplier : float
        Backoff growth factor per attempt.
    jitter : float
        Max fraction of the backoff added as random jitter (0.1 = +10%).
    retry_on : tuple of exception types
        Only these are retried; anything else propagates immediately.
    seed : int, optional
        Seeds the jitter stream for reproducible delay sequences.
    max_elapsed : float, optional
        Total wall-clock budget in seconds across ALL attempts. A retry
        whose backoff sleep would carry the elapsed time past the budget
        is not taken: the policy gives up immediately with a
        :class:`RetryError` instead. This bounds the worst case of a
        retry storm — a supervised step's retries can never outlast its
        checkpoint interval. ``None`` (default) means unbounded.
    sleep : callable
        Injection point for tests (defaults to ``time.sleep``).
    clock : callable
        Monotonic-time source for the ``max_elapsed`` budget (injection
        point for tests; defaults to ``time.monotonic``).
    """

    max_attempts: int = 3
    base_delay: float = 0.05
    max_delay: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    retry_on: Tuple[Type[BaseException], ...] = (OSError, TimeoutError)
    seed: Optional[int] = None
    max_elapsed: Optional[float] = None
    sleep: Callable[[float], None] = field(default=_time.sleep, repr=False)
    clock: Callable[[], float] = field(default=_time.monotonic, repr=False)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.base_delay < 0 or self.max_delay < 0:
            raise ValueError("delays must be non-negative")
        if self.max_elapsed is not None and self.max_elapsed < 0:
            raise ValueError(f"max_elapsed must be >= 0, got {self.max_elapsed}")

    def delays(self) -> List[float]:
        """The (deterministic given ``seed``) delay schedule: one entry per
        retry, i.e. ``max_attempts - 1`` values."""
        rng = _random.Random(self.seed)
        out = []
        for i in range(self.max_attempts - 1):
            backoff = self.base_delay * (self.multiplier**i)
            d = backoff * (1.0 + self.jitter * rng.random())
            out.append(min(d, self.max_delay))
        return out

    def call(self, fn: Callable, *args, label: Optional[str] = None, **kwargs):
        """Run ``fn(*args, **kwargs)`` under this policy.

        Retries on ``retry_on`` exceptions up to ``max_attempts`` total
        tries with backoff between them; raises :class:`RetryError` (with
        the attempt history, chained to the last failure) when exhausted.
        """
        label = label or getattr(fn, "__name__", "operation")
        attempts: List[Tuple[int, BaseException, Optional[float]]] = []
        schedule = self.delays()
        t0 = self.clock()
        for i in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as exc:
                delay = schedule[i] if i < len(schedule) else None
                if delay is not None and self.max_elapsed is not None:
                    # a sleep that would carry us past the budget is never
                    # taken: give up NOW, so a retry storm is bounded by
                    # max_elapsed rather than by the full attempt schedule
                    if (self.clock() - t0) + delay > self.max_elapsed:
                        attempts.append((i, exc, None))
                        err = RetryError(
                            f"{label} (wall-clock budget max_elapsed="
                            f"{self.max_elapsed}s exhausted)",
                            attempts,
                        )
                        raise err from exc
                attempts.append((i, exc, delay))
                if delay is None:
                    err = RetryError(label, attempts)
                    raise err from exc
                self.sleep(delay)

    def wrap(self, fn: Callable, label: Optional[str] = None) -> Callable:
        """Decorator form of :meth:`call`."""

        def wrapped(*args, **kwargs):
            return self.call(fn, *args, label=label, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped


# the no-op policy: io.py wires retries through this by default so
# behavior is unchanged unless the caller (or checkpoint I/O) opts in
NO_RETRY = RetryPolicy(max_attempts=1)
