"""Signal processing (reference ``heat/core/signal.py``).

The reference's ``convolve`` is the canonical halo-exchange stencil: pad ->
``get_halo(M//2)`` -> local conv1d on the halo-extended shard -> trim
(``signal.py:16-148``). A global convolution under XLA generates the same
neighbor exchange on ICI automatically; the explicit ``ppermute`` halo
helper lives in :mod:`heat_tpu.parallel.halo` for custom stencils.
"""
from __future__ import annotations

import jax.numpy as jnp

from . import types
from .dndarray import DNDarray

__all__ = ["convolve"]


def convolve(a: DNDarray, v: DNDarray, mode: str = "full") -> DNDarray:
    """1-D discrete convolution (reference ``signal.py:16``)."""
    from . import factories

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError(f"convolve requires 1-D inputs, got {a.ndim}-D and {v.ndim}-D")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"unsupported mode {mode!r}")
    if v.shape[0] > a.shape[0]:
        a, v = v, a
    if mode == "same" and v.shape[0] % 2 == 0:
        raise ValueError("mode 'same' cannot be used with even-sized kernel")
    promoted = types.promote_types(a.dtype, v.dtype)
    jt = promoted.jax_type()
    if a.split is not None and a.comm.size > 1:
        # one jitted sharded program: GSPMD emits the halo exchange
        # (bounded; see core/_movement.convolve_padded)
        from ._movement import convolve_padded

        buf, out_shape = convolve_padded(
            a.larray, a.gshape, a.split, v._logical(), mode, jt, a.comm
        )
        return DNDarray._from_buffer(
            buf, out_shape, promoted, a.split, device=a.device, comm=a.comm
        )
    result = jnp.convolve(a._logical().astype(jt), v._logical().astype(jt), mode=mode)
    return DNDarray(
        result,
        dtype=types.canonical_heat_type(result.dtype),
        split=a.split,
        device=a.device,
        comm=a.comm,
    )
