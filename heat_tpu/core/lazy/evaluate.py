"""Lowering captured graphs into single fused XLA programs.

Two cached abstract-evaluation layers keep the warm path at zero traces
and zero compiles (region-asserted via ``COMPILE_STATS`` in the tests):

- :func:`infer_meta` answers "what layout does this op produce?" at
  capture time by running the *original eager dispatcher* on abstract
  values (``jax.eval_shape``) under trace-safe mode, so a pending
  result's ``gshape``/``dtype``/``split``/``lcounts`` follow exactly the
  same rules as eager execution — there is no second copy of the
  promotion/broadcast/layout logic to drift. Results are cached in a
  bounded ``ExecutableCache`` keyed by (kind, op, statics, operand
  layouts), so only the first sighting of an op shape traces.

- :func:`evaluate` lowers a pending subgraph into ONE ``jax.jit``
  program that reconstructs plain DNDarrays from the leaf buffers and
  replays the recorded dispatcher calls; XLA fuses the chain and inserts
  collectives only where the sharded computation actually needs them
  (e.g. a cross-split reduction). Programs live in a bounded
  ``ExecutableCache`` keyed by the serialized graph + leaf layouts +
  communicator, so a warm replay is a single cached dispatch.

Replay correctness leans on one invariant: the functions below never
re-enter capture (trace-safe mode turns ``capture.active()`` off) and
never move data host-side (``_hooks.trace_barrier`` sites raise, which
:mod:`heat_tpu.core.lazy.capture` converts into an eager fallback at
capture time — such an op is simply never part of a graph).
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

import jax

from .. import _hooks
from .._cache import ExecutableCache
from ..dndarray import DNDarray
from .graph import Leaf, Node, NodeMeta, scalar_token, stats_inc

__all__ = ["infer_meta", "evaluate", "META_CACHE", "PROGRAM_CACHE"]

# op-shape metadata probes: one eval_shape per distinct (op, layout)
META_CACHE = ExecutableCache(maxsize=1024)
# fused executables: one jit per distinct (graph, leaf layouts, comm)
PROGRAM_CACHE = ExecutableCache(maxsize=256)


def _reconstruct(meta: NodeMeta, buf) -> DNDarray:
    """A plain DNDarray over ``buf`` with ``meta``'s layout. Only called
    under trace-safe mode, where ``_place``/``_from_ragged`` skip
    ``device_put`` (tracers cannot be placed; the program's
    ``out_shardings`` pin final placement)."""
    if meta.lcounts is not None:
        return DNDarray._from_ragged(
            buf, meta.gshape, meta.dtype, meta.split, meta.lcounts, meta.device, meta.comm
        )
    return DNDarray._from_buffer(
        buf, meta.gshape, meta.dtype, meta.split, meta.device, meta.comm
    )


def _replay_one(kind: str, op, statics, args) -> DNDarray:
    """Re-execute one captured call through the original eager
    dispatcher (``out=`` and non-default ``where=`` are never captured,
    so the replay surface is exactly the supported set)."""
    from .. import _operations as ops

    if kind == "binary":
        (fn_kwargs,) = statics
        return ops._binary_op(op, args[0], args[1], fn_kwargs=fn_kwargs or None)
    if kind == "local":
        no_cast, out_dtype, kwargs = statics
        return ops._local_op(op, args[0], no_cast=no_cast, out_dtype=out_dtype, **kwargs)
    if kind == "reduce":
        axis, keepdims, out_dtype, neutral, kwargs = statics
        return ops._reduce_op(
            op, args[0], axis=axis, keepdims=keepdims, out_dtype=out_dtype,
            neutral=neutral, **kwargs,
        )
    if kind == "matmul":
        # ``op`` IS basics.matmul (it keys the signature); calling it
        # re-enters its capture hook, which declines under trace-safe
        return op(args[0], args[1])
    if kind == "argreduce":
        from .. import statistics

        (axis,) = statics
        return statistics._arg_reduce(op, args[0], axis, None)
    axis, dtype, neutral = statics  # kind == "cum"
    return ops._cum_op(op, args[0], axis, dtype=dtype, neutral=neutral)


def infer_meta(kind: str, op, sig_statics, statics, operands, comm) -> NodeMeta:
    """Layout of the result of one captured call, without running it.

    ``operands`` is the capture-order list of ``("meta", NodeMeta)`` /
    ``("scalar", value)`` pairs. Raises whatever the dispatcher would
    raise for an unsupported combination (including
    ``TraceBarrierError`` for ops that need a host-side exchange) — the
    caller turns any failure into an eager fallback."""
    tokens = tuple(
        ("m",) + v.token if tag == "meta" else ("s",) + tuple(scalar_token(v))
        for tag, v in operands
    )
    key = (kind, op, sig_statics, tokens, comm)
    hit = META_CACHE.get(key)
    if hit is not None:
        return hit

    structs = [
        jax.ShapeDtypeStruct(v.pshape, v.dtype.jax_type())
        for tag, v in operands
        if tag == "meta"
    ]
    box: List[NodeMeta] = []

    def probe(*bufs):
        it = iter(bufs)
        args = [
            _reconstruct(v, next(it)) if tag == "meta" else v for tag, v in operands
        ]
        res = _replay_one(kind, op, statics, args)
        box.append(NodeMeta.of(res))
        return res._raw

    _hooks.enter_trace_safe()
    try:
        jax.eval_shape(probe, *structs)
    finally:
        _hooks.exit_trace_safe()
    meta = box[0]
    META_CACHE[key] = meta
    return meta


def _collect(targets: Sequence[Node]) -> List[Node]:
    """Unevaluated ancestor closure of ``targets`` in creation order
    (creation order IS topological order: operands always precede their
    consumers)."""
    found = {}
    stack = list(targets)
    while stack:
        n = stack.pop()
        if id(n) in found or n.buffer is not None:
            continue
        found[id(n)] = n
        for tag, v in n.inputs:
            if tag == "node" and v.buffer is None:
                stack.append(v)
    return sorted(found.values(), key=lambda n: n.seq)


def _build_program(spec, leaf_metas, out_ids, out_metas, comm):
    """One jitted program replaying ``spec`` over the leaf buffers.

    The spec closes over only plain Python data (ops, statics, layout
    metadata) — never over leaf buffers — so a cached program pins no
    device memory beyond its executable. Output shardings are pinned
    explicitly from the recorded layouts; inputs arrive committed with
    their eager shardings."""
    shardings = tuple(comm.array_sharding(m.pshape, m.split) for m in out_metas)

    def run(*bufs):
        _hooks.enter_trace_safe()
        try:
            leaves = [_reconstruct(m, b) for m, b in zip(leaf_metas, bufs)]
            env: List[DNDarray] = []
            for kind, op, statics, wiring in spec:
                args = [
                    env[v] if tag == "n" else (leaves[v] if tag == "l" else v)
                    for tag, v in wiring
                ]
                env.append(_replay_one(kind, op, statics, args))
            return tuple(env[i]._raw for i in out_ids)
        finally:
            _hooks.exit_trace_safe()

    return jax.jit(run, out_shardings=shardings)


def _evaluate_group(comm, targets: Sequence[Node]) -> None:
    nodes = _collect(targets)
    if not nodes:
        return
    index = {id(n): i for i, n in enumerate(nodes)}
    target_ids = {id(n) for n in targets}

    leaf_bufs, leaf_metas = [], []
    leaf_ix = {}
    spec, sig_nodes = [], []
    for n in nodes:
        wiring, sig_args = [], []
        for tag, v in n.inputs:
            if tag == "node" and v.buffer is None:
                wiring.append(("n", index[id(v)]))
                sig_args.append(("n", index[id(v)]))
            elif tag == "scalar":
                wiring.append(("s", v))
                sig_args.append(("s",) + tuple(scalar_token(v)))
            else:
                buf = v.buffer  # Leaf, or an already-evaluated Node
                meta = v.meta
                j = leaf_ix.get(id(buf))
                if j is None:
                    j = len(leaf_bufs)
                    leaf_ix[id(buf)] = j
                    leaf_bufs.append(buf)
                    leaf_metas.append(meta)
                wiring.append(("l", j))
                sig_args.append(("l", j))
        spec.append((n.kind, n.op, n.statics, tuple(wiring)))
        sig_nodes.append((n.kind, n.op, n.sig_statics, tuple(sig_args)))

    for buf in leaf_bufs:
        if buf.is_deleted():
            raise RuntimeError(
                "a buffer captured into a lazy graph was donated before "
                "evaluation (in-place __setitem__ on a source array inside "
                "a ht.lazy() scope); materialize consumers before mutating "
                "their inputs"
            )

    # a node stays a program output while its LazyDNDarray is reachable
    # (someone may still read it) or it was explicitly forced; dead
    # intermediates stay fused away inside the program
    out_ids = tuple(
        i
        for i, n in enumerate(nodes)
        if id(n) in target_ids or (n.ref is not None and n.ref() is not None)
    )
    out_metas = [nodes[i].meta for i in out_ids]

    sig = (comm, tuple(m.token for m in leaf_metas), tuple(sig_nodes), out_ids)
    prog = PROGRAM_CACHE.get(sig)
    if prog is None:
        prog = _build_program(spec, leaf_metas, out_ids, out_metas, comm)
        PROGRAM_CACHE[sig] = prog
        stats_inc("graphs_captured")
    else:
        stats_inc("cache_hits")
    stats_inc("fused_dispatches")

    outs = prog(*leaf_bufs)
    for i, buf in zip(out_ids, outs):
        n = nodes[i]
        n.buffer = buf
        arr = n.ref() if n.ref is not None else None
        if arr is not None:
            arr._lazy_fill(buf)
    for n in nodes:
        if n.buffer is not None:
            n.release_inputs()


def evaluate(targets: Sequence[Node]) -> None:
    """Materialize ``targets`` (and their unevaluated ancestors), one
    fused program per communicator (disjoint chains on different meshes
    cannot share a jit)."""
    pending, seen = [], set()
    for n in targets:
        if n.buffer is None and id(n) not in seen:
            seen.add(id(n))
            pending.append(n)
    if not pending:
        return
    groups: List[Tuple[object, List[Node]]] = []
    for n in pending:
        for c, lst in groups:
            if c == n.meta.comm:
                lst.append(n)
                break
        else:
            groups.append((n.meta.comm, [n]))
    for c, lst in groups:
        _evaluate_group(c, lst)
