"""Lowering captured graphs into single fused XLA programs.

Two cached abstract-evaluation layers keep the warm path at zero traces
and zero compiles (region-asserted via ``COMPILE_STATS`` in the tests):

- :func:`infer_meta` answers "what layout does this op produce?" at
  capture time by running the *original eager dispatcher* on abstract
  values (``jax.eval_shape``) under trace-safe mode, so a pending
  result's ``gshape``/``dtype``/``split``/``lcounts`` follow exactly the
  same rules as eager execution — there is no second copy of the
  promotion/broadcast/layout logic to drift. Results are cached in a
  bounded ``ExecutableCache`` keyed by (kind, op, statics, operand
  layouts), so only the first sighting of an op shape traces.

- :func:`evaluate` lowers a pending subgraph into ONE ``jax.jit``
  program that reconstructs plain DNDarrays from the leaf buffers and
  replays the recorded dispatcher calls; XLA fuses the chain and inserts
  collectives only where the sharded computation actually needs them
  (e.g. a cross-split reduction). Programs live in a bounded
  ``ExecutableCache`` keyed by the serialized graph + leaf layouts +
  communicator, so a warm replay is a single cached dispatch.

Replay correctness leans on one invariant: the functions below never
re-enter capture (trace-safe mode turns ``capture.active()`` off) and
never move data host-side (``_hooks.trace_barrier`` sites raise, which
:mod:`heat_tpu.core.lazy.capture` converts into an eager fallback at
capture time — such an op is simply never part of a graph).

Cross-chain common-subexpression reuse
--------------------------------------
Serving workloads evaluate N distinct chains that share a long prefix
(every endpoint standardizes its input the same way, then applies its
own head). Compiling each chain monolithically re-traces the shared
prefix N times. On a program-cache miss, :func:`_evaluate_group`
therefore consults a bounded registry of previously compiled chain
signatures: when the new chain's serialized prefix (ops, statics,
operand wiring AND the leaf layouts it touches) matches a registered
chain for at least :data:`_CSE_MIN_PREFIX` nodes, the shared prefix is
compiled ONCE as its own cached program and the new chain becomes a
composite — prefix program + remainder program — stored under the full
signature like any other executable. ``FUSE_STATS["cse_hits"]`` counts
the compilations that *reused* an already-compiled prefix; a warm
replay of a composite is still exactly one cached lookup (one
``fused_dispatch``, one ``cache_hit``, zero traces).

The cut is collective-safe by construction: boundary outputs keep
their recorded eager shardings (no resharding is introduced), and the
registry is populated in evaluation order, which the SPMD lockstep
discipline already requires to be rank-uniform — the replicated serve
dispatch tick (:mod:`heat_tpu.serve.tick`) compiles endpoints in the
same order on every rank.
"""
from __future__ import annotations

import threading
from typing import List, Sequence, Tuple

import jax

from .. import _hooks
from .._cache import ExecutableCache
from ..dndarray import DNDarray
from .graph import Leaf, Node, NodeMeta, scalar_token, stats_inc

__all__ = ["infer_meta", "evaluate", "META_CACHE", "PROGRAM_CACHE"]

# op-shape metadata probes: one eval_shape per distinct (op, layout)
META_CACHE = ExecutableCache(maxsize=1024)
# fused executables: one jit per distinct (graph, leaf layouts, comm)
# (shared-prefix programs live here too, under "cse"-tagged keys, so
# they ride the same LRU bound instead of pinning executables forever)
PROGRAM_CACHE = ExecutableCache(maxsize=256)

# shortest shared prefix worth a program cut: a 1-node prefix saves one
# op trace but costs an extra dispatch boundary forever
_CSE_MIN_PREFIX = 2
# registry of recently compiled chain signatures, newest last:
# (comm, sig_nodes, leaf_tokens) triples. Bounded like the executable
# caches — an evicted chain only costs a missed reuse opportunity.
_CSE_MAX_CHAINS = 32
_CSE_CHAINS: List[Tuple] = []
_CSE_LOCK = threading.Lock()


def _reconstruct(meta: NodeMeta, buf) -> DNDarray:
    """A plain DNDarray over ``buf`` with ``meta``'s layout. Only called
    under trace-safe mode, where ``_place``/``_from_ragged`` skip
    ``device_put`` (tracers cannot be placed; the program's
    ``out_shardings`` pin final placement)."""
    if meta.lcounts is not None:
        return DNDarray._from_ragged(
            buf, meta.gshape, meta.dtype, meta.split, meta.lcounts, meta.device, meta.comm
        )
    return DNDarray._from_buffer(
        buf, meta.gshape, meta.dtype, meta.split, meta.device, meta.comm
    )


def _replay_one(kind: str, op, statics, args) -> DNDarray:
    """Re-execute one captured call through the original eager
    dispatcher (``out=`` and non-default ``where=`` are never captured,
    so the replay surface is exactly the supported set)."""
    from .. import _operations as ops

    if kind == "binary":
        (fn_kwargs,) = statics
        return ops._binary_op(op, args[0], args[1], fn_kwargs=fn_kwargs or None)
    if kind == "local":
        no_cast, out_dtype, kwargs = statics
        return ops._local_op(op, args[0], no_cast=no_cast, out_dtype=out_dtype, **kwargs)
    if kind == "reduce":
        axis, keepdims, out_dtype, neutral, kwargs = statics
        return ops._reduce_op(
            op, args[0], axis=axis, keepdims=keepdims, out_dtype=out_dtype,
            neutral=neutral, **kwargs,
        )
    if kind == "matmul":
        # ``op`` IS basics.matmul (it keys the signature); calling it
        # re-enters its capture hook, which declines under trace-safe
        return op(args[0], args[1])
    if kind == "argreduce":
        from .. import statistics

        (axis,) = statics
        return statistics._arg_reduce(op, args[0], axis, None)
    axis, dtype, neutral = statics  # kind == "cum"
    return ops._cum_op(op, args[0], axis, dtype=dtype, neutral=neutral)


def infer_meta(kind: str, op, sig_statics, statics, operands, comm) -> NodeMeta:
    """Layout of the result of one captured call, without running it.

    ``operands`` is the capture-order list of ``("meta", NodeMeta)`` /
    ``("scalar", value)`` pairs. Raises whatever the dispatcher would
    raise for an unsupported combination (including
    ``TraceBarrierError`` for ops that need a host-side exchange) — the
    caller turns any failure into an eager fallback."""
    tokens = tuple(
        ("m",) + v.token if tag == "meta" else ("s",) + tuple(scalar_token(v))
        for tag, v in operands
    )
    key = (kind, op, sig_statics, tokens, comm)
    hit = META_CACHE.get(key)
    if hit is not None:
        return hit

    structs = [
        jax.ShapeDtypeStruct(v.pshape, v.dtype.jax_type())
        for tag, v in operands
        if tag == "meta"
    ]
    box: List[NodeMeta] = []

    def probe(*bufs):
        it = iter(bufs)
        args = [
            _reconstruct(v, next(it)) if tag == "meta" else v for tag, v in operands
        ]
        res = _replay_one(kind, op, statics, args)
        box.append(NodeMeta.of(res))
        return res._raw

    _hooks.enter_trace_safe()
    try:
        jax.eval_shape(probe, *structs)
    finally:
        _hooks.exit_trace_safe()
    meta = box[0]
    META_CACHE[key] = meta
    return meta


def _collect(targets: Sequence[Node]) -> List[Node]:
    """Unevaluated ancestor closure of ``targets`` in creation order
    (creation order IS topological order: operands always precede their
    consumers)."""
    found = {}
    stack = list(targets)
    while stack:
        n = stack.pop()
        if id(n) in found or n.buffer is not None:
            continue
        found[id(n)] = n
        for tag, v in n.inputs:
            if tag == "node" and v.buffer is None:
                stack.append(v)
    return sorted(found.values(), key=lambda n: n.seq)


def _build_program(spec, leaf_metas, out_ids, out_metas, comm):
    """One jitted program replaying ``spec`` over the leaf buffers.

    The spec closes over only plain Python data (ops, statics, layout
    metadata) — never over leaf buffers — so a cached program pins no
    device memory beyond its executable. Output shardings are pinned
    explicitly from the recorded layouts; inputs arrive committed with
    their eager shardings."""
    shardings = tuple(comm.array_sharding(m.pshape, m.split) for m in out_metas)

    def run(*bufs):
        _hooks.enter_trace_safe()
        try:
            leaves = [_reconstruct(m, b) for m, b in zip(leaf_metas, bufs)]
            env: List[DNDarray] = []
            for kind, op, statics, wiring in spec:
                args = [
                    env[v] if tag == "n" else (leaves[v] if tag == "l" else v)
                    for tag, v in wiring
                ]
                env.append(_replay_one(kind, op, statics, args))
            return tuple(env[i]._raw for i in out_ids)
        finally:
            _hooks.exit_trace_safe()

    return jax.jit(run, out_shardings=shardings)


def _cse_prefix_len(sig_nodes, leaf_tokens, entry_nodes, entry_leaves) -> int:
    """Length of the longest common serialized prefix of two chains.

    Node signatures must match exactly AND every leaf a prefix node
    touches must have the same layout token in both chains (leaf slots
    are assigned in first-use order, so identical wiring implies
    identical slot numbering — only the layouts can differ)."""
    k = 0
    for a, b in zip(sig_nodes, entry_nodes):
        if a != b:
            break
        ok = True
        for ent in a[3]:  # ("n", i) | ("l", j) | ("s", *token)
            if ent[0] != "l":
                continue
            v = ent[1]
            if (
                v >= len(leaf_tokens)
                or v >= len(entry_leaves)
                or leaf_tokens[v] != entry_leaves[v]
            ):
                ok = False
                break
        if not ok:
            break
        k += 1
    return k


def _cse_register(comm, sig_nodes, leaf_tokens) -> None:
    """Record a compiled chain so later chains can reuse its prefix."""
    if len(sig_nodes) < _CSE_MIN_PREFIX:
        return
    entry = (comm, sig_nodes, leaf_tokens)
    with _CSE_LOCK:
        if entry in _CSE_CHAINS:
            return
        _CSE_CHAINS.append(entry)
        del _CSE_CHAINS[:-_CSE_MAX_CHAINS]


def _cse_compile(comm, nodes, spec, sig_nodes, leaf_metas, out_ids, out_metas):
    """Composite program for a chain sharing a prefix with a seen chain,
    or None when no registered chain shares at least ``_CSE_MIN_PREFIX``
    serialized nodes. The shared prefix compiles as its own cached
    program (keyed by its serialized form + boundary, so every chain
    with the same prefix and cut reuses ONE executable); the remainder
    compiles per chain and consumes the boundary buffers as extra
    leaves. The composite replays as prefix-then-remainder with outputs
    routed back into full-graph order."""
    leaf_tokens = tuple(m.token for m in leaf_metas)
    with _CSE_LOCK:
        chains = list(_CSE_CHAINS)
    k = 0
    for e_comm, e_nodes, e_leaves in chains:
        if e_comm != comm:
            continue
        k = max(k, _cse_prefix_len(sig_nodes, leaf_tokens, e_nodes, e_leaves))
    # the full chain always keeps at least its last node in the
    # remainder: the final node is necessarily a target (nothing after
    # it consumes it), so the remainder program is never empty
    k = min(k, len(nodes) - 1)
    if k < _CSE_MIN_PREFIX:
        return None

    # boundary: prefix nodes the remainder consumes, plus prefix nodes
    # that are program outputs in their own right
    need = {i for i in out_ids if i < k}
    for _, _, _, wiring in spec[k:]:
        for tag, v in wiring:
            if tag == "n" and v < k:
                need.add(v)
    boundary = tuple(sorted(need))
    if not boundary:
        return None

    # leaves are numbered in first-use order, so the prefix touches
    # exactly slots [0, nlp)
    used = [
        v for _, _, _, wiring in spec[:k] for tag, v in wiring if tag == "l"
    ]
    nlp = 1 + max(used) if used else 0

    boundary_metas = [nodes[i].meta for i in boundary]
    psig = ("cse", comm, leaf_tokens[:nlp], tuple(sig_nodes[:k]), boundary)
    pprog = PROGRAM_CACHE.get(psig)
    if pprog is None:
        pprog = _build_program(spec[:k], leaf_metas[:nlp], boundary,
                               boundary_metas, comm)
        PROGRAM_CACHE[psig] = pprog
    else:
        stats_inc("cse_hits")

    # remainder: rewrite wiring so prefix nodes arrive as extra leaves
    # appended after the graph's own leaf slots
    slot = {i: len(leaf_metas) + j for j, i in enumerate(boundary)}
    rspec = []
    for kind, op, statics, wiring in spec[k:]:
        rw = tuple(
            (("n", v - k) if v >= k else ("l", slot[v]))
            if tag == "n" else (tag, v)
            for tag, v in wiring
        )
        rspec.append((kind, op, statics, rw))
    r_out = tuple(i - k for i in out_ids if i >= k)
    r_metas = [nodes[i].meta for i in out_ids if i >= k]
    rprog = _build_program(rspec, list(leaf_metas) + boundary_metas,
                           r_out, r_metas, comm)

    # output routing: each full-graph output comes from one of the two
    # programs, in full out_ids order
    route, ri = [], 0
    for i in out_ids:
        if i < k:
            route.append(("p", boundary.index(i)))
        else:
            route.append(("r", ri))
            ri += 1

    def run(*bufs):
        pouts = pprog(*bufs[:nlp])
        routs = rprog(*bufs, *pouts)
        return tuple(
            pouts[j] if tag == "p" else routs[j] for tag, j in route
        )

    return run


def _evaluate_group(comm, targets: Sequence[Node]) -> None:
    nodes = _collect(targets)
    if not nodes:
        return
    index = {id(n): i for i, n in enumerate(nodes)}
    target_ids = {id(n) for n in targets}

    leaf_bufs, leaf_metas = [], []
    leaf_ix = {}
    spec, sig_nodes = [], []
    for n in nodes:
        wiring, sig_args = [], []
        for tag, v in n.inputs:
            if tag == "node" and v.buffer is None:
                wiring.append(("n", index[id(v)]))
                sig_args.append(("n", index[id(v)]))
            elif tag == "scalar":
                wiring.append(("s", v))
                sig_args.append(("s",) + tuple(scalar_token(v)))
            else:
                buf = v.buffer  # Leaf, or an already-evaluated Node
                meta = v.meta
                j = leaf_ix.get(id(buf))
                if j is None:
                    j = len(leaf_bufs)
                    leaf_ix[id(buf)] = j
                    leaf_bufs.append(buf)
                    leaf_metas.append(meta)
                wiring.append(("l", j))
                sig_args.append(("l", j))
        spec.append((n.kind, n.op, n.statics, tuple(wiring)))
        sig_nodes.append((n.kind, n.op, n.sig_statics, tuple(sig_args)))

    for buf in leaf_bufs:
        if buf.is_deleted():
            raise RuntimeError(
                "a buffer captured into a lazy graph was donated before "
                "evaluation (in-place __setitem__ on a source array inside "
                "a ht.lazy() scope); materialize consumers before mutating "
                "their inputs"
            )

    # a node stays a program output while its LazyDNDarray is reachable
    # (someone may still read it) or it was explicitly forced; dead
    # intermediates stay fused away inside the program
    out_ids = tuple(
        i
        for i, n in enumerate(nodes)
        if id(n) in target_ids or (n.ref is not None and n.ref() is not None)
    )
    out_metas = [nodes[i].meta for i in out_ids]

    sig = (comm, tuple(m.token for m in leaf_metas), tuple(sig_nodes), out_ids)
    prog = PROGRAM_CACHE.get(sig)
    if prog is None:
        prog = _cse_compile(
            comm, nodes, spec, tuple(sig_nodes), leaf_metas, out_ids, out_metas
        )
        if prog is None:
            prog = _build_program(spec, leaf_metas, out_ids, out_metas, comm)
        PROGRAM_CACHE[sig] = prog
        stats_inc("graphs_captured")
        _cse_register(comm, tuple(sig_nodes),
                      tuple(m.token for m in leaf_metas))
    else:
        stats_inc("cache_hits")
    stats_inc("fused_dispatches")

    outs = prog(*leaf_bufs)
    for i, buf in zip(out_ids, outs):
        n = nodes[i]
        n.buffer = buf
        arr = n.ref() if n.ref is not None else None
        if arr is not None:
            arr._lazy_fill(buf)
    for n in nodes:
        if n.buffer is not None:
            n.release_inputs()


def evaluate(targets: Sequence[Node]) -> None:
    """Materialize ``targets`` (and their unevaluated ancestors), one
    fused program per communicator (disjoint chains on different meshes
    cannot share a jit)."""
    pending, seen = [], set()
    for n in targets:
        if n.buffer is None and id(n) not in seen:
            seen.add(id(n))
            pending.append(n)
    if not pending:
        return
    groups: List[Tuple[object, List[Node]]] = []
    for n in pending:
        for c, lst in groups:
            if c == n.meta.comm:
                lst.append(n)
                break
        else:
            groups.append((n.meta.comm, [n]))
    for c, lst in groups:
        _evaluate_group(c, lst)
