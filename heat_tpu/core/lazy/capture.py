"""Capture side of the lazy-fusion subsystem.

:class:`LazyScope` (exposed as ``ht.lazy()``) pushes a scope onto a
module-level stack; while any scope is active the four generic
dispatchers in :mod:`heat_tpu.core._operations` offer each call to this
module *before* dispatching. A supported call is recorded as a
:class:`~heat_tpu.core.lazy.graph.Node` and answered with a
:class:`LazyDNDarray` — a real DNDarray whose buffer does not exist yet.
An unsupported call (``out=``, non-default ``where=``, unhashable
statics, a per-call closure op, an operand that would need a host-side
ragged exchange, ...) is *declined*: the dispatcher proceeds eagerly,
``FUSE_STATS["eager_fallbacks"]`` counts it, and the answer is correct
either way — capture is a performance path, never a semantics path.

The escape hatch is the buffer property: DNDarray compiles every
``self.__array`` read to the fixed attribute name ``_DNDarray__array``,
and LazyDNDarray intercepts exactly that name with a data descriptor.
*Any* base-class code path that touches real data — ``.numpy()``,
``print``, ``.item()``, indexing, I/O, resplit, an op outside the
supported set — therefore forces evaluation of the pending subgraph
transparently, with zero per-method shimming. Metadata stays free:
``shape``/``dtype``/``split``/``lcounts``/``lshape_map`` answer from the
node's inferred layout without materializing.
"""
from __future__ import annotations

import functools
import threading
import weakref
from typing import List, Optional

from .. import _hooks, _operations
from ..dndarray import DNDarray
from . import evaluate
from .graph import Leaf, Node, NodeMeta, scalar_token, stats_inc

__all__ = ["LazyDNDarray", "LazyScope", "lazy", "fuse", "active",
           "binary", "local", "reduce", "cum", "matmul", "argreduce"]

# innermost-last stack of open ht.lazy() scopes, PER THREAD: a serving
# dispatcher thread replaying requests must not see (or append to) a
# client thread's open scope — concurrent ht.lazy() scopes are
# independent by construction
_TLS = threading.local()


def _scopes() -> List["_Scope"]:
    s = getattr(_TLS, "scopes", None)
    if s is None:
        s = _TLS.scopes = []
    return s


# why the most recent capture was declined (debugging aid; not API)
_LAST_DECLINE: Optional[str] = None


def active() -> bool:
    """True when dispatcher calls should be offered for capture: some
    scope is open on THIS thread and we are not inside our own
    replay/inference (which runs the dispatchers eagerly under
    trace-safe mode)."""
    return bool(_scopes()) and not _hooks.in_trace_safe()


class _Scope:
    __slots__ = ("created",)

    def __init__(self):
        self.created: List[Node] = []


class LazyDNDarray(DNDarray):
    """A DNDarray whose buffer is a pending node of a captured graph.

    Layout metadata (``gshape``/``dtype``/``split``/``lcounts``) is
    inferred at capture time by the same dispatcher code the eager path
    runs, so metadata consumers never force. The physical buffer
    materializes on first access — through scope exit (fused program),
    or on demand when base-class code reads ``_DNDarray__array`` (the
    name-mangled spelling of every ``self.__array`` in dndarray.py,
    intercepted below by a data descriptor, which takes precedence over
    the instance dict)."""

    @classmethod
    def _from_node(cls, node: Node) -> "LazyDNDarray":
        out = cls.__new__(cls)
        m = node.meta
        out._DNDarray__comm = m.comm
        out._DNDarray__device = m.device
        out._DNDarray__dtype = m.dtype
        out._DNDarray__split = m.split
        out._DNDarray__gshape = m.gshape
        out._DNDarray__lcounts = m.lcounts
        out._lazy_node = node
        node.ref = weakref.ref(out)
        return out

    # The buffer trap. The getter materializes; the setter (hit by
    # larray=/-_set_buffer-style rebinds, e.g. in-place operators) simply
    # detaches this array from its node by storing a concrete buffer.
    @property
    def _DNDarray__array(self):
        buf = self.__dict__.get("_lazy_buf")
        if buf is None:
            buf = _force(self)
        return buf

    @_DNDarray__array.setter
    def _DNDarray__array(self, value):
        self.__dict__["_lazy_buf"] = value

    def _lazy_fill(self, buf) -> None:
        """Install the evaluated buffer (called by the evaluator)."""
        self.__dict__["_lazy_buf"] = buf

    @property
    def pshape(self):
        """Physical buffer shape — from the inferred layout while
        pending (the base property would read the buffer and force)."""
        buf = self.__dict__.get("_lazy_buf")
        if buf is not None:
            return tuple(buf.shape)
        return self._lazy_node.meta.pshape

    @property
    def padded(self) -> bool:
        return self.lcounts is not None or self.pshape != self.gshape

    @property
    def is_materialized(self) -> bool:
        """True once this result's buffer exists (evaluation ran)."""
        return self.__dict__.get("_lazy_buf") is not None


def _force(arr: LazyDNDarray):
    """Materialize ``arr`` now: evaluate its pending ancestor closure as
    one fused program. Counted as an eager fallback when it happens
    inside an open scope (something needed real data mid-capture)."""
    node = arr._lazy_node
    if node.buffer is None:
        if active():
            stats_inc("eager_fallbacks")
        evaluate.evaluate([node])
    arr.__dict__["_lazy_buf"] = node.buffer
    return node.buffer


# ------------------------------------------------------------------ public API
class LazyScope:
    """Context manager recording supported DNDarray ops into a graph.

    On clean exit every still-reachable pending result created in the
    scope is evaluated in one fused program (per communicator); on an
    exception the scope is popped *without* evaluating — eager execution
    is fully restored, and any escaped pending arrays materialize
    transparently on first access."""

    def __init__(self):
        self._scope: Optional[_Scope] = None

    def __enter__(self) -> "LazyScope":
        self._scope = _Scope()
        _scopes().append(self._scope)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        scope, self._scope = self._scope, None
        try:
            _scopes().remove(scope)
        except ValueError:  # pragma: no cover - defensive (misnested exit)
            pass
        if exc_type is None and scope is not None:
            targets = [
                n
                for n in scope.created
                if n.buffer is None and n.ref is not None and n.ref() is not None
            ]
            if targets:
                evaluate.evaluate(targets)
        return False


def lazy() -> LazyScope:
    """Open a lazy-evaluation scope::

        with ht.lazy():
            z = (x - mu) / sigma      # recorded, not dispatched
            s = ht.sum(z * z, axis=0)
        # scope exit: one fused XLA program computes z and s

    Results are bit-identical to eager execution: evaluation replays the
    recorded calls through the original dispatchers inside one
    ``jax.jit``. Anything that needs real data mid-scope (``.numpy()``,
    ``print``, ``.item()``, indexing, an unsupported op) forces the
    pending subgraph and continues; see docs/PERFORMANCE.md.
    """
    return LazyScope()


def fuse(fn):
    """Decorator form of :func:`lazy`: the whole function body records
    into one scope and its results are evaluated (fused) on return::

        @ht.fuse
        def standardize(x, mu, sigma):
            return (x - mu) / sigma
    """

    @functools.wraps(fn)
    def fused(*args, **kwargs):
        with LazyScope():
            return fn(*args, **kwargs)

    return fused


# ------------------------------------------------------------- capture points
def _decline(reason: str):
    global _LAST_DECLINE
    _LAST_DECLINE = reason
    stats_inc("eager_fallbacks")
    return NotImplemented


def _op_token_ok(op) -> bool:
    """Ops key caches by object identity: module-level functions and
    ``_cache_stable`` closures are stable; per-call closures / partials
    would make every graph signature unique (the G001 retrace bug) and
    are declined."""
    if isinstance(op, functools.partial):
        return False
    if "<locals>" in getattr(op, "__qualname__", "") and not getattr(
        op, "_cache_stable", False
    ):
        return False
    try:
        hash(op)
    except TypeError:  # pragma: no cover - defensive
        return False
    return True


def _operand(t: DNDarray):
    """Graph wiring for a DNDarray operand: a pending lazy result links
    by node; anything concrete (including an already-materialized lazy
    result) snapshots its buffer + layout as a leaf."""
    if isinstance(t, LazyDNDarray):
        node = getattr(t, "_lazy_node", None)
        if (
            node is not None
            and node.buffer is None
            and t.__dict__.get("_lazy_buf") is None
        ):
            return ("node", node)
    return ("leaf", Leaf(t._raw, NodeMeta.of(t)))


def _capture(kind: str, op, raw_operands, statics, sig_statics):
    """Common tail of the four capture points: validate, wire operands,
    infer the result layout through the eager rules, and hand back a
    pending LazyDNDarray. Any failure (unhashable statics, an op that
    would need a host-side exchange under trace, a genuine user error
    the eager path will re-raise) declines."""
    if not _op_token_ok(op):
        return _decline("per-call closure or unhashable op")
    operands = []
    comm = None
    for t in raw_operands:
        if isinstance(t, DNDarray):
            if comm is None:
                comm = t.comm
            elif t.comm != comm:
                return _decline("operands on different communicators")
            operands.append(_operand(t))
        else:
            tok = scalar_token(t)
            if tok is None:
                return _decline("untokenizable scalar operand")
            operands.append(("scalar", t))
    if comm is None:
        return _decline("no DNDarray operand")
    try:
        hash(sig_statics)
    except TypeError:
        return _decline("unhashable statics")
    infer_specs = [
        (("meta", v.meta) if tag in ("node", "leaf") else (tag, v))
        for tag, v in operands
    ]
    try:
        meta = evaluate.infer_meta(kind, op, sig_statics, statics, infer_specs, comm)
    except Exception as e:
        # includes TraceBarrierError (op needs a host-side exchange) and
        # genuine user errors, which the eager path will raise identically
        return _decline(f"{type(e).__name__}: {e}")
    node = Node(kind, op, operands, statics, sig_statics, meta)
    _scopes()[-1].created.append(node)
    return LazyDNDarray._from_node(node)


def binary(operation, t1, t2, out, where, fn_kwargs):
    if out is not None or where is not True:
        return _decline("out=/where= not captured")
    kwargs = dict(fn_kwargs) if fn_kwargs else {}
    kwargs_key = _operations._kwargs_key(kwargs)
    if kwargs_key is None:
        return _decline("unhashable fn_kwargs")
    if not (isinstance(t1, DNDarray) or isinstance(t2, DNDarray)):
        return _decline("no DNDarray operand")
    for t in (t1, t2):
        if not isinstance(t, (DNDarray,) + _operations.Scalar):
            return _decline("non-scalar, non-DNDarray operand")
    return _capture("binary", operation, (t1, t2), (kwargs,), ("b", kwargs_key))


def local(operation, x, out, no_cast, out_dtype, kwargs):
    if out is not None or not isinstance(x, DNDarray):
        return _decline("out= / non-DNDarray input")
    kwargs = dict(kwargs)
    kwargs_key = _operations._kwargs_key(kwargs)
    if kwargs_key is None:
        return _decline("unhashable kwargs")
    return _capture(
        "local", operation, (x,), (bool(no_cast), out_dtype, kwargs),
        ("l", bool(no_cast), out_dtype, kwargs_key),
    )


def reduce(operation, x, axis, out, keepdims, out_dtype, neutral, kwargs):
    if out is not None or not isinstance(x, DNDarray):
        return _decline("out= / non-DNDarray input")
    kwargs = dict(kwargs)
    kwargs_key = _operations._kwargs_key(kwargs)
    if kwargs_key is None:
        return _decline("unhashable kwargs")
    return _capture(
        "reduce", operation, (x,),
        (axis, bool(keepdims), out_dtype, neutral, kwargs),
        ("r", _operations._axis_key(axis), bool(keepdims), out_dtype, neutral, kwargs_key),
    )


def cum(operation, x, axis, out, dtype, neutral):
    if out is not None or not isinstance(x, DNDarray):
        return _decline("out= / non-DNDarray input")
    return _capture(
        "cum", operation, (x,), (axis, dtype, neutral),
        ("c", _operations._axis_key(axis), dtype, neutral),
    )


def argreduce(operation, x, axis, out):
    """Capture point for :func:`heat_tpu.core.statistics._arg_reduce`
    (argmax/argmin) — the tail of the canonical predict pipeline. The
    whole eager body (padding mask, flat-index remap, int64 cast) is
    traceable, so it replays verbatim inside the fused jit."""
    if out is not None or not isinstance(x, DNDarray):
        return _decline("out= / non-DNDarray input")
    return _capture(
        "argreduce", operation, (x,), (axis,),
        ("a", _operations._axis_key(axis)),
    )


def matmul(a, b, allow_resplit):
    """Capture point for :func:`heat_tpu.core.linalg.basics.matmul` — the
    contraction a captured predict pipeline (standardize -> matmul ->
    argmax) needs to replay as ONE fused program. ``jnp.matmul`` on
    sharded operands is fully traceable (GSPMD inserts the collectives),
    so the whole eager path replays under the fused jit; only the
    explicit-resplit variant moves data host-side and must decline."""
    if allow_resplit:
        return _decline("matmul allow_resplit= not captured")
    if not (isinstance(a, DNDarray) and isinstance(b, DNDarray)):
        return _decline("matmul needs two DNDarray operands")
    from ..linalg import basics  # deferred: linalg must not load before core

    return _capture("matmul", basics.matmul, (a, b), (), ("m",))
