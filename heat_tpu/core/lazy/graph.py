"""Expression-graph representation for the lazy-fusion subsystem.

A captured op becomes a :class:`Node`; its operands are other nodes
(pending captured results), :class:`Leaf` snapshots of concrete
DNDarray buffers, or plain Python scalars held as statics. The graph is
deliberately *metadata-complete*: every node carries the full layout
tuple (``gshape``/``dtype``/``split``/``lcounts``/``pshape``) computed
at capture time by abstract-evaluating the same dispatcher code the
eager path runs (see :mod:`heat_tpu.core.lazy.evaluate`), so user code
can read ``.shape``/``.dtype``/``.lshape_map`` off a pending result
without forcing it.

Signatures
----------
A fused program is cached by a *signature*: the topologically serialized
graph (op identities, static kwargs, operand wiring) plus the leaf
layout tuples and the communicator — the ``(graph hash, mesh, split,
lcounts, dtype)`` key of the graftlint G001/G002 discipline. Scalars are
tokenized by type and value (floats via ``float.hex`` so a NaN keys
consistently — nan != nan would make every lookup miss, the
``_jitted_reduce`` "__nan__" lesson), and ops key by object identity,
which is stable for ``jnp`` module functions and for module-level
closures marked ``_cache_stable`` — per-call ``<locals>`` closures are
declined at capture instead of poisoning the cache.
"""
from __future__ import annotations

import itertools
import threading
from typing import Any, Optional, Tuple

__all__ = ["FUSE_STATS", "reset_fuse_stats", "stats_inc",
           "NodeMeta", "Leaf", "Node", "scalar_token"]

# Counters for the lazy-fusion subsystem (module-level like LAYOUT_STATS /
# MOVE_STATS; re-exported as ``heat_tpu.FUSE_STATS``):
#
# - ``graphs_captured``   distinct pending subgraphs lowered into a new
#                         fused program (program-cache misses);
# - ``fused_dispatches``  fused-program executions (a warm chain is
#                         exactly one of these);
# - ``cache_hits``        executions served by a cached executable — on a
#                         warm replay ``cache_hits`` rises with
#                         ``fused_dispatches`` while ``graphs_captured``
#                         and COMPILE_STATS compiles/traces stay flat;
# - ``eager_fallbacks``   ops inside a ``ht.lazy()`` scope that could not
#                         be captured (unsupported form, ``out=``, an op
#                         needing a host-side exchange, ...) plus forced
#                         mid-scope materializations (``.numpy()``,
#                         ``print``, indexing, ``.item()``); either way
#                         the op itself runs eagerly and stays correct;
# - ``cse_hits``          program-cache misses that reused an already-
#                         compiled shared-prefix program instead of
#                         re-tracing it (cross-chain common-subexpression
#                         reuse in :mod:`heat_tpu.core.lazy.evaluate` —
#                         N endpoints sharing a standardize-style prefix
#                         compile it once; warm replay of the composite
#                         still counts exactly one fused_dispatch and one
#                         cache_hit).
FUSE_STATS = {
    "graphs_captured": 0,
    "fused_dispatches": 0,
    "eager_fallbacks": 0,
    "cache_hits": 0,
    "cse_hits": 0,
}


# FUSE_STATS is written from every thread that captures or evaluates
# (the serve dispatcher thread beside any number of client threads);
# ``d[k] += 1`` is a read-modify-write that loses counts under the GIL's
# bytecode-level interleaving, so all increments go through this lock.
_STATS_LOCK = threading.Lock()


def stats_inc(key: str, n: int = 1) -> None:
    """Thread-safe FUSE_STATS increment (the only sanctioned writer)."""
    with _STATS_LOCK:
        FUSE_STATS[key] += n


def reset_fuse_stats() -> None:
    """Zero all FUSE_STATS counters (test/bench isolation)."""
    with _STATS_LOCK:
        for k in FUSE_STATS:
            FUSE_STATS[k] = 0


# next(_seq) is atomic at the C level in CPython, so node sequence
# numbers stay unique across capturing threads without a lock
_seq = itertools.count()


class NodeMeta:
    """Full layout metadata of a (pending or concrete) DNDarray.

    ``token`` is the hashable signature fragment: physical shape, heat
    dtype, split axis and ragged ``lcounts`` — everything that changes
    the traced program. ``comm``/``device`` ride along for
    reconstruction but the communicator enters the signature once per
    graph (all nodes of one fused program share it)."""

    __slots__ = ("gshape", "dtype", "split", "lcounts", "pshape", "device", "comm")

    def __init__(self, gshape, dtype, split, lcounts, pshape, device, comm):
        self.gshape = tuple(gshape)
        self.dtype = dtype
        self.split = split
        self.lcounts = None if lcounts is None else tuple(lcounts)
        self.pshape = tuple(pshape)
        self.device = device
        self.comm = comm

    @property
    def token(self) -> Tuple:
        return (self.pshape, self.gshape, self.dtype, self.split, self.lcounts)

    @classmethod
    def of(cls, x) -> "NodeMeta":
        """Snapshot a live DNDarray's layout (lazy or concrete — the
        LazyDNDarray ``pshape``/``lcounts`` overrides answer from node
        metadata without forcing)."""
        # graftflow: F002 - lcounts is replicated layout metadata by
        # construction (set from global layout decisions on every rank),
        # so a signature keyed by it is rank-uniform; see _operations.
        return cls(x.gshape, x.dtype, x.split, x.lcounts, x.pshape, x.device, x.comm)


class Leaf:
    """A concrete operand captured by reference: the physical buffer as
    it was at capture time plus its layout. Holding the ``jax.Array``
    itself (not the DNDarray) pins the *value*: a later in-place update
    of the source array rebinds its buffer and cannot retroactively
    change an already-captured graph. The one sharp edge is donation
    (basic-index ``__setitem__`` donates the old buffer); evaluation
    checks ``is_deleted()`` and raises a clear error instead of reading
    freed memory."""

    __slots__ = ("buffer", "meta")

    def __init__(self, buffer, meta: NodeMeta):
        self.buffer = buffer
        self.meta = meta


class Node:
    """One captured dispatcher call.

    ``kind`` selects the replay entry point (``"binary"`` / ``"local"``
    / ``"reduce"`` / ``"cum"``); ``inputs`` is the operand wiring as
    ``("node", Node) | ("leaf", Leaf) | ("scalar", value)`` pairs in
    dispatcher argument order; ``statics`` is the kind-specific tuple of
    non-array arguments exactly as the dispatcher received them (replay
    passes them back verbatim); ``sig_statics`` is their hashable
    tokenized form. ``buffer`` is filled by evaluation; ``ref`` weakly
    tracks the LazyDNDarray wrapping this node so scope exit knows which
    pending results are still reachable."""

    __slots__ = ("seq", "kind", "op", "inputs", "statics", "sig_statics",
                 "meta", "buffer", "ref", "__weakref__")

    def __init__(self, kind, op, inputs, statics, sig_statics, meta):
        self.seq = next(_seq)
        self.kind = kind
        self.op = op
        self.inputs = tuple(inputs)
        self.statics = statics
        self.sig_statics = sig_statics
        self.meta = meta
        self.buffer = None
        self.ref = None

    def release_inputs(self) -> None:
        """Drop operand references once ``buffer`` is set — evaluated
        nodes act as leaves for any later program, so keeping the wiring
        alive would pin ancestor buffers for no reason."""
        self.inputs = ()


def scalar_token(v) -> Optional[Tuple[str, Any]]:
    """Hashable, value-faithful signature token for a scalar operand, or
    None when the value cannot be tokenized. The Python type enters the
    token because promotion is type-sensitive (np.float32(2) and 2.0
    promote differently); floats key by ``hex()`` so NaN has one stable
    spelling."""
    if isinstance(v, bool):
        return ("bool", v)
    if isinstance(v, int):
        return ("int", v)
    if isinstance(v, float):
        return ("float", v.hex())
    if isinstance(v, complex):
        return ("complex", v.real.hex(), v.imag.hex())
    try:  # numpy scalars: dtype-qualified, value via float/int round trip
        import numpy as np

        if isinstance(v, np.bool_):
            return ("np.bool_", bool(v))
        if isinstance(v, np.integer):
            return (type(v).__name__, int(v))
        if isinstance(v, np.floating):
            return (type(v).__name__, float(v).hex())
        if isinstance(v, np.complexfloating):
            c = complex(v)
            return (type(v).__name__, c.real.hex(), c.imag.hex())
    except TypeError:  # pragma: no cover - defensive
        pass
    return None
