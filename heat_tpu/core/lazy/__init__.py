"""Opt-in deferred execution: fuse DNDarray op chains into single XLA programs.

Public surface (re-exported as ``ht.lazy`` / ``ht.fuse`` /
``ht.FUSE_STATS``):

- :func:`~heat_tpu.core.lazy.capture.lazy` — context manager; supported
  ops inside the scope are recorded instead of dispatched and the whole
  chain runs as ONE fused ``jax.jit`` program at scope exit;
- :func:`~heat_tpu.core.lazy.capture.fuse` — decorator form;
- ``FUSE_STATS`` / :func:`reset_fuse_stats` — capture/dispatch counters.

Importing this package installs the capture hook into
:mod:`heat_tpu.core._operations`; with no open scope the hook is a single
``is None``-guarded attribute read per dispatch.
"""
from . import capture, evaluate, graph
from .capture import LazyDNDarray, LazyScope, fuse, lazy
from .evaluate import META_CACHE, PROGRAM_CACHE
from .graph import FUSE_STATS, reset_fuse_stats

from .. import _operations

# hand the dispatchers their capture entry points (kept None until this
# package is imported so _operations has no import-cycle on lazy)
_operations._capture = capture

__all__ = [
    "lazy",
    "fuse",
    "LazyScope",
    "LazyDNDarray",
    "FUSE_STATS",
    "reset_fuse_stats",
    "META_CACHE",
    "PROGRAM_CACHE",
]
