"""Statistical operations (reference ``heat/core/statistics.py``, 1997 LoC).

The reference implements parallel Welford moment-merging
(``__merge_moments``, ``statistics.py:1043``) and custom MPI argmax/argmin
ops over stacked (value, index) buffers (``statistics.py:1335-1404``).
Under XLA a single global ``jnp`` reduction over a sharded array compiles to
the identical local-partial + all-reduce schedule, so all of that machinery
disappears; what remains is axis/ddof bookkeeping and the unbiased
skew/kurtosis corrections.
"""
from __future__ import annotations

import weakref
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import _hooks, types
from . import _operations
from ._cache import ExecutableCache
from ._operations import (
    _binary_op,
    _local_op,
    _mask_padding,
    _neutral_value,
    _reduce_op,
    _reduced_shape,
    _reduced_split,
)
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = [
    "argmax",
    "argmin",
    "average",
    "bincount",
    "bucketize",
    "cov",
    "digitize",
    "histc",
    "histogram",
    "kurtosis",
    "max",
    "nanmax",
    "nanmean",
    "nanmin",
    "maximum",
    "mean",
    "median",
    "min",
    "minimum",
    "percentile",
    "skew",
    "std",
    "var",
]


def argmax(x: DNDarray, axis=None, out=None, **kwargs) -> DNDarray:
    """Index of the maximum (reference ``statistics.py`` via MPI_ARGMAX)."""
    return _arg_reduce(jnp.argmax, x, axis, out)


def argmin(x: DNDarray, axis=None, out=None, **kwargs) -> DNDarray:
    """Index of the minimum (reference via MPI_ARGMIN)."""
    return _arg_reduce(jnp.argmin, x, axis, out)


def _arg_reduce(op, x, axis, out):
    # offer the call for lazy capture before the buffer read below can
    # force a pending operand (same slot protocol as the generic
    # dispatchers) — this is the tail of the standardize -> matmul ->
    # argmax predict pipeline, which must replay as ONE fused program
    if _operations._capture is not None and _operations._capture.active():
        res = _operations._capture.argreduce(op, x, axis, out)
        if res is not NotImplemented:
            return res
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    axis = sanitize_axis(x.shape, axis)
    arr = x.larray
    if x.padded:
        # padding can never win: fill it with the op's worst value
        fill = _neutral_value("min" if op is jnp.argmax else "max", arr.dtype)
        arr = _mask_padding(arr, x.gshape, x.split, fill)
    result = op(arr, axis=axis)
    if x.padded and axis is None and x.ndim > 1:
        # flat indices refer to the padded buffer; remap to logical layout
        coords = jnp.unravel_index(result, arr.shape)
        result = jnp.ravel_multi_index(coords, x.gshape, mode="clip")
    split = _reduced_split(x.split, axis if axis is not None else None, x.ndim, False)
    result = result.astype(jnp.int64)
    out_gshape = _reduced_shape(x.gshape, axis, False)
    if split is not None and tuple(result.shape) != out_gshape:
        res = DNDarray._from_buffer(result, out_gshape, types.int64, split, x.device, x.comm)
    else:
        res = DNDarray(
            result,
            gshape=out_gshape,
            dtype=types.int64,
            split=split,
            device=x.device,
            comm=x.comm,
        )
    if out is not None:
        from ._operations import _write_out

        return _write_out(out, res)
    return res


def average(x: DNDarray, axis=None, weights: Optional[DNDarray] = None, returned: bool = False):
    """Weighted average (reference ``statistics.py:189``)."""
    if weights is None:
        result = mean(x, axis)
        if returned:
            n = x.size if axis is None else np.prod([x.shape[a] for a in _axes(x, axis)])
            from . import factories

            return result, factories.full_like(result, float(n))
        return result
    axis_s = sanitize_axis(x.shape, axis)
    w = weights._logical() if isinstance(weights, DNDarray) else jnp.asarray(weights)
    xa = x._logical()
    if w.ndim != xa.ndim:
        if axis_s is None or isinstance(axis_s, tuple):
            raise TypeError("Axis must be specified when shapes of x and weights differ.")
        shape = [1] * xa.ndim
        shape[axis_s] = -1
        w = w.reshape(shape)
    wsum = jnp.sum(jnp.broadcast_to(w, xa.shape), axis=axis_s)
    # numpy parity: zero weight sums raise. Host-provided weights are
    # checked for free on their (small) host copy; device-resident
    # (DNDarray) weights pay one small fetch — average is an eager
    # analytics entry point, not a training-loop op.
    if not isinstance(weights, DNDarray) and isinstance(axis_s, (int, type(None))):
        # graftlint: host-sync - host-provided weights, checked on their host copy
        wnp = np.asarray(weights, dtype=np.float64).reshape(tuple(w.shape))
        if axis_s is None:
            zero = bool(wnp.sum() == 0)
        elif wnp.shape[axis_s] == xa.shape[axis_s]:
            zero = bool(np.any(wnp.sum(axis=axis_s) == 0))
        else:  # weights broadcast along the reduced axis
            zero = bool(np.any(wnp == 0))
    else:
        zero = bool(jnp.any(wsum == 0))
    if zero:
        raise ZeroDivisionError("Weights sum to zero, can't be normalized")
    result = jnp.sum(xa * w, axis=axis_s) / wsum
    split = _reduced_split(x.split, axis_s, x.ndim, False)
    res = DNDarray(result, dtype=types.canonical_heat_type(result.dtype), split=split, device=x.device, comm=x.comm)
    if returned:
        wres = DNDarray(jnp.broadcast_to(wsum, result.shape), split=split, device=x.device, comm=x.comm)
        return res, wres
    return res


def _axes(x, axis):
    if axis is None:
        return tuple(range(x.ndim))
    axis = sanitize_axis(x.shape, axis)
    return (axis,) if isinstance(axis, int) else axis


def bincount(x: DNDarray, weights=None, minlength: int = 0) -> DNDarray:
    """Count occurrences of each value (reference ``statistics.py:322``)."""
    w = weights._logical() if isinstance(weights, DNDarray) else weights
    result = jnp.bincount(x._logical(), weights=w, minlength=minlength)
    return DNDarray(result, dtype=types.canonical_heat_type(result.dtype), split=None, device=x.device, comm=x.comm)


def bucketize(input: DNDarray, boundaries, out_int32: bool = False, right: bool = False, out=None) -> DNDarray:
    """Index of the bucket each value falls into (reference
    ``statistics.py:393``)."""
    b = boundaries._logical() if isinstance(boundaries, DNDarray) else jnp.asarray(boundaries)
    # torch semantics: right=False -> first i with x <= b[i] (searchsorted
    # 'left'), right=True -> first i with x < b[i] ('right'); the flag was
    # inverted until the round-4 depth sweep compared against torch
    side = "right" if right else "left"
    idx_type = types.int32 if out_int32 else types.int64
    jt = idx_type.jax_type()
    return _local_op(lambda t: jnp.searchsorted(b, t, side=side).astype(jt), input, out=out, no_cast=True, out_dtype=idx_type)


def digitize(x: DNDarray, bins, right: bool = False) -> DNDarray:
    """Index of the bin each value belongs to (reference
    ``statistics.py:541``)."""
    b = bins._logical() if isinstance(bins, DNDarray) else jnp.asarray(bins)
    return _local_op(lambda t: jnp.digitize(t, b, right=right).astype(jnp.int64), x, no_cast=True, out_dtype=types.int64)


def cov(m: DNDarray, y: Optional[DNDarray] = None, rowvar: bool = True, bias: bool = False, ddof: Optional[int] = None) -> DNDarray:
    """Covariance matrix estimate (reference ``statistics.py:466``)."""
    if ddof is None:
        ddof = 0 if bias else 1
    x = m._logical()
    if x.ndim == 1:
        x = x[None, :]
    elif not rowvar and x.shape[0] != 1:
        x = x.T
    if y is not None:
        ya = y._logical()
        if ya.ndim == 1:
            ya = ya[None, :]
        elif not rowvar:
            ya = ya.T
        x = jnp.concatenate([x, ya], axis=0)
    avg = jnp.mean(x, axis=1, keepdims=True)
    fact = x.shape[1] - ddof
    xc = x - avg
    result = (xc @ xc.conj().T) / fact
    split = 0 if m.split is not None else None
    return DNDarray(jnp.squeeze(result), dtype=types.canonical_heat_type(result.dtype), split=split if result.ndim > 1 else None, device=m.device, comm=m.comm)


def histc(input: DNDarray, bins: int = 100, min: float = 0.0, max: float = 0.0, out=None) -> DNDarray:
    """Histogram with equal-width bins (torch-style; reference
    ``statistics.py:616``)."""
    arr = input._logical()
    lo, hi = float(min), float(max)
    if lo == 0.0 and hi == 0.0:
        lo, hi = float(jnp.min(arr)), float(jnp.max(arr))
    hist, _ = jnp.histogram(arr, bins=bins, range=(lo, hi))
    res = DNDarray(hist.astype(input.dtype.jax_type()), dtype=input.dtype, split=None, device=input.device, comm=input.comm)
    if out is not None:
        from ._operations import _write_out

        return _write_out(out, res)
    return res


def histogram(a: DNDarray, bins: int = 10, range=None, normed=None, weights=None, density=None):
    """numpy-style histogram (reference exposes torch histc; numpy parity
    added for convenience)."""
    hist, edges = jnp.histogram(a._logical(), bins=bins, range=range, density=density)
    return (
        DNDarray(hist, split=None, device=a.device, comm=a.comm),
        DNDarray(edges, split=None, device=a.device, comm=a.comm),
    )


def kurtosis(x: DNDarray, axis=None, unbiased: bool = True, Fischer: bool = True) -> DNDarray:
    """Kurtosis (reference ``statistics.py:727``; ``unbiased`` applies the
    sample-size correction, ``Fischer`` subtracts 3 — reference arg names).
    Moment merging is XLA's problem now."""
    axis_s = sanitize_axis(x.shape, axis)
    arr = x._logical().astype(jnp.promote_types(x.larray.dtype, jnp.float32))
    n = arr.size if axis_s is None else arr.shape[axis_s]
    mu = jnp.mean(arr, axis=axis_s, keepdims=True)
    m2 = jnp.mean((arr - mu) ** 2, axis=axis_s)
    m4 = jnp.mean((arr - mu) ** 4, axis=axis_s)
    g2 = m4 / (m2**2)
    if unbiased and n > 3:
        g2 = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 - 3 * (n - 1)) + 3
    if Fischer:
        g2 = g2 - 3
    split = _reduced_split(x.split, axis_s, x.ndim, False)
    return DNDarray(g2, dtype=types.canonical_heat_type(g2.dtype), split=split, device=x.device, comm=x.comm)


def skew(x: DNDarray, axis=None, unbiased: bool = True) -> DNDarray:
    """Skewness (reference ``statistics.py:1676``; ``unbiased`` applies the
    Fisher-Pearson sample correction)."""
    axis_s = sanitize_axis(x.shape, axis)
    arr = x._logical().astype(jnp.promote_types(x.larray.dtype, jnp.float32))
    n = arr.size if axis_s is None else arr.shape[axis_s]
    mu = jnp.mean(arr, axis=axis_s, keepdims=True)
    m2 = jnp.mean((arr - mu) ** 2, axis=axis_s)
    m3 = jnp.mean((arr - mu) ** 3, axis=axis_s)
    g1 = m3 / (m2**1.5)
    if unbiased and n > 2:
        g1 = g1 * np.sqrt(n * (n - 1)) / (n - 2)
    split = _reduced_split(x.split, axis_s, x.ndim, False)
    return DNDarray(g1, dtype=types.canonical_heat_type(g1.dtype), split=split, device=x.device, comm=x.comm)


def _nan_propagating(op):
    """Wrap a reduction so NaN wins (torch/numpy semantics): the sharded
    cross-device max/min collective silently drops NaN (maximum(nan, x)
    resolves to x in the all-reduce combiner), so an explicit isnan
    reduction rides along — XLA fuses the sibling passes."""

    def run(arr, axis=None, keepdims=False, **kw):
        r = op(arr, axis=axis, keepdims=keepdims, **kw)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            bad = jnp.any(jnp.isnan(arr), axis=axis, keepdims=keepdims)
            r = jnp.where(bad, jnp.asarray(jnp.nan, r.dtype), r)
        return r

    return run


# ONE closure per op, hoisted to module level: a fresh closure per call
# would make every ht.max/ht.min a cache miss in _jitted_reduce_cached
# (recompile each call, executables accumulating in the cache forever).
# Module-level identity keys the cache once; _cache_stable marks them as
# safe to cache despite being closures (see _operations._jitted_reduce).
_NANPROP_MAX = _nan_propagating(jnp.max)
_NANPROP_MIN = _nan_propagating(jnp.min)
_NANPROP_MAX._cache_stable = True
_NANPROP_MIN._cache_stable = True


def max(x: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Maximum along axis (reference ``statistics.py:781``); NaN wins."""
    return _reduce_op(
        _NANPROP_MAX, x, axis=axis, out=out, keepdims=bool(keepdim or keepdims), neutral="min"
    )


def maximum(x1, x2, out=None) -> DNDarray:
    """Elementwise maximum (reference ``statistics.py``)."""
    return _binary_op(jnp.maximum, x1, x2, out=out)


def mean(x: DNDarray, axis=None, where=None) -> DNDarray:
    """Arithmetic mean (reference ``statistics.py:891`` — local moments +
    Allreduce + pairwise merging). Dispatches through the one-pass moments
    panel (see :func:`_moments_panel`): a following ``ht.std``/``ht.var``
    on the same buffer reuses the memoized (count, mean, M2) and costs
    zero additional data reads."""
    if where is not None and isinstance(x, DNDarray):
        return _where_moment(jnp.mean, x, axis, where, 0)
    if isinstance(x, DNDarray):
        axis_s = sanitize_axis(x.shape, axis)
        stats = _moments_panel(x, axis_s)
        if stats is not None:
            return _wrap_moment(x, axis_s, stats[1])
    return _reduce_op(jnp.mean, x, axis=axis)


def nanmax(x: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Maximum ignoring NaNs (numpy extra beyond the reference)."""
    return _reduce_op(jnp.nanmax, x, axis=axis, out=out, keepdims=bool(keepdim or keepdims), neutral=("nan", "min"))


def nanmin(x: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Minimum ignoring NaNs (numpy extra beyond the reference)."""
    return _reduce_op(jnp.nanmin, x, axis=axis, out=out, keepdims=bool(keepdim or keepdims), neutral=("nan", "max"))


def nanmean(x: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Mean ignoring NaNs (numpy extra beyond the reference)."""
    return _reduce_op(jnp.nanmean, x, axis=axis, out=out, keepdims=bool(keepdim or keepdims), neutral=("nan", None))


def _streaming_percentile(chunks, q_host, axis, kd) -> DNDarray:
    """Single-pass approximate percentile over a ``ChunkIterator`` via a
    KLL sketch (rank error <= the sketch's ``eps``, ~1.4% at defaults)."""
    if axis is not None:
        raise ValueError(
            "streaming percentile/median folds all elements (axis=None "
            f"semantics); per-axis reduction is not supported, got axis={axis}"
        )
    if kd:
        raise ValueError("keepdim is not supported on the streaming path")
    from ..stream.sketch import KLLSketch

    sk = KLLSketch()
    for chunk in chunks:
        sk.update(chunk)
    return sk.percentile(q_host.tolist())


def _check_array_arg(x, name: str):
    """Reject non-DNDarray inputs with a message that names the streaming
    sketch path — a ``ChunkIterator`` is valid, anything else is not."""
    if not isinstance(x, DNDarray):
        raise TypeError(
            f"{name} expects a DNDarray (exact, in-memory) or a "
            "heat_tpu.stream.ChunkIterator (single-pass approximate KLL "
            f"sketch path), got {type(x).__name__}"
        )


def median(x: DNDarray, axis=None, keepdim: bool = False, keepdims=None) -> DNDarray:
    """Median (reference ``statistics.py:1017``, gather-based; when the
    reduced axis is the split axis the distributed-sort percentile path
    runs instead — O(n/P) memory, see :func:`percentile`). A
    ``ChunkIterator`` input streams through the KLL sketch instead
    (approximate, see ``docs/STREAMING.md``)."""
    kd = bool(keepdim or keepdims)
    from ..stream.chunked import ChunkIterator

    if isinstance(x, ChunkIterator):
        return _streaming_percentile(x, np.asarray(50.0), axis, kd)
    _check_array_arg(x, "median")
    axis_s = sanitize_axis(x.shape, axis)
    if _use_sorted_percentile(x, axis_s):
        result = _sorted_percentile(x, jnp.asarray(50.0), axis_s, "linear", kd)
        return DNDarray(result, dtype=types.canonical_heat_type(result.dtype), split=None, device=x.device, comm=x.comm)
    result = jnp.median(x._logical(), axis=axis_s, keepdims=kd)
    split = _reduced_split(x.split, axis_s, x.ndim, kd)
    return DNDarray(result, dtype=types.canonical_heat_type(result.dtype), split=split, device=x.device, comm=x.comm)


def min(x: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Minimum along axis (reference ``statistics.py:1114``); NaN wins."""
    return _reduce_op(
        _NANPROP_MIN, x, axis=axis, out=out, keepdims=bool(keepdim or keepdims), neutral="max"
    )


def minimum(x1, x2, out=None) -> DNDarray:
    return _binary_op(jnp.minimum, x1, x2, out=out)


def _use_sorted_percentile(x: DNDarray, axis_s) -> bool:
    """True when the reduction runs along the split axis of a distributed,
    sortable array — the case where ``jnp.percentile`` on the logical view
    would all-gather O(n) to every device."""
    return (
        x.split is not None
        and x.comm.size > 1
        and not types.issubdtype(x.dtype, types.complexfloating)
        and (axis_s is None or axis_s == x.split)
    )


def _sorted_percentile(x: DNDarray, q_arr: jnp.ndarray, axis_s, method: str, kd: bool) -> jnp.ndarray:
    """Percentile via sort + O(q) takes, with numpy's exact semantics
    (q-dims first, float32/float64 compute, NaN propagates to every q,
    round-half-even tie-breaking for ``nearest``). The sort is the
    distributed transposition sort when the reduced axis is the split
    axis of a multi-device array, a local ``jnp.sort`` otherwise — one
    interpolation code path either way (``jnp.percentile``'s own
    ``nearest`` rounds ties differently from numpy, so it is not used)."""
    from . import manipulations as manip

    if axis_s is None and x.ndim > 1:
        xs, ax = manip.flatten(x), 0
    else:
        xs, ax = x, (0 if axis_s is None else axis_s)
    if xs.split == ax and xs.comm.size > 1:
        sv, _ = manip.sort(xs, axis=ax)
        arr = sv._logical()
    else:
        arr = jnp.sort(xs._logical(), axis=ax)
    n = arr.shape[ax]
    ct = jnp.float64 if arr.dtype == jnp.float64 else jnp.float32
    q = q_arr.astype(ct)
    # numpy's virtual-index arithmetic, exactly: q/100 is a float64 true
    # division, THEN cast to the array's inexact dtype (ints promote to
    # f64), then multiplied by (n-1) in that dtype. Evaluating q/100*(n-1)
    # all in f32 hit XLA's reciprocal rewrite (30/100*90 -> 26.999998,
    # selecting flat[26] where numpy takes flat[27], ADVICE r2); evaluating
    # it all in f64 diverges the other way for f32 arrays (numpy's f32 cast
    # makes 0.3 round UP, so 'higher' at q=30, n=91 takes flat[28]).
    idx_t = ct if jnp.issubdtype(arr.dtype, jnp.floating) else jnp.float64
    pos = (q_arr.astype(jnp.float64) / 100.0).astype(idx_t) * (n - 1)
    lo_i = jnp.clip(jnp.floor(pos).astype(jnp.int64), 0, n - 1)
    hi_i = jnp.clip(jnp.ceil(pos).astype(jnp.int64), 0, n - 1)
    take = lambda i: jnp.take(arr, i, axis=ax).astype(ct)
    if method == "lower":
        res = take(lo_i)
    elif method == "higher":
        res = take(hi_i)
    elif method == "nearest":
        res = take(jnp.clip(jnp.round(pos).astype(jnp.int64), 0, n - 1))
    else:
        vlo, vhi = take(lo_i), take(hi_i)
        if method == "midpoint":
            res = (vlo + vhi) / 2
        else:  # linear
            # gamma in the index dtype, cast to ct for the lerp (numpy casts
            # gamma to the array dtype before _lerp)
            w = (pos - jnp.floor(pos)).astype(ct)
            w = w.reshape((1,) * ax + q.shape + (1,) * (arr.ndim - 1 - ax))
            res = vlo + w * (vhi - vlo)
    # numpy layout: q-dims lead the reduced shape
    qn = q.ndim
    if qn and ax:
        perm = list(range(ax, ax + qn)) + list(range(ax)) + list(range(ax + qn, res.ndim))
        res = jnp.transpose(res, perm)
    # NaN propagates to every q (numpy partition semantics)
    if jnp.issubdtype(arr.dtype, jnp.floating):
        anynan = jnp.any(jnp.isnan(arr), axis=ax)  # psum'd over the split axis
        res = jnp.where(anynan.reshape((1,) * qn + anynan.shape), jnp.asarray(jnp.nan, ct), res)
    if kd:
        restore = (x.ndim * (1,)) if axis_s is None else None
        if restore is not None:
            res = res.reshape(tuple(q.shape) + restore)
        else:
            res = jnp.expand_dims(res, qn + ax)
    return res


def percentile(x: DNDarray, q, axis=None, out=None, interpolation: str = "linear", keepdim: bool = False, keepdims=None) -> DNDarray:
    """q-th percentile (reference ``statistics.py:1406``, gather-based).

    When the reduced axis is the split axis, the computation routes
    through the distributed transposition sort + O(q) element takes
    (:mod:`heat_tpu.parallel.dsort`) instead of ``jnp.percentile`` on the
    logical view, which would all-gather the full array to every device.

    A ``ChunkIterator`` input streams through the KLL sketch instead:
    single-pass, fixed memory, approximate within the sketch's rank-error
    bound (see ``docs/STREAMING.md``)."""
    kd = bool(keepdim or keepdims)
    q_arr = q._logical() if isinstance(q, DNDarray) else jnp.asarray(q)
    q_host = np.asarray(q_arr)  # graftlint: host-sync - O(q) scalars, validated eagerly
    # negated all-form so NaN q fails too, like numpy
    if q_host.size and not np.all((q_host >= 0) & (q_host <= 100)):
        raise ValueError("percentiles must be in the range [0, 100]")
    from ..stream.chunked import ChunkIterator

    if isinstance(x, ChunkIterator):
        res = _streaming_percentile(x, q_host, axis, kd)
        if out is not None:
            from ._operations import _write_out

            return _write_out(out, res)
        return res
    _check_array_arg(x, "percentile")
    axis_s = sanitize_axis(x.shape, axis)
    method = {"lower": "lower", "higher": "higher", "midpoint": "midpoint", "nearest": "nearest", "linear": "linear"}[interpolation]
    if (axis_s is None or isinstance(axis_s, int)) and not types.issubdtype(
        x.dtype, types.complexfloating
    ):
        result = _sorted_percentile(x, q_arr, axis_s, method, kd)
    else:  # tuple axis: jnp fallback (gather semantics, like the reference)
        result = jnp.percentile(x._logical().astype(jnp.float64 if x.larray.dtype == jnp.float64 else jnp.float32), q_arr, axis=axis_s, method=method, keepdims=kd)
    res = DNDarray(result, dtype=types.canonical_heat_type(result.dtype), split=None, device=x.device, comm=x.comm)
    if out is not None:
        from ._operations import _write_out

        return _write_out(out, res)
    return res


# --------------------------------------------------------------------------
# one-pass moments panel (kernels.moments dispatch)
#
# ht.mean + ht.std on the same buffer used to read the data three times
# (mean; std's own mean + centered pass). The panel computes (count, mean,
# M2) along the requested axis in ONE read — the pallas kernel on TPU, its
# raw-jnp shifted-sums twin under XLA — and memoizes the tiny result per
# buffer, so the second call of the pair costs zero data reads. mean /
# var(ddof) / std all finalize from the same three numbers.

_PANEL_PROGRAMS = ExecutableCache(maxsize=64)
# id(buffer) -> (weakref, mode, {axis_key: (count, mean, m2)}). Keyed by
# id() because jax Arrays are weakref-able but NOT hashable (elementwise
# __eq__); the death callback drops the slot, so a recycled id can never
# alias a dead buffer, and the identity re-check below guards the rest.
_PANELS: dict = {}
_PANELS_CAP = 32  # tiny entries (scalars + one (f,) row); bound per G002


def _axis_key(axis_s) -> str:
    return "all" if axis_s is None else str(axis_s)


def _panel_program(ndim: int, split, padded: bool, axis_s):
    """Jitted one-read shifted-sums moments program for 1-D/2-D buffers:
    ``s1 = Σ(x−x₀)`` and ``s2 = Σ(x−x₀)²`` fuse into a single XLA
    traversal (variance is shift-invariant), unlike the dependent
    ``mean → mean((x−mean)²)`` chain. Sharded operands compile to the
    local-partial + psum schedule automatically."""
    key = ("moments_panel", ndim, split, padded, _axis_key(axis_s))
    prog = _PANEL_PROGRAMS.get(key)
    if prog is not None:
        return prog

    def run(xa, n0, n1):
        x = xa.astype(jnp.promote_types(xa.dtype, jnp.float32))
        shift = x[(0,) * x.ndim]  # first element is always logically valid
        if padded:
            it = jax.lax.broadcasted_iota(jnp.int32, x.shape, split)
            nv = (n0, n1)[split] if x.ndim == 2 else n0
            xs = jnp.where(it < nv, x - shift, jnp.asarray(0.0, x.dtype))
        else:
            xs = x - shift
        if axis_s is None and x.ndim == 2:
            c = n0 * n1
            s1 = jnp.sum(xs)
            s2 = jnp.sum(xs * xs)
        else:
            ax = 0 if axis_s is None else axis_s
            c = n1 if (x.ndim == 2 and ax == 1) else n0
            s1 = jnp.sum(xs, axis=ax)
            s2 = jnp.sum(xs * xs, axis=ax)
        c = jnp.asarray(c, x.dtype)
        mean_ = shift + s1 / c
        m2 = jnp.maximum(s2 - s1 * s1 / c, 0.0)
        return c, mean_, m2

    _PANEL_PROGRAMS[key] = jax.jit(run)
    return _PANEL_PROGRAMS[key]


@jax.jit
def _panel_cols_merge(cnt, mean, m2):
    """Chan-merge equal-count per-column moments (the pallas kernel's
    output) into the whole-buffer moments: counts add, the grand mean is
    the column-mean average, and each column's M2 gains the between-column
    ``n·(mean_c − gmean)²`` term."""
    f = mean.shape[0]
    total = cnt * f
    gmean = jnp.mean(mean)
    dm = mean - gmean
    return total, gmean, jnp.sum(m2) + cnt * jnp.sum(dm * dm)


def _panel_kernel_stats(x: DNDarray, arr, interpret: bool):
    """Axis-0 and whole-buffer moments via the pallas kernel (one read),
    or None when the kernel's layout preconditions fail (the caller then
    uses the XLA panel — never a second read of a memoized buffer)."""
    from .kernels import moments_local, moments_sharded

    buf = arr if arr.ndim == 2 else arr.reshape(-1, 1)
    p = x.comm.size
    if x.split == 0 and p > 1:
        if buf.shape[0] % p:
            return None
        cnt, mean_, m2 = moments_sharded(
            buf, x.gshape[0], x.comm.mesh, interpret=interpret
        )
    elif x.split is None or p == 1:
        cnt, mean_, m2 = moments_local(buf, x.gshape[0], interpret=interpret)
    else:
        return None
    if arr.ndim == 2:
        return {"0": (cnt, mean_, m2), "all": _panel_cols_merge(cnt, mean_, m2)}
    # axis 0 of a 1-D array IS the whole buffer: serve both keys
    t = (cnt, mean_[0], m2[0])
    return {"all": t, "0": t}


def _moments_panel(x: DNDarray, axis_s):
    """(count, mean, M2) of ``x`` along ``axis_s`` from the one-pass
    panel, or None when the panel declines (ragged layouts, int/complex
    dtypes, >2-D, tuple axes, open lazy scopes, traced contexts — the
    caller falls back to ``_reduce_op``'s masked paths)."""
    if x.ndim not in (1, 2) or 0 in tuple(x.gshape):
        return None
    if axis_s is not None and not isinstance(axis_s, int):
        return None
    if getattr(x, "lcounts", None) is not None:
        return None
    if _operations._capture is not None and _operations._capture.active():
        return None  # lazy scope: _reduce_op's capture hook must see the call
    arr = x.larray
    if not isinstance(arr, jax.Array) or isinstance(arr, jax.core.Tracer):
        return None
    if _hooks.in_trace_safe():
        return None
    if arr.dtype not in (jnp.float32, jnp.float64):
        return None
    from .kernels import dispatch_mode, record_dispatch

    req_mode = dispatch_mode("moments_onepass")
    akey = _axis_key(axis_s)
    bid = id(arr)
    ent = _PANELS.get(bid)
    # entries key by the REQUESTED mode: a panel the kernel declined (and
    # the XLA program computed) must still hit while dispatch_mode keeps
    # answering 'pallas' — otherwise every declined axis recomputes and
    # re-creating the entry drops the buffer's other memoized axes
    if ent is not None and (ent[0]() is not arr or ent[1] != req_mode):
        ent = None
    if ent is not None and akey in ent[2]:
        # memo hit: zero data reads; report the mode that computed it
        record_dispatch("moments_onepass", ent[3].get(akey, req_mode))
        return ent[2][akey]
    entries = None
    mode = req_mode
    if (
        mode in ("pallas", "interpret")
        and arr.dtype == jnp.float32
        and (arr.ndim == 1 or axis_s in (None, 0))
    ):
        entries = _panel_kernel_stats(x, arr, interpret=(mode != "pallas"))
    if entries is None:
        mode = "xla"
        n0 = float(x.gshape[0])
        n1 = float(x.gshape[1]) if x.ndim == 2 else 1.0
        prog = _panel_program(arr.ndim, x.split, bool(x.padded), axis_s)
        entries = {akey: prog(arr, n0, n1)}
    record_dispatch("moments_onepass", mode)
    if ent is None:
        if len(_PANELS) >= _PANELS_CAP:
            _PANELS.pop(next(iter(_PANELS)))  # FIFO bound
        ent = (
            weakref.ref(arr, lambda _, bid=bid: _PANELS.pop(bid, None)),
            req_mode,
            {},
            {},
        )
        _PANELS[bid] = ent
    ent[2].update(entries)
    for k in entries:
        ent[3][k] = mode
    return ent[2][akey]


def _wrap_moment(x: DNDarray, axis_s, result) -> DNDarray:
    """Wrap a finalized moment like ``_reduce_op``'s tail: reduced split,
    reduced gshape, ``_from_buffer`` when the result keeps padded length."""
    out_split = _reduced_split(x.split, axis_s, x.ndim, False)
    dtype = types.canonical_heat_type(result.dtype)
    out_gshape = _reduced_shape(x.gshape, axis_s, False)
    if out_split is not None and tuple(result.shape) != tuple(out_gshape):
        return DNDarray._from_buffer(result, out_gshape, dtype, out_split, x.device, x.comm)
    return DNDarray(
        result, gshape=out_gshape, dtype=dtype, split=out_split,
        device=x.device, comm=x.comm,
    )


def _where_moment(op, x: DNDarray, axis, where, ddof: int) -> DNDarray:
    """``where=``-masked moments, decline-to-eager: a mask buffer cannot
    key the panel memo (jax Arrays are unhashable and the mask is
    arbitrary), so the masked reduction runs eagerly on the logical view —
    the same escape hatch as the lazy layer's unhashable-kwarg fallback."""
    axis_s = sanitize_axis(x.shape, axis)
    w = where._logical() if isinstance(where, DNDarray) else jnp.asarray(where)
    kw = {} if op is jnp.mean else {"ddof": ddof}
    result = op(
        x._logical(),
        axis=axis_s,
        where=jnp.broadcast_to(w.astype(bool), tuple(x.gshape)),
        **kw,
    )
    return _wrap_moment(x, axis_s, result)


def std(x: DNDarray, axis=None, ddof: int = 0, where=None, **kwargs) -> DNDarray:
    """Standard deviation (reference ``statistics.py:1784``).

    ``ddof`` and ``where=`` both route through the one-pass moments panel
    when they can; ``where=`` declines to the eager masked reduction."""
    if where is not None and isinstance(x, DNDarray):
        return _where_moment(jnp.std, x, axis, where, ddof)
    if isinstance(x, DNDarray):
        axis_s = sanitize_axis(x.shape, axis)
        stats = _moments_panel(x, axis_s)
        if stats is not None:
            c, _, m2 = stats
            return _wrap_moment(x, axis_s, jnp.sqrt(m2 / (c - ddof)))
    return _reduce_op(jnp.std, x, axis=axis, ddof=ddof)


def var(x: DNDarray, axis=None, ddof: int = 0, where=None, **kwargs) -> DNDarray:
    """Variance (reference ``statistics.py:1854``).

    ``ddof`` and ``where=`` both route through the one-pass moments panel
    when they can; ``where=`` declines to the eager masked reduction."""
    if where is not None and isinstance(x, DNDarray):
        return _where_moment(jnp.var, x, axis, where, ddof)
    if isinstance(x, DNDarray):
        axis_s = sanitize_axis(x.shape, axis)
        stats = _moments_panel(x, axis_s)
        if stats is not None:
            c, _, m2 = stats
            return _wrap_moment(x, axis_s, m2 / (c - ddof))
    return _reduce_op(jnp.var, x, axis=axis, ddof=ddof)
