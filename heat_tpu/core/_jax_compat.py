"""Version-compatibility shims for the underlying jax runtime.

The library targets the modern ``jax.shard_map`` entry point (keyword
``check_vma``). Older runtimes (<= 0.4.x) only ship
``jax.experimental.shard_map.shard_map`` with the keyword spelled
``check_rep``. Importing this module installs a translating wrapper at
``jax.shard_map`` when the top-level name is missing, so every
``from jax import shard_map`` site in the package works on both runtimes.

This must be imported before any module that does
``from jax import shard_map`` at module scope (``heat_tpu.core.__init__``
imports it first).
"""
from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, check_rep=None, **kwargs):
        """``jax.shard_map`` signature adapter over the experimental API."""
        if check_rep is None and check_vma is not None:
            check_rep = check_vma
        if check_rep is not None:
            kwargs["check_rep"] = check_rep
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )

    jax.shard_map = shard_map

if not hasattr(jax.lax, "axis_size"):

    def _axis_size(axis_name):
        """``jax.lax.axis_size`` backport: on runtimes without it,
        ``psum(1, axis)`` of a Python scalar evaluates statically inside
        ``shard_map``/``pmap`` and yields the mapped axis size as an int."""
        return jax.lax.psum(1, axis_name)

    jax.lax.axis_size = _axis_size
