"""Memory helpers (reference ``heat/core/memory.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["copy", "sanitize_memory_layout"]


def copy(x: DNDarray) -> DNDarray:
    """Deep copy (reference ``memory.py:13``). Preserves a ragged layout
    exactly (the copy carries the same per-shard counts)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")
    if x.lcounts is not None:
        return DNDarray._from_ragged(
            jnp.copy(x._raw), x.gshape, x.dtype, x.split, x.lcounts, x.device, x.comm
        )
    return DNDarray(
        jnp.copy(x.larray), gshape=x.gshape, dtype=x.dtype, split=x.split, device=x.device, comm=x.comm
    )


def sanitize_memory_layout(x, order: str = "C"):
    """Reference ``memory.py:42`` permuted strides for C/F order. XLA owns
    physical layout (tiled HBM), so logical order is always C; 'F' requests
    are accepted and ignored."""
    if order not in ("C", "F"):
        raise ValueError(f"order must be 'C' or 'F', got {order}")
    return x
