"""Exponential and logarithmic functions (reference ``heat/core/exponential.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from ._operations import _local_op
from .dndarray import DNDarray

__all__ = [
    "exp",
    "expm1",
    "exp2",
    "log",
    "log2",
    "log10",
    "log1p",
    "logaddexp",
    "logaddexp2",
    "sqrt",
    "rsqrt",
    "square",
    "cbrt",
]


def exp(x, out=None) -> DNDarray:
    """Elementwise e**x."""
    return _local_op(jnp.exp, x, out=out)


def expm1(x, out=None) -> DNDarray:
    return _local_op(jnp.expm1, x, out=out)


def exp2(x, out=None) -> DNDarray:
    return _local_op(jnp.exp2, x, out=out)


def log(x, out=None) -> DNDarray:
    return _local_op(jnp.log, x, out=out)


def log2(x, out=None) -> DNDarray:
    return _local_op(jnp.log2, x, out=out)


def log10(x, out=None) -> DNDarray:
    return _local_op(jnp.log10, x, out=out)


def log1p(x, out=None) -> DNDarray:
    return _local_op(jnp.log1p, x, out=out)


def logaddexp(x1, x2, out=None) -> DNDarray:
    """log(exp(x1) + exp(x2)) (reference ``exponential.py:210``)."""
    from ._operations import _binary_op

    return _binary_op(jnp.logaddexp, x1, x2, out=out)


def logaddexp2(x1, x2, out=None) -> DNDarray:
    """log2(2**x1 + 2**x2) (reference ``exponential.py``)."""
    from ._operations import _binary_op

    return _binary_op(jnp.logaddexp2, x1, x2, out=out)


def sqrt(x, out=None) -> DNDarray:
    return _local_op(jnp.sqrt, x, out=out)


def rsqrt(x, out=None) -> DNDarray:
    """Reciprocal square root (rsqrt is a single TPU VPU op)."""
    return _local_op(lambda t: jnp.reciprocal(jnp.sqrt(t)), x, out=out)


def square(x, out=None) -> DNDarray:
    return _local_op(jnp.square, x, out=out, no_cast=True)


def cbrt(x, out=None) -> DNDarray:
    return _local_op(jnp.cbrt, x, out=out)
