"""Tiling metadata (reference ``heat/core/tiling.py``, 1257 LoC).

The reference's tile classes *drive communication*: ``SplitTiles`` indexes
the Isend/Irecv mesh of ``resplit_`` and ``SquareDiagTiles`` the CAQR tile
loops. On TPU resplit is one ``device_put`` and QR is TSQR, so no code
path needs tiles to move data — but the classes remain useful (and
API-required) as *metadata views*: global tile boundaries, per-process
ownership, and tile indexing over the canonical XLA layout.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


class SplitTiles:
    """World-size tiles in every dimension (reference ``tiling.py:14``).

    ``tile_ends_g[d]`` holds the global end index of each tile along dim
    ``d``; ``tile_locations`` maps each tile to the process owning it
    (ownership follows the split dimension).
    """

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        comm = arr.comm
        size = comm.size
        ends = []
        for dim, length in enumerate(arr.gshape):
            block = -(-length // size) if length else 0
            e = np.minimum((np.arange(size) + 1) * block, length)
            ends.append(e)
        self.__tile_ends_g = np.stack(ends) if ends else np.zeros((0, size), dtype=np.int64)
        # ownership: tiles along the split dim belong to that process;
        # replicated arrays are owned by process 0
        shape = tuple(size for _ in arr.gshape)
        locs = np.zeros(shape, dtype=np.int64)
        if arr.split is not None:
            idx = [None] * len(shape)
            reshape = [1] * len(shape)
            reshape[arr.split] = size
            locs = locs + np.arange(size).reshape(reshape)
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_ends_g(self) -> np.ndarray:
        """(ndim, size) global end indices (reference ``tiling.py``)."""
        return self.__tile_ends_g

    @property
    def tile_locations(self) -> np.ndarray:
        """size^ndim ownership map (reference ``tiling.py``)."""
        return self.__tile_locations

    @property
    def tile_dimensions(self) -> np.ndarray:
        """(ndim, size) tile extents."""
        starts = np.zeros_like(self.__tile_ends_g)
        starts[:, 1:] = self.__tile_ends_g[:, :-1]
        return self.__tile_ends_g - starts

    def __getitem__(self, key) -> Optional[np.ndarray]:
        """The global slab of tile ``key`` (returns host data; the
        reference returned the local torch view)."""
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for dim, k in enumerate(key):
            ends = self.__tile_ends_g[dim]
            start = 0 if k == 0 else int(ends[k - 1])
            slices.append(slice(start, int(ends[k])))
        return self.__arr.numpy()[tuple(slices)]


class SquareDiagTiles:
    """Square tiles along the diagonal (reference ``tiling.py:331``).

    Computes the CAQR tile decomposition metadata: per-process row/column
    tile counts and global tile boundary indices. Data movement never uses
    these on TPU (QR is TSQR), but the indexing scheme is preserved for
    API parity and inspection.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 1):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError("arr must be 2D")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        self.__arr = arr
        size = arr.comm.size
        m, n = arr.gshape
        # square tile edge from the split-axis block size
        split = arr.split if arr.split is not None else 0
        block = -(-arr.gshape[split] // size)
        tile = max(1, -(-block // tiles_per_proc))
        row_starts = list(range(0, m, tile))
        col_starts = list(range(0, n, tile))
        self.__row_inds = row_starts
        self.__col_inds = col_starts
        self.__tile_rows = len(row_starts)
        self.__tile_cols = len(col_starts)
        self.__tiles_per_proc = tiles_per_proc
        # reference semantics: tiles are partitioned across processes along
        # the split dimension only; the other dimension is fully visible to
        # every process
        if split == 0:
            per = -(-self.__tile_rows // size)
            self.__tile_rows_per_process = [
                max(0, min(per, self.__tile_rows - r * per)) for r in range(size)
            ]
            self.__tile_columns_per_process = [self.__tile_cols] * size
        else:
            per = -(-self.__tile_cols // size)
            self.__tile_columns_per_process = [
                max(0, min(per, self.__tile_cols - r * per)) for r in range(size)
            ]
            self.__tile_rows_per_process = [self.__tile_rows] * size

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def row_indices(self) -> List[int]:
        return self.__row_inds

    @property
    def col_indices(self) -> List[int]:
        return self.__col_inds

    @property
    def tile_columns(self) -> int:
        return self.__tile_cols

    @property
    def tile_rows(self) -> int:
        return self.__tile_rows

    @property
    def tile_columns_per_process(self) -> List[int]:
        return self.__tile_columns_per_process

    @property
    def tile_rows_per_process(self) -> List[int]:
        return self.__tile_rows_per_process

    def __getitem__(self, key) -> Optional[np.ndarray]:
        if not isinstance(key, tuple):
            key = (key,)
        row, col = (key + (slice(None),))[:2] if len(key) < 2 else key
        rs = self.__row_inds + [self.__arr.gshape[0]]
        cs = self.__col_inds + [self.__arr.gshape[1]]
        r_slice = slice(rs[row], rs[row + 1]) if isinstance(row, int) else slice(None)
        c_slice = slice(cs[col], cs[col + 1]) if isinstance(col, int) else slice(None)
        return self.__arr.numpy()[r_slice, c_slice]
