"""Tiling metadata (reference ``heat/core/tiling.py``, 1257 LoC).

The reference's tile classes *drive communication*: ``SplitTiles`` indexes
the Isend/Irecv mesh of ``resplit_`` and ``SquareDiagTiles`` the CAQR tile
loops. On TPU resplit is one ``device_put`` and QR is TSQR, so no code
path needs tiles to move data. ``SquareDiagTiles`` still drives the QR
schedule: ``qr(tiles_per_proc=)`` reads its row decomposition to shape
the local level of the two-level TSQR tree (``linalg/qr.py``). Both
classes are additionally *functional tile views* over the canonical XLA
layout: global tile boundaries, per-process ownership, and tile
``__getitem__``/``__setitem__`` that read from and write through to the
sharded device buffer (the reference's in-place tile assignment API; int
and slice-of-tiles keys).

Cost model: XLA arrays are immutable, so each tile write is a full-array
functional update (and each read fetches through ``.numpy()``) — per-tile
access costs O(n), not O(tile). Loops over many tiles should batch their
updates into one DNDarray setitem; these views exist for parity and
inspection, not as a high-throughput update path.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles", "factor_block_edge"]


def factor_block_edge(arr: DNDarray, tiles_per_proc: int, mi: int) -> int:
    """Panel width for the blocked factorizations (``linalg/factorizations``).

    The ``SquareDiagTiles`` row-tile edge for ``tiles_per_proc``, snapped
    down to the largest divisor of the per-device row count ``mi`` — a
    factorization panel must never straddle a device boundary, so the edge
    has to divide the local block exactly (the same geometry source
    ``qr(tiles_per_proc=)`` consumes, with the divisor constraint the
    right-looking panel schedule adds on top)."""
    mi = max(1, int(mi))
    if tiles_per_proc <= 1 or mi <= 1:
        return mi
    ri = SquareDiagTiles(arr, tiles_per_proc).row_indices
    edge = ri[1] - ri[0] if len(ri) > 1 else mi
    edge = max(1, min(int(edge), mi))
    while mi % edge:
        edge -= 1
    return edge


def _tile_range(ends, k) -> slice:
    """Global element slice covered by tile index ``k`` (int or slice of
    tile indices) given cumulative tile ``ends`` along one dimension."""
    n_tiles = len(ends)
    if isinstance(k, slice):
        if k.step not in (None, 1):
            raise IndexError(
                "tile views cover contiguous tile ranges; slice step must be 1"
            )
        idxs = range(*k.indices(n_tiles))
        if len(idxs) == 0:
            return slice(0, 0)
        first, last = idxs[0], idxs[-1]
        start = 0 if first == 0 else int(ends[first - 1])
        return slice(start, int(ends[last]))
    k = int(k)
    if k < 0:
        k += n_tiles
    if not 0 <= k < n_tiles:
        raise IndexError(f"tile index {k} out of range for {n_tiles} tiles")
    start = 0 if k == 0 else int(ends[k - 1])
    return slice(start, int(ends[k]))


class SplitTiles:
    """World-size tiles in every dimension (reference ``tiling.py:14``).

    ``tile_ends_g[d]`` holds the global end index of each tile along dim
    ``d``; ``tile_locations`` maps each tile to the process owning it
    (ownership follows the split dimension).
    """

    def __init__(self, arr: DNDarray):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        self.__arr = arr
        comm = arr.comm
        size = comm.size
        ends = []
        for dim, length in enumerate(arr.gshape):
            block = -(-length // size) if length else 0
            e = np.minimum((np.arange(size) + 1) * block, length)
            ends.append(e)
        self.__tile_ends_g = np.stack(ends) if ends else np.zeros((0, size), dtype=np.int64)
        # ownership: tiles along the split dim belong to that process;
        # replicated arrays are owned by process 0
        shape = tuple(size for _ in arr.gshape)
        locs = np.zeros(shape, dtype=np.int64)
        if arr.split is not None:
            idx = [None] * len(shape)
            reshape = [1] * len(shape)
            reshape[arr.split] = size
            locs = locs + np.arange(size).reshape(reshape)
        self.__tile_locations = locs

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def tile_ends_g(self) -> np.ndarray:
        """(ndim, size) global end indices (reference ``tiling.py``)."""
        return self.__tile_ends_g

    @property
    def tile_locations(self) -> np.ndarray:
        """size^ndim ownership map (reference ``tiling.py``)."""
        return self.__tile_locations

    @property
    def tile_dimensions(self) -> np.ndarray:
        """(ndim, size) tile extents."""
        starts = np.zeros_like(self.__tile_ends_g)
        starts[:, 1:] = self.__tile_ends_g[:, :-1]
        return self.__tile_ends_g - starts

    def _tile_slices(self, key) -> Tuple[slice, ...]:
        if not isinstance(key, tuple):
            key = (key,)
        slices = []
        for dim, k in enumerate(key):
            slices.append(_tile_range(self.__tile_ends_g[dim], k))
        return tuple(slices)

    def __getitem__(self, key) -> Optional[np.ndarray]:
        """The global slab of tile ``key`` (returns host data; the
        reference returned the local torch view)."""
        return self.__arr.numpy()[self._tile_slices(key)]

    def __setitem__(self, key, value) -> None:
        """Write tile ``key`` through to the (device-resident, sharded)
        array — the reference's in-place tile assignment
        (``tiling.py:292-330``), routed through DNDarray setitem."""
        self.__arr[self._tile_slices(key)] = value


class SquareDiagTiles:
    """Square tiles along the diagonal (reference ``tiling.py:331``).

    Computes the CAQR tile decomposition metadata: per-process row/column
    tile counts and global tile boundary indices. Data movement never uses
    these on TPU (QR is TSQR), but ``qr(tiles_per_proc=)`` consumes the
    row decomposition to shape its local factorization tree, and the
    indexing scheme is preserved for API parity and inspection.
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 1):
        if not isinstance(arr, DNDarray):
            raise TypeError(f"arr must be a DNDarray, got {type(arr)}")
        if arr.ndim != 2:
            raise ValueError("arr must be 2D")
        if tiles_per_proc < 1:
            raise ValueError("tiles_per_proc must be >= 1")
        self.__arr = arr
        size = arr.comm.size
        m, n = arr.gshape
        # square tile edge from the split-axis block size
        split = arr.split if arr.split is not None else 0
        block = -(-arr.gshape[split] // size)
        tile = max(1, -(-block // tiles_per_proc))
        row_starts = list(range(0, m, tile))
        col_starts = list(range(0, n, tile))
        self.__row_inds = row_starts
        self.__col_inds = col_starts
        self.__tile_rows = len(row_starts)
        self.__tile_cols = len(col_starts)
        self.__tiles_per_proc = tiles_per_proc
        # reference semantics: tiles are partitioned across processes along
        # the split dimension only; the other dimension is fully visible to
        # every process
        if split == 0:
            per = -(-self.__tile_rows // size)
            self.__tile_rows_per_process = [
                max(0, min(per, self.__tile_rows - r * per)) for r in range(size)
            ]
            self.__tile_columns_per_process = [self.__tile_cols] * size
        else:
            per = -(-self.__tile_cols // size)
            self.__tile_columns_per_process = [
                max(0, min(per, self.__tile_cols - r * per)) for r in range(size)
            ]
            self.__tile_rows_per_process = [self.__tile_rows] * size

    @property
    def arr(self) -> DNDarray:
        return self.__arr

    @property
    def row_indices(self) -> List[int]:
        return self.__row_inds

    @property
    def col_indices(self) -> List[int]:
        return self.__col_inds

    @property
    def tile_columns(self) -> int:
        return self.__tile_cols

    @property
    def tile_rows(self) -> int:
        return self.__tile_rows

    @property
    def tile_columns_per_process(self) -> List[int]:
        return self.__tile_columns_per_process

    @property
    def tile_rows_per_process(self) -> List[int]:
        return self.__tile_rows_per_process

    def _tile_slices(self, key) -> Tuple[slice, slice]:
        if not isinstance(key, tuple):
            key = (key,)
        row, col = (key + (slice(None),))[:2] if len(key) < 2 else key
        r_ends = np.asarray(self.__row_inds[1:] + [self.__arr.gshape[0]])
        c_ends = np.asarray(self.__col_inds[1:] + [self.__arr.gshape[1]])
        return _tile_range(r_ends, row), _tile_range(c_ends, col)

    def __getitem__(self, key) -> Optional[np.ndarray]:
        return self.__arr.numpy()[self._tile_slices(key)]

    def __setitem__(self, key, value) -> None:
        """Write tile ``(row, col)`` through to the sharded array (the
        reference's CAQR loops assigned tiles in place,
        ``tiling.py:830-870``)."""
        self.__arr[self._tile_slices(key)] = value
