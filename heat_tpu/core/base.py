"""sklearn-style estimator base classes (reference ``heat/core/base.py``)."""
from __future__ import annotations

import inspect
from typing import Dict, List

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_estimator",
    "is_clusterer",
    "is_regressor",
    "is_transformer",
]


class BaseEstimator:
    """Estimator base with sklearn-clone-compatible params handling
    (reference ``base.py:13``)."""

    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return sorted(
            p.name
            for p in sig.parameters.values()
            if p.name != "self" and p.kind != p.VAR_KEYWORD and p.kind != p.VAR_POSITIONAL
        )

    def get_params(self, deep: bool = True) -> Dict:
        """Parameters of this estimator (reference ``base.py:27``)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        """Set parameters (reference ``base.py:56``)."""
        if not params:
            return self
        valid = self.get_params(deep=True)
        for key, value in params.items():
            key, delim, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"Invalid parameter {key} for estimator {self}")
            if delim:
                getattr(self, key).set_params(**{sub_key: value})
            else:
                setattr(self, key, value)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params(deep=False).items())
        return f"{self.__class__.__name__}({params})"


class ClassificationMixin:
    """Mixin for classifiers (reference ``base.py:98``)."""

    _estimator_type = "classifier"

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


class TransformMixin:
    """Mixin for transformers (reference ``base.py``)."""

    def fit(self, x):
        raise NotImplementedError()

    def fit_transform(self, x):
        return self.fit(x).transform(x)

    def transform(self, x):
        raise NotImplementedError()


class ClusteringMixin:
    """Mixin for clusterers (reference ``base.py:145``)."""

    _estimator_type = "clusterer"

    def fit(self, x):
        raise NotImplementedError()

    def fit_predict(self, x):
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """Mixin for regressors (reference ``base.py:176``)."""

    _estimator_type = "regressor"

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


def is_estimator(estimator) -> bool:
    """reference ``base.py:233``"""
    return isinstance(estimator, BaseEstimator)


def is_classifier(estimator) -> bool:
    return getattr(estimator, "_estimator_type", None) == "classifier"


def is_clusterer(estimator) -> bool:
    return getattr(estimator, "_estimator_type", None) == "clusterer"


def is_regressor(estimator) -> bool:
    return getattr(estimator, "_estimator_type", None) == "regressor"


def is_transformer(estimator) -> bool:
    return hasattr(estimator, "transform") and hasattr(estimator, "fit")
