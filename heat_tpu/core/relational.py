"""Relational operations (reference ``heat/core/relational.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import _binary_op
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater_equal", "gt", "greater", "le", "less_equal", "lt", "less", "ne", "not_equal"]


def eq(x, y) -> DNDarray:
    """Elementwise ==, bool result (reference ``relational.py``)."""
    return _bool_op(jnp.equal, x, y)


def _bool_op(op, t1, t2) -> DNDarray:
    res = _binary_op(op, t1, t2)
    if res.dtype != types.bool:
        res = res.astype(types.bool)
    return res


def equal(x, y) -> bool:
    """Global equality to a single python bool (reference
    ``relational.py:80`` — Allreduce(LAND); here one jnp.all on the sharded
    comparison, psum'd by XLA)."""
    try:
        res = _binary_op(jnp.equal, x, y)
    except ValueError:
        return False
    return bool(jnp.all(res._logical()))


def ge(x, y) -> DNDarray:
    return _bool_op(jnp.greater_equal, x, y)


greater_equal = ge


def gt(x, y) -> DNDarray:
    return _bool_op(jnp.greater, x, y)


greater = gt


def le(x, y) -> DNDarray:
    return _bool_op(jnp.less_equal, x, y)


less_equal = le


def lt(x, y) -> DNDarray:
    return _bool_op(jnp.less, x, y)


less = lt


def ne(x, y) -> DNDarray:
    return _bool_op(jnp.not_equal, x, y)


not_equal = ne
