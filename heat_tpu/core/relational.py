"""Relational operations (reference ``heat/core/relational.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from . import types
from ._operations import _binary_op
from .dndarray import DNDarray

__all__ = ["eq", "equal", "ge", "greater_equal", "gt", "greater", "le", "less_equal", "lt", "less", "ne", "not_equal"]


def eq(t1, t2) -> DNDarray:
    """Elementwise ==, bool result (reference ``relational.py``)."""
    return _bool_op(jnp.equal, t1, t2)


def _bool_op(op, t1, t2) -> DNDarray:
    res = _binary_op(op, t1, t2)
    if res.dtype != types.bool:
        res = res.astype(types.bool)
    return res


def equal(t1, t2) -> bool:
    """Global equality to a single python bool (reference
    ``relational.py:80`` — Allreduce(LAND); here one jnp.all on the sharded
    comparison, psum'd by XLA)."""
    try:
        res = _binary_op(jnp.equal, t1, t2)
    except ValueError:
        return False
    return bool(jnp.all(res.larray))


def ge(t1, t2) -> DNDarray:
    return _bool_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2) -> DNDarray:
    return _bool_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2) -> DNDarray:
    return _bool_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2) -> DNDarray:
    return _bool_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2) -> DNDarray:
    return _bool_op(jnp.not_equal, t1, t2)


not_equal = ne
