"""Input/output/distribution checks (reference ``heat/core/sanitation.py``)."""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from . import types
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis

__all__ = ["sanitize_in", "sanitize_in_tensor", "sanitize_infinity", "sanitize_out", "sanitize_distribution", "sanitize_sequence", "sanitize_lshape", "sanitize_split", "scalar_to_1d", "validate_layout"]


_WARNED_KNOBS = set()


def warn_parity_noop(func: str, knob: str, why: str) -> None:
    """Warn ONCE per (func, knob) that a reference API knob is accepted
    but has no effect on TPU (VERDICT r3 weak item 5: silent
    accepted-and-ignored knobs gave tuning users no signal)."""
    key = (func, knob)
    if key in _WARNED_KNOBS:
        return
    _WARNED_KNOBS.add(key)
    import warnings

    warnings.warn(
        f"{func}: {knob} is accepted for reference-API parity but has no "
        f"effect on TPU ({why})",
        UserWarning,
        stacklevel=3,
    )


def sanitize_in(x) -> None:
    """Require a DNDarray (reference ``sanitation.py:159``)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")


def sanitize_infinity(x: DNDarray):
    """Largest representable value for x's dtype (reference helper)."""
    dtype = x.dtype
    if types.heat_type_is_exact(dtype):
        return types.iinfo(dtype).max
    return float("inf")


def sanitize_out(out, output_shape, output_split, output_device, output_comm=None) -> None:
    """Validate an out= argument (reference ``sanitation.py:259``)."""
    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, but was {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {out.shape}")
    if out.split != output_split:
        raise ValueError(f"Expecting output buffer with split {output_split}, got {out.split}")


def sanitize_distribution(*args: DNDarray, target: DNDarray, diff_map=None):
    """Bring operands onto the target's distribution (reference
    ``sanitation.py:31``). On TPU this is a resplit (device_put), never a
    point-to-point exchange."""
    out = []
    for arg in args:
        if not isinstance(arg, DNDarray):
            raise TypeError(f"expected DNDarray, got {type(arg)}")
        if arg.split != target.split and arg.ndim == target.ndim:
            out.append(arg.resplit(target.split))
        else:
            out.append(arg)
    return out[0] if len(out) == 1 else tuple(out)


def sanitize_sequence(seq) -> list:
    """Normalize a sequence argument to a list (reference ``sanitation.py``)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    if isinstance(seq, DNDarray):
        return seq.tolist()
    raise TypeError(f"seq must be a list, tuple or DNDarray, got {type(seq)}")


def sanitize_lshape(array: DNDarray, tensor) -> None:
    """Verify a local tensor fits the array's shard layout (reference
    ``sanitation.py:213``)."""
    if tuple(tensor.shape) != tuple(array.lshape):
        raise ValueError(f"local tensor shape {tensor.shape} does not match lshape {array.lshape}")


def sanitize_split(shape, split) -> Optional[int]:
    """Validate (and normalize negatives of) a ``split`` annotation against
    a global shape; raises ValueError outside ``[-ndim, ndim)``. The
    resilience layer and the checkpoint manifest reader both route through
    this so an on-disk/in-memory split is checked in one place."""
    return sanitize_axis(tuple(int(s) for s in shape), split)


def validate_layout(gshape, split, lshape_map, comm) -> None:
    """Cross-check the structural invariants tying ``gshape``, ``split``
    and ``lshape_map`` together (used by :func:`heat_tpu.resilience.validate`
    and ``DNDarray.health_check``).

    Raises ValueError naming the first violated invariant:

    - ``lshape_map`` has one row per shard (``comm.size``) and one column
      per dimension;
    - non-split columns all equal the global extent;
    - the split column sums to the global split extent;
    - ``split`` (when not None) indexes a real dimension.
    """
    gshape = tuple(int(s) for s in gshape)
    split = sanitize_split(gshape, split)
    lmap = np.asarray(lshape_map)
    if lmap.shape != (comm.size, len(gshape)):
        raise ValueError(
            f"lshape_map shape {lmap.shape} does not match "
            f"(size, ndim) = ({comm.size}, {len(gshape)})"
        )
    for d in range(len(gshape)):
        if split is not None and d == split:
            total = int(lmap[:, d].sum())
            if total != gshape[d]:
                raise ValueError(
                    f"split-dim {d} shard extents {lmap[:, d].tolist()} sum to "
                    f"{total}, but gshape[{d}] = {gshape[d]}"
                )
        else:
            bad = [int(v) for v in lmap[:, d] if int(v) != gshape[d]]
            if bad:
                raise ValueError(
                    f"non-split dim {d}: shard extents {lmap[:, d].tolist()} "
                    f"disagree with gshape[{d}] = {gshape[d]}"
                )


def sanitize_in_tensor(x) -> None:
    """Require a raw jax array (reference ``sanitation.py`` required a
    torch.Tensor)."""
    import jax

    if not isinstance(x, jax.Array):
        raise TypeError(f"input needs to be a jax.Array, but was {type(x)}")


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Turn a scalar DNDarray into a 1-element 1-D DNDarray (reference
    ``sanitation.py``)."""
    if x.ndim != 0:
        return x
    return DNDarray(
        x.larray.reshape(1), dtype=x.dtype, split=None, device=x.device, comm=x.comm
    )
