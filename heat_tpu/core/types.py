"""Heat-compatible dtype hierarchy backed by JAX dtypes.

Mirrors the class-hierarchy dtype system of the reference
(``heat/core/types.py:64-414``): ``datatype`` -> ``bool``/``number`` ->
ints/floats/complex leaves. Each leaf is a *class* (never instantiated) that
maps onto a ``jax.numpy`` dtype. On TPU we additionally expose ``bfloat16``
as a first-class type (the MXU-native format), which the reference only used
internally for DASO gradient compression.
"""
from __future__ import annotations

import builtins
from typing import Type, Union

import jax.numpy as jnp
import numpy as np

__all__ = [
    "datatype",
    "generic",
    "number",
    "integer",
    "signedinteger",
    "unsignedinteger",
    "bool",
    "bool_",
    "floating",
    "int8",
    "byte",
    "int16",
    "short",
    "int32",
    "int",
    "int64",
    "long",
    "uint8",
    "ubyte",
    "float16",
    "bfloat16",
    "float32",
    "float",
    "float64",
    "double",
    "flexible",
    "complexfloating",
    "complex64",
    "cfloat",
    "csingle",
    "float_",
    "int_",
    "complex",
    "complex128",
    "cdouble",
    "canonical_heat_type",
    "heat_type_of",
    "heat_type_is_exact",
    "heat_type_is_inexact",
    "heat_type_is_complexfloating",
    "issubdtype",
    "iscomplex",
    "isreal",
    "promote_types",
    "result_type",
    "can_cast",
    "finfo",
    "iinfo",
]


class datatype:
    """Base class of the heat type hierarchy (reference ``types.py:64``)."""

    _jax_type: np.dtype = None
    _char: str = None

    @classmethod
    def jax_type(cls) -> np.dtype:
        """The ``jax.numpy`` dtype this heat type maps to."""
        return cls._jax_type

    # name kept for API familiarity with the reference's ``torch_type()``
    @classmethod
    def torch_type(cls):  # pragma: no cover - compat alias
        return cls._jax_type

    @classmethod
    def char(cls) -> str:
        return cls._char

    def __new__(cls, *value, device=None, comm=None):
        # Calling a type object casts, like ht.float32(x) in the reference.
        from . import factories

        if len(value) == 0:
            value = (0,)
        if len(value) == 1:
            return factories.array(value[0], dtype=cls, device=device, comm=comm)
        raise TypeError(f"function takes at most 1 argument ({len(value)} given)")


class generic(datatype):
    pass


class bool(generic):
    _jax_type = jnp.bool_
    _char = "u1"


class number(generic):
    pass


class integer(number):
    pass


class signedinteger(integer):
    pass


class unsignedinteger(integer):
    pass


class inexact(number):
    pass


class floating(inexact):
    pass


class complexfloating(inexact):
    pass


class flexible(generic):
    pass


class int8(signedinteger):
    _jax_type = jnp.int8
    _char = "i1"


class int16(signedinteger):
    _jax_type = jnp.int16
    _char = "i2"


class int32(signedinteger):
    _jax_type = jnp.int32
    _char = "i4"


class int64(signedinteger):
    _jax_type = jnp.int64
    _char = "i8"


class uint8(unsignedinteger):
    _jax_type = jnp.uint8
    _char = "u1"


class float16(floating):
    _jax_type = jnp.float16
    _char = "f2"


class bfloat16(floating):
    # TPU-native extension: MXU matmuls run natively in bf16.
    _jax_type = jnp.bfloat16
    _char = "bf2"


class float32(floating):
    _jax_type = jnp.float32
    _char = "f4"


class float64(floating):
    _jax_type = jnp.float64
    _char = "f8"


class complex64(complexfloating):
    _jax_type = jnp.complex64
    _char = "c8"


class complex128(complexfloating):
    _jax_type = jnp.complex128
    _char = "c16"


# aliases (reference ``types.py``)
bool_ = bool
byte = int8
short = int16
int = int32
long = int64
ubyte = uint8
half = float16
float = float32
double = float64
cfloat = complex64
csingle = complex64
cdouble = complex128
float_ = float32
int_ = int32
# reference ``types.py:367``: ``complex`` is the abstract class; as a dtype
# argument it canonicalizes to complex64, same as the python builtin
complex = complexfloating

_HEAT_TYPES = [
    bool,
    uint8,
    int8,
    int16,
    int32,
    int64,
    float16,
    bfloat16,
    float32,
    float64,
    complex64,
    complex128,
]

# numpy-dtype -> heat type
_NP_TO_HEAT = {np.dtype(t._jax_type): t for t in _HEAT_TYPES}

# python builtins / strings
_EXTRA_CANONICAL = {
    builtins.bool: bool,
    # the TYPE `int` maps to int32 exactly like the reference
    # (``types.py:489``) — consistent with heat_type_of's scalar rule
    builtins.int: int32,
    builtins.float: float32,
    builtins.complex: complex64,
    complexfloating: complex64,
    "bool": bool,
    "b1": bool,
    "uint8": uint8,
    "u1": uint8,
    "int8": int8,
    "i1": int8,
    "int16": int16,
    "i2": int16,
    "int32": int32,
    "i4": int32,
    "int": int32,
    "int64": int64,
    "i8": int64,
    "long": int64,
    "float16": float16,
    "f2": float16,
    "half": float16,
    "bfloat16": bfloat16,
    "float32": float32,
    "f4": float32,
    "float": float32,
    "float64": float64,
    "f8": float64,
    "double": float64,
    "complex64": complex64,
    "c8": complex64,
    "complex128": complex128,
    "c16": complex128,
}


def canonical_heat_type(a_type) -> Type[datatype]:
    """Canonicalize a type-like object into a heat type class.

    Accepts heat types, python builtins, strings, numpy/jax dtypes
    (reference ``types.py:495``).
    """
    if isinstance(a_type, type) and issubclass(a_type, datatype):
        if getattr(a_type, "_jax_type", None) is None:
            if a_type in _EXTRA_CANONICAL:
                return _EXTRA_CANONICAL[a_type]
            raise TypeError(
                f"abstract heat type {a_type.__name__!r} cannot be used as a "
                "concrete dtype (pick e.g. float32/complex64)"
            )
        return a_type
    try:
        if a_type in _EXTRA_CANONICAL:
            return _EXTRA_CANONICAL[a_type]
    except TypeError:
        pass
    try:
        return _NP_TO_HEAT[np.dtype(a_type)]
    except (TypeError, KeyError):
        raise TypeError(f"data type {a_type!r} not understood")


def heat_type_of(obj) -> Type[datatype]:
    """Infer the heat type of an array-like object (reference ``types.py:565``)."""
    dtype = getattr(obj, "dtype", None)
    if dtype is not None:
        if isinstance(dtype, type) and issubclass(dtype, datatype):
            return dtype
        return canonical_heat_type(dtype)
    if isinstance(obj, (builtins.bool, np.bool_)):
        return bool
    if isinstance(obj, (builtins.int, np.integer)):
        # type-based like the reference (``types.py:489``: builtins.int ->
        # int32), independent of np.result_type's platform default
        return int32
    if isinstance(obj, (builtins.float, np.floating)):
        return float32
    if isinstance(obj, (builtins.complex, np.complexfloating)):
        return complex64
    if isinstance(obj, (list, tuple)):
        return canonical_heat_type(np.asarray(obj).dtype)
    raise TypeError(f"cannot determine heat type of {type(obj)}")


def heat_type_is_exact(ht_dtype) -> builtins.bool:
    """True for integer/bool heat types."""
    return issubclass(canonical_heat_type(ht_dtype), (integer, bool))


def heat_type_is_inexact(ht_dtype) -> builtins.bool:
    """True for floating/complex heat types."""
    return issubclass(canonical_heat_type(ht_dtype), inexact)


def heat_type_is_complexfloating(ht_dtype) -> builtins.bool:
    return issubclass(canonical_heat_type(ht_dtype), complexfloating)


def issubdtype(arg1, arg2) -> builtins.bool:
    """np.issubdtype over the heat hierarchy."""
    if not (isinstance(arg1, type) and issubclass(arg1, datatype)):
        arg1 = canonical_heat_type(arg1)
    if isinstance(arg2, type) and issubclass(arg2, datatype):
        return issubclass(arg1, arg2)
    return issubclass(arg1, canonical_heat_type(arg2))


def iscomplex(x):
    """Elementwise: imaginary part nonzero (reference ``types.py``)."""
    from . import _operations

    def _local(t):
        if jnp.iscomplexobj(t):
            return jnp.imag(t) != 0
        return jnp.zeros(t.shape, dtype=jnp.bool_)

    return _operations.__dict__["_local_op"](_local, x, out_dtype=bool)


def isreal(x):
    from . import _operations

    def _local(t):
        if jnp.iscomplexobj(t):
            return jnp.imag(t) == 0
        return jnp.ones(t.shape, dtype=jnp.bool_)

    return _operations.__dict__["_local_op"](_local, x, out_dtype=bool)


def promote_types(type1, type2) -> Type[datatype]:
    """Bit-width-preserving common type (reference ``types.py:836``):
    the first type both operands cast to under the 'intuitive' rule —
    e.g. ``int32 + float32 -> float32`` (numpy would say float64)."""
    _init_promotion_tables()
    t1 = canonical_heat_type(type1)
    t2 = canonical_heat_type(type2)
    if t1 is t2:
        return t1  # identity, incl. float16/bfloat16 (outside the table)
    if t1 in (float16, bfloat16) and t2 in (float16, bfloat16):
        return float32  # mixed half-precision formats widen
    return _PROMOTE_TABLE[_type_code(t1)][_type_code(t2)]


def result_type(*operands) -> Type[datatype]:
    """Promotion with operand precedence (reference ``types.py:868-948``):
    arrays > types > scalars; within the same kind the higher-precedence
    operand's type wins outright."""

    def classify(arg):
        # (heat type, precedence): 0 array, 1 type, 2 scalar array, 3 scalar
        if isinstance(arg, type) and issubclass(arg, datatype):
            try:
                return canonical_heat_type(arg), 1  # complexfloating -> c64
            except TypeError:
                # other abstract classes pass through; merge()'s parent-kind
                # loop resolves them against concrete operands (reference
                # result_type_rec, types.py:928)
                return arg, 1
        dt = getattr(arg, "dtype", None)
        if dt is not None and not isinstance(arg, np.dtype):
            t = dt if isinstance(dt, type) and issubclass(dt, datatype) else canonical_heat_type(dt)
            prec = 0 if len(getattr(arg, "shape", ())) > 0 else 2
            return t, prec
        if isinstance(arg, (builtins.bool, builtins.int, builtins.float, builtins.complex)) and not isinstance(arg, np.generic):
            return canonical_heat_type(type(arg)), 3
        if isinstance(arg, np.ndarray):
            return canonical_heat_type(arg.dtype), 0 if arg.ndim > 0 else 2
        if isinstance(arg, (list, tuple)):
            # python sequences take the factory's inference (floats ->
            # float32, matching the reference's torch.tensor defaults)
            a = np.asarray(arg)
            t = float32 if a.dtype == np.float64 else canonical_heat_type(a.dtype)
            return t, 0 if a.ndim > 0 else 2
        return canonical_heat_type(arg), 1

    def merge(a, b):
        (t1, p1), (t2, p2) = a, b
        if t1 is t2:
            return t1, min(p1, p2)
        if p1 == p2:
            return promote_types(t1, t2), p1
        for parent in (bool, integer, floating, complexfloating):
            if issubdtype(t1, parent) and issubdtype(t2, parent):
                return (t1, min(p1, p2)) if p1 < p2 else (t2, min(p1, p2))
        # different kinds: the higher kind wins regardless of precedence
        return (t2, min(p1, p2)) if _type_code(t1) < _type_code(t2) else (t1, min(p1, p2))

    if not operands:
        raise TypeError("result_type requires at least one operand")
    acc = classify(operands[0])
    for op in operands[1:]:
        acc = merge(acc, classify(op))
    return acc[0]


def can_cast(from_, to, casting="intuitive") -> builtins.bool:
    """Whether a cast is allowed under the given rule (reference
    ``types.py:671``): no/safe/same_kind/unsafe plus the reference's
    ``intuitive`` (= safe + same-width int->float, e.g. int32->float32).
    Python scalars resolve to their heat type (``heat_type_of``) and consult
    the cast table — type-based, exactly like the reference implementation
    (``types.py:729-734``); e.g. ``can_cast(5, uint8)`` is False because
    int32 -> uint8 is not a safe cast, regardless of the value."""
    _init_promotion_tables()
    to_t = canonical_heat_type(to)
    if isinstance(
        from_, (builtins.bool, builtins.int, builtins.float, builtins.complex)
    ) and not isinstance(from_, np.generic):
        from_ = heat_type_of(from_)

    if hasattr(from_, "dtype") and not isinstance(from_, np.dtype):
        d = from_.dtype
        from_t = d if isinstance(d, type) and issubclass(d, datatype) else canonical_heat_type(d)
    else:
        from_t = canonical_heat_type(from_)

    if casting == "no":
        return from_t is to_t
    if casting == "unsafe":
        return True
    # half-precision types sit outside the reference table: value-preserving
    # only when widening (f16 -> f32/f64/c*, bf16 -> f32/f64/c*)
    halves = (float16, bfloat16)
    if from_t in halves or to_t in halves:
        if from_t is to_t:
            return True
        widening = from_t in halves and to_t in (float32, float64, complex64, complex128)
        if casting in ("safe", "intuitive"):
            return widening
        # same_kind: any float->float or float->complex conversion
        return issubclass(from_t, (floating, complexfloating)) and issubclass(
            to_t, (floating, complexfloating)
        ) or widening
    i, j = _type_code(from_t), _type_code(to_t)
    if casting == "safe":
        return _SAFE_CAST[i][j]
    if casting == "intuitive":
        return _INTUITIVE_CAST[i][j]
    if casting == "same_kind":
        return _SAFE_CAST[i][j] or np.can_cast(
            np.dtype(from_t._jax_type), np.dtype(to_t._jax_type), casting="same_kind"
        )
    raise ValueError(f"unknown casting rule {casting!r}")


# ---------------------------------------------------------------------------
# Promotion tables (reference ``types.py:605-668``). The reference's
# "intuitive" rule preserves bit width where numpy widens; promotion picks
# the first type (in ``_promotion_order``) both operands cast to.
# ---------------------------------------------------------------------------


def _promotion_order():
    return [bool, uint8, int8, int16, int32, int64, float32, float64, complex64, complex128]


def _cast_tables():
    T, F = True, False
    # rows/cols follow _promotion_order()
    safe = [
        # bool u8  i8  i16 i32 i64 f32 f64 c64 c128
        [T, T, T, T, T, T, T, T, T, T],  # bool
        [F, T, F, T, T, T, T, T, T, T],  # uint8
        [F, F, T, T, T, T, T, T, T, T],  # int8
        [F, F, F, T, T, T, T, T, T, T],  # int16
        [F, F, F, F, T, T, F, T, F, T],  # int32
        [F, F, F, F, F, T, F, T, F, T],  # int64
        [F, F, F, F, F, F, T, T, T, T],  # float32
        [F, F, F, F, F, F, F, T, F, T],  # float64
        [F, F, F, F, F, F, F, F, T, T],  # complex64
        [F, F, F, F, F, F, F, F, F, T],  # complex128
    ]
    # "intuitive" = safe plus same-width int->float/complex (int32->float32)
    intuitive = [row[:] for row in safe]
    intuitive[4][6] = intuitive[4][8] = True  # int32 -> float32 / complex64
    return safe, intuitive


_TYPE_ORDER = None
_SAFE_CAST = None
_INTUITIVE_CAST = None
_PROMOTE_TABLE = None


def _init_promotion_tables():
    global _TYPE_ORDER, _SAFE_CAST, _INTUITIVE_CAST, _PROMOTE_TABLE
    if _PROMOTE_TABLE is not None:
        return
    _TYPE_ORDER = _promotion_order()
    _SAFE_CAST, _INTUITIVE_CAST = _cast_tables()
    n = len(_TYPE_ORDER)
    _PROMOTE_TABLE = [[None] * n for _ in range(n)]
    for i in range(n):
        for j in range(n):
            for t in range(n):
                if _INTUITIVE_CAST[i][t] and _INTUITIVE_CAST[j][t]:
                    _PROMOTE_TABLE[i][j] = _TYPE_ORDER[t]
                    break


def _type_code(t) -> builtins.int:
    _init_promotion_tables()
    t = canonical_heat_type(t)
    if t is float16 or t is bfloat16:
        # half-precision extensions (absent from the reference's table):
        # treated as float32 for promotion purposes
        t = float32
    try:
        return _TYPE_ORDER.index(t)
    except ValueError:
        raise TypeError(f"type {t} has no promotion rule") from None


class finfo:
    """Machine limits for floating point types (reference ``types.py:950``)."""

    def __new__(cls, dtype):
        h = canonical_heat_type(dtype)
        if not issubclass(h, (floating, complexfloating)):
            raise TypeError(f"data type {dtype} not inexact")
        return super().__new__(cls)._init(h)

    def _init(self, h):
        info = jnp.finfo(h._jax_type)
        self.bits = info.bits
        self.eps = builtins.float(info.eps)
        self.max = builtins.float(info.max)
        self.min = builtins.float(info.min)
        self.tiny = builtins.float(info.tiny)
        return self


class iinfo:
    """Machine limits for integer types (reference ``types.py:1007``)."""

    def __new__(cls, dtype):
        h = canonical_heat_type(dtype)
        if not issubclass(h, (integer, bool)):
            raise TypeError(f"data type {dtype} not an integer type")
        return super().__new__(cls)._init(h)

    def _init(self, h):
        if h is bool:
            self.bits, self.max, self.min = 8, 1, 0
            return self
        info = jnp.iinfo(h._jax_type)
        self.bits = info.bits
        self.max = builtins.int(info.max)
        self.min = builtins.int(info.min)
        return self
