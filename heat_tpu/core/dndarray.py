"""DNDarray — a distributed n-dimensional array backed by a global ``jax.Array``.

Reference: ``heat/core/dndarray.py`` (1763 LoC). There, a DNDarray is a
*local* ``torch.Tensor`` shard plus global metadata, and every cross-rank
interaction is hand-written MPI. Here the underlying object is a **global**
``jax.Array`` carrying a ``NamedSharding`` over the device mesh; the Heat
``split`` axis maps 1:1 onto the mesh axis ``"split"`` of the array's
``PartitionSpec``. Consequences:

- **Padded buffers**: JAX requires every sharded dimension to be divisible
  by the mesh size, so the stored buffer is padded along the split axis to
  ``P * ceil(n / P)`` (``pshape``); the logical extent ``gshape`` is
  metadata. Padding sits strictly at the global tail, so logical index ->
  buffer index is the identity for every valid element; the valid region of
  device ``r``'s block is exactly the reference's ceil-div ``comm.chunk``.
  Pad content is *unspecified* — reductions/contractions mask it with the
  op's neutral element (see ``_operations``), data-movement ops work on the
  logical view (:meth:`_logical`). For divisible shapes (and ``split=None``)
  buffer == logical array and nothing changes.
- **Ragged layouts**: ``redistribute_`` (reference ``dndarray.py:1029``)
  accepts any partition of the split extent; a non-canonical target leaves
  the array in a *ragged* layout (``lcounts`` per-shard valid counts,
  data at offset 0 of each fixed-size block). Elementwise ops, reductions
  and cumops compute directly on ragged buffers (``_operations`` masks
  ragged-invalid rows exactly like tail padding), so ``balance_``
  (reference ``dndarray.py:470``) is reserved for consumers that need the
  canonical ceil-div map — matmul tiles, ``resplit_``, I/O assembly —
  reached via :attr:`larray`. See ``docs/PERFORMANCE.md`` for the layout
  model and per-op alignment costs.
- ``resplit_`` (reference ``dndarray.py:1235-1357``, tile-by-tile
  Isend/Irecv) is a single ``jax.device_put`` to a new sharding — XLA emits
  the optimal all-to-all/all-gather over ICI.
- halo exchange (reference ``dndarray.py:333-441``) is available both as
  global-slice metadata here and as a ``ppermute`` collective in
  :mod:`heat_tpu.parallel.halo` for use inside ``shard_map``.
- distributed ``__getitem__``/``__setitem__`` (reference
  ``dndarray.py:652-1676``, ~1000 lines of rank-local index translation)
  reduce to global ``jnp`` indexing plus a small split-propagation rule.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import _hooks
from . import communication as comm_module
from . import devices, types
from .communication import MeshCommunication, sanitize_comm
from .devices import Device
from .stride_tricks import sanitize_axis

__all__ = ["DNDarray", "LAYOUT_STATS"]

# Running count of ragged→canonical rebalances actually performed by
# ``balance_`` (no-op calls are not counted). Tests hook this to assert
# that hot compute paths never force the rebalance round-trip.
LAYOUT_STATS = {"rebalances": 0}


class LocalIndex:
    """Kept for reference-API parity (``dndarray.py`` helper); indexing the
    global array covers all uses on TPU."""

    def __init__(self, obj):
        self.obj = obj

    def __getitem__(self, key):
        return self.obj[key]


class DNDarray:
    """Distributed N-Dimensional array (reference ``dndarray.py:63-86``).

    Parameters
    ----------
    array : jax.Array or array-like
        The global data. Will be placed with the sharding implied by
        ``split`` if not already.
    dtype : heat type, optional
        Inferred from ``array`` if omitted.
    split : int or None
        Axis sharded over the mesh, or None for replication.
    device, comm : placement metadata.
    balanced : bool
        Accepted for API parity; always True on TPU (XLA canonical layout).
    """

    def __init__(
        self,
        array,
        gshape: Optional[Tuple[int, ...]] = None,
        dtype=None,
        split: Optional[int] = None,
        device: Optional[Device] = None,
        comm: Optional[MeshCommunication] = None,
        balanced: bool = True,
    ):
        self.__comm = sanitize_comm(comm)
        self.__device = devices.sanitize_device(device)
        if dtype is not None:
            dtype = types.canonical_heat_type(dtype)
        if not isinstance(array, jax.Array):
            array = jnp.asarray(array, dtype=None if dtype is None else dtype.jax_type())
        if dtype is None:
            dtype = types.canonical_heat_type(array.dtype)
        elif array.dtype != np.dtype(dtype.jax_type()):
            array = array.astype(dtype.jax_type())
        if array.ndim == 0:
            split = None
        if gshape is None:
            gshape = tuple(array.shape)
        else:
            gshape = tuple(int(s) for s in gshape)
        split = sanitize_axis(gshape, split)
        self.__dtype = dtype
        self.__split = split
        self.__gshape = gshape
        self.__lcounts = None
        self.__array = _place(array, self.__comm, split, gshape)

    @classmethod
    def _from_buffer(
        cls,
        buffer: jax.Array,
        gshape: Tuple[int, ...],
        dtype,
        split: Optional[int],
        device: Optional[Device] = None,
        comm: Optional[MeshCommunication] = None,
    ) -> "DNDarray":
        """Wrap an already-padded, already-placed physical buffer.

        Internal fast path for op results: ``buffer.shape`` must equal
        ``comm.padded_shape(gshape, split)``.
        """
        out = cls.__new__(cls)
        out._DNDarray__comm = sanitize_comm(comm)
        out._DNDarray__device = devices.sanitize_device(device)
        out._DNDarray__dtype = types.canonical_heat_type(dtype)
        out._DNDarray__split = split
        out._DNDarray__gshape = tuple(int(s) for s in gshape)
        out._DNDarray__lcounts = None
        out._DNDarray__array = _place(buffer, out._DNDarray__comm, split, out._DNDarray__gshape)
        return out

    @classmethod
    def _from_ragged(
        cls,
        buffer: jax.Array,
        gshape: Tuple[int, ...],
        dtype,
        split: int,
        lcounts: Tuple[int, ...],
        device: Optional[Device] = None,
        comm: Optional[MeshCommunication] = None,
    ) -> "DNDarray":
        """Wrap a *ragged-layout* physical buffer: device ``r`` holds
        ``lcounts[r]`` valid split-axis rows at offset 0 of its block
        (block size ``buffer.shape[split] // P``). This is the TPU
        representation of the reference's unbalanced arrays
        (``dndarray.py:1029``): raggedness is real, observable through
        ``lshape_map``/``local_shards``/``counts_displs``, and elementwise
        ops / reductions / cumops compute directly on it (ragged-invalid
        rows are masked like tail padding — see
        :mod:`heat_tpu.core._operations`). Only consumers of the
        canonical ceil-div map (:meth:`larray`) rebalance.
        """
        comm = sanitize_comm(comm)
        lcounts = tuple(int(c) for c in lcounts)
        gshape = tuple(int(s) for s in gshape)
        p = comm.size
        if len(lcounts) != p or sum(lcounts) != gshape[split]:
            raise ValueError(
                f"lcounts {lcounts} do not partition extent {gshape[split]} over {p} shards"
            )
        if buffer.shape[split] % p or buffer.shape[split] // p < max(lcounts, default=0):
            raise ValueError(
                f"buffer split dim {buffer.shape[split]} cannot hold blocks of {max(lcounts)}"
            )
        out = cls.__new__(cls)
        out._DNDarray__comm = comm
        out._DNDarray__device = devices.sanitize_device(device)
        out._DNDarray__dtype = types.canonical_heat_type(dtype)
        out._DNDarray__split = split
        out._DNDarray__gshape = gshape
        out._DNDarray__lcounts = lcounts
        if _hooks.in_trace_safe():
            # lazy-fusion replay: see _place — placement is the jit's job
            out._DNDarray__array = buffer
        else:
            out._DNDarray__array = jax.device_put(
                buffer, comm.array_sharding(buffer.shape, split)
            )
        return out

    # ------------------------------------------------------------------ meta
    @property
    def larray(self) -> jax.Array:
        """The underlying global physical buffer (``jax.Array``).

        The reference returns the rank-local torch shard
        (``dndarray.py:110``); under single-controller JAX the process
        addresses the global sharded array, which is the analogous handle.
        **The buffer is padded along the split axis** when the logical
        extent does not divide the mesh size (``pshape`` vs ``gshape``);
        use :meth:`_logical` for the exact logical array. Per-device shards
        are available via :attr:`local_shards`.

        A ragged-layout array (after ``redistribute_`` to a non-canonical
        map) is rebalanced in place first — this accessor hands out the
        canonical ceil-div buffer, which is what matmul tiling, resplit
        and I/O assembly consume. Hot compute paths (elementwise ops,
        reductions, cumops) do NOT route through here on ragged arrays;
        they read :attr:`_raw` and mask per-shard ``lcounts`` instead
        (see ``_operations``), so the rebalance (one bounded interval
        exchange, counted in ``LAYOUT_STATS``) only happens for ops that
        genuinely need the canonical map.

        NOTE: basic-index ``__setitem__`` updates the buffer IN PLACE
        (donated scatter — the torch-like mutation the reference performs
        on its local tensor); a handle obtained from this property before
        a setitem is invalidated by it. Re-read ``larray`` after mutating.
        """
        if self.__lcounts is not None:
            self.balance_()
        return self.__array

    @larray.setter
    def larray(self, value):
        """Replace the data; ``value`` is interpreted as the *logical*
        global array (it will be padded/placed as needed)."""
        if not isinstance(value, jax.Array):
            value = jnp.asarray(value)
        gshape = tuple(value.shape)
        split = sanitize_axis(gshape, self.__split)
        self.__lcounts = None
        self.__array = _place(value, self.__comm, split, gshape)
        self.__gshape = gshape
        self.__split = split
        self.__dtype = types.canonical_heat_type(value.dtype)

    def _set_buffer(self, buffer: jax.Array, gshape=None) -> None:
        """Replace the physical buffer in place (internal; buffer must be
        padded for the current split)."""
        gshape = self.__gshape if gshape is None else tuple(int(s) for s in gshape)
        self.__lcounts = None
        self.__array = _place(buffer, self.__comm, self.__split, gshape)
        self.__gshape = gshape
        self.__dtype = types.canonical_heat_type(buffer.dtype)

    @property
    def pshape(self) -> Tuple[int, ...]:
        """Shape of the physical buffer (== ``gshape`` unless padded)."""
        return tuple(self.__array.shape)

    @property
    def _raw(self) -> jax.Array:
        """The physical buffer exactly as stored — no rebalance, no trim.
        Internal: for layout-preserving plumbing (copy, the ragged mover);
        everything else wants :attr:`larray` or :meth:`_logical`."""
        return self.__array

    @property
    def lcounts(self) -> Optional[Tuple[int, ...]]:
        """Per-split-shard valid row counts when the array is in a ragged
        (non-canonical) layout, else None. Set by ``redistribute_`` with a
        non-canonical target map; cleared by ``balance_`` or any
        computation (see :attr:`larray`)."""
        return getattr(self, "_DNDarray__lcounts", None)

    @property
    def padded(self) -> bool:
        """True when the buffer carries tail padding along the split axis."""
        return self.lcounts is not None or tuple(self.__array.shape) != self.__gshape

    def _logical(self) -> jax.Array:
        """The exact logical global array (buffer with tail padding sliced
        off; a ragged array is rebalanced first). Cheap no-op when not
        padded; otherwise an XLA slice that may reshard — intended for
        data-movement ops, not hot elementwise paths.
        """
        if not self.padded:
            return self.__array
        buf = self.larray  # rebalances a ragged layout in place
        sl = tuple(slice(0, s) for s in self.__gshape)
        return buf[sl]

    def _iter_local_shards(self, dedup: bool = False):
        """Yield ``(split_start, trimmed_shard)`` for each addressable
        shard in split-start order — THE padded-shard trimming invariant
        (valid extent = min(n - start, block)); every consumer of
        process-local shard data routes through here so the formula lives
        once. ``dedup`` skips replicated devices (multi-axis meshes) that
        hold the same split coordinate."""
        shards = sorted(
            self.__array.addressable_shards,
            key=lambda s: tuple(sl.start or 0 for sl in s.index),
        )
        split = self.__split
        lcounts = self.lcounts
        if lcounts is not None:
            # ragged layout: shard r holds lcounts[r] valid rows at local
            # offset 0; its logical start is the running displacement
            block = self.__array.shape[split] // self.__comm.size
            _, displs = self.counts_displs()
            seen = set()
            for s in shards:
                r = (s.index[split].start or 0) // block
                if dedup:
                    if r in seen:
                        continue
                    seen.add(r)
                sl = [slice(None)] * self.ndim
                sl[split] = slice(0, int(lcounts[r]))
                yield int(displs[r]), s.data[tuple(sl)]
            return
        if dedup and split is None:
            # every replica would share key 0 and all but one shard would
            # silently vanish; callers must handle replicated arrays
            raise ValueError("dedup=True requires a split array")
        seen = set()
        for s in shards:
            start = 0 if split is None else (s.index[split].start or 0)
            if dedup:
                if start in seen:
                    continue
                seen.add(start)
            if split is None or not self.padded:
                yield start, s.data
                continue
            n = self.__gshape[split]
            valid = max(0, min(n - start, s.data.shape[split]))
            sl = [slice(None)] * self.ndim
            sl[split] = slice(0, valid)
            yield start, s.data[tuple(sl)]

    @property
    def local_shards(self) -> List[jax.Array]:
        """Per-device addressable shards, trimmed to their *valid* extent
        (TPU-native view of 'local' data): shard ``r``'s shape equals the
        reference's ``comm.chunk`` result even when the buffer is padded."""
        return [data for _, data in self._iter_local_shards()]

    @property
    def comm(self) -> MeshCommunication:
        return self.__comm

    @comm.setter
    def comm(self, comm):
        buf = self.larray  # rebalance under the old comm first
        self.__comm = sanitize_comm(comm)
        self.__array = _place(buf, self.__comm, self.__split)

    @property
    def device(self) -> Device:
        return self.__device

    @device.setter
    def device(self, device):
        self.__device = devices.sanitize_device(device)

    @property
    def dtype(self):
        return self.__dtype

    @property
    def split(self) -> Optional[int]:
        return self.__split

    @property
    def gshape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.__gshape

    @property
    def lshape(self) -> Tuple[int, ...]:
        """Shape of the data addressable by *this process* (reference: the
        rank-local shape, ``dndarray.py:172``). Single-host this is the
        whole logical array; multi-host it is the union of the valid chunks
        of this process's devices (a contiguous split-axis range, since mesh
        order is process-major)."""
        if self.__split is None:
            return self.__gshape
        counts, displs = self.counts_displs()
        pid = jax.process_index()
        # Index devices by their coordinate along the mesh's SPLIT axis only
        # (_split_ranks): on a multi-axis mesh (e.g. DASO's (slow, split))
        # the raveled device order must not index counts/displs (length =
        # split extent). A process owning devices at several slow positions
        # sees the union of their split ranges (the slow axis replicates a
        # split-sharded array).
        mine = sorted(
            {
                r
                for r, d in comm_module._split_ranks(self.__comm)
                if d.process_index == pid
            }
        )
        if not mine:  # pragma: no cover - defensive
            mine = list(range(len(counts)))
        lo = displs[mine[0]]
        hi = displs[mine[-1]] + counts[mine[-1]]
        lshape = list(self.__gshape)
        lshape[self.__split] = hi - lo
        return tuple(lshape)

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, ndim) map of every shard's shape — computed, not
        communicated (reference ``dndarray.py:569-600`` used an Allreduce)."""
        lcounts = self.lcounts
        if lcounts is not None:
            out = np.tile(np.asarray(self.__gshape, dtype=np.int64), (self.__comm.size, 1))
            out[:, self.__split] = lcounts
            return out
        return self.__comm.lshape_map(self.gshape, self.__split)

    def create_lshape_map(self, force_check: bool = False) -> np.ndarray:
        return self.lshape_map

    @property
    def balanced(self) -> bool:
        return self.lcounts is None

    def is_balanced(self, force_check: bool = False) -> bool:
        """Whether the layout is the canonical ceil-div one (reference
        ``dndarray.py:508``). False only after a ``redistribute_`` to a
        non-canonical target map."""
        return self.lcounts is None

    def health_check(self, check_values: bool = False) -> "DNDarray":
        """Validate this array's distributed invariants — ``gshape`` vs
        ``lshape_map`` vs the physical buffer, dtype annotation, split
        range; ``check_values=True`` additionally scans the logical values
        for NaN/Inf. Raises :class:`heat_tpu.resilience.ValidationError`
        on any violation; returns ``self`` when healthy (chainable)."""
        from ..resilience.validate import validate

        return validate(self, check_values=check_values)

    @property
    def ndim(self) -> int:
        return len(self.__gshape)

    @property
    def size(self) -> int:
        return int(np.prod(self.__gshape)) if self.__gshape else 1

    @property
    def gnumel(self) -> int:
        return self.size

    @property
    def lnumel(self) -> int:
        return int(np.prod(self.lshape))

    @property
    def nbytes(self) -> int:
        return self.size * np.dtype(self.__dtype.jax_type()).itemsize

    @property
    def gnbytes(self) -> int:
        return self.nbytes

    @property
    def lnbytes(self) -> int:
        return self.lnumel * np.dtype(self.__dtype.jax_type()).itemsize

    @property
    def imag(self) -> "DNDarray":
        from . import complex_math

        return complex_math.imag(self)

    @property
    def real(self) -> "DNDarray":
        from . import complex_math

        return complex_math.real(self)

    @property
    def T(self) -> "DNDarray":
        from .linalg import transpose

        return transpose(self)

    @property
    def loc(self) -> LocalIndex:
        return LocalIndex(self.larray)

    @property
    def lloc(self) -> LocalIndex:
        """Local-shard indexing view (reference ``dndarray.py:239``)."""
        return LocalIndex(self.larray)

    @property
    def stride(self) -> Tuple[int, ...]:
        """Element strides of the (C-contiguous) global array (reference
        ``dndarray.py:308``)."""
        strides = []
        acc = 1
        for dim in reversed(self.gshape):
            strides.append(acc)
            acc *= dim
        return tuple(reversed(strides))

    @property
    def strides(self) -> Tuple[int, ...]:
        """Byte strides, numpy-style (reference ``dndarray.py:315``)."""
        item = np.dtype(self.__dtype.jax_type()).itemsize
        return tuple(s * item for s in self.stride)

    @property
    def halo_next(self):
        """Halos received from the *next* shard, for every inter-shard
        boundary (reference ``dndarray.py:124`` stored the per-rank received
        buffer; single-controller JAX exposes all boundaries at once).

        Shape ``(num_shards - 1, ..., halo_size, ...)`` with ``halo_size``
        replacing the split dimension: entry ``i`` is the halo shard ``i``
        receives from shard ``i + 1``.
        """
        hs = self.halo_size
        if hs == 0 or self.__split is None:
            return None
        counts, displs = self.counts_displs()  # honors a ragged layout
        log = self._logical()  # slices below are in logical coordinates
        slabs = []
        for i in range(1, len(counts)):
            # a halo crosses boundary i only when both neighbors hold >= hs
            if counts[i - 1] < hs or counts[i] < hs:
                continue
            sl = [slice(None)] * self.ndim
            sl[self.__split] = slice(displs[i], displs[i] + hs)
            slabs.append(log[tuple(sl)])
        return jnp.stack(slabs) if slabs else None

    @property
    def halo_prev(self):
        """Halos received from the *previous* shard, for every inter-shard
        boundary (reference ``dndarray.py:131``): entry ``i`` is the halo
        shard ``i + 1`` receives from shard ``i``."""
        hs = self.halo_size
        if hs == 0 or self.__split is None:
            return None
        counts, displs = self.counts_displs()  # honors a ragged layout
        log = self._logical()
        slabs = []
        for i in range(1, len(counts)):
            if counts[i - 1] < hs or counts[i] < hs:
                continue
            sl = [slice(None)] * self.ndim
            sl[self.__split] = slice(max(displs[i] - hs, 0), displs[i])
            slabs.append(log[tuple(sl)])
        return jnp.stack(slabs) if slabs else None

    def counts_displs(self) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
        """Per-device item counts and offsets along the split axis
        (reference ``dndarray.py:543``)."""
        if self.__split is None:
            raise ValueError(
                "Non-distributed DNDarray. Cannot calculate counts and displacements."
            )
        counts = self.lshape_map[:, self.__split]
        displs = np.concatenate([[0], np.cumsum(counts)[:-1]])
        return tuple(int(c) for c in counts), tuple(int(d) for d in displs)

    def is_distributed(self) -> bool:
        """Whether data lives on more than one device (reference
        ``dndarray.py:952``)."""
        return self.__split is not None and self.__comm.is_distributed()

    def cpu(self) -> "DNDarray":
        """Return a host-memory copy (reference ``dndarray.py:560`` moved
        torch storage to CPU). The returned DNDarray's buffer lives on the
        JAX CPU backend — it does not occupy accelerator HBM."""
        host = jax.device_put(
            jnp.asarray(self.numpy()), jax.local_devices(backend="cpu")[0]
        )
        out = DNDarray.__new__(DNDarray)
        out._DNDarray__comm = self.__comm
        out._DNDarray__device = devices.cpu
        out._DNDarray__dtype = self.__dtype
        out._DNDarray__split = None
        out._DNDarray__gshape = self.__gshape
        out._DNDarray__lcounts = None
        out._DNDarray__array = host
        return out

    # ------------------------------------------------------------- placement
    def resplit_(self, axis: Optional[int] = None) -> "DNDarray":
        """In-place redistribution to a new split axis (reference
        ``dndarray.py:1235``). One ``device_put``; XLA chooses the collective
        (all-gather for ``axis=None``, all-to-all for split->split).

        Watchdog-bounded (label ``collective.resplit``) when
        ``resilience.deadlines`` is active — a resharding that wedges on
        the interconnect surfaces as ``CollectiveTimeout``, not a hang."""
        from . import _hooks

        axis = sanitize_axis(self.gshape, axis)
        if axis == self.__split:
            return self

        def reshard():
            _hooks.fault_point(
                "collective.resplit", gshape=self.__gshape, to_split=axis
            )
            out = _place(self._logical(), self.__comm, axis, self.__gshape, force=True)
            if _hooks.get_deadline_runner() is not None:
                out = out.block_until_ready()  # keep the wedge inside the deadline
            return out

        self.__array = _hooks.guarded_call("collective.resplit", reshard)
        self.__split = axis
        return self

    def resplit(self, axis: Optional[int] = None) -> "DNDarray":
        """Out-of-place resplit (reference ``manipulations.py:3329``)."""
        axis = sanitize_axis(self.gshape, axis)
        return DNDarray(
            self._logical(),
            gshape=self.__gshape,
            dtype=self.__dtype,
            split=axis,
            device=self.__device,
            comm=self.__comm,
        )

    def redistribute_(self, lshape_map=None, target_map=None) -> "DNDarray":
        """Move data to a target per-shard shape map (reference
        ``dndarray.py:1029-1233``, chained Send/Recv there).

        Any map that partitions the split extent is accepted, like the
        reference's — including skewed and empty shards:

        - the current map (canonical or ragged): no-op;
        - the canonical map of a *different* split axis: one resharding
          (XLA chooses the collective);
        - any other partition of the split extent: a ragged interval
          exchange (:func:`heat_tpu.parallel.flatmove.ragged_move` —
          colored ``ppermute`` rounds, per-device memory O(block)). The
          result is a *ragged-layout* array: ``lshape_map`` /
          ``local_shards`` / ``counts_displs`` reflect the target map
          exactly; any subsequent computation rebalances first (see
          :attr:`larray`).

        ``lshape_map`` (the current-layout hint in the reference, computed
        there with an Allreduce) is validated against the true metadata.
        """
        if lshape_map is not None:
            given = np.asarray(lshape_map)
            if given.shape != self.lshape_map.shape or not np.array_equal(
                given, self.lshape_map
            ):
                raise ValueError(
                    f"lshape_map {given.tolist()} does not describe this array's "
                    f"current layout {self.lshape_map.tolist()}"
                )
        if target_map is None:
            return self
        target = np.asarray(target_map)
        # 0-d arrays have an empty (size, 0) map — matching lshape_map's
        # convention, so the identity early-return below covers them
        size, ndim = self.__comm.size, self.ndim
        if target.shape != (size, ndim):
            raise ValueError(
                f"target_map must have shape {(size, ndim)}, got {target.shape}"
            )
        if (target < 0).any():
            raise ValueError("target_map entries must be non-negative")
        if np.array_equal(target, self.lshape_map):
            return self  # already in this layout (covers split=None too)
        split = self.__split
        if split is not None:
            non_split = [k for k in range(ndim) if k != split]
            counts = target[:, split]
            if (
                all((target[:, k] == self.__gshape[k]).all() for k in non_split)
                and int(counts.sum()) == self.__gshape[split]
            ):
                return self._ragged_redistribute(tuple(int(c) for c in counts))
        for axis in ([split] if split is not None else []) + [
            k for k in range(self.ndim) if k != split
        ]:
            if np.array_equal(target, self.__comm.lshape_map(self.gshape, axis)):
                if axis != self.__split:
                    self.resplit_(axis)
                return self
        raise ValueError(
            "target_map neither partitions the split extent nor matches the "
            "canonical layout of any split axis"
        )

    def _ragged_redistribute(self, counts: Tuple[int, ...]) -> "DNDarray":
        """In-place interval exchange from the current layout to per-shard
        split-axis ``counts`` (sum equals the split extent)."""
        from ..parallel.flatmove import ragged_move

        split = self.__split
        p = self.__comm.size
        cur = tuple(int(c) for c in self.lshape_map[:, split])
        canonical = self.__comm.counts_displs_shape(self.__gshape, split)[0]
        b_out = max(1, max(counts))
        if counts == tuple(canonical):
            # target IS the canonical map: land exactly on the canonical
            # padded buffer and drop the ragged state
            b_out = self.__comm.padded_dim(self.__gshape[split]) // p
        if counts == cur and self.__array.shape[split] // p == b_out:
            # already in the target layout PHYSICALLY (counts alone are
            # not enough: a ragged buffer whose counts happen to equal a
            # map can still carry a wider block — e.g. a shuffle result
            # whose group counts coincide with the ceil-div map)
            if counts == tuple(canonical) and self.__lcounts is not None:
                self.__lcounts = None
                self.__array = _place(
                    self.__array, self.__comm, split, self.__gshape, force=True
                )
            return self
        _hooks.trace_barrier("redistribute_")
        buf = ragged_move(self.__array, split, cur, counts, b_out, self.__comm)
        if counts == tuple(canonical):
            self.__lcounts = None
            self.__array = _place(buf, self.__comm, split, self.__gshape, force=True)
        else:
            self.__lcounts = counts
            self.__array = jax.device_put(
                buf, self.__comm.array_sharding(buf.shape, split)
            )
        return self

    def balance_(self) -> "DNDarray":
        """Rebalance to the canonical ceil-div layout (reference
        ``dndarray.py:470``). No-op unless the array is in a ragged layout
        from ``redistribute_``; then one bounded interval exchange.

        Elementwise ops, reductions and cumops compute directly on ragged
        layouts (see :mod:`heat_tpu.core._operations`), so this is only
        needed by consumers of the canonical ceil-div map — matmul tiling,
        ``resplit_``, I/O assembly — all of which reach it via
        :attr:`larray`. ``LAYOUT_STATS["rebalances"]`` counts the
        exchanges actually performed (tests hook it to prove hot paths
        stay ragged)."""
        if self.lcounts is not None:
            _hooks.trace_barrier("balance_")
            LAYOUT_STATS["rebalances"] += 1
            canonical, _, _ = self.__comm.counts_displs_shape(self.__gshape, self.__split)
            self._ragged_redistribute(tuple(canonical))
        return self

    def get_halo(self, halo_size: int) -> None:
        """Fetch split-axis neighbor halos (reference ``dndarray.py:333-441``).

        Stores ``halo_prev``/``halo_next`` global-slice views. The
        collective version for use inside ``shard_map`` lives in
        :func:`heat_tpu.parallel.halo.exchange`.
        """
        if not isinstance(halo_size, int) or halo_size < 0:
            raise (TypeError if not isinstance(halo_size, int) else ValueError)(
                f"halo_size needs to be a non-negative int, got {halo_size}"
            )
        self.__halo_size = halo_size

    @property
    def halo_size(self) -> int:
        return getattr(self, "_DNDarray__halo_size", 0)

    def array_with_halos(self) -> jax.Array:
        """Global array (halos are implicit in the global view); kept for
        API parity with reference ``dndarray.py:445``."""
        return self.larray

    # ------------------------------------------------------------ conversion
    def astype(self, dtype, copy: bool = True) -> "DNDarray":
        """Cast to a new heat type (reference ``dndarray.py:451``).
        Layout-preserving: a ragged array casts in place without
        rebalancing (elementwise, no data movement)."""
        dtype = types.canonical_heat_type(dtype)
        buf = self.__array
        casted = buf.astype(dtype.jax_type())
        if copy:
            if casted is buf:
                # same-dtype astype returns the SAME array; a true copy is
                # required because basic-index setitem donates its buffer
                # (an aliasing "copy" would be deleted with the original)
                casted = jnp.copy(casted)
            if self.__lcounts is not None:
                return DNDarray._from_ragged(
                    casted, self.__gshape, dtype, self.__split, self.__lcounts,
                    self.__device, self.__comm,
                )
            return DNDarray._from_buffer(
                casted, self.__gshape, dtype, self.__split, self.__device, self.__comm
            )
        self.__array = casted
        self.__dtype = dtype
        return self

    def numpy(self) -> np.ndarray:
        """Gather the logical global array to host memory (reference
        ``dndarray.py:991``). Tail padding is sliced off host-side.

        Multi-host, a split array is assembled with ONE ragged process
        allgather of the valid local blocks (every process must call —
        collective, like the reference's ``resplit(None)`` gather)."""
        _hooks.observe("host.gather", shape=self.__gshape)
        buf = self.larray
        if getattr(buf, "is_fully_addressable", True):
            host = np.asarray(jax.device_get(buf))
            if tuple(host.shape) != self.__gshape:
                host = host[tuple(slice(0, s) for s in self.__gshape)]
            return host
        if self.__split is None:
            # replicated: any local device holds the full array
            return np.asarray(jax.device_get(buf.addressable_shards[0].data))
        split = self.__split
        shards = [
            (start, np.asarray(jax.device_get(shard)))
            for start, shard in self._iter_local_shards(dedup=True)
            if shard.shape[split] > 0  # empty trims carry no data
        ]
        starts = [s for s, _ in shards]
        sizes = [d.shape[split] for _, d in shards]
        contiguous = all(
            starts[i] + sizes[i] == starts[i + 1] for i in range(len(shards) - 1)
        )
        # fast path: each process owns one contiguous split range and
        # process order equals split order (process-major meshes — the
        # default); a permuted mesh takes the place-by-offset fallback
        # (the alignment guard assemble_local_shards applies, comm:489).
        # The decision must be GLOBAL — ranks disagreeing on the path
        # would dispatch different collective sequences — so the local
        # contiguity flag rides along with the range start.
        from jax.experimental import multihost_utils

        lo = starts[0] if starts else self.__gshape[split]
        meta = np.asarray(
            multihost_utils.process_allgather(
                np.asarray([lo, int(contiguous)], np.int64)
            )
        ).reshape(-1, 2)
        aligned = bool(meta[:, 1].all()) and bool(
            (np.diff(meta[:, 0]) > 0).all()
            # strictly increasing: EQUAL starts mean a replication axis
            # spans processes (each holds the full range) — concatenating
            # replicas would multiply the extent; the coverage-mask
            # fallback handles that layout
        )
        np_dtype = np.dtype(self.__dtype.jax_type())
        if aligned:
            if shards:
                local = np.concatenate([d for _, d in shards], axis=split)
            else:  # pragma: no cover - a process with no valid rows
                shape = list(self.__gshape)
                shape[split] = 0
                local = np.zeros(shape, np_dtype)
            blocks = comm_module.ragged_process_allgather(local, axis=split)
            return np.concatenate(blocks, axis=split)
        # fallback (permuted device order): place local shards at their
        # logical offsets and merge across processes by coverage mask
        out = np.zeros(self.__gshape, np_dtype)
        covered = np.zeros(self.__gshape[split], bool)
        for start, d in shards:
            sl = [slice(None)] * self.ndim
            sl[split] = slice(start, start + d.shape[split])
            out[tuple(sl)] = d
            covered[start : start + d.shape[split]] = True
        all_out = np.asarray(multihost_utils.process_allgather(out))
        all_cov = np.asarray(multihost_utils.process_allgather(covered))
        for p_i in range(all_out.shape[0]):
            mask = all_cov[p_i] & ~covered
            if mask.any():
                sl = [slice(None)] * self.ndim
                sl[split] = mask
                out[tuple(sl)] = all_out[p_i][tuple(sl)]
                covered |= all_cov[p_i]
        return out

    def __array__(self, dtype=None):
        out = self.numpy()
        return out.astype(dtype) if dtype is not None else out

    def tolist(self, keepsplit: bool = False):
        return self.numpy().tolist()

    def item(self):
        """Scalar extraction (reference ``dndarray.py:955``)."""
        _hooks.observe("host.item")
        if self.padded:
            return self._logical().item()
        return self.__array.item()

    def __bool__(self) -> bool:
        return bool(self.__cast(bool))

    def __int__(self) -> int:
        return int(self.__cast(int))

    def __float__(self) -> float:
        return float(self.__cast(float))

    def __complex__(self) -> complex:
        return complex(self.__cast(complex))

    def __cast(self, cast_function):
        if np.prod(self.shape) == 1:
            _hooks.observe("host.scalar")
            return cast_function(self._logical().reshape(()).item())
        raise TypeError("only size-1 arrays can be converted to Python scalars")

    def __len__(self) -> int:
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.gshape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # --------------------------------------------------------------- fill ops
    def fill_diagonal(self, value) -> "DNDarray":
        """Fill the main diagonal in place (reference ``dndarray.py:608``)."""
        n = min(self.gshape[0], self.gshape[1]) if self.ndim >= 2 else 0
        if self.ndim != 2:
            raise ValueError("input array must be 2D")
        idx = jnp.arange(n)
        self.__array = _place(
            self.larray.at[idx, idx].set(value),
            self.__comm,
            self.__split,
            self.__gshape,
        )
        return self

    # -------------------------------------------------------------- indexing
    def __getitem__(self, key) -> "DNDarray":
        """Global indexing (reference ``dndarray.py:652-908``).

        The result's split follows the reference's rules: slicing keeps the
        split (shifted over removed dims); a scalar index on the split axis
        replicates; advanced indexing on the split axis yields split=0.
        """
        buf = self.larray  # rebalances a ragged layout first
        key_t, out_split = self.__translate_key(key)
        fast = self.__basic_getitem(buf, key_t, out_split)
        if fast is not None:
            return fast
        result = buf[key_t]
        if isinstance(result, jax.Array) and result.ndim == 0:
            out_split = None
        return DNDarray(
            result,
            dtype=self.__dtype,
            split=out_split if result.ndim else None,
            device=self.__device,
            comm=self.__comm,
        )

    def __basic_getitem(self, buf, key_t, out_split):
        """Basic-index fast path: one cached pinned pipeline per key
        structure (ints become traced operands). Returns None when the key
        is not basic (advanced/bool/scalar-bool) or the array is not
        distributed — the caller then takes the eager path."""
        if self.__split is None or not self.__comm.is_distributed():
            return None
        key_seq = list(key_t) if isinstance(key_t, tuple) else [key_t]
        struct: List[Tuple] = []
        ints: List[int] = []
        in_dim = 0
        for pos, k in enumerate(key_seq):
            if k is None:
                struct.append(("n",))
                continue
            if isinstance(k, (bool, np.bool_)):
                return None
            if isinstance(k, (int, np.integer)):
                k = int(k)
                if k < 0:  # dynamic gather clamps; wrap host-side
                    k += self.__gshape[in_dim]
                if not 0 <= k < self.__gshape[in_dim]:
                    # traced indices clamp/zero instead of raising; keep
                    # the reference's (numpy's) IndexError contract
                    raise IndexError(
                        f"index {k} is out of bounds for axis {in_dim} with "
                        f"size {self.__gshape[in_dim]}"
                    )
                # split-dim ints lower as a one-hot contraction ('I') so
                # GSPMD never gathers the operand
                struct.append(("I",) if in_dim == self.__split else ("i",))
                ints.append(k)
                in_dim += 1
            elif isinstance(k, slice):
                if in_dim == self.__split:
                    start, stop, step = k.indices(self.__gshape[in_dim])
                    if step != 1:
                        return self.__strided_split_getitem(
                            buf, key_seq, pos, start, stop, step
                        )
                struct.append(("s", k.start, k.stop, k.step))
                in_dim += 1
            else:
                return None
        # shape of the logical result (independent of the int values)
        static_key = tuple(
            0 if t[0] in ("i", "I") else (slice(t[1], t[2], t[3]) if t[0] == "s" else None)
            for t in struct
        )
        out_gshape = jax.eval_shape(
            lambda b: b[static_key], jax.ShapeDtypeStruct(buf.shape, buf.dtype)
        ).shape
        if len(out_gshape) == 0 or 0 in out_gshape:
            # scalar or empty result: nothing to distribute (XLA refuses
            # pinned shardings on zero-size outputs)
            return None
        from ._movement import getitem_executable

        fn = getitem_executable(
            buf.shape, buf.dtype, self.__split, tuple(struct),
            tuple(out_gshape), out_split, self.__comm,
        )
        return DNDarray._from_buffer(
            fn(buf, *ints), out_gshape, self.__dtype, out_split,
            self.__device, self.__comm,
        )

    def __strided_split_getitem(self, buf, key_seq, pos, start, stop, step):
        """A step != 1 slice on the split axis: GSPMD's partitioner would
        all-gather (strided selection breaks the interval structure), so
        run the strided-take interval-exchange kernel
        (:func:`heat_tpu.parallel.flatmove.strided_take`) — negative
        steps as positive-take + pinned flip — then apply the remaining
        key dims through the regular pipeline."""
        from ..parallel.flatmove import strided_take

        split = self.__split
        m = len(range(start, stop, step))
        if m == 0:
            return None  # empty result: the eager path handles it exactly
        if step > 0:
            buf2, _ = strided_take(
                buf, split, self.__gshape[split], start, stop, step, self.__comm
            )
        else:
            first = start + step * (m - 1)
            buf2, _ = strided_take(
                buf, split, self.__gshape[split], first, start + 1, -step, self.__comm
            )
        mid_gshape = tuple(
            m if d == split else s for d, s in enumerate(self.__gshape)
        )
        mid = DNDarray._from_buffer(
            buf2, mid_gshape, self.__dtype, split, self.__device, self.__comm
        )
        if step < 0:
            from ._movement import flip_padded

            mid = DNDarray._from_buffer(
                flip_padded(mid.larray, mid_gshape, split, split, self.__comm),
                mid_gshape, self.__dtype, split, self.__device, self.__comm,
            )
        rest = list(key_seq)
        rest[pos] = slice(None)
        return mid[tuple(rest)]

    def __setitem__(self, key, value) -> None:
        """Global scatter-update (reference ``dndarray.py:1359-1676``).

        Keys are normalized to the logical extent, so only valid elements
        are ever written; tail padding stays untouched.

        Basic-index keys (ints/slices) run as a cached donated jitted
        scatter with pinned shardings — in-place on device, O(updates)
        for a loop of setitems, matching the reference's local in-place
        write (``dndarray.py:1359``). Advanced keys fall back to an eager
        sharding-preserving update."""
        buf = self.larray  # rebalances a ragged layout first
        key_t, _ = self.__translate_key(key)
        if isinstance(value, DNDarray):
            value = value._logical()
        value = jnp.asarray(value, dtype=self.__dtype.jax_type())
        struct: List[Tuple] = []
        ints: List[int] = []
        in_dim = 0
        for k in key_t if isinstance(key_t, tuple) else (key_t,):
            if k is None or isinstance(k, (bool, np.bool_)):
                break  # newaxis / scalar-bool keys: rare, eager path
            if isinstance(k, (int, np.integer)):
                k = int(k)
                if k < 0:
                    k += self.__gshape[in_dim]
                if not 0 <= k < self.__gshape[in_dim]:
                    # a traced scatter index would silently DROP the
                    # out-of-bounds update; keep the IndexError contract
                    raise IndexError(
                        f"index {k} is out of bounds for axis {in_dim} with "
                        f"size {self.__gshape[in_dim]}"
                    )
                struct.append(("i",))
                ints.append(k)
                in_dim += 1
            elif isinstance(k, slice):
                struct.append(("s", k.start, k.stop, k.step))
                in_dim += 1
            else:
                break
        else:
            from ._movement import setitem_executable

            if value is buf:
                # self-assignment (a[:] = a on an unpadded array): the
                # donated argument must not alias an operand
                value = jnp.copy(value)
            fn = setitem_executable(
                buf.shape, buf.dtype, self.__split, tuple(struct),
                tuple(value.shape), value.dtype, self.__comm,
            )
            self.__array = fn(buf, value, *ints)
            return
        # advanced indexing: eager update keeps the operand's sharding, so
        # _place is a metadata no-op (no forced device_put)
        self.__array = _place(
            buf.at[key_t].set(value),
            self.__comm,
            self.__split,
            self.__gshape,
        )

    def __translate_key(self, key):
        """Normalize an index key against the *logical* shape and compute
        the resulting split axis.

        Keys addressing the (possibly padded) split dimension are rewritten
        so they can never select tail padding: slices get explicit logical
        bounds, negative scalars/arrays are wrapped mod the logical extent,
        boolean masks are False-padded to the buffer extent.
        """
        split = self.__split
        if isinstance(key, DNDarray):
            # coordinate-list indexing: x[nonzero(x)] with an (n, ndim) int
            # key selects per-row coordinates (reference torch-style
            # ``dndarray.py:700-707`` handling of nonzero results)
            if (
                key.ndim == 2
                and self.ndim > 1
                and key.gshape[1] == self.ndim
                and types.issubdtype(key.dtype, types.integer)
            ):
                logical_key = key._logical()
                cols = tuple(logical_key[:, d] for d in range(self.ndim))
                return cols, (0 if split is not None else None)
            key = key._logical()
        if not isinstance(key, tuple):
            key = (key,)
        key = tuple(k._logical() if isinstance(k, DNDarray) else k for k in key)
        # jnp accepts builtin-bool scalar keys but asserts on np.bool_ ones
        key = tuple(bool(k) if isinstance(k, np.bool_) else k for k in key)
        # expand ellipsis ("in"/.index would trip elementwise == on array keys);
        # a multi-dim boolean mask consumes mask.ndim input dims
        def _consumed(k):
            if k is None or k is Ellipsis:
                return 0
            if isinstance(k, (bool, np.bool_)):
                return 0  # scalar bool adds an axis, consumes no input dim
            a = np.asarray(k) if not isinstance(k, (jax.Array, np.ndarray, slice, int, np.integer)) else k
            if isinstance(a, (jax.Array, np.ndarray)) and a.dtype == np.bool_:
                return a.ndim
            return 1

        n_specified = sum(_consumed(k) for k in key)
        e = next((i for i, k in enumerate(key) if k is Ellipsis), None)
        if e is not None:
            fill = (slice(None),) * (self.ndim - n_specified)
            key = key[:e] + fill + key[e + 1 :]
            n_specified = self.ndim  # ellipsis expansion covers every dim
        # numpy's IndexError contract on EVERY path: static jnp indexing
        # clamps out-of-bounds scalars instead of raising
        dim = 0
        for k in key:
            c = _consumed(k)
            if c and dim + c > self.ndim:
                raise IndexError(
                    f"too many indices for array with {self.ndim} dimensions"
                )
            if isinstance(k, (int, np.integer)) and not isinstance(k, (bool, np.bool_)):
                d = self.__gshape[dim]
                if not -d <= int(k) < d:
                    raise IndexError(
                        f"index {int(k)} is out of bounds for axis {dim} with size {d}"
                    )
            dim += c
        if split is None:
            return key, None
        needs_norm = self.padded
        n_split = self.__gshape[split]
        n_buf = self.__array.shape[split]
        if needs_norm and n_specified <= split:
            # make sure the split dim is explicitly keyed so normalization
            # below can exclude the tail padding
            key = key + (slice(None),) * (split + 1 - n_specified)
        # walk input dims -> output dims to find where split lands,
        # normalizing split-dim keys against the logical extent
        in_dim = 0
        out_dim = 0
        out_split: Optional[int] = None
        new_key = []
        for k in key:
            if k is None:
                new_key.append(k)
                out_dim += 1
                continue
            if isinstance(k, (bool, np.bool_)):
                new_key.append(k)
                out_dim += 1  # scalar bool adds an axis, consumes none
                continue
            if in_dim == split:
                if isinstance(k, slice):
                    out_split = out_dim
                    if needs_norm:
                        k = _normalize_slice(k, n_split)
                elif isinstance(k, (int, np.integer)):
                    out_split = None  # scalar on split axis -> replicated bcast
                    if not -n_split <= int(k) < n_split:
                        # validate HERE: wrapping an already-wrapped value
                        # downstream would alias a valid index
                        raise IndexError(
                            f"index {int(k)} is out of bounds for axis "
                            f"{split} with size {n_split}"
                        )
                    if needs_norm and k < 0:
                        k = int(k) + n_split
                else:
                    out_split = 0  # advanced index on split axis -> split 0
                    if needs_norm:
                        arr = jnp.asarray(k)
                        if arr.dtype == jnp.bool_:
                            # mask covers dims [in_dim, in_dim + arr.ndim);
                            # False-pad the split-dim axis to buffer extent
                            pads = [(0, 0)] * arr.ndim
                            pads[split - in_dim] = (0, n_buf - n_split)
                            k = jnp.pad(arr, pads, constant_values=False)
                        else:
                            k = jnp.where(arr < 0, arr + n_split, arr)
                new_key.append(k)
                in_dim += 1
                out_dim += 1 if not isinstance(k, (int, np.integer)) else 0
                continue
            if isinstance(k, (int, np.integer)):
                in_dim += 1
            elif isinstance(k, slice):
                in_dim += 1
                out_dim += 1
            else:  # array-like advanced index
                arr = np.asarray(k) if not isinstance(arr_k := k, jax.Array) else arr_k
                if arr.dtype == np.bool_ or arr.dtype == jnp.bool_:
                    if needs_norm and in_dim < split < in_dim + arr.ndim:
                        pads = [(0, 0)] * arr.ndim
                        pads[split - in_dim] = (0, n_buf - n_split)
                        k = jnp.pad(jnp.asarray(arr), pads, constant_values=False)
                    in_dim += arr.ndim
                else:
                    in_dim += 1
                out_dim += 1
            new_key.append(k)
        key = tuple(new_key)
        # trailing unindexed dims: split stays at its offset position
        if in_dim <= split and out_split is None:
            out_split = out_dim + (split - in_dim)
        return key, out_split

    # ------------------------------------------------------------ arithmetic
    def __add__(self, other):
        from . import arithmetics

        return arithmetics.add(self, other)

    __radd__ = __add__

    def __sub__(self, other):
        from . import arithmetics

        return arithmetics.sub(self, other)

    def __rsub__(self, other):
        from . import arithmetics

        return arithmetics.sub(other, self)

    def __mul__(self, other):
        from . import arithmetics

        return arithmetics.mul(self, other)

    __rmul__ = __mul__

    def __truediv__(self, other):
        from . import arithmetics

        return arithmetics.div(self, other)

    def __rtruediv__(self, other):
        from . import arithmetics

        return arithmetics.div(other, self)

    def __floordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(self, other)

    def __rfloordiv__(self, other):
        from . import arithmetics

        return arithmetics.floordiv(other, self)

    def __mod__(self, other):
        from . import arithmetics

        return arithmetics.mod(self, other)

    def __rmod__(self, other):
        from . import arithmetics

        return arithmetics.mod(other, self)

    def __pow__(self, other, modulo=None):
        from . import arithmetics

        return arithmetics.pow(self, other)

    def __rpow__(self, other):
        from . import arithmetics

        return arithmetics.pow(other, self)

    def __matmul__(self, other):
        from .linalg import matmul

        return matmul(self, other)

    def __neg__(self):
        from . import arithmetics

        return arithmetics.neg(self)

    def __pos__(self):
        from . import arithmetics

        return arithmetics.pos(self)

    def __abs__(self):
        from . import rounding

        return rounding.abs(self)

    def __invert__(self):
        from . import arithmetics

        return arithmetics.invert(self)

    def __and__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_and(self, other)

    def __or__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_or(self, other)

    def __xor__(self, other):
        from . import arithmetics

        return arithmetics.bitwise_xor(self, other)

    def __lshift__(self, other):
        from . import arithmetics

        return arithmetics.left_shift(self, other)

    def __rshift__(self, other):
        from . import arithmetics

        return arithmetics.right_shift(self, other)

    # in-place variants: replace buffer, keep metadata
    def __iadd__(self, other):
        return self.__set_from(self.__add__(other))

    def __isub__(self, other):
        return self.__set_from(self.__sub__(other))

    def __imul__(self, other):
        return self.__set_from(self.__mul__(other))

    def __itruediv__(self, other):
        return self.__set_from(self.__truediv__(other))

    def __set_from(self, result: "DNDarray") -> "DNDarray":
        self.__array = result.larray
        self.__dtype = result.dtype
        self.__split = result.split
        return self

    # ------------------------------------------------------------ relational
    def __eq__(self, other):
        from . import relational

        return relational.eq(self, other)

    def __ne__(self, other):
        from . import relational

        return relational.ne(self, other)

    def __lt__(self, other):
        from . import relational

        return relational.lt(self, other)

    def __le__(self, other):
        from . import relational

        return relational.le(self, other)

    def __gt__(self, other):
        from . import relational

        return relational.gt(self, other)

    def __ge__(self, other):
        from . import relational

        return relational.ge(self, other)

    __hash__ = None

    # ------------------------------------------------------------ reductions
    def sum(self, axis=None, out=None, keepdims=False):
        from . import arithmetics

        return arithmetics.sum(self, axis=axis, out=out, keepdims=keepdims)

    def prod(self, axis=None, out=None, keepdims=False):
        from . import arithmetics

        return arithmetics.prod(self, axis=axis, out=out, keepdims=keepdims)

    def mean(self, axis=None):
        from . import statistics

        return statistics.mean(self, axis)

    def std(self, axis=None, ddof=0):
        from . import statistics

        return statistics.std(self, axis, ddof=ddof)

    def var(self, axis=None, ddof=0):
        from . import statistics

        return statistics.var(self, axis, ddof=ddof)

    def min(self, axis=None, out=None, keepdims=None):
        from . import statistics

        return statistics.min(self, axis=axis, out=out, keepdims=keepdims)

    def max(self, axis=None, out=None, keepdims=None):
        from . import statistics

        return statistics.max(self, axis=axis, out=out, keepdims=keepdims)

    def argmin(self, axis=None, out=None):
        from . import statistics

        return statistics.argmin(self, axis=axis, out=out)

    def argmax(self, axis=None, out=None):
        from . import statistics

        return statistics.argmax(self, axis=axis, out=out)

    def all(self, axis=None, out=None, keepdims=False):
        from . import logical

        return logical.all(self, axis=axis, out=out, keepdims=keepdims)

    def any(self, axis=None, out=None, keepdims=False):
        from . import logical

        return logical.any(self, axis=axis, out=out, keepdims=keepdims)

    def cumsum(self, axis):
        from . import arithmetics

        return arithmetics.cumsum(self, axis)

    def cumprod(self, axis):
        from . import arithmetics

        return arithmetics.cumprod(self, axis)

    # ---------------------------------------------------------- manipulation
    def reshape(self, *shape, new_split=None):
        from . import manipulations

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return manipulations.reshape(self, shape, new_split=new_split)

    def flatten(self):
        from . import manipulations

        return manipulations.flatten(self)

    def ravel(self):
        from . import manipulations

        return manipulations.ravel(self)

    def squeeze(self, axis=None):
        from . import manipulations

        return manipulations.squeeze(self, axis)

    def expand_dims(self, axis):
        from . import manipulations

        return manipulations.expand_dims(self, axis)

    def transpose(self, axes=None):
        from .linalg import transpose

        return transpose(self, axes)

    def flip(self, axis=None):
        from . import manipulations

        return manipulations.flip(self, axis)

    def unique(self, sorted=False, return_inverse=False, axis=None):
        from . import manipulations

        return manipulations.unique(self, sorted=sorted, return_inverse=return_inverse, axis=axis)

    def copy(self):
        from . import memory

        return memory.copy(self)

    def abs(self, out=None, dtype=None):
        from . import rounding

        return rounding.abs(self, out=out, dtype=dtype)

    def ceil(self, out=None):
        from . import rounding

        return rounding.ceil(self, out)

    def floor(self, out=None):
        from . import rounding

        return rounding.floor(self, out)

    def round(self, decimals=0, out=None, dtype=None):
        from . import rounding

        return rounding.round(self, decimals, out, dtype)

    def trunc(self, out=None):
        from . import rounding

        return rounding.trunc(self, out)

    def exp(self, out=None):
        from . import exponential

        return exponential.exp(self, out)

    def log(self, out=None):
        from . import exponential

        return exponential.log(self, out)

    def sqrt(self, out=None):
        from . import exponential

        return exponential.sqrt(self, out)

    def sin(self, out=None):
        from . import trigonometrics

        return trigonometrics.sin(self, out)

    def cos(self, out=None):
        from . import trigonometrics

        return trigonometrics.cos(self, out)

    def tan(self, out=None):
        from . import trigonometrics

        return trigonometrics.tan(self, out)

    def tanh(self, out=None):
        from . import trigonometrics

        return trigonometrics.tanh(self, out)

    def isclose(self, other, rtol=1e-05, atol=1e-08, equal_nan=False):
        from . import logical

        return logical.isclose(self, other, rtol=rtol, atol=atol, equal_nan=equal_nan)

    def nonzero(self):
        from . import indexing

        return indexing.nonzero(self)

    def clip(self, a_min, a_max, out=None):
        from . import rounding

        return rounding.clip(self, a_min, a_max, out)

    def tril(self, k=0):
        from .linalg import tril

        return tril(self, k)

    def triu(self, k=0):
        from .linalg import triu

        return triu(self, k)

    # ----------------------------------------------------------------- print
    def __repr__(self) -> str:
        from . import printing

        return printing.__str__(self)

    def __str__(self) -> str:
        from . import printing

        return printing.__str__(self)


def _normalize_slice(s: slice, n: int) -> slice:
    """Rewrite ``s`` with explicit bounds for a logical extent ``n`` so it
    can be applied to a tail-padded buffer without selecting padding."""
    start, stop, step = s.indices(n)
    if step < 0:
        # stop == -1 means "run through index 0"; an explicit -1 would wrap
        return slice(start, None if stop < 0 else stop, step)
    return slice(start, stop, step)


def _place(
    array: jax.Array,
    comm: MeshCommunication,
    split: Optional[int],
    gshape: Optional[Tuple[int, ...]] = None,
    force: bool = False,
) -> jax.Array:
    """Ensure ``array`` is the padded physical buffer for (comm, split,
    gshape), carrying the even NamedSharding over the mesh.

    ``array`` may arrive as the logical array (shape == gshape; it is
    zero-padded along the split dim to a multiple of the mesh size) or as an
    already-padded buffer (shape == padded_shape; taken as-is). Every shape
    is shardable this way — non-divisible logical extents get tail padding
    instead of the replication fallback of round 1.
    """
    gshape = tuple(array.shape) if gshape is None else tuple(int(s) for s in gshape)
    if split is not None:
        target_shape = comm.padded_shape(gshape, split)
        if tuple(array.shape) == gshape and gshape != target_shape:
            pad = [(0, t - s) for t, s in zip(target_shape, array.shape)]
            array = jnp.pad(array, pad)
        elif tuple(array.shape) != target_shape:
            raise ValueError(
                f"buffer shape {tuple(array.shape)} matches neither logical {gshape} "
                f"nor padded {target_shape}"
            )
    if _hooks.in_trace_safe():
        # lazy-fusion replay: tracers cannot be device_put; the fused
        # program's out_shardings pin the final placement instead
        return array
    target = comm.array_sharding(array.shape, split)
    current = getattr(array, "sharding", None)
    if not force and current is not None and current.is_equivalent_to(target, array.ndim):
        return array
    if not target.is_fully_addressable and getattr(array, "is_fully_addressable", True):
        # Multi-controller staging: device_put of a process-local value onto
        # a process-spanning sharding makes jax issue a blocking
        # broadcast_one_to_all (its cross-process equality check), which can
        # deadlock against async collectives already in flight. Assemble the
        # global array from per-device local shards instead — no collective;
        # the value-replicated-across-processes contract is documented at
        # the factories/chunked-reader host boundary.
        if not target.addressable_devices:
            # A mesh this process owns no slice of cannot hold data placed
            # BY this process (jax dies with an opaque IndexError deep in
            # make_array_from_callback — and only on the device-less ranks,
            # so the group crashes divergently). Name the real mistake:
            # sub-meshes must be drawn round-robin across processes, not as
            # a jax.devices()[:k] prefix (tests/_mh_helpers.submesh).
            raise ValueError(
                f"sharding mesh owns no devices addressable by process "
                f"{jax.process_index()}; every participating process must "
                f"hold at least one mesh device — build sub-meshes spanning "
                f"all processes (e.g. an equal share of each process's "
                f"local devices), not as a global device-list prefix"
            )
        host = np.asarray(array)
        return jax.make_array_from_callback(
            # np.array: own the shard memory (callback results may be aliased
            # zero-copy) without promoting 0-d shards the way
            # ascontiguousarray would
            host.shape, target, lambda idx: np.array(host[idx], copy=True)
        )
    return jax.device_put(array, target)
