"""Version information (reference ``heat/core/version.py``)."""
major: int = 1
minor: int = 1
micro: int = 1
extension: str = "tpu"

__version__ = f"{major}.{minor}.{micro}-{extension}"
