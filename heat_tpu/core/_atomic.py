"""Atomic file writes — THE helper every durable writer routes through.

``core.io`` (HDF5 / netCDF / CSV saves) and the resilience checkpointer
share this one implementation: write to ``<path>.tmp-<suffix>`` in the
same directory, then ``os.replace`` onto the destination. A crash, raised
injected fault, or torn write mid-stream can leave at most a stale temp
file — the previously-committed destination is never corrupted.

Fault-injection sites (:mod:`heat_tpu.core._hooks`):

- ``io.open``   — before the temp file is created (simulated open failure)
- ``io.write``  — after payload bytes are staged, before commit; the
  injector may truncate/corrupt the mutable payload (torn write)
- ``io.commit`` — immediately before the ``os.replace`` rename
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional, Union

from . import _hooks

__all__ = ["atomic_write", "atomic_write_bytes", "tmp_path_for"]


def tmp_path_for(path: Union[str, os.PathLike], suffix: Optional[str] = None) -> str:
    """Temp-file name next to ``path``: ``<path>.tmp-<pid>`` by default.

    ``suffix`` overrides the pid — rank-serialized multi-host writers must
    pass a deterministic suffix so all processes stage into the SAME file.
    """
    path = os.fspath(path)
    return f"{path}.tmp-{os.getpid() if suffix is None else suffix}"


@contextlib.contextmanager
def atomic_write(path: Union[str, os.PathLike], suffix: Optional[str] = None) -> Iterator[str]:
    """Context manager yielding a temp path that is renamed onto ``path``
    only if the block completes; on any failure the temp file is removed
    and ``path`` is untouched.

    >>> with atomic_write("out.h5") as tmp:
    ...     write_everything_to(tmp)
    # out.h5 now exists (old contents replaced atomically), or the
    # exception propagated and out.h5 still holds its old contents.
    """
    path = os.fspath(path)
    _hooks.fault_point("io.open", path=path)
    tmp = tmp_path_for(path, suffix)
    try:
        yield tmp
        _hooks.fault_point("io.commit", path=path, tmp_path=tmp)
        os.replace(tmp, path)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def atomic_write_bytes(path: Union[str, os.PathLike], payload: bytes, suffix: Optional[str] = None) -> None:
    """Atomically write ``payload`` to ``path``.

    The payload passes through the ``io.write`` fault point as a mutable
    ``bytearray`` — an injected torn write truncates or flips bytes there,
    producing exactly the partial/corrupt file a real crash would, while
    the rename discipline still protects any previously-committed file.
    """
    with atomic_write(path, suffix=suffix) as tmp:
        buf = bytearray(payload)
        ctx = _hooks.fault_point("io.write", path=path, payload=buf)
        buf = ctx.get("payload", buf)
        with open(tmp, "wb") as f:
            f.write(bytes(buf))
            f.flush()
            os.fsync(f.fileno())
