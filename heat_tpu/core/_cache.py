"""Bounded LRU cache for compiled executables.

The movement/kernel modules key jitted programs by (shape, dtype, mesh,
schedule). A plain module dict never evicts, so shape-polymorphic
workloads (e.g. a training loop over variable-length batches) grow the
caches without bound and pin compiled executables plus their Mesh
objects (round-3 ADVICE). This LRU keeps the hot executables — re-jitting
an evicted shape only costs a retrace, and XLA's own persistent
compilation cache still dedupes the compile."""
from __future__ import annotations

import threading
from collections import OrderedDict

from . import _hooks

__all__ = ["ExecutableCache"]


class ExecutableCache(OrderedDict):
    """OrderedDict with LRU eviction; drop-in for the module-level dicts.

    Thread-safe for the lookup/insert/evict cycle: the serving layer
    (:mod:`heat_tpu.serve`) drives PROGRAM_CACHE/META_CACHE from a
    dispatcher thread while client threads capture concurrently, and an
    unguarded ``move_to_end`` racing an eviction corrupts the
    OrderedDict's internal linked list. One re-entrant lock per cache
    covers every mutating path (``observe`` fires inside it, which is
    fine — observers only count)."""

    def __init__(self, maxsize: int = 256):
        super().__init__()
        self.maxsize = int(maxsize)
        self._lock = threading.RLock()

    def get(self, key, default=None):
        with self._lock:
            try:
                value = super().__getitem__(key)
            except KeyError:
                return default
            self._touch(key)
            return value

    def __getitem__(self, key):
        with self._lock:
            value = super().__getitem__(key)
            self._touch(key)
            return value

    def __setitem__(self, key, value):
        with self._lock:
            is_new = key not in self
            super().__setitem__(key, value)
            self.move_to_end(key)
            # evict oldest-first WITHOUT OrderedDict.popitem: on CPython
            # 3.10 popitem() re-enters the overridden __getitem__ after
            # unlinking the node, so the LRU touch raised KeyError and
            # corrupted the cache the first time it ever filled up
            while len(self) > self.maxsize:
                del self[next(iter(self))]
            if is_new:
                # a new key means a program was (or is about to be) traced
                # for it — the sanitizer counts these to catch key-design
                # bugs where repeated logical work never hits
                _hooks.observe("cache.insert", size=len(self))

    def pop(self, key, *default):
        with self._lock:
            return super().pop(key, *default)

    def clear(self):
        with self._lock:
            super().clear()

    def _touch(self, key) -> None:
        # inherited methods (pop, popitem, ...) may call __getitem__ for a
        # key they have already unlinked — a failed recency touch must not
        # turn a successful lookup into a KeyError
        try:
            self.move_to_end(key)
        except KeyError:
            pass
