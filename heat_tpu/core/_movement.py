"""Jitted padded pipelines for global data movement.

The reference hand-writes bounded-memory communication for every global
data movement: an Alltoallv reshuffle for ``reshape``
(``/root/reference/heat/core/manipulations.py:1821``), a split-case
analysis with redistribution for ``concatenate``
(``manipulations.py:188``), a ring for ``outer``
(``/root/reference/heat/core/linalg/basics.py:1372``).

The TPU-native equivalent is NOT a hand-scheduled kernel: XLA's SPMD
partitioner already compiles sharded reshape/concatenate into
collective-permute / all-to-all programs with O(n/P) per-device memory —
*when it is given the whole movement as one program with explicit input
and output shardings*. Running the ops eagerly on logical views (round-2
state) compiled each step separately with compiler-chosen intermediate
placements that nothing asserted.

This module therefore runs each movement op as ONE jitted program:

    physical padded buffer(s) -> unpad -> jnp op -> repad -> physical buffer

with ``in_shardings``/``out_shardings`` pinned to the canonical padded
layout on both ends. ``tests/test_distribution_proofs.py`` compiles these
pipelines on an 8-device mesh at representative sizes and asserts the
emitted HLO stays bounded (no all-gather at scale, max per-device buffer
<= c * n/P) — the dsort-style proof the round-2 verdict asked for. The
``*_executable`` functions expose the underlying jit wrappers so the
proof tests lower EXACTLY the program production calls run.

Where GSPMD does NOT stay bounded (top-k along the split axis all-gathers
the full operand), a hand-written shard_map kernel exists instead:
:mod:`heat_tpu.parallel.dtopk`.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ._cache import ExecutableCache

__all__ = [
    "reshape_padded",
    "concatenate_padded",
    "outer_padded",
    "convolve_padded",
    "unfold_padded",
    "roll_padded",
    "flip_padded",
    "pad_padded",
    "diff_padded",
]

# compiled-executable cache: jax.jit wrappers must be reused across calls
# (a fresh jit() closure per call would re-trace every time)
_EXEC_CACHE = ExecutableCache()  # bounded LRU (round-3 ADVICE)


def _cached(key, build):
    fn = _EXEC_CACHE.get(key)
    if fn is None:
        fn = _EXEC_CACHE[key] = build()
    return fn


def _unpad(a: jax.Array, gshape: Tuple[int, ...]) -> jax.Array:
    if tuple(a.shape) == tuple(gshape):
        return a
    return a[tuple(slice(0, s) for s in gshape)]


def _repad(a: jax.Array, pshape: Tuple[int, ...]) -> jax.Array:
    if tuple(a.shape) == tuple(pshape):
        return a
    return jnp.pad(a, [(0, p - s) for p, s in zip(pshape, a.shape)])


def _out_pshape(comm, shape: Tuple[int, ...], split: Optional[int]) -> Tuple[int, ...]:
    return comm.padded_shape(shape, split) if split is not None else tuple(shape)


def pad_to_divisible(x: jax.Array, p: int, dims, comm, split_dim: int = 0) -> jax.Array:
    """Tail-pad the given dims of ``x`` to multiples of ``p`` with zeros
    and place the result on the canonical ``split_dim`` sharding — the
    shared entry half of the pad-and-trim contract (ring/Ulysses/halo)."""
    pads = [(0, (-s) % p if d in dims else 0) for d, s in enumerate(x.shape)]
    if not any(hi for _, hi in pads):
        return x
    xp = jnp.pad(x, pads)
    return jax.device_put(xp, comm.array_sharding(tuple(xp.shape), split_dim))


def reshape_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    out_shape: Tuple[int, ...],
    new_split: Optional[int],
    comm,
):
    """The cached jit wrapper for one reshape pipeline; `.lower()`-able."""
    out_shape = tuple(int(s) for s in out_shape)
    pshape = _out_pshape(comm, out_shape, new_split)
    key = (
        "reshape",
        tuple(buf_shape),
        str(dtype),
        tuple(gshape),
        split,
        out_shape,
        new_split,
        comm.mesh,
    )

    def build():
        in_sh = comm.array_sharding(tuple(buf_shape), split)
        out_sh = comm.array_sharding(pshape, new_split)

        def pipeline(a):
            return _repad(jnp.reshape(_unpad(a, gshape), out_shape), pshape)

        return jax.jit(pipeline, in_shardings=in_sh, out_shardings=out_sh)

    return _cached(key, build)


# below this size a gather is cheaper than a permute schedule and XLA is
# right to choose it; above it the bounded path must win
_KERNEL_CUTOFF_BYTES = 1 << 20


def reshape_plan(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    out_shape: Tuple[int, ...],
    new_split: Optional[int],
    comm,
):
    """Decide how production runs this reshape. Returns ``(mode, fn)``:

    - ``("gspmd", jit)`` — GSPMD's lowering is bounded (or the array is
      small enough that its gather is the right cost call);
    - ``("kernel", jit)`` — GSPMD gathers at scale on a split-0 -> split-0
      move; the flatmove interval-exchange kernel runs instead;
    - ``("via0", None)`` — GSPMD gathers on a non-0 split; production
      re-splits to 0 (a runtime device_put, point-to-point), runs the
      kernel, and re-splits to the target.

    Decided once per configuration by inspecting the compiled HLO; cached.
    """
    import numpy as _np

    out_shape = tuple(int(s) for s in out_shape)
    fn = reshape_executable(
        tuple(buf_shape), dtype, tuple(gshape), split, out_shape, new_split, comm
    )
    nbytes = int(_np.prod(buf_shape, dtype=_np.int64)) * _np.dtype(dtype).itemsize
    if (
        split is not None
        and new_split is not None
        and comm.size > 1
        and nbytes >= _KERNEL_CUTOFF_BYTES
    ):
        dkey = (
            "reshape_gathers",
            tuple(buf_shape),
            str(dtype),
            tuple(gshape),
            split,
            out_shape,
            new_split,
            comm.mesh,
        )
        gathers = _EXEC_CACHE.get(dkey)
        if gathers is None:
            spec = jax.ShapeDtypeStruct(tuple(buf_shape), dtype)
            gathers = "all-gather" in fn.lower(spec).compile().as_text()
            _EXEC_CACHE[dkey] = gathers
        if gathers:
            if split == 0 and new_split == 0:
                from ..parallel.flatmove import reshape_flatmove_executable

                return "kernel", reshape_flatmove_executable(
                    tuple(buf_shape), dtype, tuple(gshape), out_shape, comm
                )
            return "via0", None
    return "gspmd", fn


def planned_reshape_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    out_shape: Tuple[int, ...],
    new_split: Optional[int],
    comm,
):
    """The single-program executable production runs for this
    configuration (the proof tests lower exactly this); None when the
    plan is the composite ``via0`` route."""
    return reshape_plan(buf_shape, dtype, gshape, split, out_shape, new_split, comm)[1]


def _resplit_buffer(
    buf: jax.Array,
    gshape: Tuple[int, ...],
    s_from: Optional[int],
    s_to: Optional[int],
    comm,
) -> jax.Array:
    """Move a padded buffer between canonical split layouts with one
    runtime device_put (point-to-point shard copies, no compiled gather)."""
    if s_from == s_to:
        return buf
    logical = _unpad(buf, gshape)
    pshape = _out_pshape(comm, tuple(gshape), s_to)
    return jax.device_put(
        _repad(logical, pshape), comm.array_sharding(pshape, s_to)
    )


def reshape_padded(
    buf: jax.Array,
    gshape: Tuple[int, ...],
    split: Optional[int],
    out_shape: Tuple[int, ...],
    new_split: Optional[int],
    comm,
) -> jax.Array:
    """Reshape as one sharded program; returns the padded physical buffer
    for ``(out_shape, new_split)``. Replaces the reference's Alltoallv
    reshuffle (``manipulations.py:1821``): GSPMD's collective-permute
    lowering where its reshape partitioner stays bounded, the
    interval-exchange kernel (:mod:`heat_tpu.parallel.flatmove`) where the
    compiled HLO shows it gathering — decided once per shape by
    inspecting the compiled program, proven in
    ``tests/test_distribution_proofs.py``."""
    out_shape = tuple(int(s) for s in out_shape)
    mode, fn = reshape_plan(
        tuple(buf.shape), buf.dtype, tuple(gshape), split, out_shape, new_split, comm
    )
    if mode == "via0":
        from ..parallel.flatmove import reshape_via_flatmove

        buf0 = _resplit_buffer(buf, gshape, split, 0, comm)
        mid = reshape_via_flatmove(buf0, tuple(gshape), out_shape, comm)
        return _resplit_buffer(mid, out_shape, 0, new_split, comm)
    return fn(buf)


def concatenate_executable(
    buf_shapes: Sequence[Tuple[int, ...]],
    dtypes: Sequence,
    gshapes: Sequence[Tuple[int, ...]],
    splits: Sequence[Optional[int]],
    axis: int,
    out_shape: Tuple[int, ...],
    out_split: Optional[int],
    jt,
    comm,
):
    out_shape = tuple(int(s) for s in out_shape)
    pshape = _out_pshape(comm, out_shape, out_split)
    gshapes = tuple(tuple(g) for g in gshapes)
    key = (
        "concat",
        tuple(tuple(b) for b in buf_shapes),
        tuple(str(d) for d in dtypes),
        str(jnp.dtype(jt)),
        gshapes,
        tuple(splits),
        axis,
        out_split,
        comm.mesh,
    )

    def build():
        in_shs = tuple(
            comm.array_sharding(tuple(b), s) for b, s in zip(buf_shapes, splits)
        )
        out_sh = comm.array_sharding(pshape, out_split)

        def pipeline(*arrs):
            parts = [_unpad(a, g).astype(jt) for a, g in zip(arrs, gshapes)]
            return _repad(jnp.concatenate(parts, axis=axis), pshape)

        return jax.jit(pipeline, in_shardings=in_shs, out_shardings=out_sh)

    return _cached(key, build)


def concatenate_padded(
    bufs: Sequence[jax.Array],
    gshapes: Sequence[Tuple[int, ...]],
    splits: Sequence[Optional[int]],
    axis: int,
    out_shape: Tuple[int, ...],
    out_split: Optional[int],
    jt,
    comm,
) -> jax.Array:
    """Concatenate as one sharded program over the physical buffers; the
    per-input tail padding is sliced off and the result repadded inside
    the same jit, so GSPMD emits the all-to-all exchange directly
    (reference: the split-case analysis at ``manipulations.py:188``)."""
    return concatenate_executable(
        [tuple(b.shape) for b in bufs],
        [b.dtype for b in bufs],
        gshapes,
        splits,
        axis,
        out_shape,
        out_split,
        jt,
        comm,
    )(*bufs)


def unfold_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    axis: int,
    size: int,
    step: int,
    comm,
):
    n = int(gshape[axis])
    n_win = (n - size) // step + 1
    out_shape = tuple(gshape[:axis]) + (n_win,) + tuple(gshape[axis + 1 :]) + (size,)
    pshape = _out_pshape(comm, out_shape, split)
    key = (
        "unfold",
        tuple(buf_shape),
        str(dtype),
        tuple(gshape),
        split,
        axis,
        size,
        step,
        comm.mesh,
    )

    def build():
        from jax import lax

        in_sh = comm.array_sharding(tuple(buf_shape), split)
        out_sh = comm.array_sharding(pshape, split)

        def pipeline(a):
            v = _unpad(a, gshape)
            # size STATIC strided slices (window offset j over all window
            # starts) — GSPMD partitions these with collective-permutes
            # only; the vmap-of-dynamic-slice form all-gathers the operand
            cols = [
                lax.slice_in_dim(
                    v, j, j + (n_win - 1) * step + 1, stride=step, axis=axis
                )
                for j in range(size)
            ]
            return _repad(jnp.stack(cols, axis=-1), pshape)

        return jax.jit(pipeline, in_shardings=in_sh, out_shardings=out_sh)

    return _cached(key, build), out_shape


def unfold_padded(
    buf: jax.Array,
    gshape: Tuple[int, ...],
    split: Optional[int],
    axis: int,
    size: int,
    step: int,
    comm,
) -> Tuple[jax.Array, Tuple[int, ...]]:
    """Sliding windows (torch unfold semantics: window dim appended last)
    as one sharded program of static strided slices — O(n/P) per device,
    proven in ``tests/test_distribution_proofs.py``."""
    fn, out_shape = unfold_executable(
        tuple(buf.shape), buf.dtype, tuple(gshape), split, axis, size, step, comm
    )
    return fn(buf), out_shape


def setitem_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    split: Optional[int],
    key_struct: Tuple[Tuple, ...],
    value_shape: Tuple[int, ...],
    value_dtype,
    comm,
):
    """Donated in-place scatter for basic-index ``__setitem__``.

    The reference writes into the rank-local torch shard in place
    (``dndarray.py:1359``) — O(touched elements) per call. The eager
    ``at[].set`` + re-place path copied the whole buffer per call
    (O(n·updates) for a loop of setitems). Here the update runs as ONE
    cached jitted program with the buffer donated and both shardings
    pinned: XLA updates in place, so a loop of scalar setitems costs
    O(updates). Integer indices are traced operands — every scalar-row
    update of the same structure reuses one executable.

    ``key_struct`` elements: ``('i',)`` an integer index passed as an
    operand; ``('s', start, stop, step)`` a static slice.
    """
    key = (
        "setitem", tuple(buf_shape), str(dtype), split, key_struct,
        tuple(value_shape), str(value_dtype), comm.mesh,
    )

    def build():
        sh = comm.array_sharding(tuple(buf_shape), split)
        n_ints = sum(1 for t in key_struct if t[0] == "i")
        jt = jnp.dtype(dtype)

        def pipeline(b, v, *ints):
            it = iter(ints)
            k = tuple(
                next(it) if t[0] == "i" else slice(t[1], t[2], t[3])
                for t in key_struct
            )
            return b.at[k].set(jnp.asarray(v, dtype=jt))

        return jax.jit(
            pipeline,
            donate_argnums=0,
            in_shardings=(sh,) + (None,) * (1 + n_ints),
            out_shardings=sh,
        )

    return _cached(key, build)


def getitem_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    split: Optional[int],
    key_struct: Tuple[Tuple, ...],
    out_gshape: Tuple[int, ...],
    out_split: Optional[int],
    comm,
):
    """Basic-index ``__getitem__`` as one pinned pipeline: input on the
    canonical padded layout, output repadded onto ITS canonical layout.
    The reference's rank-local case analysis (``dndarray.py:652-908``)
    becomes one cached program per key structure; integer indices are
    traced operands (every row fetch shares one executable). A basic
    slice of a split array stays collective-permute/slice only — proven
    in ``tests/test_indexing_proofs.py``.

    ``key_struct`` tags: ``('i',)`` dynamic int on an unsplit dim (local
    gather); ``('I',)`` dynamic int ON the split dim — lowered as a
    one-hot contraction so GSPMD reduces locally and all-reduces O(row)
    instead of gathering the operand (the reference's owner-Bcast,
    ``dndarray.py:789``); ``('s', start, stop, step)`` static slice;
    ``('n',)`` newaxis."""
    out_pshape = _out_pshape(comm, out_gshape, out_split)
    key = (
        "getitem", tuple(buf_shape), str(dtype), split, key_struct,
        tuple(out_gshape), out_split, comm.mesh,
    )

    def build():
        in_sh = comm.array_sharding(tuple(buf_shape), split)
        n_ints = sum(1 for t in key_struct if t[0] in ("i", "I"))
        out_sh = comm.array_sharding(out_pshape, out_split)
        # output axis at which a split-dim dynamic int lands: dims
        # emitted by entries before it ('s'/'n' emit one, 'i' none)
        split_axis_pos = 0
        for t in key_struct:
            if t[0] == "I":
                break
            if t[0] in ("s", "n"):
                split_axis_pos += 1

        def pipeline(b, *ints):
            it = iter(ints)
            k = []
            dyn_split = None
            for t in key_struct:
                if t[0] == "i":
                    k.append(next(it))
                elif t[0] == "I":
                    dyn_split = next(it)
                    k.append(slice(None))
                elif t[0] == "s":
                    k.append(slice(t[1], t[2], t[3]))
                else:
                    k.append(None)
            r = b[tuple(k)]
            if dyn_split is not None:
                extent = r.shape[split_axis_pos]
                shape = [1] * r.ndim
                shape[split_axis_pos] = extent
                mask = (jnp.arange(extent) == dyn_split).reshape(shape)
                # select-then-sum, NOT multiply: r * mask would turn
                # inf/nan rows elsewhere in the array into nan (inf*0)
                zero = jnp.zeros((), r.dtype)
                r = jnp.where(mask, r, zero).sum(axis=split_axis_pos).astype(r.dtype)
            return _repad(r, out_pshape)

        return jax.jit(
            pipeline,
            in_shardings=(in_sh,) + (None,) * n_ints,
            out_shardings=out_sh,
        )

    return _cached(key, build)


def outer_executable(
    a_shape: Tuple[int, ...],
    a_dtype,
    a_gshape: Tuple[int, ...],
    a_split: Optional[int],
    b_shape: Tuple[int, ...],
    b_dtype,
    b_gshape: Tuple[int, ...],
    b_split: Optional[int],
    out_split: Optional[int],
    comm,
):
    n = 1
    for s in a_gshape:
        n *= int(s)
    m = 1
    for s in b_gshape:
        m *= int(s)
    out_shape = (n, m)
    pshape = _out_pshape(comm, out_shape, out_split)
    key = (
        "outer",
        tuple(a_shape),
        str(a_dtype),
        tuple(a_gshape),
        a_split,
        tuple(b_shape),
        str(b_dtype),
        tuple(b_gshape),
        b_split,
        out_split,
        comm.mesh,
    )

    def build():
        in_shs = (
            comm.array_sharding(tuple(a_shape), a_split),
            comm.array_sharding(tuple(b_shape), b_split),
        )
        out_sh = comm.array_sharding(pshape, out_split)

        def pipeline(x, y):
            return _repad(jnp.outer(_unpad(x, a_gshape), _unpad(y, b_gshape)), pshape)

        return jax.jit(pipeline, in_shardings=in_shs, out_shardings=out_sh)

    return _cached(key, build), out_shape


def convolve_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    v_len: int,
    v_dtype,
    mode: str,
    jt,
    comm,
):
    n = int(gshape[0])
    out_len = {"full": n + v_len - 1, "same": n, "valid": n - v_len + 1}[mode]
    out_shape = (out_len,)
    pshape = _out_pshape(comm, out_shape, split)
    key = (
        "convolve",
        tuple(buf_shape),
        str(dtype),
        tuple(gshape),
        split,
        v_len,
        str(v_dtype),
        mode,
        str(jnp.dtype(jt)),
        comm.mesh,
    )

    def build():
        in_shs = (
            comm.array_sharding(tuple(buf_shape), split),
            comm.array_sharding((v_len,), None),
        )
        out_sh = comm.array_sharding(pshape, split)

        def pipeline(a, v):
            r = jnp.convolve(_unpad(a, gshape).astype(jt), v.astype(jt), mode=mode)
            return _repad(r, pshape)

        return jax.jit(pipeline, in_shardings=in_shs, out_shardings=out_sh)

    return _cached(key, build), out_shape


def convolve_padded(
    buf: jax.Array,
    gshape: Tuple[int, ...],
    split: Optional[int],
    v: jax.Array,
    mode: str,
    jt,
    comm,
) -> Tuple[jax.Array, Tuple[int, ...]]:
    """1-D convolution as one sharded program: with the output sharding
    pinned, GSPMD emits the neighbor halo exchange (collective-permutes,
    O(n/P) per device — the reference's explicit ``get_halo`` stencil,
    ``signal.py:16-148``); the eager logical-view route left the
    intermediate placement to chance. Proven in
    ``tests/test_distribution_proofs.py``."""
    fn, out_shape = convolve_executable(
        tuple(buf.shape), buf.dtype, tuple(gshape), split, int(v.shape[0]),
        v.dtype, mode, jt, comm,
    )
    return fn(buf, v), out_shape


def roll_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    shift,
    axis,
    comm,
):
    """Circular shift as one pinned pipeline. The reference sends each
    rank's displaced block to its new owner (``manipulations.py:1989``);
    with both shardings pinned GSPMD emits the equivalent
    collective-permute schedule (proven in the proof suite)."""
    key = ("roll", tuple(buf_shape), str(dtype), tuple(gshape), split, shift, axis, comm.mesh)

    def build():
        sh = comm.array_sharding(tuple(buf_shape), split)

        def pipeline(a):
            return _repad(jnp.roll(_unpad(a, gshape), shift, axis=axis), tuple(buf_shape))

        return jax.jit(pipeline, in_shardings=sh, out_shardings=sh)

    return _cached(key, build)


def roll_padded(buf, gshape, split, shift, axis, comm):
    return roll_executable(tuple(buf.shape), buf.dtype, tuple(gshape), split, shift, axis, comm)(buf)


def flip_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    axis,
    comm,
):
    """Axis reversal as one pinned pipeline: a split-axis flip reverses
    the block partition — a pure collective-permute under GSPMD."""
    key = ("flip", tuple(buf_shape), str(dtype), tuple(gshape), split, axis, comm.mesh)

    def build():
        sh = comm.array_sharding(tuple(buf_shape), split)

        def pipeline(a):
            return _repad(jnp.flip(_unpad(a, gshape), axis=axis), tuple(buf_shape))

        return jax.jit(pipeline, in_shardings=sh, out_shardings=sh)

    return _cached(key, build)


def flip_padded(buf, gshape, split, axis, comm):
    return flip_executable(tuple(buf.shape), buf.dtype, tuple(gshape), split, axis, comm)(buf)


def pad_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    pad_width: Tuple[Tuple[int, int], ...],
    mode: str,
    constant_values,
    comm,
):
    """``jnp.pad`` as one pinned pipeline. Padding at the *front* of the
    split axis shifts every element's owner (the reference redistributes,
    ``manipulations.py:1128``); pinned shardings make GSPMD emit the
    bounded permute schedule. Returns ``(fn, out_shape)``."""
    out_shape = tuple(int(s) + lo + hi for s, (lo, hi) in zip(gshape, pad_width))
    pshape = _out_pshape(comm, out_shape, split)
    key = (
        "pad", tuple(buf_shape), str(dtype), tuple(gshape), split,
        tuple(pad_width), mode, constant_values, comm.mesh,
    )

    def build():
        in_sh = comm.array_sharding(tuple(buf_shape), split)
        out_sh = comm.array_sharding(pshape, split)

        def pipeline(a):
            x = _unpad(a, gshape)
            if mode == "constant":
                r = jnp.pad(x, pad_width, mode=mode, constant_values=constant_values)
            else:
                r = jnp.pad(x, pad_width, mode=mode)
            return _repad(r, pshape)

        return jax.jit(pipeline, in_shardings=in_sh, out_shardings=out_sh)

    return _cached(key, build), out_shape


def pad_padded(buf, gshape, split, pad_width, mode, constant_values, comm):
    fn, out_shape = pad_executable(
        tuple(buf.shape), buf.dtype, tuple(gshape), split,
        tuple(tuple(int(v) for v in p) for p in pad_width), mode, constant_values, comm,
    )
    return fn(buf), out_shape


def diff_executable(
    buf_shape: Tuple[int, ...],
    dtype,
    gshape: Tuple[int, ...],
    split: Optional[int],
    n: int,
    axis: int,
    pre_shape: Optional[Tuple[int, ...]],
    app_shape: Optional[Tuple[int, ...]],
    comm,
):
    """n-th discrete difference as one pinned pipeline — the split-axis
    neighbor halo the reference hand-sends (``arithmetics.py:293``)
    becomes one collective-permute per order. Returns ``(fn, out_shape)``.
    ``prepend``/``append`` ride along as replicated operands."""
    ext = int(gshape[axis])
    if pre_shape is not None:
        ext += int(pre_shape[axis])
    if app_shape is not None:
        ext += int(app_shape[axis])
    out_shape = tuple(
        (ext - n) if i == axis else int(s) for i, s in enumerate(gshape)
    )
    pshape = _out_pshape(comm, out_shape, split)
    key = (
        "diff", tuple(buf_shape), str(dtype), tuple(gshape), split, n, axis,
        pre_shape, app_shape, comm.mesh,
    )

    def build():
        in_shs = [comm.array_sharding(tuple(buf_shape), split)]
        if pre_shape is not None:
            in_shs.append(comm.array_sharding(tuple(pre_shape), None))
        if app_shape is not None:
            in_shs.append(comm.array_sharding(tuple(app_shape), None))
        out_sh = comm.array_sharding(pshape, split)

        def pipeline(a, *edges):
            it = iter(edges)
            pre = next(it) if pre_shape is not None else None
            app = next(it) if app_shape is not None else None
            r = jnp.diff(_unpad(a, gshape), n=n, axis=axis, prepend=pre, append=app)
            return _repad(r, pshape)

        return jax.jit(pipeline, in_shardings=tuple(in_shs), out_shardings=out_sh)

    return _cached(key, build), out_shape


def diff_padded(buf, gshape, split, n, axis, pre, app, comm):
    fn, out_shape = diff_executable(
        tuple(buf.shape), buf.dtype, tuple(gshape), split, n, axis,
        None if pre is None else tuple(pre.shape),
        None if app is None else tuple(app.shape),
        comm,
    )
    args = [buf] + [e for e in (pre, app) if e is not None]
    return fn(*args), out_shape


def outer_padded(
    a: jax.Array,
    a_gshape: Tuple[int, ...],
    a_split: Optional[int],
    b: jax.Array,
    b_gshape: Tuple[int, ...],
    b_split: Optional[int],
    out_split: Optional[int],
    comm,
) -> Tuple[jax.Array, Tuple[int, int]]:
    """Outer product as one sharded program (reference ring:
    ``linalg/basics.py:1372``). With the output row-split, GSPMD gathers
    only the *second operand* (O(m) per device) and each device writes its
    own O(nm/P) output shard — asserted bounded in
    ``tests/test_distribution_proofs.py``. Returns (buffer, out_shape)."""
    fn, out_shape = outer_executable(
        tuple(a.shape), a.dtype, a_gshape, a_split,
        tuple(b.shape), b.dtype, b_gshape, b_split,
        out_split, comm,
    )
    return fn(a, b), out_shape
