# graftlint: hot-path
"""Distributed dense factorizations (reference ``heat/core/linalg``'s
pivoted elimination loops, ``basics.py:160-420``).

Blocked right-looking **Cholesky** and **LU with partial pivoting** over
row-split operands, plus a distributed **triangular solve**, all running
as single ``shard_map`` programs per call — local XLA compute and
explicit ``jax.lax`` collectives, never a full-operand gather:

- the block geometry comes from :func:`heat_tpu.core.tiling.factor_block_edge`
  (the ``SquareDiagTiles`` row decomposition snapped to a divisor of the
  per-device row count, so a panel never straddles a device boundary);
- panel/diagonal blocks travel as **masked psum broadcasts**: the owning
  device contributes its ``(bs, ·)`` slab, everyone else zeros, one psum
  replicates it — O(bs·n) per step, not O(n²);
- LU pivots are chosen **tournament-style**: each device reduces its own
  candidate column to a ``(max, row)`` pair, one ``all_gather`` of ``p``
  pairs replicates the argmax decision — O(p) bytes per column;
- the Cholesky trailing update all-gathers only the current ``(n_pad, bs)``
  panel; the LU trailing update needs no gather at all (each device owns
  its multiplier rows);
- ``solve``/``inv`` ride the right-hand side through the same elimination
  as augmented columns (forward substitution is implicit), then a blocked
  back substitution walks the panels in reverse inside the same program.

Row counts that don't divide the mesh are zero-row padded and the padded
square is identity-extended (``[[A, 0], [0, I]]``), so the padded system
stays nonsingular and the logical solution/determinant is unchanged.

Every jitted block program lives in a bounded :class:`ExecutableCache`
keyed on hashable statics ``(kind, mesh, p, mi, n, bs, ...)`` — one
compile per geometry, re-used across calls (counter-asserted in
``tests/test_factorizations.py`` via ``COMPILE_STATS``).

Exactly-singular LU pivots zero their multipliers instead of dividing,
so ``det`` of a singular matrix is an exact 0 like numpy's; a non-SPD
``cholesky`` operand yields NaNs like ``jnp.linalg.cholesky`` (numpy
raises instead). ``cholesky`` reads the full operand and assumes it is
Hermitian (numpy reads only the lower triangle).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from .. import types
from .._cache import ExecutableCache
from .._operations import _mask_padding
from ..communication import SPLIT_AXIS
from ..dndarray import DNDarray

__all__ = ["cholesky", "solve", "solve_triangular"]

# one bounded program cache for every factorization kind; keys are pure
# hashable statics, so repeated logical work never re-traces
_FACTOR_CACHE = ExecutableCache()


def _i32(x):
    return jnp.asarray(x, jnp.int32)


def _dslice(M, r, c, nr, nc):
    return jax.lax.dynamic_slice(M, (_i32(r), _i32(c)), (nr, nc))


def _dupdate(M, S, r, c):
    return jax.lax.dynamic_update_slice(M, S, (_i32(r), _i32(c)))



# --------------------------------------------------------------- traced utils
def _identity_extend(block: jnp.ndarray, grow: jnp.ndarray, n: int, n_pad: int):
    """Column-pad the local ``(mi, n)`` block to ``(mi, n_pad)`` and set
    ones on the padding diagonal: the padded operand is ``[[A, 0], [0, I]]``,
    nonsingular whenever ``A`` is, with the logical factors unchanged."""
    blk = jnp.pad(block, ((0, 0), (0, n_pad - n)))
    pad_diag = (grow[:, None] == jnp.arange(n_pad)[None, :]) & (grow[:, None] >= n)
    return jnp.where(pad_diag, jnp.ones((), blk.dtype), blk)


def _bcast_rows(M: jnp.ndarray, pos, nrows: int, i, mi: int):
    """Rows ``[pos, pos+nrows)`` of the row-sharded ``M`` replicated to every
    device via a masked psum; also returns the owner's local offset and the
    per-device ownership predicate (``nrows`` never straddles devices: single
    rows by construction, slabs because the panel width divides ``mi``)."""
    lr = jnp.clip(pos - i * mi, 0, mi - nrows)
    s = _dslice(M, lr, 0, nrows, M.shape[1])
    own = (pos >= i * mi) & (pos + nrows <= (i + 1) * mi)
    slab = jax.lax.psum(jnp.where(own, s, jnp.zeros_like(s)), SPLIT_AXIS)
    return slab, lr, own


def _put_rows(M: jnp.ndarray, pos, rows: jnp.ndarray, i, mi: int, keep_cols=None):
    """Owner-only write of ``rows`` at global row ``pos``; with ``keep_cols``
    the masked columns keep their current values (panel columns are final
    when the recorded pivot swaps are replayed on the rest of the matrix)."""
    nrows = rows.shape[0]
    lr = jnp.clip(pos - i * mi, 0, mi - nrows)
    own = (pos >= i * mi) & (pos + nrows <= (i + 1) * mi)
    cur = _dslice(M, lr, 0, nrows, M.shape[1])
    new = rows if keep_cols is None else jnp.where(keep_cols[None, :], cur, rows)
    return _dupdate(M, jnp.where(own, new, cur), lr, 0)


# ------------------------------------------------------------- LU block kernel
def _build_lu(mesh, p: int, mi: int, n: int, bs: int, mode: str, k: int):
    """The shard_map LU program for one geometry key.

    ``mode``: ``"det"`` (no RHS, returns the replicated determinant),
    ``"solve"`` (``k`` RHS columns ride the elimination), ``"inv"`` (the
    identity is built in-kernel and rides the elimination). The RHS columns
    undergo the same row swaps and rank updates as the operand, so after
    the panel sweep they hold ``L⁻¹ P b`` — forward substitution for free —
    and a reverse panel walk back-substitutes in the same program.
    """
    n_pad = mi * p
    nb = n_pad // bs
    kw = n_pad if mode == "inv" else k
    W = n_pad + kw

    def local_fn(*operands):
        i = jax.lax.axis_index(SPLIT_AXIS)
        grow = i * mi + jnp.arange(mi)  # global row ids of this shard
        cols = jnp.arange(W)
        A = _identity_extend(operands[0], grow, n, n_pad)
        if mode == "solve":
            A = jnp.concatenate([A, operands[1]], axis=1)
        elif mode == "inv":
            eye = (grow[:, None] == jnp.arange(n_pad)[None, :]).astype(A.dtype)
            A = jnp.concatenate([A, eye], axis=1)
        one = jnp.ones((), A.dtype)

        def col_step(j, st):
            Pl, swaps, sign, off = st
            c = off + j  # global pivot position
            colv = _dslice(Pl, 0, j, mi, 1)[:, 0]
            cand = jnp.where(grow >= c, jnp.abs(colv), -jnp.inf)
            gmax, gidx = jax.lax.all_gather(
                (jnp.max(cand), grow[jnp.argmax(cand)]), SPLIT_AXIS
            )
            piv = gidx[jnp.argmax(gmax)]  # tournament winner, replicated
            rc, _, _ = _bcast_rows(Pl, c, 1, i, mi)
            rp, _, _ = _bcast_rows(Pl, piv, 1, i, mi)
            Pl = _put_rows(Pl, c, rp, i, mi)
            Pl = _put_rows(Pl, piv, rc, i, mi)
            sign = sign * jnp.where(piv == c, one, -one)
            swaps = swaps.at[j].set(piv.astype(jnp.int32))
            pivval = rp[0, j]
            colv = _dslice(Pl, 0, j, mi, 1)[:, 0]
            # singular pivot: zero the multipliers so det -> exact 0
            mult = jnp.where(pivval == 0, jnp.zeros_like(colv), colv / jnp.where(pivval == 0, one, pivval))
            below = grow > c
            Pl = _dupdate(Pl, jnp.where(below, mult, colv)[:, None], 0, j)
            # rank-1 update restricted to the remaining panel columns
            urow = jnp.where(jnp.arange(bs) > j, rp[0], jnp.zeros((), A.dtype))
            Pl = Pl - jnp.where(below, mult, 0)[:, None] * urow[None, :]
            return Pl, swaps, sign, off

        def swap_step(j, st):
            A, swaps, off, in_panel = st
            c = off + j
            r2 = swaps[j]
            rowc, _, _ = _bcast_rows(A, c, 1, i, mi)
            rowp, _, _ = _bcast_rows(A, r2, 1, i, mi)
            A = _put_rows(A, c, rowp, i, mi, keep_cols=in_panel)
            A = _put_rows(A, r2, rowc, i, mi, keep_cols=in_panel)
            return A, swaps, off, in_panel

        def panel_step(kb, carry):
            A, sign = carry
            off = kb * bs
            # ---- panel factorization with per-column tournament pivoting
            Pl = _dslice(A, 0, off, mi, bs)
            Pl, swaps, sign, _ = jax.lax.fori_loop(
                0, bs, col_step, (Pl, jnp.zeros((bs,), jnp.int32), sign, off)
            )
            A = _dupdate(A, Pl, 0, off)
            # ---- replay the recorded swaps on the non-panel columns
            in_panel = (cols >= off) & (cols < off + bs)
            A, _, _, _ = jax.lax.fori_loop(0, bs, swap_step, (A, swaps, off, in_panel))
            # ---- U block row: unit-lower solve on the broadcast slab
            slab, lr0, own = _bcast_rows(A, off, bs, i, mi)
            Lkk = _dslice(slab, 0, off, bs, bs)
            solved = jax.lax.linalg.triangular_solve(
                Lkk, slab, left_side=True, lower=True, unit_diagonal=True
            )
            keep = cols < off + bs  # panel and earlier columns are final
            ublk = jnp.where(keep[None, :], slab, solved)
            cur = _dslice(A, lr0, 0, bs, W)
            A = _dupdate(A, jnp.where(own, ublk, cur), lr0, 0)
            # ---- trailing update: each device already owns its L rows
            Lpan = _dslice(A, 0, off, mi, bs)
            Lm = jnp.where((grow >= off + bs)[:, None], Lpan, jnp.zeros((), A.dtype))
            Um = jnp.where(keep[None, :], jnp.zeros((), A.dtype), ublk)
            return A - Lm @ Um, sign

        A, sign = jax.lax.fori_loop(0, nb, panel_step, (A, one))

        if mode == "det":
            d = jnp.take_along_axis(A, grow[:, None], axis=1)[:, 0]
            dg = jax.lax.all_gather(d, SPLIT_AXIS).reshape(n_pad)
            valid = jnp.arange(n_pad) < n
            return sign * jnp.prod(jnp.where(valid, dg, one))

        def back_step(t, A):
            off = (nb - 1 - t) * bs
            slab, lr0, own = _bcast_rows(A, off, bs, i, mi)
            Ukk = _dslice(slab, 0, off, bs, bs)
            xk = jax.lax.linalg.triangular_solve(
                Ukk, slab[:, n_pad:], left_side=True, lower=False
            )
            cur = _dslice(A, lr0, n_pad, bs, kw)
            A = _dupdate(A, jnp.where(own, xk, cur), lr0, n_pad)
            # eliminate this solved block from every row above it
            Ucol = _dslice(A, 0, off, mi, bs)
            upd = jnp.where((grow < off)[:, None], Ucol, jnp.zeros((), A.dtype)) @ xk
            return _dupdate(A, A[:, n_pad:] - upd, 0, n_pad)

        A = jax.lax.fori_loop(0, nb, back_step, A)
        return A[:, n_pad:]

    in_specs = (P(SPLIT_AXIS, None),) * (2 if mode == "solve" else 1)
    out_specs = P() if mode == "det" else P(SPLIT_AXIS, None)
    # det (and the pivot decisions feeding it) is computed redundantly and
    # identically on every device from all-gathered values
    return shard_map(
        local_fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
    )


# -------------------------------------------------------- Cholesky block kernel
def _build_cholesky(mesh, p: int, mi: int, n: int, bs: int):
    """Blocked right-looking Cholesky: masked-psum broadcast of the diagonal
    block, local panel triangular solve, one ``(n_pad, bs)`` panel all-gather
    for the trailing syrk — never the full operand."""
    n_pad = mi * p
    nb = n_pad // bs

    def local_fn(block):
        i = jax.lax.axis_index(SPLIT_AXIS)
        grow = i * mi + jnp.arange(mi)
        cols = jnp.arange(n_pad)
        A = _identity_extend(block, grow, n, n_pad)

        def step(kb, A):
            off = kb * bs
            slab, lr0, own = _bcast_rows(A, off, bs, i, mi)
            Akk = _dslice(slab, 0, off, bs, bs)
            Lkk = jnp.linalg.cholesky(Akk)
            Pcol = _dslice(A, 0, off, mi, bs)
            # rows below the panel solve X @ Lkk^H = P locally
            sol = jax.lax.linalg.triangular_solve(
                Lkk, Pcol, left_side=False, lower=True, transpose_a=True, conjugate_a=True
            )
            below = (grow >= off + bs)[:, None]
            newP = jnp.where(below, sol, Pcol)
            curk = _dslice(newP, lr0, 0, bs, bs)
            newP = _dupdate(newP, jnp.where(own, Lkk, curk), lr0, 0)
            A = _dupdate(A, newP, 0, off)
            # trailing syrk from the replicated panel (bs columns only)
            Wg = jax.lax.all_gather(newP, SPLIT_AXIS).reshape(n_pad, bs)
            Wg = jnp.where((cols >= off + bs)[:, None], Wg, jnp.zeros((), A.dtype))
            Lm = jnp.where(below, newP, jnp.zeros((), A.dtype))
            return A - Lm @ Wg.conj().T

        A = jax.lax.fori_loop(0, nb, step, A)
        # the factorization never wrote the strict upper triangle; zero it
        return jnp.where(grow[:, None] >= cols[None, :], A, jnp.zeros((), A.dtype))

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=P(SPLIT_AXIS, None),
        out_specs=P(SPLIT_AXIS, None),
        check_vma=False,
    )


# ------------------------------------------------- triangular-solve block kernel
def _build_trisolve(mesh, p: int, mi: int, n: int, bs: int, k: int, lower: bool, unit: bool):
    """Blocked forward (lower) / backward (upper) substitution over the same
    panel schedule: one masked-psum slab broadcast and one local GEMM per
    block — O(bs·(n+k)) bytes per step."""
    n_pad = mi * p
    nb = n_pad // bs
    W = n_pad + k

    def local_fn(tblock, bblock):
        i = jax.lax.axis_index(SPLIT_AXIS)
        grow = i * mi + jnp.arange(mi)
        A = jnp.concatenate([_identity_extend(tblock, grow, n, n_pad), bblock], axis=1)

        def step(t, A):
            off = (t if lower else nb - 1 - t) * bs
            slab, lr0, own = _bcast_rows(A, off, bs, i, mi)
            Tkk = _dslice(slab, 0, off, bs, bs)
            xk = jax.lax.linalg.triangular_solve(
                Tkk, slab[:, n_pad:], left_side=True, lower=lower, unit_diagonal=unit
            )
            cur = _dslice(A, lr0, n_pad, bs, k)
            A = _dupdate(A, jnp.where(own, xk, cur), lr0, n_pad)
            rem = (grow >= off + bs) if lower else (grow < off)
            Tcol = _dslice(A, 0, off, mi, bs)
            upd = jnp.where(rem[:, None], Tcol, jnp.zeros((), A.dtype)) @ xk
            return _dupdate(A, A[:, n_pad:] - upd, 0, n_pad)

        A = jax.lax.fori_loop(0, nb, step, A)
        return A[:, n_pad:]

    return shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(P(SPLIT_AXIS, None), P(SPLIT_AXIS, None)),
        out_specs=P(SPLIT_AXIS, None),
        check_vma=False,
    )


# ------------------------------------------------------------------ dispatch
def _compiled(key: Tuple, builder):
    """One jitted program per hashable geometry key (bounded LRU)."""
    fn = _FACTOR_CACHE.get(key)
    if fn is None:
        fn = _FACTOR_CACHE[key] = jax.jit(builder())
    return fn


def _trim_cols(f, n: int):
    """Compose ``f`` with its ``[:, :n]`` column trim so both compile as
    ONE program.  An eager slice of the non-fully-addressable result
    would dispatch an implicit cross-process gather program outside the
    compiled lockstep schedule — nondeterministically racy under async
    dispatch at ws>1 (rank aborts observed in the ws-2 burn-down)."""
    def run(*args):
        return f(*args)[:, :n]

    return run


def _first_col(f):
    """Compose ``f`` with its ``[:, 0]`` vector trim — same single-program
    rationale as :func:`_trim_cols` for the 1-D right-hand-side paths."""
    def run(*args):
        return f(*args)[:, 0]

    return run


def _dist2d(a: DNDarray) -> bool:
    return a.ndim == 2 and a.split is not None and a.comm.is_distributed()


def _geometry(a: DNDarray, tiles_per_proc: int = 1) -> Tuple[int, int, int]:
    """(p, mi, bs) of the row-split operand ``a``."""
    from ..tiling import factor_block_edge

    comm = a.comm
    p = comm.size
    mi = comm.padded_dim(a.gshape[0]) // p
    return p, mi, factor_block_edge(a, tiles_per_proc, mi)


def _prep(a: DNDarray, ftype) -> jnp.ndarray:
    """The split-0 operand's buffer with zeroed tail padding."""
    arr = a.larray.astype(ftype)
    if a.padded:
        arr = _mask_padding(arr, a.gshape, 0, 0)
    return arr


def _rhs_buffer(b: DNDarray, n: int, n_pad: int, ftype) -> jnp.ndarray:
    """The RHS as an ``(n_pad, k)`` buffer aligned with the operand rows.

    A split-0 RHS reuses its sharded buffer in place (zero movement); a
    replicated (or column-split) RHS is row-padded — O(n·k), never O(n²).
    """
    if b.split == 0:
        buf = b.larray.astype(ftype)
        if b.padded:
            buf = _mask_padding(buf, b.gshape, 0, 0)
        return buf if b.ndim == 2 else buf[:, None]
    logical = b._logical().astype(ftype)
    if b.ndim == 1:
        logical = logical[:, None]
    return jnp.pad(logical, ((0, n_pad - n), (0, 0)))


def _square_2d_check(name: str, a) -> None:
    if not isinstance(a, DNDarray):
        raise TypeError(f"{name} expects a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"{name} requires a 2-D array, got {a.ndim}-D")
    if a.gshape[0] != a.gshape[1]:
        raise RuntimeError(f"{name} requires a square matrix, got {a.gshape}")


def _float_type(*arrs):
    t = jnp.float32
    for x in arrs:
        t = jnp.promote_types(x.larray.dtype, t)
    return t


# ------------------------------------------------------------- public surface
def cholesky(a: DNDarray, tiles_per_proc: int = 1) -> DNDarray:
    """Cholesky factor ``L`` (lower) of a Hermitian positive-definite 2-D
    operand.

    Split-0 operands factor distributed: blocked right-looking panels with
    masked-psum diagonal broadcasts and an ``(n, bs)`` panel all-gather per
    step — no full-operand gather. A split-1 operand is Hermitian, so its
    conjugate transpose (zero data movement) factors instead.
    ``tiles_per_proc`` shapes the panel width via the same
    ``SquareDiagTiles`` row decomposition ``qr`` consumes. Non-SPD inputs
    yield NaNs (``jnp`` semantics; numpy raises)."""
    _square_2d_check("cholesky", a)
    with jax.default_matmul_precision("highest"):
        ftype = _float_type(a)
        comm = a.comm
        if not _dist2d(a):
            from ..kernels import dispatch_mode, record_dispatch
            from ..kernels.panel_update import MAX_FUSED_N, cholesky_blocked

            arr = a._logical().astype(ftype)
            mode = dispatch_mode("chol_panel_fused")
            if not (
                mode in ("pallas", "interpret")
                and arr.shape[0] <= MAX_FUSED_N
                and jnp.dtype(ftype) == jnp.float32  # kernel is f32/MXU only
            ):
                mode = "fallback"
            record_dispatch("chol_panel_fused", mode)
            if mode == "fallback":
                L = jnp.linalg.cholesky(arr)
            else:
                # panel-fused kernel: factor + trailing update in one VMEM
                # residency (f32 — its in-kernel solve runs on the MXU)
                L = cholesky_blocked(arr, interpret=(mode != "pallas")).astype(ftype)
            return DNDarray(L, split=a.split, device=a.device, comm=comm)
        m = a
        if a.split != 0:  # A Hermitian: chol(A) = chol(A^H), A^H is split 0
            from .. import complex_math

            m = a.T
            if jnp.issubdtype(ftype, jnp.complexfloating):
                m = complex_math.conj(m)
        n = a.gshape[0]
        p, mi, bs = _geometry(m, tiles_per_proc)
        fn = _compiled(
            ("chol", comm.mesh, p, mi, n, bs, jnp.dtype(ftype).name),
            lambda: _trim_cols(_build_cholesky(comm.mesh, p, mi, n, bs), n),
        )
        buf = fn(_prep(m, ftype))
        return DNDarray._from_buffer(
            buf, (n, n), types.canonical_heat_type(buf.dtype), 0, a.device, comm
        )


def solve(a: DNDarray, b: DNDarray) -> DNDarray:
    """Solution of ``a @ x = b`` for a square 2-D ``a`` (numpy shape rules:
    ``b`` is a vector or a column stack).

    Split operands run the distributed blocked LU with tournament
    pivoting; the RHS rides the elimination as augmented columns and a
    reverse panel walk back-substitutes inside the same shard_map program
    — no full-operand gather. A split-1 ``a`` pays one bounded resplit
    first. The result is row-split like the operand."""
    _square_2d_check("solve", a)
    if not isinstance(b, DNDarray):
        raise TypeError(f"solve expects a DNDarray rhs, got {type(b)}")
    if b.ndim not in (1, 2):
        raise ValueError(f"solve rhs must be 1-D or 2-D, got {b.ndim}-D")
    n = a.gshape[0]
    if b.gshape[0] != n:
        raise ValueError(f"dimension mismatch: a has {n} rows, b has {b.gshape[0]}")
    with jax.default_matmul_precision("highest"):
        ftype = _float_type(a, b)
        comm = a.comm
        if not _dist2d(a):
            x = jnp.linalg.solve(a._logical().astype(ftype), b._logical().astype(ftype))
            return DNDarray(x, split=None, device=a.device, comm=comm)
        A0 = a if a.split == 0 else a.resplit(0)
        p, mi, bs = _geometry(A0)
        k = 1 if b.ndim == 1 else b.gshape[1]
        vec = b.ndim == 1
        fn = _compiled(
            ("lu-solve", comm.mesh, p, mi, n, bs, k, vec, jnp.dtype(ftype).name),
            lambda: (_first_col if vec else (lambda f: f))(
                _build_lu(comm.mesh, p, mi, n, bs, "solve", k)
            ),
        )
        X = fn(_prep(A0, ftype), _rhs_buffer(b, n, mi * p, ftype))
        ht = types.canonical_heat_type(X.dtype)
        if vec:
            return DNDarray._from_buffer(X, (n,), ht, 0, a.device, comm)
        return DNDarray._from_buffer(X, (n, k), ht, 0, a.device, comm)


def solve_triangular(
    a: DNDarray, b: DNDarray, lower: bool = False, unit_diagonal: bool = False
) -> DNDarray:
    """Solution of the triangular system ``a @ x = b`` (scipy signature
    subset).

    Split-0 operands run the distributed blocked forward/back substitution
    — one masked-psum slab broadcast and one local GEMM per panel, no
    full-operand gather; replicated operands solve locally. ``lstsq``'s
    well-conditioned path and the factorization tests route through here."""
    _square_2d_check("solve_triangular", a)
    if not isinstance(b, DNDarray):
        raise TypeError(f"solve_triangular expects a DNDarray rhs, got {type(b)}")
    if b.ndim not in (1, 2):
        raise ValueError(f"rhs must be 1-D or 2-D, got {b.ndim}-D")
    n = a.gshape[0]
    if b.gshape[0] != n:
        raise ValueError(f"dimension mismatch: a has {n} rows, b has {b.gshape[0]}")
    with jax.default_matmul_precision("highest"):
        ftype = _float_type(a, b)
        comm = a.comm
        if not _dist2d(a):
            x = jax.scipy.linalg.solve_triangular(
                a._logical().astype(ftype),
                b._logical().astype(ftype),
                lower=lower,
                unit_diagonal=unit_diagonal,
            )
            return DNDarray(x, split=None, device=a.device, comm=comm)
        A0 = a if a.split == 0 else a.resplit(0)
        p, mi, bs = _geometry(A0)
        k = 1 if b.ndim == 1 else b.gshape[1]
        vec = b.ndim == 1
        fn = _compiled(
            ("trisolve", comm.mesh, p, mi, n, bs, k, vec, bool(lower),
             bool(unit_diagonal), jnp.dtype(ftype).name),
            lambda: (_first_col if vec else (lambda f: f))(
                _build_trisolve(
                    comm.mesh, p, mi, n, bs, k, bool(lower), bool(unit_diagonal)
                )
            ),
        )
        X = fn(_prep(A0, ftype), _rhs_buffer(b, n, mi * p, ftype))
        ht = types.canonical_heat_type(X.dtype)
        if vec:
            return DNDarray._from_buffer(X, (n,), ht, 0, a.device, comm)
        return DNDarray._from_buffer(X, (n, k), ht, 0, a.device, comm)


# --------------------------------------------- det / inv backends (basics.py)
def _det_impl(a: DNDarray) -> DNDarray:
    """Determinant backend: distributed pivoted LU for split 2-D operands
    (``det(A) == det(A^T)`` turns a split-1 operand into split-0 for free),
    per-shard local LU for batch-split stacks, local LU otherwise."""
    ftype = _float_type(a)
    comm = a.comm
    with jax.default_matmul_precision("highest"):
        if _dist2d(a):
            m = a if a.split == 0 else a.T
            n = a.gshape[-1]
            p, mi, bs = _geometry(m)
            fn = _compiled(
                ("lu-det", comm.mesh, p, mi, n, bs, jnp.dtype(ftype).name),
                lambda: _build_lu(comm.mesh, p, mi, n, bs, "det", 0),
            )
            d = fn(_prep(m, ftype))
            return DNDarray(d, split=None, device=a.device, comm=comm)
        batch_split = (
            a.ndim > 2 and a.split is not None and a.split < a.ndim - 2
            and comm.is_distributed()
        )
        if batch_split:
            # each shard LU-factors its own stack; padding dets are garbage
            # padding like any other buffer tail
            res = jnp.linalg.det(a.larray.astype(ftype))
            return DNDarray._from_buffer(
                res, a.gshape[:-2], types.canonical_heat_type(res.dtype),
                a.split, a.device, comm,
            )
        result = jnp.linalg.det(a._logical().astype(ftype))
        split = a.split if (a.ndim > 2 and a.split is not None and a.split < a.ndim - 2) else None
        return DNDarray(result, split=split, device=a.device, comm=comm)


def _inv_impl(a: DNDarray) -> DNDarray:
    """Inverse backend: distributed LU with the identity riding as augmented
    columns (``inv(A) == inv(A^T)^T`` handles split-1 with zero movement),
    per-shard local inverse for batch-split stacks, local otherwise."""
    ftype = _float_type(a)
    comm = a.comm
    with jax.default_matmul_precision("highest"):
        if _dist2d(a):
            m = a if a.split == 0 else a.T
            n = a.gshape[-1]
            p, mi, bs = _geometry(m)
            fn = _compiled(
                ("lu-inv", comm.mesh, p, mi, n, bs, jnp.dtype(ftype).name),
                lambda: _trim_cols(_build_lu(comm.mesh, p, mi, n, bs, "inv", 0), n),
            )
            buf = fn(_prep(m, ftype))
            X = DNDarray._from_buffer(
                buf, (n, n), types.canonical_heat_type(buf.dtype), 0, a.device, comm
            )
            return X if a.split == 0 else X.T
        if (
            a.ndim > 2 and a.split is not None and a.split < a.ndim - 2
            and comm.is_distributed()
        ):
            # singular zero-padding stacks invert to NaN padding — masked by
            # every consumer like any other buffer tail
            res = jnp.linalg.inv(a.larray.astype(ftype))
            return DNDarray._from_buffer(
                res, a.gshape, types.canonical_heat_type(res.dtype),
                a.split, a.device, comm,
            )
        result = jnp.linalg.inv(a._logical().astype(ftype))
        return DNDarray(result, split=a.split, device=a.device, comm=comm)
