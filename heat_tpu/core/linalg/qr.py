"""QR decomposition (reference ``heat/core/linalg/qr.py``, 1042 LoC).

The reference implements tiled CAQR over ``SquareDiagTiles`` with explicit
tile sends (``qr.py:319-866``). The TPU-native algorithm is **TSQR**
(communication-avoiding QR for tall-skinny matrices): one local QR per
shard — CholeskyQR2 (MXU matmuls) for tall floating blocks, Householder
otherwise — an all-gather of the tiny R factors over ICI, one replicated
merge QR, and a local back-multiply, expressed in ~40 lines of
``shard_map``. Row counts that don't divide the mesh are zero-row padded
(QR of [A; 0] has the same R and a zero-row-extended Q).
"""
from __future__ import annotations

import collections
import numbers
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from .. import sanitation, types
from .._cache import ExecutableCache
from .._operations import _mask_padding
from ..communication import SPLIT_AXIS
from ..dndarray import DNDarray

__all__ = ["qr"]

QR_out = collections.namedtuple("QR", "Q, R")

# jitted TSQR shard_map programs keyed on the static geometry — the
# program used to be rebuilt (and retraced) on EVERY qr() call from a
# fresh closure, which the compile sanitizer flagged as the dominant
# dispatch cost of the distributed path
_TSQR_CACHE = ExecutableCache()


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
    method: str = "auto",
) -> QR_out:
    """QR decomposition of a 2-D DNDarray (reference ``qr.py:17``).

    ``tiles_per_proc`` tunes the factorization tree exactly as in the
    reference's CAQR (``qr.py:319-866``): each process's local block is
    factored as ``tiles_per_proc`` square-ish row tiles (geometry from
    :class:`~heat_tpu.core.tiling.SquareDiagTiles`, the same tile map the
    reference's tile loops walk) whose small R factors merge locally
    before the global ICI merge — a two-level TSQR tree. ``1`` (default)
    factors each local block whole, which is optimal when the block fits
    HBM comfortably; larger values bound the peak Householder working set
    per tile. ``overwrite_a`` is accepted for API parity only; XLA owns
    buffer reuse.

    ``method``: ``"auto"`` (default) runs **CholeskyQR2** for tall-skinny
    floating inputs — two Gram-matmul + Cholesky passes, entirely
    MXU-resident, 13-18x the measured Householder rate on a v5e chip — with a
    device-side orthogonality check that falls back to Householder when
    the conditioning defeats it (CholQR2 is O(eps)-orthogonal for
    cond(A) <~ eps^-1/2; the check costs one extra (n, n) Gram).
    ``"householder"`` forces the LAPACK-style path, ``"cholqr2"`` forces
    the fast path (still guarded).
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"expected a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D array, got {a.ndim}-D")
    if method not in ("auto", "householder", "cholqr2"):
        raise ValueError(f"unknown qr method {method!r}")
    # reference contract (`qr.py:79-82`): TypeError for non-integral input
    # (integer-likes such as np.integer are fine), ValueError only for < 1
    if not isinstance(tiles_per_proc, numbers.Integral) or isinstance(tiles_per_proc, bool):
        raise TypeError(f"tiles_per_proc must be an int, got {type(tiles_per_proc)}")
    tiles_per_proc = int(tiles_per_proc)
    if tiles_per_proc < 1:
        raise ValueError(f"tiles_per_proc must be positive, got {tiles_per_proc}")
    if overwrite_a:
        sanitation.warn_parity_noop("qr", "overwrite_a", "XLA owns buffer reuse")
    # full f32 accumulation on the MXU: the reference's torch QR is exact
    # f32; bf16 matmul passes would break the Q@R residual at ~1e-2.
    with jax.default_matmul_precision("highest"):
        return _qr_impl(a, calc_q, method, tiles_per_proc)


def _use_cholqr2(method: str, m: int, n: int, dtype) -> bool:
    if method == "cholqr2":
        return True
    if method != "auto":
        return False
    return (
        jnp.issubdtype(dtype, jnp.floating)
        and m >= 4 * n
        and n >= 1
    )


def _cholqr2_core(x: jnp.ndarray):
    """CholeskyQR2 passes only: (q, r, bad) with no control flow, so it
    stays cheap under ``jax.vmap`` (a vmapped ``lax.cond`` degrades to
    ``select`` and would execute BOTH branches per tile)."""

    def chol_pass(v):
        # conjugate transpose: the Gram of a complex input must be
        # Hermitian or the fast path can never pass its own orthogonality
        # guard (r3 ADVICE); .conj() is a no-op for real dtypes
        g = v.conj().T @ v
        lt = jnp.linalg.cholesky(g)  # lower; R = lt^H
        q = jax.lax.linalg.triangular_solve(
            lt, v, left_side=False, lower=True, transpose_a=True, conjugate_a=True
        )  # solves q @ lt^H = v
        return q, lt.conj().T

    q1, r1 = chol_pass(x)
    q2, r2 = chol_pass(q1)
    r = r2 @ r1
    eye = jnp.eye(x.shape[1], dtype=x.dtype)
    ortho_err = jnp.max(jnp.abs(q2.conj().T @ q2 - eye))
    tol = 10 * jnp.finfo(x.dtype).eps * x.shape[1]
    bad = (
        jnp.any(~jnp.isfinite(r))
        | jnp.any(~jnp.isfinite(q2))
        | (ortho_err > tol)
    )
    return q2, r, bad


def _cholqr2_with_fallback(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """CholeskyQR2 (Fukaya et al.): Q,R from two Gram+Cholesky passes.

    All the FLOPs are (m, n) x (n, n) matmuls — MXU work — instead of the
    sequential Householder reflections ``jnp.linalg.qr`` lowers to. A
    final on-device orthogonality test routes ill-conditioned inputs to
    Householder inside one ``lax.cond`` (no host round-trip).
    """

    if x.shape[0] < x.shape[1]:
        # wide input: reduced-QR shapes differ from CholQR2's (and the
        # Gram is singular anyway) — Householder directly
        return tuple(jnp.linalg.qr(x))

    q2, r, bad = _cholqr2_core(x)
    return jax.lax.cond(
        bad,
        lambda v: tuple(jnp.linalg.qr(v)),
        lambda v: (q2, r),
        x,
    )


# one fused program for the whole non-distributed factorization — called
# eagerly, CholQR2's ~10 constituent ops would each round-trip HBM
_cholqr2_jit = jax.jit(_cholqr2_with_fallback)
_householder_jit = jax.jit(jnp.linalg.qr)


def _cholqr2_batched_with_fallback(tiles: jnp.ndarray):
    """Tile-batched CholeskyQR2 with ONE fallback decision for the whole
    batch: the vmapped body carries no ``cond`` (which would select-execute
    both branches per tile); a single scalar ``any(bad)`` predicate routes
    the entire batch to Householder only when some tile needs it."""
    q2, r, bad = jax.vmap(_cholqr2_core)(tiles)
    return jax.lax.cond(
        jnp.any(bad),
        lambda ts: tuple(jax.vmap(jnp.linalg.qr)(ts)),
        lambda ts: (q2, r),
        tiles,
    )


def _tile_geometry(a: DNDarray, tiles_per_proc: int, mi: int) -> Tuple[int, int]:
    """(n_tiles, tile_rows) of the local TSQR level for ``tiles_per_proc``.

    The row-tile edge comes from SquareDiagTiles — the same square-tile
    decomposition the reference's CAQR loops walk
    (`/root/reference/heat/core/tiling.py:331`, `qr.py:319-866`) — so the
    knob maps onto the identical geometry.
    """
    if tiles_per_proc <= 1 or mi <= 1:
        return 1, mi
    from ..tiling import SquareDiagTiles

    ri = SquareDiagTiles(a, tiles_per_proc).row_indices
    tile_rows = ri[1] - ri[0] if len(ri) > 1 else mi
    return max(1, -(-mi // tile_rows)), tile_rows


def _qr_impl(
    a: DNDarray, calc_q: bool, method: str = "auto", tiles_per_proc: int = 1
) -> QR_out:
    ftype = jnp.promote_types(a.larray.dtype, jnp.float32)
    m, n = a.gshape
    comm = a.comm
    p = comm.size

    if a.split != 0 or p == 1:
        # replicated, single-device, or column-split (for split=1 the
        # reduced factors are column-blocked; gather and factor once —
        # the reference's ``__split1_qr_loop`` did a per-block loop)
        x = a._logical().astype(ftype)
        if _use_cholqr2(method, m, n, x.dtype):
            q, r = _cholqr2_jit(x)
        else:
            q, r = _householder_jit(x)
        # world-size-invariant metadata: split=0 input yields a replicated
        # R exactly like the distributed TSQR path (the ws=1 degenerate
        # case must not carry different splits than ws>1)
        r_split = None if a.split == 0 else a.split
        Q = DNDarray(q, split=a.split, device=a.device, comm=comm) if calc_q else None
        return QR_out(Q, DNDarray(r, split=r_split, device=a.device, comm=comm))

    # split == 0: TSQR. The buffer is already tail-padded to a multiple of
    # the mesh size; zero the padding (QR of [A; 0] has the same R and a
    # zero-row-extended Q).
    arr = a.larray.astype(ftype)
    if a.padded:
        arr = _mask_padding(arr, a.gshape, 0, 0)
    mp = arr.shape[0]
    mesh = comm.mesh
    mi = mp // p

    n_tiles, tile_rows = _tile_geometry(a, tiles_per_proc, mi)

    def _factor_block(blk, rows):
        # the local factorization takes the MXU-resident CholeskyQR2 when
        # the block is tall enough (guarded by the same on-device fallback)
        if _use_cholqr2(method, rows, n, blk.dtype):
            return _cholqr2_with_fallback(blk)
        return jnp.linalg.qr(blk)

    def _local_factor(block):
        """(mi, n) local shard -> local (q1, r1) via the tile tree.

        Full tiles factor as one batch; a ragged tail tile factors
        separately at its TRUE row count — zero-padding it would make its
        Gram singular and deterministically trip the batch-level CholQR2
        fallback (review finding), killing the fast path for every
        non-divisible mi.

        Mesh-level padding (m % p != 0) is different: the LAST device's
        block ends in zero rows that can leave a tile with < n valid rows.
        That is per-device-dynamic (axis_index-dependent), so no static
        tile partition can exclude it; the batch cond runs per device
        inside shard_map, so only that one device reroutes to Householder
        while the rest keep CholQR2 — the correct degradation, not a
        global loss of the fast path.
        """
        if n_tiles <= 1:
            return _factor_block(block, mi)
        n_full, rem = divmod(mi, tile_rows)
        tiles = block[: n_full * tile_rows].reshape(n_full, tile_rows, n)
        if _use_cholqr2(method, tile_rows, n, block.dtype) and tile_rows >= n:
            # one batch-level fallback cond — NOT vmap(_factor_block),
            # whose per-tile cond would select-execute both branches
            q_t, r_t = _cholqr2_batched_with_fallback(tiles)
        else:
            q_t, r_t = jax.vmap(jnp.linalg.qr)(tiles)
        # q_t: (nf, tile_rows, k0), r_t: (nf, k0, n)
        k0 = r_t.shape[1]
        rs = r_t.reshape(n_full * k0, n)
        if rem:
            q_tail, r_tail = _factor_block(block[n_full * tile_rows :], rem)
            rs = jnp.concatenate([rs, r_tail], axis=0)
        qm, r1 = jnp.linalg.qr(rs)  # local merge
        k1 = qm.shape[1]
        q1 = jnp.einsum(
            "tik,tkj->tij", q_t, qm[: n_full * k0].reshape(n_full, k0, k1)
        ).reshape(n_full * tile_rows, k1)
        if rem:
            q1 = jnp.concatenate([q1, q_tail @ qm[n_full * k0 :]], axis=0)
        return q1, r1

    def _tsqr_local(block):
        block = block.reshape(mi, n)
        q1, r1 = _local_factor(block)  # (mi, kk), (kk, n)
        kk = r1.shape[0]
        rs = jax.lax.all_gather(r1, SPLIT_AXIS)  # (p, kk, n)
        q2, r2 = jnp.linalg.qr(rs.reshape(p * kk, n))  # merge factor
        i = jax.lax.axis_index(SPLIT_AXIS)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, i * kk, kk, axis=0)
        q_local = q1 @ q2_block  # (mi, K)
        return q_local[None], r2

    # one compiled program per static geometry. calc_q=False gets its own
    # R-only variant so XLA dead-code-eliminates the whole back-multiply
    # (the eager shard_map computed and discarded Q on every R-only call).
    key = (
        "tsqr", mesh, p, mi, n, n_tiles, tile_rows, method, calc_q,
        jnp.dtype(ftype).name,
    )
    fn = _TSQR_CACHE.get(key)
    if fn is None:
        body = _tsqr_local if calc_q else (lambda block: _tsqr_local(block)[1])
        out_specs = (P(SPLIT_AXIS, None, None), P()) if calc_q else P()
        fn = _TSQR_CACHE[key] = jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=P(SPLIT_AXIS, None),
                out_specs=out_specs,
                # R is computed redundantly (and identically) on every
                # device from the all-gathered factors; tell shard_map to
                # trust the replication
                check_vma=False,
            )
        )
    if not calc_q:
        r = fn(arr)
        return QR_out(None, DNDarray(r, split=None, device=a.device, comm=comm))
    q_sh, r = fn(arr)
    r_dnd = DNDarray(r, split=None, device=a.device, comm=comm)
    # the padded rows of Q are exact zeros; keep them as canonical buffer pad
    q_buf = q_sh.reshape(mp, q_sh.shape[-1])
    Q = DNDarray._from_buffer(
        q_buf, (m, q_buf.shape[-1]), types.canonical_heat_type(q_buf.dtype), 0, a.device, comm
    )
    return QR_out(Q, r_dnd)
