"""QR decomposition (reference ``heat/core/linalg/qr.py``, 1042 LoC).

The reference implements tiled CAQR over ``SquareDiagTiles`` with explicit
tile sends (``qr.py:319-866``). The TPU-native algorithm is **TSQR**
(communication-avoiding QR for tall-skinny matrices): one local Householder
QR per shard on the MXU, an all-gather of the tiny R factors over ICI, one
replicated merge QR, and a local back-multiply — expressed in ~40 lines of
``shard_map``. Row counts that don't divide the mesh are zero-row padded
(QR of [A; 0] has the same R and a zero-row-extended Q).
"""
from __future__ import annotations

import collections
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P
from jax import shard_map

from .. import types
from .._operations import _mask_padding
from ..communication import SPLIT_AXIS
from ..dndarray import DNDarray

__all__ = ["qr"]

QR_out = collections.namedtuple("QR", "Q, R")


def qr(
    a: DNDarray,
    tiles_per_proc: int = 1,
    calc_q: bool = True,
    overwrite_a: bool = False,
) -> QR_out:
    """QR decomposition of a 2-D DNDarray (reference ``qr.py:17``).

    ``tiles_per_proc``/``overwrite_a`` are accepted for API parity; the TSQR
    schedule has no tuning knob to expose and XLA owns buffer reuse.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"expected a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"qr requires a 2-D array, got {a.ndim}-D")
    # full f32 accumulation on the MXU: the reference's torch QR is exact
    # f32; bf16 matmul passes would break the Q@R residual at ~1e-2.
    with jax.default_matmul_precision("highest"):
        return _qr_impl(a, calc_q)


def _qr_impl(a: DNDarray, calc_q: bool) -> QR_out:
    ftype = jnp.promote_types(a.larray.dtype, jnp.float32)
    m, n = a.gshape
    comm = a.comm
    p = comm.size

    if a.split is None or p == 1:
        q, r = jnp.linalg.qr(a._logical().astype(ftype))
        Q = DNDarray(q, split=a.split, device=a.device, comm=comm) if calc_q else None
        return QR_out(Q, DNDarray(r, split=a.split, device=a.device, comm=comm))

    if a.split == 1:
        # column-split: the reduced factors are column-blocked; gather and
        # factor once (reference ``__split1_qr_loop`` did a per-block loop).
        q, r = jnp.linalg.qr(a._logical().astype(ftype))
        Q = DNDarray(q, split=1, device=a.device, comm=comm) if calc_q else None
        return QR_out(Q, DNDarray(r, split=1, device=a.device, comm=comm))

    # split == 0: TSQR. The buffer is already tail-padded to a multiple of
    # the mesh size; zero the padding (QR of [A; 0] has the same R and a
    # zero-row-extended Q).
    arr = a.larray.astype(ftype)
    if a.padded:
        arr = _mask_padding(arr, a.gshape, 0, 0)
    mp = arr.shape[0]
    mesh = comm.mesh

    def _tsqr_local(block):
        # block: (mp/p, n) local shard
        block = block.reshape(mp // p, n)
        q1, r1 = jnp.linalg.qr(block)  # (mi, kk), (kk, n)
        kk = r1.shape[0]
        rs = jax.lax.all_gather(r1, SPLIT_AXIS)  # (p, kk, n)
        q2, r2 = jnp.linalg.qr(rs.reshape(p * kk, n))  # merge factor
        i = jax.lax.axis_index(SPLIT_AXIS)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, i * kk, kk, axis=0)
        q_local = q1 @ q2_block  # (mi, K)
        return q_local[None], r2

    q_sh, r = shard_map(
        _tsqr_local,
        mesh=mesh,
        in_specs=P(SPLIT_AXIS, None),
        out_specs=(P(SPLIT_AXIS, None, None), P()),
        # R is computed redundantly (and identically) on every device from
        # the all-gathered factors; tell shard_map to trust the replication
        check_vma=False,
    )(arr)
    r_dnd = DNDarray(r, split=None, device=a.device, comm=comm)
    if not calc_q:
        return QR_out(None, r_dnd)
    # the padded rows of Q are exact zeros; keep them as canonical buffer pad
    q_buf = q_sh.reshape(mp, q_sh.shape[-1])
    Q = DNDarray._from_buffer(
        q_buf, (m, q_buf.shape[-1]), types.canonical_heat_type(q_buf.dtype), 0, a.device, comm
    )
    return QR_out(Q, r_dnd)
