"""Iterative solvers (reference ``heat/core/linalg/solver.py``).

``cg`` and ``lanczos`` are written against the DNDarray API exactly like
the reference — every matvec is a sharded ``matmul`` whose reduction XLA
compiles to a psum over ICI.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import factories
from ..dndarray import DNDarray
from .basics import matmul, transpose

__all__ = ["cg", "lanczos"]


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A`` (reference ``solver.py:13``)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b and x0 need to be DNDarrays, got {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("x0 needs to be a 1D vector")

    with jax.default_matmul_precision("highest"):
        return _cg_impl(A, b, x0, out)


def _cg_impl(A, b, x0, out):
    r = b - matmul(A, x0)
    p = r.copy()
    rsold = matmul(r, r)
    x = x0.copy()

    for _ in range(len(b)):
        Ap = matmul(A, p)
        alpha = rsold / matmul(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = matmul(r, r)
        if float(jnp.sqrt(rsnew.larray)) < 1e-10:
            break
        p = r + (rsnew / rsold) * p
        rsold = rsnew

    if out is not None:
        out.larray = x.larray
        return out
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric matrix (reference
    ``solver.py:68``). Returns (V, T) with A ~= V T V^T.

    Full re-orthogonalization is applied every step (the reference
    re-orthogonalizes conditionally); the extra matvec is cheap on the MXU
    and buys numerical stability.
    """
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, got {type(A)}")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")
    m = int(m)

    with jax.default_matmul_precision("highest"):
        return _lanczos_impl(A, m, v0, V_out, T_out)


def _lanczos_impl(A, m, v0, V_out, T_out):
    n = A.shape[0]
    arr = A.larray.astype(jnp.promote_types(A.larray.dtype, jnp.float32))
    if v0 is None:
        v = jnp.ones(n, dtype=arr.dtype) / jnp.sqrt(float(n))
    else:
        v = v0.larray.astype(arr.dtype)
        v = v / jnp.linalg.norm(v)

    V = jnp.zeros((m, n), dtype=arr.dtype)
    alphas = jnp.zeros(m, dtype=arr.dtype)
    betas = jnp.zeros(m, dtype=arr.dtype)

    V = V.at[0].set(v)
    w = arr @ v
    alpha = jnp.dot(w, v)
    w = w - alpha * v
    alphas = alphas.at[0].set(alpha)

    for i in range(1, m):
        beta = jnp.linalg.norm(w)
        v_next = jnp.where(beta > 1e-12, w / jnp.where(beta == 0, 1.0, beta), jnp.zeros_like(w))
        # full re-orthogonalization against previous Lanczos vectors
        v_next = v_next - V.T @ (V @ v_next)
        nrm = jnp.linalg.norm(v_next)
        v_next = jnp.where(nrm > 1e-12, v_next / jnp.where(nrm == 0, 1.0, nrm), v_next)
        V = V.at[i].set(v_next)
        w = arr @ v_next
        alpha = jnp.dot(w, v_next)
        w = w - alpha * v_next - beta * V[i - 1]
        alphas = alphas.at[i].set(alpha)
        betas = betas.at[i].set(beta)

    T = jnp.diag(alphas) + jnp.diag(betas[1:], 1) + jnp.diag(betas[1:], -1)
    V_dnd = DNDarray(V.T, split=None, device=A.device, comm=A.comm)
    T_dnd = DNDarray(T, split=None, device=A.device, comm=A.comm)
    if V_out is not None:
        V_out.larray = V_dnd.larray
        V_dnd = V_out
    if T_out is not None:
        T_out.larray = T_dnd.larray
        T_dnd = T_out
    return V_dnd, T_dnd
