"""Iterative solvers (reference ``heat/core/linalg/solver.py``).

The reference's ``cg`` (``solver.py:13``) checks convergence on the host
every iteration and ``lanczos`` (``solver.py:68``) is an eager Python loop —
per-iteration host round-trips. Here both are **device-resident programs**:
``cg`` is one ``lax.while_loop`` with the convergence test on device, and
``lanczos`` is one ``lax.fori_loop`` — a single dispatch each, with GSPMD
inserting the matvec psums over ICI inside the loop body.

Padding discipline: the square operand is zero-extended to its padded
buffer extent on *both* axes and every Krylov vector carries a zero tail.
Zero rows/columns keep the iteration exactly in the valid subspace (the
residual's tail entries start at 0 and stay 0), so no per-iteration masking
is needed.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import factories
from .._operations import _mask_padding
from ..dndarray import DNDarray

__all__ = ["cg", "lanczos"]


def _square_padded(A: DNDarray, ftype):
    """(n_pad, n_pad) zero-extended operand and the padded extent."""
    n = A.gshape[0]
    arr = A.larray.astype(ftype)
    if A.padded:
        arr = _mask_padding(arr, A.gshape, A.split, 0)
    n_pad = arr.shape[A.split] if A.split is not None else n
    pad = [(0, n_pad - s) for s in arr.shape]
    if any(p for _, p in pad):
        arr = jnp.pad(arr, pad)
    return arr, n, n_pad


def _padded_vector(v: DNDarray, n: int, n_pad: int, ftype):
    vec = v._logical().astype(ftype)
    if n_pad != n:
        vec = jnp.pad(vec, (0, n_pad - n))
    return vec


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for s.p.d. ``A`` (reference ``solver.py:13``).

    One compiled ``lax.while_loop``; convergence (``sqrt(r.r) < 1e-10``,
    the reference's threshold) is evaluated on device — no host sync until
    the final result is read.
    """
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b and x0 need to be DNDarrays, got {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("x0 needs to be a 1D vector")

    ftype = jnp.promote_types(A.larray.dtype, jnp.float32)
    arr, n, n_pad = _square_padded(A, ftype)
    bv = _padded_vector(b, n, n_pad, ftype)
    xv = _padded_vector(x0, n, n_pad, ftype)

    with jax.default_matmul_precision("highest"):
        x = _cg_device(arr, bv, xv, n)

    res = DNDarray(x[:n], split=b.split, device=b.device, comm=b.comm)
    if out is not None:
        out.larray = res._logical()
        return out
    return res


@jax.jit
def _cg_device(arr, bv, xv, n):
    r0 = bv - arr @ xv
    state = (xv, r0, r0, jnp.dot(r0, r0), jnp.int32(0))

    def cond(s):
        _, _, _, rs, i = s
        return jnp.logical_and(rs >= 1e-20, i < n)

    def body(s):
        x, r, p, rsold, i = s
        Ap = arr @ p
        alpha = rsold / jnp.dot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = jnp.dot(r, r)
        p = r + (rsnew / rsold) * p
        return (x, r, p, rsnew, i + 1)

    x, *_ = jax.lax.while_loop(cond, body, state)
    return x


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric matrix (reference
    ``solver.py:68``). Returns (V, T) with A ~= V T V^T.

    One compiled ``lax.fori_loop`` over the m steps — O(1) dispatches where
    the reference paid a collective round-trip per step. Full
    re-orthogonalization is applied every step (the reference
    re-orthogonalizes conditionally); the extra matvec is cheap on the MXU
    and buys numerical stability.
    """
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, got {type(A)}")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")
    m = int(m)

    ftype = jnp.promote_types(A.larray.dtype, jnp.float32)
    arr, n, n_pad = _square_padded(A, ftype)
    if v0 is None:
        v = jnp.pad(jnp.ones(n, dtype=arr.dtype) / jnp.sqrt(float(n)), (0, n_pad - n))
    else:
        v = _padded_vector(v0, n, n_pad, arr.dtype)
        v = v / jnp.linalg.norm(v)

    with jax.default_matmul_precision("highest"):
        V, T = _lanczos_device(arr, v, m)

    V_dnd = DNDarray(V[:, :n].T, split=None, device=A.device, comm=A.comm)
    T_dnd = DNDarray(T, split=None, device=A.device, comm=A.comm)
    if V_out is not None:
        V_out.larray = V_dnd._logical()
        V_dnd = V_out
    if T_out is not None:
        T_out.larray = T_dnd._logical()
        T_dnd = T_out
    return V_dnd, T_dnd


def _lanczos_loop(arr, V, alphas, betas, w, m):
    def body(i, state):
        V, alphas, betas, w = state
        beta = jnp.linalg.norm(w)
        v_next = jnp.where(beta > 1e-12, w / jnp.where(beta == 0, 1.0, beta), jnp.zeros_like(w))
        # full re-orthogonalization against previous Lanczos vectors
        v_next = v_next - V.T @ (V @ v_next)
        nrm = jnp.linalg.norm(v_next)
        v_next = jnp.where(nrm > 1e-12, v_next / jnp.where(nrm == 0, 1.0, nrm), v_next)
        V = V.at[i].set(v_next)
        w2 = arr @ v_next
        alpha = jnp.dot(w2, v_next)
        w2 = w2 - alpha * v_next - beta * V[i - 1]
        return (V, alphas.at[i].set(alpha), betas.at[i].set(beta), w2)

    return jax.lax.fori_loop(1, m, body, (V, alphas, betas, w))


# module-level jit: arr enters as a traced operand and the iteration count is
# a static argument, so repeated same-shape solves reuse one executable (a
# per-call jitted lambda here retraced the whole fori_loop on every solve)
_lanczos_jit = jax.jit(_lanczos_loop, static_argnames="m")


def _lanczos_device(arr, v, m):
    n_pad = arr.shape[0]

    V = jnp.zeros((m, n_pad), dtype=arr.dtype)
    alphas = jnp.zeros(m, dtype=arr.dtype)
    betas = jnp.zeros(m, dtype=arr.dtype)

    V = V.at[0].set(v)
    w = arr @ v
    alpha = jnp.dot(w, v)
    w = w - alpha * v
    alphas = alphas.at[0].set(alpha)

    V, alphas, betas, _ = _lanczos_jit(arr, V, alphas, betas, w, m=m)

    T = jnp.diag(alphas) + jnp.diag(betas[1:], 1) + jnp.diag(betas[1:], -1)
    return V, T
