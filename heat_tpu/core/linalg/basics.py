"""Linear algebra basics (reference ``heat/core/linalg/basics.py``, 2398 LoC).

The reference hand-implements a SUMMA-style block matmul with Ibcast
pipelines for every split combination (``basics.py:424-1094``). On TPU the
entire case analysis is deleted: ``jnp.matmul`` on sharded operands under
GSPMD compiles to the communication-optimal schedule on the MXU (this is
exactly the scaling-book recipe — annotate shardings, let XLA insert the
collectives). What this module keeps is the *split metadata* rule for the
result, matching the reference's conventions.
"""
from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from .. import types
from .. import _operations
from .._operations import _local_op, _mask_padding, _reduced_split
from ..dndarray import DNDarray
from ..stride_tricks import sanitize_axis

__all__ = [
    "cross",
    "det",
    "dot",
    "inv",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
]


def _contract_safe(x: DNDarray, jt, contract_dim: int):
    """Operand buffer for a contraction: if the tail padding lies on the
    contracted dimension, zero it so padded products vanish exactly (garbage
    could be inf/nan, where 0*garbage != 0)."""
    buf = x.larray.astype(jt)
    if x.padded and x.split == contract_dim:
        buf = _mask_padding(buf, x.gshape, x.split, 0)
    return buf


def _matmul_gshape(sa: Tuple[int, ...], sb: Tuple[int, ...]) -> Tuple[int, ...]:
    """Logical matmul result shape from logical operand shapes (numpy's
    matmul shape semantics, including 1-D promotion and batch broadcast),
    derived analytically — no host arrays are materialized."""
    a1, b1 = len(sa) == 1, len(sb) == 1
    ea = (1,) + tuple(sa) if a1 else tuple(sa)
    eb = tuple(sb) + (1,) if b1 else tuple(sb)
    if ea[-1] != eb[-2]:
        raise ValueError(f"matmul: contraction mismatch {sa} x {sb}")
    batch = np.broadcast_shapes(ea[:-2], eb[:-2])
    core = () if a1 and b1 else (eb[-1],) if a1 else (ea[-2],) if b1 else (ea[-2], eb[-1])
    return tuple(batch) + core


def _wrap_result(result, out_gshape, split, dtype, device, comm) -> DNDarray:
    """Wrap a raw matmul/contraction result whose dims may carry padding
    inherited from either operand: trim every dim to its logical extent
    except the split dim, which keeps its canonical padded extent."""
    if split is not None:
        split = split % len(out_gshape)  # mat@vec: -2 from the matrix case
    target = comm.padded_shape(out_gshape, split)
    if tuple(result.shape) == target:
        return DNDarray._from_buffer(result, out_gshape, dtype, split, device, comm)
    sl = []
    for i, (r, g) in enumerate(zip(result.shape, out_gshape)):
        if split is not None and i == split and r >= target[i]:
            sl.append(slice(0, target[i]))
        else:
            sl.append(slice(0, g))
    result = result[tuple(sl)]
    if tuple(result.shape) == target:
        return DNDarray._from_buffer(result, out_gshape, dtype, split, device, comm)
    return DNDarray(result, gshape=out_gshape, dtype=dtype, split=split, device=device, comm=comm)


def _matmul_out_split(a: DNDarray, b: DNDarray, out_ndim: int) -> Optional[int]:
    """Result split of a matmul: row-split a -> row-split out; col-split b ->
    col-split out; contracted splits -> replicated (XLA psums over ICI)."""
    if a.ndim >= 2 and a.split == a.ndim - 2:
        return out_ndim - 2
    if b.ndim >= 2 and b.split == b.ndim - 1:
        return out_ndim - 1
    if a.split is not None and a.ndim >= 2 and a.split < a.ndim - 2:
        return a.split  # batch-dim split
    return None


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False) -> DNDarray:
    """Matrix product of two DNDarrays (reference ``basics.py:424``)."""
    # offer the call for lazy capture before touching any buffer (the
    # same slot protocol as the generic dispatchers in _operations):
    # inside an open ht.lazy() scope this records a "matmul" node and a
    # captured predict pipeline fuses standardize -> matmul -> argmax
    # into one program; NotImplemented means proceed eagerly
    if _operations._capture is not None and _operations._capture.active():
        res = _operations._capture.matmul(a, b, allow_resplit)
        if res is not NotImplemented:
            return res
    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("both operands must be DNDarrays")
    promoted = types.promote_types(a.dtype, b.dtype)
    jt = promoted.jax_type()
    if a.ndim == 0 or b.ndim == 0:
        raise ValueError("matmul: operands must have ndim >= 1")
    # validate logical shapes up front: the padded-buffer zero-fill below
    # must never paper over a genuine contraction mismatch
    out_gshape = _matmul_gshape(a.gshape, b.gshape)
    buf_a = _contract_safe(a, jt, a.ndim - 1 if a.ndim > 1 else 0)
    buf_b = _contract_safe(b, jt, b.ndim - 2 if b.ndim > 1 else 0)

    # padding on a BATCH dim breaks jnp.matmul's broadcast semantics (a
    # size-1 batch dim padded to P no longer broadcasts; unequal padded
    # extents fail outright). It is only safe when both operands carry the
    # identical batch layout; otherwise drop to the logical view.
    def _batch_padded(x):
        return x.padded and x.split is not None and x.ndim > 2 and x.split < x.ndim - 2

    pa, pb = _batch_padded(a), _batch_padded(b)
    if pa or pb:
        identical = (
            pa
            and pb
            and a.ndim == b.ndim
            and a.split == b.split
            and a.gshape[a.split] == b.gshape[b.split]
        )
        if not identical:
            if pa:
                buf_a = a._logical().astype(jt)
            if pb:
                buf_b = b._logical().astype(jt)
    # align (possibly padded) contraction extents with zero fill
    ka = buf_a.shape[-1] if a.ndim > 1 else buf_a.shape[0]
    kb = buf_b.shape[-2] if b.ndim > 1 else buf_b.shape[0]
    if ka != kb:
        tgt = max(ka, kb)
        if ka < tgt:
            pad = [(0, 0)] * buf_a.ndim
            pad[-1 if a.ndim > 1 else 0] = (0, tgt - ka)
            buf_a = jnp.pad(buf_a, pad)
        else:
            pad = [(0, 0)] * buf_b.ndim
            pad[-2 if b.ndim > 1 else 0] = (0, tgt - kb)
            buf_b = jnp.pad(buf_b, pad)
    result = jnp.matmul(buf_a, buf_b)
    if result.ndim == 0:
        return DNDarray(result, dtype=promoted, split=None, device=a.device, comm=a.comm)
    split = _matmul_out_split(a, b, result.ndim)
    return _wrap_result(result, out_gshape, split, promoted, a.device, a.comm)


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None):
    """Dot product (reference ``basics.py:246``)."""
    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("both operands must be DNDarrays")
    if a.ndim == 1 and b.ndim == 1:
        result = jnp.dot(a._logical(), b._logical())
        res = DNDarray(result, split=None, device=a.device, comm=a.comm)
        if out is not None:
            from .._operations import _write_out

            return _write_out(out, res)
        return res
    if a.ndim <= 2 and b.ndim <= 2:
        res = matmul(a, b)
        if out is not None:
            from .._operations import _write_out

            return _write_out(out, res)
        return res
    raise NotImplementedError("ht.dot not implemented for >2 dimensions")


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    """Conjugated dot product of flattened inputs (reference ``basics.py:2236``)."""
    result = jnp.vdot(x1._logical(), x2._logical())
    return DNDarray(result, split=None, device=x1.device, comm=x1.comm)


def vecdot(x1: DNDarray, x2: DNDarray, axis: Optional[int] = None, keepdim=None, keepdims: bool = False) -> DNDarray:
    """Vector dot along an axis (reference ``basics.py:2272``)."""
    keepdims = bool(keepdim or keepdims)
    if axis is None:
        axis = -1
    axis = sanitize_axis(tuple(np.broadcast_shapes(x1.shape, x2.shape)), axis)
    result = jnp.sum(jnp.conj(x1._logical()) * x2._logical(), axis=axis, keepdims=keepdims)
    ndim = max(x1.ndim, x2.ndim)
    anchor = x1 if x1.split is not None else x2
    split = _reduced_split(anchor.split, axis, ndim, keepdims)
    return DNDarray(result, split=split, device=x1.device, comm=x1.comm)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product (reference ``basics.py:1372`` used a ring Send/Recv of
    shards to bound per-device temps).

    One jitted sharded program here: with the output row-split, GSPMD
    gathers only the second operand (O(m) per device) while each device
    writes its own O(nm/P) output shard — the same bound as the
    reference's ring, asserted in ``tests/test_distribution_proofs.py``."""
    if split is None:
        split = 0 if (a.split is not None or b.split is not None) else None
    if split is None:
        result = jnp.outer(a._logical(), b._logical())
        res = DNDarray(result, split=None, device=a.device, comm=a.comm)
    else:
        from .._movement import outer_padded

        jt = types.promote_types(a.dtype, b.dtype).jax_type()
        buf, out_shape = outer_padded(
            a.larray.astype(jt),
            a.gshape,
            a.split,
            b.larray.astype(jt),
            b.gshape,
            b.split,
            split,
            a.comm,
        )
        res = DNDarray._from_buffer(
            buf, out_shape, types.canonical_heat_type(buf.dtype), split,
            device=a.device, comm=a.comm,
        )
    if out is not None:
        from .._operations import _write_out

        return _write_out(out, res)
    return res


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of a onto b (reference ``basics.py``)."""
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection requires 1-D vectors, got {a.ndim}, {b.ndim}")
    return (dot(a, b) / dot(b, b)) * b


def cross(a: DNDarray, b: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1) -> DNDarray:
    """Cross product (reference ``basics.py:47``; numpy axis semantics —
    ``axis`` overrides ``axisa``/``axisb``/``axisc``)."""
    result = jnp.cross(
        a._logical(), b._logical(),
        axisa=axisa, axisb=axisb, axisc=axisc,
        axis=None if axis == -1 else axis,
    )
    split = a.split if a.split is not None else b.split
    if split is not None and result.ndim != a.ndim:
        split = None
    return DNDarray(result, split=split, device=a.device, comm=a.comm)


def det(a: DNDarray) -> DNDarray:
    """Determinant (reference ``basics.py:160``, a distributed pivoted
    elimination with per-row Bcasts there).

    Split 2-D operands run the distributed blocked LU with tournament
    pivoting (:mod:`~heat_tpu.core.linalg.factorizations`) — no
    full-operand gather; batch-split stacks LU-factor per shard with zero
    communication; replicated operands run the local batched LU."""
    _square_check(a)
    from .factorizations import _det_impl

    return _det_impl(a)


def inv(a: DNDarray) -> DNDarray:
    """Matrix inverse (reference ``basics.py:312``).

    Split 2-D operands run the distributed blocked LU with the identity
    riding the elimination as augmented columns
    (:mod:`~heat_tpu.core.linalg.factorizations`) — no full-operand
    gather; batch-split stacks invert per shard; replicated operands run
    the local LU-based inverse."""
    _square_check(a)
    from .factorizations import _inv_impl

    return _inv_impl(a)


def _square_check(a: DNDarray):
    if a.ndim < 2:
        raise RuntimeError(f"DNDarray must be at least two-dimensional, got {a.ndim}")
    if a.shape[-1] != a.shape[-2]:
        raise RuntimeError("Last two dimensions of the DNDarray must be square")


def _float_type(a: DNDarray):
    return jnp.promote_types(a.larray.dtype, jnp.float32)


def matrix_norm(x: DNDarray, axis: Optional[Tuple[int, int]] = None, keepdims: bool = False, ord=None) -> DNDarray:
    """Matrix norm (reference ``basics.py:1095``)."""
    if axis is None:
        if x.ndim != 2:
            raise ValueError("axis must be given for arrays that are not 2-D")
        axis = (0, 1)
    axis = sanitize_axis(x.shape, axis)
    row, col = axis
    arr = x._logical().astype(_float_type(x))
    # after the inner sum drops an axis, the outer reduction index shifts
    # (reference basics.py:1176-1212 does the same adjustment)
    col_adj = col - 1 if (col > row and not keepdims) else col
    row_adj = row - 1 if (row > col and not keepdims) else row
    if ord is None or ord == "fro":
        result = jnp.sqrt(jnp.sum(jnp.abs(arr) ** 2, axis=axis, keepdims=keepdims))
    elif ord == 1:
        result = jnp.max(jnp.sum(jnp.abs(arr), axis=row, keepdims=keepdims), axis=col_adj, keepdims=keepdims)
    elif ord == -1:
        result = jnp.min(jnp.sum(jnp.abs(arr), axis=row, keepdims=keepdims), axis=col_adj, keepdims=keepdims)
    elif ord == np.inf:
        result = jnp.max(jnp.sum(jnp.abs(arr), axis=col, keepdims=keepdims), axis=row_adj, keepdims=keepdims)
    elif ord == -np.inf:
        result = jnp.min(jnp.sum(jnp.abs(arr), axis=col, keepdims=keepdims), axis=row_adj, keepdims=keepdims)
    elif ord in (2, -2, "nuc"):
        # singular-value norms: the reference raises NotImplementedError
        # (basics.py:1193-1218); here XLA's batched SVD covers them
        moved = jnp.moveaxis(arr, (row, col), (-2, -1))
        s = jnp.linalg.svd(moved, compute_uv=False)
        if ord == 2:
            result = jnp.max(s, axis=-1)
        elif ord == -2:
            result = jnp.min(s, axis=-1)
        else:
            result = jnp.sum(s, axis=-1)
        if keepdims:
            result = jnp.expand_dims(result, axis=(row, col))
    else:
        raise ValueError(f"Invalid norm order {ord} for matrices")
    split = _reduced_split(x.split, axis, x.ndim, keepdims)
    return DNDarray(result, split=split, device=x.device, comm=x.comm)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector norm (reference ``basics.py:2309``)."""
    axis_s = sanitize_axis(x.shape, axis)
    arr = x._logical().astype(_float_type(x))
    result = jnp.linalg.norm(
        arr if axis_s is not None or x.ndim == 1 else arr.ravel(),
        ord=2 if ord is None else ord,
        axis=axis_s if axis_s is not None else None if x.ndim > 1 else 0,
        keepdims=keepdims,
    )
    split = _reduced_split(x.split, axis_s if axis_s is not None else None, x.ndim, keepdims)
    return DNDarray(result, split=split, device=x.device, comm=x.comm)


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """General norm dispatch (reference ``basics.py:1223``)."""
    if axis is None and ord is None:
        arr = x._logical().astype(_float_type(x))
        return DNDarray(jnp.sqrt(jnp.sum(jnp.abs(arr) ** 2)), split=None, device=x.device, comm=x.comm)
    if axis is None:
        if x.ndim == 1:
            return vector_norm(x, axis=0, keepdims=keepdims, ord=ord)
        if x.ndim == 2:
            return matrix_norm(x, axis=(0, 1), keepdims=keepdims, ord=ord)
        raise ValueError("improper number of dimensions to norm")
    if isinstance(axis, (int, np.integer)):
        return vector_norm(x, axis=axis, keepdims=keepdims, ord=ord)
    if isinstance(axis, tuple) and len(axis) == 2:
        return matrix_norm(x, axis=axis, keepdims=keepdims, ord=ord)
    raise TypeError(f"axis must be an int or 2-tuple, got {axis}")


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None):
    """Sum along diagonals (reference ``basics.py:1629``)."""
    result = jnp.trace(a._logical(), offset=offset, axis1=axis1, axis2=axis2)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    res = DNDarray(result, split=None, device=a.device, comm=a.comm)
    if out is not None:
        from .._operations import _write_out

        return _write_out(out, res)
    return res


def transpose(a: DNDarray, axes: Optional[List[int]] = None) -> DNDarray:
    """Permute dimensions; the split axis label moves with its dimension —
    zero data movement (reference ``basics.py:2051`` same trick)."""
    if not isinstance(a, DNDarray):
        raise TypeError(f"a must be a DNDarray, got {type(a)}")
    if axes is None:
        axes = tuple(reversed(range(a.ndim)))
    else:
        axes = tuple(int(ax) for ax in axes)
        if len(axes) != a.ndim:
            raise ValueError("axes do not match tensor shape")
    result = jnp.transpose(a.larray, axes)
    new_split = axes.index(a.split) if a.split is not None else None
    new_gshape = tuple(a.gshape[ax] for ax in axes)
    return DNDarray._from_buffer(result, new_gshape, a.dtype, new_split, a.device, a.comm)


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower-triangular part (reference ``basics.py:2191`` via ``__tri_op``)."""
    return _tri_op(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper-triangular part (reference ``basics.py:2214``)."""
    return _tri_op(m, k, jnp.triu)


def _tri_op(m: DNDarray, k: int, op) -> DNDarray:
    if not isinstance(m, DNDarray):
        raise TypeError(f"expected m to be a DNDarray, got {type(m)}")
    vector = m.ndim == 1
    if vector:
        # reference semantics: a 1-D input becomes a (n, n) triangle of tiles
        arr = m._logical()
        result = op(jnp.tile(arr, (arr.shape[0], 1)), k=k)
        split = 0 if m.split is not None else None
        return DNDarray(result, dtype=m.dtype, split=split, device=m.device, comm=m.comm)
    # 2-D+: triangle masks use absolute indices, which padding never shifts
    result = op(m.larray, k=k)
    return DNDarray._from_buffer(result, m.gshape, m.dtype, m.split, m.device, m.comm)
