"""Singular value decomposition.

The reference ships only an empty stub (``heat/core/linalg/svd.py:1-5``,
"Future file for SVD functions") — this module goes beyond parity. The
TPU-native algorithm for tall-skinny matrices is **TSQR + SVD-of-R**: a
communication-avoiding QR (one all-gather of k×k factors over ICI) followed
by a replicated small SVD, with U recovered by a sharded matmul on the MXU.
"""
from __future__ import annotations

import collections
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..dndarray import DNDarray
from .qr import qr

__all__ = ["svd"]

SVD_out = collections.namedtuple("SVD", "U, S, Vh")


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """SVD of a 2-D DNDarray.

    For split=0 (tall-skinny) inputs uses distributed TSQR + local SVD of R;
    otherwise a global ``jnp.linalg.svd`` (GSPMD chooses the schedule).
    Only ``full_matrices=False`` (reduced) is supported distributed.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"expected a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D array, got {a.ndim}-D")
    if full_matrices and a.split is not None:
        raise NotImplementedError("full_matrices=True is not supported for split arrays")
    with jax.default_matmul_precision("highest"):
        return _svd_impl(a, full_matrices, compute_uv)


def _svd_impl(a: DNDarray, full_matrices: bool, compute_uv: bool):
    m, n = a.shape

    if a.split == 0 and m >= n and a.comm.size > 1:
        Q, R = qr(a, calc_q=compute_uv)
        if not compute_uv:
            s = jnp.linalg.svd(R.larray, compute_uv=False)
            return DNDarray(s, split=None, device=a.device, comm=a.comm)
        u_r, s, vh = jnp.linalg.svd(R.larray, full_matrices=False)
        U = Q @ DNDarray(u_r, split=None, device=a.device, comm=a.comm)
        return SVD_out(
            U,
            DNDarray(s, split=None, device=a.device, comm=a.comm),
            DNDarray(vh, split=None, device=a.device, comm=a.comm),
        )

    ftype = jnp.promote_types(a.larray.dtype, jnp.float32)
    if not compute_uv:
        s = jnp.linalg.svd(a.larray.astype(ftype), compute_uv=False)
        return DNDarray(s, split=None, device=a.device, comm=a.comm)
    u, s, vh = jnp.linalg.svd(a.larray.astype(ftype), full_matrices=full_matrices)
    return SVD_out(
        DNDarray(u, split=a.split if a.split == 0 else None, device=a.device, comm=a.comm),
        DNDarray(s, split=None, device=a.device, comm=a.comm),
        DNDarray(vh, split=1 if a.split == 1 else None, device=a.device, comm=a.comm),
    )
