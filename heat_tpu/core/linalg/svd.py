"""Singular value decomposition.

The reference ships only an empty stub (``heat/core/linalg/svd.py:1-5``,
"Future file for SVD functions") — this module goes beyond parity. The
TPU-native algorithm for tall-skinny matrices is **TSQR + SVD-of-R**: a
communication-avoiding QR (one all-gather of k×k factors over ICI) followed
by a replicated small SVD, with U recovered by a sharded matmul on the MXU.
"""
from __future__ import annotations

import collections
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..dndarray import DNDarray
from .qr import qr

__all__ = ["lstsq", "pinv", "rsvd", "svd"]

SVD_out = collections.namedtuple("SVD", "U, S, Vh")


def rsvd(
    a: DNDarray,
    rank: int,
    n_oversamples: int = 10,
    n_iter: int = 2,
    random_state: Optional[int] = None,
):
    """Randomized truncated SVD (Halko-Martinsson-Tropp) of a distributed
    2-D array — rank-``rank`` approximation for matrices of ANY shape/split.

    Beyond the reference (its ``svd.py`` is an empty stub). The schedule is
    TPU-native end to end: the range finder is two sharded MXU matmuls per
    power iteration (GSPMD inserts the collectives), orthonormalization and
    the small SVD run on the (n, k+p) / (k+p, k+p) replicated factors.

    Returns ``SVD(U, S, Vh)`` with ``U (m, rank)`` carrying ``a``'s row
    split, ``S (rank,)`` and ``Vh (rank, n)`` replicated.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"expected a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"rsvd requires a 2-D array, got {a.ndim}-D")
    m, n = a.shape
    k = rank + n_oversamples
    if not 0 < rank <= min(m, n):
        raise ValueError(f"rank must be in [1, {min(m, n)}], got {rank}")
    k = min(k, min(m, n))

    if random_state is not None:
        # local key: must not perturb the library-global RNG stream
        key = jax.random.fold_in(jax.random.PRNGKey(random_state), k * n)
    else:
        from .. import random as ht_random

        key = ht_random._next_key(k * n)

    ftype = jnp.promote_types(a.larray.dtype, jnp.float32)
    from .._operations import _mask_padding

    A = a.larray.astype(ftype)
    if a.padded:
        if a.split == 0:
            # zero the tail padding: padded rows contribute exact zeros to
            # every product, and the TSQR path consumes the even buffer
            A = _mask_padding(A, a.gshape, a.split, 0)
        else:
            # column padding would leak into omega/Vh extents; materialize
            A = a._logical().astype(ftype)
    distributed_rows = a.split == 0 and a.comm.size > 1

    def ortho(Y):
        # tall (m, k) panel: communication-avoiding TSQR when the rows are
        # sharded (one all-gather of k x k factors), local QR otherwise
        if distributed_rows:
            from .. import types as _t

            Qd, _ = qr(
                DNDarray._from_buffer(
                    Y, (m, Y.shape[1]), _t.canonical_heat_type(Y.dtype), 0, a.device, a.comm
                )
            )
            return Qd.larray
        return jnp.linalg.qr(Y)[0]

    with jax.default_matmul_precision("highest"):
        omega = jax.random.normal(key, (n, k), dtype=ftype)
        Y = A @ omega  # (m, k) - sharded like A's rows
        # power iterations with QR re-orthonormalization for stability
        Q = ortho(Y)
        for _ in range(n_iter):
            Z = A.T @ Q  # (n, k) - replicated after the psum
            Z = jnp.linalg.qr(Z)[0]
            Y = A @ Z
            Q = ortho(Y)
        B = Q.T @ A  # (k, n) - replicated after the psum
        u_b, s, vh = jnp.linalg.svd(B, full_matrices=False)
        U = Q @ u_b  # (m, k), row-sharded
    U = U[:, :rank]
    s = s[:rank]
    vh = vh[:rank]
    if a.split == 0:
        from .. import types as _t

        U_dnd = DNDarray._from_buffer(
            U, (m, rank), _t.canonical_heat_type(U.dtype), 0, a.device, a.comm
        )
    else:
        U_dnd = DNDarray(U, split=None, device=a.device, comm=a.comm)
    return SVD_out(
        U_dnd,
        DNDarray(s, split=None, device=a.device, comm=a.comm),
        DNDarray(vh, split=None, device=a.device, comm=a.comm),
    )


def svd(a: DNDarray, full_matrices: bool = False, compute_uv: bool = True):
    """SVD of a 2-D DNDarray.

    For split=0 (tall-skinny) inputs uses distributed TSQR + local SVD of R;
    otherwise a global ``jnp.linalg.svd`` (GSPMD chooses the schedule).
    Only ``full_matrices=False`` (reduced) is supported distributed.
    """
    if not isinstance(a, DNDarray):
        raise TypeError(f"expected a DNDarray, got {type(a)}")
    if a.ndim != 2:
        raise ValueError(f"svd requires a 2-D array, got {a.ndim}-D")
    if full_matrices and a.split is not None:
        raise NotImplementedError("full_matrices=True is not supported for split arrays")
    with jax.default_matmul_precision("highest"):
        return _svd_impl(a, full_matrices, compute_uv)


def lstsq(a: DNDarray, b: DNDarray, rcond: Optional[float] = None) -> DNDarray:
    """Least-squares solution of ``a @ x = b`` (beyond the reference).

    Tall row-sharded systems solve via the distributed TSQR (one k×k
    all-gather) + a replicated triangular solve — the communication-avoiding
    schedule for the regression workloads the reference targets; other
    shapes go through the SVD pseudoinverse with ``rcond`` clipping.
    """
    if not isinstance(a, DNDarray) or not isinstance(b, DNDarray):
        raise TypeError("lstsq expects DNDarray operands")
    if a.ndim != 2 or b.ndim not in (1, 2):
        raise ValueError(f"bad operand ranks {a.ndim}, {b.ndim}")
    m, n = a.shape
    if b.shape[0] != m:
        raise ValueError(f"dimension mismatch: a has {m} rows, b has {b.shape[0]}")
    from .. import complex_math

    with jax.default_matmul_precision("highest"):
        if m >= n and rcond is None:
            ftype = jnp.promote_types(a.larray.dtype, jnp.float32)
            eps_cut = float(jnp.finfo(ftype).eps) * max(m, n)
            Q, R = qr(a)
            diag = jnp.abs(jnp.diagonal(R._logical()))
            if float(jnp.min(diag)) > eps_cut * float(jnp.max(diag)):
                # well-conditioned: qᴴ b is replicated after the psum, and
                # the k x k triangular system routes through the shared
                # solver (local branch here — R is replicated; a split R
                # would run the distributed block substitution)
                from .factorizations import solve_triangular

                qhb = complex_math.conj(Q).T @ b
                return solve_triangular(R, qhb, lower=False)
            # rank-deficient: match numpy's min-norm solution via the SVD
        p = pinv(a, rcond=rcond)
        return p @ b


def pinv(a: DNDarray, rcond: Optional[float] = None) -> DNDarray:
    """Moore-Penrose pseudoinverse via the SVD (beyond the reference:
    its ``svd.py`` is an empty stub).

    ``rcond=None`` derives the cutoff from the operand dtype's machine
    epsilon, ``eps * max(m, n)`` — numpy's default — instead of a fixed
    constant, so ill-conditioned but full-rank float64 systems keep their
    genuine singular values."""
    if not isinstance(a, DNDarray):
        raise TypeError("pinv expects a DNDarray")
    if a.ndim != 2:
        raise ValueError(f"pinv requires a 2-D array, got {a.ndim}-D")
    U, s, Vh = svd(a, full_matrices=False)
    if rcond is None:
        ftype = jnp.promote_types(a.larray.dtype, jnp.float32)
        rcond = float(jnp.finfo(ftype).eps) * max(a.gshape)
    # logical views on the SMALL factors only: Vh inherits split=1 from a
    # split-1 operand and its BUFFER carries column padding that must not
    # leak into the result's extent (caught at world size 5 with n=64 ->
    # padded 65). U is the tall factor — it stays sharded and contracts
    # through the DNDarray matmul (GSPMD psum), never a full gather.
    from .. import complex_math

    sl = s._logical()
    cutoff = rcond * jnp.max(sl)
    s_inv = jnp.where(sl > cutoff, 1.0 / sl, 0.0)
    with jax.default_matmul_precision("highest"):
        vs = Vh._logical().conj().T * s_inv[None, :]
        Uh = complex_math.conj(U).T  # row-split U -> column-split U^H
        return DNDarray(vs, split=None, device=a.device, comm=a.comm) @ Uh


def _svd_impl(a: DNDarray, full_matrices: bool, compute_uv: bool):
    m, n = a.shape

    if a.split == 0 and m >= n and a.comm.size > 1:
        Q, R = qr(a, calc_q=compute_uv)
        if not compute_uv:
            s = jnp.linalg.svd(R.larray, compute_uv=False)
            return DNDarray(s, split=None, device=a.device, comm=a.comm)
        u_r, s, vh = jnp.linalg.svd(R.larray, full_matrices=False)
        U = Q @ DNDarray(u_r, split=None, device=a.device, comm=a.comm)
        return SVD_out(
            U,
            DNDarray(s, split=None, device=a.device, comm=a.comm),
            DNDarray(vh, split=None, device=a.device, comm=a.comm),
        )

    ftype = jnp.promote_types(a.larray.dtype, jnp.float32)
    if not compute_uv:
        s = jnp.linalg.svd(a._logical().astype(ftype), compute_uv=False)
        return DNDarray(s, split=None, device=a.device, comm=a.comm)
    u, s, vh = jnp.linalg.svd(a._logical().astype(ftype), full_matrices=full_matrices)
    return SVD_out(
        DNDarray(u, split=a.split if a.split == 0 else None, device=a.device, comm=a.comm),
        DNDarray(s, split=None, device=a.device, comm=a.comm),
        DNDarray(vh, split=1 if a.split == 1 else None, device=a.device, comm=a.comm),
    )
