"""Distributed linear algebra (reference ``heat/core/linalg/``)."""
from . import basics, solver, svd
from .basics import *
from .qr import qr
from .solver import *
from .svd import lstsq, pinv, rsvd, svd
