"""Distributed linear algebra (reference ``heat/core/linalg/``)."""
from . import basics, factorizations, solver, svd
from .basics import *
from .factorizations import cholesky, solve, solve_triangular
from .qr import qr
from .solver import *
from .svd import lstsq, pinv, rsvd, svd
