"""Shape and data manipulations (reference ``heat/core/manipulations.py``,
4028 LoC — the largest file in the reference).

The reference hand-writes the communication for every global data movement:
``concatenate`` (case analysis over both splits), ``reshape``
(Alltoallv reshuffle), ``sort`` (parallel sample-sort: local sort -> pivot
exchange -> Alltoallv buckets -> merge), ``resplit`` (SplitTiles
Isend/Irecv mesh), ``topk`` (custom MPI op). On TPU each of these is one
global ``jnp`` call — XLA compiles sharded sort to the same
bucket-exchange pattern over ICI — plus an output-split rule.
"""
from __future__ import annotations

import collections
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "balance",
    "broadcast_arrays",
    "broadcast_to",
    "column_stack",
    "concatenate",
    "diag",
    "diagonal",
    "dsplit",
    "expand_dims",
    "flatten",
    "flip",
    "fliplr",
    "flipud",
    "hsplit",
    "hstack",
    "moveaxis",
    "pad",
    "ravel",
    "redistribute",
    "repeat",
    "reshape",
    "resplit",
    "roll",
    "rot90",
    "row_stack",
    "shape",
    "sort",
    "split",
    "squeeze",
    "stack",
    "swapaxes",
    "tile",
    "topk",
    "unfold",
    "unique",
    "vsplit",
    "vstack",
]


def _merge_unique_across_processes(merged: np.ndarray, axis: Optional[int]) -> np.ndarray:
    """Allgather the per-process candidate sets (ragged) and re-unique —
    the reference's Allgatherv + final unique (``manipulations.py:3055``)."""
    from .communication import ragged_process_allgather

    ax = 0 if axis is None else axis
    parts = ragged_process_allgather(merged, axis=ax)
    return np.unique(np.concatenate(parts, axis=ax), axis=axis)


def _wrap(result: jnp.ndarray, like: DNDarray, split: Optional[int]) -> DNDarray:
    return DNDarray(
        result,
        dtype=types.canonical_heat_type(result.dtype),
        split=split,
        device=like.device,
        comm=like.comm,
    )


def balance(array: DNDarray, copy: bool = False) -> DNDarray:
    """Balanced version of ``array`` (reference ``manipulations.py:63``).
    A ragged-layout array (after a non-canonical ``redistribute_``) is
    rebalanced with one interval exchange; canonical arrays pass through."""
    out = array.copy() if copy else array
    return out.balance_()


def broadcast_arrays(*arrays: DNDarray) -> List[DNDarray]:
    """Broadcast arrays against each other (reference ``manipulations.py``)."""
    shapes = [a.shape for a in arrays]
    target = tuple(np.broadcast_shapes(*shapes))
    return [broadcast_to(a, target) for a in arrays]


def broadcast_to(x: DNDarray, shape) -> DNDarray:
    """Broadcast to a new shape (reference ``manipulations.py``)."""
    shape = sanitize_shape(shape)
    result = jnp.broadcast_to(x._logical(), shape)
    split = x.split + (len(shape) - x.ndim) if x.split is not None else None
    return _wrap(result, x, split)


def column_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    """Stack 1-D/2-D arrays as columns (reference ``manipulations.py``)."""
    dnd = [a if isinstance(a, DNDarray) else DNDarray(jnp.asarray(a)) for a in arrays]
    result = jnp.column_stack([a._logical() for a in dnd])
    split = next((a.split for a in dnd if a.split is not None and a.ndim > 1), None)
    if split is None and any(a.split is not None for a in dnd):
        split = 0
    return _wrap(result, dnd[0], split)


def row_stack(arrays: Sequence[DNDarray]) -> DNDarray:
    return vstack(arrays)


def concatenate(arrays: Sequence[DNDarray], axis: int = 0) -> DNDarray:
    """Join arrays along an existing axis (reference
    ``manipulations.py:188`` — a large case analysis over both operands'
    splits with redistribution; sharding propagation handles it here)."""
    if len(arrays) < 2:
        if len(arrays) == 1:
            return arrays[0]
        raise ValueError("concatenate requires at least one array")
    for a in arrays:
        if not isinstance(a, DNDarray):
            raise TypeError(f"all inputs must be DNDarrays, found {type(a)}")
    axis = sanitize_axis(arrays[0].shape, axis)
    first = arrays[0].shape
    for a in arrays[1:]:
        if a.ndim != len(first) or any(
            d != axis and a.shape[d] != first[d] for d in range(a.ndim)
        ):
            raise ValueError(
                f"all input array dimensions except axis {axis} must match "
                f"exactly: {first} vs {a.shape}"
            )
    splits = {a.split for a in arrays if a.split is not None}
    if len(splits) > 1:
        raise RuntimeError(f"DNDarrays given have differing split axes, found {splits}")
    out_split = splits.pop() if splits else None
    promoted = arrays[0].dtype
    for a in arrays[1:]:
        promoted = types.promote_types(promoted, a.dtype)
    jt = promoted.jax_type()
    out_shape = list(first)
    out_shape[axis] = sum(a.shape[axis] for a in arrays)
    if out_split is None:
        result = jnp.concatenate([a._logical().astype(jt) for a in arrays], axis=axis)
        return _wrap(result, arrays[0], None)
    # distributed: one jitted program over the physical buffers; GSPMD
    # emits the all-to-all exchange directly (the reference's split-case
    # redistribution, manipulations.py:188) — proven bounded in
    # tests/test_distribution_proofs.py
    from ._movement import concatenate_padded

    comm = arrays[0].comm
    buf = concatenate_padded(
        [a.larray for a in arrays],
        [a.gshape for a in arrays],
        [a.split for a in arrays],
        axis,
        tuple(out_shape),
        out_split,
        jt,
        comm,
    )
    return DNDarray._from_buffer(
        buf, tuple(out_shape), promoted, out_split, device=arrays[0].device, comm=comm
    )


def diag(a: DNDarray, offset: int = 0) -> DNDarray:
    """Extract or construct a diagonal (reference ``manipulations.py``)."""
    if a.ndim == 1:
        result = jnp.diag(a._logical(), k=offset)
        return _wrap(result, a, a.split)
    return diagonal(a, offset=offset)


def diagonal(a: DNDarray, offset: int = 0, dim1: int = 0, dim2: int = 1) -> DNDarray:
    """Diagonal view, split-rule parity with reference ``manipulations.py:641-650``:
    the split axis survives with its position shifted past the removed dims;
    if the split axis *is* one of the diagonal dims the result is split along
    the new last axis (the diagonal itself)."""
    dim1, dim2 = sanitize_axis(a.shape, dim1), sanitize_axis(a.shape, dim2)
    if dim1 == dim2:
        raise ValueError("dim1 and dim2 need to be different")
    result = jnp.diagonal(a._logical(), offset=offset, axis1=dim1, axis2=dim2)
    if a.split is None:
        split = None
    elif a.split in (dim1, dim2):
        split = result.ndim - 1
    else:
        split = a.split - sum(1 for d in (dim1, dim2) if d < a.split)
    return _wrap(result, a, split)


def dsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    """Split along axis 2 (reference ``manipulations.py``)."""
    return split(x, indices_or_sections, axis=2)


def expand_dims(a: DNDarray, axis: int) -> DNDarray:
    """Insert a new axis (reference ``manipulations.py``)."""
    axis = sanitize_axis(a.shape + (1,), axis)
    result = jnp.expand_dims(a._logical(), axis)
    split = a.split
    if split is not None and axis <= split:
        split += 1
    return _wrap(result, a, split)


def flatten(a: DNDarray) -> DNDarray:
    """Flatten to 1-D (reference ``manipulations.py``); result split 0.
    Routes through the jitted reshape pipeline (bounded per-device memory,
    see :mod:`heat_tpu.core._movement`)."""
    return reshape(a, (a.size,))


def flip(a: DNDarray, axis=None) -> DNDarray:
    """Reverse element order along axis (reference ``manipulations.py``).

    Distributed arrays run as one pinned pipeline: a split-axis flip
    reverses the block partition, which GSPMD lowers to collective
    permutes (proof-tested, no all-gather)."""
    if a.split is not None and a.comm.is_distributed():
        from ._movement import flip_padded

        key_axis = axis if axis is None or isinstance(axis, int) else tuple(axis)
        buf = flip_padded(a.larray, a.gshape, a.split, key_axis, a.comm)
        return DNDarray._from_buffer(buf, a.gshape, a.dtype, a.split, a.device, a.comm)
    result = jnp.flip(a._logical(), axis=axis)
    return _wrap(result, a, a.split)


def fliplr(a: DNDarray) -> DNDarray:
    return flip(a, 1)


def flipud(a: DNDarray) -> DNDarray:
    return flip(a, 0)


def hsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    if x.ndim < 2:
        return split(x, indices_or_sections, 0)
    return split(x, indices_or_sections, 1)


def hstack(arrays: Sequence[DNDarray]) -> DNDarray:
    dnd = [a if isinstance(a, DNDarray) else DNDarray(jnp.asarray(a)) for a in arrays]
    axis = 0 if dnd[0].ndim == 1 else 1
    return concatenate(dnd, axis=axis)


def moveaxis(x: DNDarray, source, destination) -> DNDarray:
    """Move axes to new positions (reference ``manipulations.py``)."""
    from .linalg import transpose

    if isinstance(source, (int, np.integer)):
        source = (source,)
    if isinstance(destination, (int, np.integer)):
        destination = (destination,)
    source = [sanitize_axis(x.shape, int(s)) for s in source]
    destination = [sanitize_axis(x.shape, int(d)) for d in destination]
    if len(source) != len(destination):
        raise ValueError("source and destination arguments must have the same number of elements")
    order = [n for n in range(x.ndim) if n not in source]
    for dest, src in sorted(zip(destination, source)):
        order.insert(dest, src)
    return transpose(x, order)


def pad(array: DNDarray, pad_width, mode: str = "constant", constant_values=0) -> DNDarray:
    """Pad an array (reference ``manipulations.py:1128``)."""
    if isinstance(pad_width, (int, np.integer)):
        np_pad = pad_width
    else:
        pw = list(pad_width)
        # heat accepts a flat (before, after) tuple for the last dim(s)
        if len(pw) and isinstance(pw[0], (int, np.integer)):
            if len(pw) != 2:
                raise ValueError("pad_width as flat sequence must have length 2")
            np_pad = [(0, 0)] * (array.ndim - 1) + [tuple(pw)]
        else:
            np_pad = [tuple(p) for p in pw]
            if len(np_pad) < array.ndim:
                np_pad = [(0, 0)] * (array.ndim - len(np_pad)) + np_pad
    if isinstance(np_pad, (int, np.integer)):
        np_pad = [(int(np_pad), int(np_pad))] * array.ndim
    np_pad = tuple(tuple(int(v) for v in p) for p in np_pad)
    if (
        array.split is not None
        and array.comm.is_distributed()
        and np.isscalar(constant_values)
    ):
        from ._movement import pad_padded

        buf, out_shape = pad_padded(
            array.larray, array.gshape, array.split, np_pad, mode, constant_values, array.comm
        )
        return DNDarray._from_buffer(
            buf, out_shape, types.canonical_heat_type(buf.dtype), array.split,
            array.device, array.comm,
        )
    if mode == "constant":
        result = jnp.pad(array._logical(), np_pad, mode=mode, constant_values=constant_values)
    else:
        result = jnp.pad(array._logical(), np_pad, mode=mode)
    return _wrap(result, array, array.split)


def ravel(a: DNDarray) -> DNDarray:
    """Flatten (reference ``manipulations.py``); no-copy views are not a TPU
    concept, XLA decides."""
    return flatten(a)


def redistribute(arr: DNDarray, lshape_map=None, target_map=None) -> DNDarray:
    """Out-of-place redistribute (reference ``manipulations.py:1513``); see
    :meth:`DNDarray.redistribute_` for layout semantics on TPU."""
    out = arr.copy()
    out.redistribute_(lshape_map=lshape_map, target_map=target_map)
    return out


def repeat(a: DNDarray, repeats, axis: Optional[int] = None) -> DNDarray:
    """Repeat elements (reference ``manipulations.py``)."""
    if isinstance(repeats, DNDarray):
        # the reference rejects non-integer DNDarray repeats with a clear
        # error instead of surfacing the backend's shape-dtype complaint
        if not (
            types.issubdtype(repeats.dtype, types.integer)
            or repeats.dtype is types.bool
        ):
            raise TypeError(
                f"invalid dtype for repeats: {repeats.dtype.__name__}, must be integer"
            )
        if repeats.ndim != 1:
            raise ValueError(
                f"repeats must be a 1d-object or integer, but was {repeats.ndim}-dimensional"
            )
        if repeats.gshape[0] == 0:
            raise ValueError("repeats must contain data")
        repeats = repeats._logical().astype(jnp.int64)
    elif isinstance(repeats, (list, tuple, np.ndarray)):
        # the reference accepts sequence repeats (torch.repeat_interleave)
        # — integers and booleans — but rejects floats/strings rather
        # than truncating them
        # the reference's sanitation order differs per container: for an
        # np.ndarray the DTYPE is checked first (can_cast to int64), so an
        # empty or 2-D float ndarray raises TypeError; for a list/tuple a
        # per-element isinstance(int) check runs first, which an empty
        # list vacuously passes (ValueError "contain data" follows)
        if isinstance(repeats, np.ndarray):
            # bool casts safely to int64; uint64 does not (values >= 2**63
            # would wrap negative under the int64 cast)
            if not np.can_cast(repeats.dtype, np.int64):
                raise TypeError(
                    f"all components of repeats must be integers, got {repeats.dtype}"
                )
            arr = repeats
        else:
            # strict Python-int check like the reference's list branch
            # (numpy scalars fail isinstance(r, int) there too); bools are
            # int subclasses and accepted
            if not all(isinstance(r, int) for r in repeats):
                raise TypeError("all components of repeats must be integers")
            try:
                arr = np.asarray(repeats, dtype=np.int64)
            except OverflowError:
                raise TypeError(
                    "all components of repeats must be integers representable as int64"
                ) from None
        if arr.size == 0:
            raise ValueError("repeats must contain data")
        if arr.ndim != 1:
            raise ValueError(
                f"repeats must be a 1d-object or integer, but was {arr.ndim}-dimensional"
            )
        repeats = jnp.asarray(arr.astype(np.int64, copy=False))
    result = jnp.repeat(a._logical(), repeats, axis=axis)
    if axis is None:
        split = 0 if a.split is not None else None
    else:
        split = a.split
    return _wrap(result, a, split)


def reshape(a: DNDarray, *shape, new_split: Optional[int] = None, **kwargs) -> DNDarray:
    """Reshape (reference ``manipulations.py:1821`` — an Alltoallv global
    reshuffle; one jnp.reshape with output resharding here)."""
    if not isinstance(a, DNDarray):
        raise TypeError(f"'a' must be a DNDarray, currently {type(a)}")
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = list(shape)
    # resolve -1 placeholder
    neg = [i for i, s in enumerate(shape) if s == -1]
    if len(neg) > 1:
        raise ValueError("can only specify one unknown dimension")
    if neg:
        known = int(np.prod([s for s in shape if s != -1])) or 1
        shape[neg[0]] = a.size // known
    shape = sanitize_shape(shape)
    if int(np.prod(shape)) != a.size:
        raise ValueError(f"cannot reshape array of size {a.size} into shape {tuple(shape)}")
    if new_split is None:
        new_split = a.split if a.split is not None and a.split < len(shape) else (0 if a.split is not None else None)
    new_split = sanitize_axis(shape, new_split)
    if a.split is None and new_split is None:
        return _wrap(jnp.reshape(a._logical(), shape), a, None)
    # distributed: one jitted program (unpad -> reshape -> repad) with
    # pinned in/out shardings — GSPMD emits the bounded collective-permute
    # exchange (the reference's Alltoallv, manipulations.py:1821); proven
    # in tests/test_distribution_proofs.py
    from ._movement import reshape_padded

    buf = reshape_padded(a.larray, a.gshape, a.split, shape, new_split, a.comm)
    return DNDarray._from_buffer(
        buf, tuple(shape), a.dtype, new_split, device=a.device, comm=a.comm
    )


def resplit(arr: DNDarray, axis: Optional[int] = None) -> DNDarray:
    """Out-of-place resplit (reference ``manipulations.py:3329`` — the
    None-target was an Allgatherv, split->split an Isend/Irecv tile mesh;
    one device_put here, XLA picks all-gather or all-to-all on ICI)."""
    return arr.resplit(axis)


def roll(x: DNDarray, shift, axis=None) -> DNDarray:
    """Circular shift (reference ``manipulations.py:1989`` — rank-to-rank
    sends there). Distributed arrays run as one pinned pipeline so the
    shifted ownership compiles to collective permutes (proof-tested)."""
    if x.split is not None and x.comm.is_distributed():
        from ._movement import roll_padded

        key_shift = shift if isinstance(shift, int) else tuple(int(s) for s in np.atleast_1d(shift))
        key_axis = axis if axis is None or isinstance(axis, int) else tuple(axis)
        buf = roll_padded(x.larray, x.gshape, x.split, key_shift, key_axis, x.comm)
        return DNDarray._from_buffer(buf, x.gshape, x.dtype, x.split, x.device, x.comm)
    result = jnp.roll(x._logical(), shift, axis=axis)
    return _wrap(result, x, x.split)


def rot90(m: DNDarray, k: int = 1, axes=(0, 1)) -> DNDarray:
    """Rotate in the plane of two axes (reference ``manipulations.py``)."""
    result = jnp.rot90(m._logical(), k=k, axes=axes)
    split = m.split
    if split in axes and k % 4 != 0:
        if k % 2 == 1:
            split = axes[1] if split == axes[0] else axes[0]
    return _wrap(result, m, split)


def shape(a: DNDarray) -> Tuple[int, ...]:
    return a.shape


def sort(a: DNDarray, axis: int = -1, descending: bool = False, out=None):
    """Sort along an axis (reference ``manipulations.py:2267`` implements a
    parallel sample-sort with Alltoallv bucket exchange).

    When the sort axis IS the split axis, a true distributed sort runs:
    block odd-even transposition over ``ppermute`` with O(n/P) memory per
    device (see :mod:`heat_tpu.parallel.dsort` — ``jnp.sort`` on a sharded
    axis would all-gather instead). Any other axis is embarrassingly
    parallel and sorts shard-locally."""
    axis = sanitize_axis(a.shape, axis)
    if (
        a.split == axis
        and a.comm.size > 1
        and not types.issubdtype(a.dtype, types.complexfloating)
    ):
        from ..parallel.dsort import distributed_sort

        vals, idxs = distributed_sort(a.larray, a.gshape, axis, a.comm, descending)
        res_v = DNDarray._from_buffer(vals, a.gshape, a.dtype, a.split, a.device, a.comm)
        res_i = DNDarray._from_buffer(
            idxs.astype(jnp.int64), a.gshape, types.int64, a.split, a.device, a.comm
        )
    else:
        arr = a._logical()
        indices = jnp.argsort(arr, axis=axis, descending=descending, stable=True)
        values = jnp.take_along_axis(arr, indices, axis=axis)
        res_v = _wrap(values, a, a.split)
        res_i = DNDarray(indices.astype(jnp.int64), dtype=types.int64, split=a.split, device=a.device, comm=a.comm)
    if out is not None:
        from ._operations import _write_out

        _write_out(out, res_v)
        return out, res_i
    return res_v, res_i


def split(x: DNDarray, indices_or_sections, axis: int = 0) -> List[DNDarray]:
    """Split into sub-arrays (reference ``manipulations.py``)."""
    axis = sanitize_axis(x.shape, axis)
    if isinstance(indices_or_sections, DNDarray):
        indices_or_sections = indices_or_sections.tolist()
    if isinstance(indices_or_sections, (list, tuple, np.ndarray)):
        parts = jnp.split(x._logical(), np.asarray(indices_or_sections, dtype=np.int64), axis=axis)
    else:
        parts = jnp.split(x._logical(), int(indices_or_sections), axis=axis)
    return [_wrap(p, x, x.split) for p in parts]


def squeeze(x: DNDarray, axis=None) -> DNDarray:
    """Remove size-1 dimensions (reference ``manipulations.py``)."""
    if axis is not None:
        axis = sanitize_axis(x.shape, axis)
        axes = (axis,) if isinstance(axis, int) else axis
        for ax in axes:
            if x.shape[ax] != 1:
                raise ValueError(f"cannot select an axis to squeeze out which has size not equal to one, got axis {ax}")
    else:
        axes = tuple(i for i, s in enumerate(x.shape) if s == 1)
    result = jnp.squeeze(x._logical(), axis=axes if axes else None)
    split = x.split
    if split is not None:
        if split in axes:
            split = None
        else:
            split -= sum(1 for ax in axes if ax < split)
    return _wrap(result, x, split)


def stack(arrays: Sequence[DNDarray], axis: int = 0, out=None) -> DNDarray:
    """Join along a new axis (reference ``manipulations.py``)."""
    dnd = [a if isinstance(a, DNDarray) else DNDarray(jnp.asarray(a)) for a in arrays]
    result = jnp.stack([a._logical() for a in dnd], axis=axis)
    base_split = next((a.split for a in dnd if a.split is not None), None)
    split = None
    if base_split is not None:
        axis_n = axis if axis >= 0 else axis + result.ndim
        split = base_split + (1 if axis_n <= base_split else 0)
    res = _wrap(result, dnd[0], split)
    if out is not None:
        from ._operations import _write_out

        return _write_out(out, res)
    return res


def swapaxes(x: DNDarray, axis1: int, axis2: int) -> DNDarray:
    """Swap two axes (reference ``manipulations.py``)."""
    from .linalg import transpose

    order = list(range(x.ndim))
    axis1 = sanitize_axis(x.shape, axis1)
    axis2 = sanitize_axis(x.shape, axis2)
    order[axis1], order[axis2] = order[axis2], order[axis1]
    return transpose(x, order)


def tile(x: DNDarray, reps) -> DNDarray:
    """Tile an array (reference ``manipulations.py``)."""
    if isinstance(reps, DNDarray):
        reps = reps.tolist()
    result = jnp.tile(x._logical(), reps)
    split = x.split
    if split is not None:
        split += result.ndim - x.ndim
    return _wrap(result, x, split)


def topk(a: DNDarray, k: int, dim: int = -1, largest: bool = True, sorted: bool = True, out=None):
    """Top-k values and indices (reference ``manipulations.py:3834``).

    Along the split axis of a multi-device array this runs the
    O(P*k)-traffic shard_map kernel (:mod:`heat_tpu.parallel.dtopk`) —
    the reference's custom ``mpi_topk`` reduction — instead of
    ``lax.top_k`` on the logical view, which GSPMD compiles to a full
    all-gather. The reduced result is re-split like the reference's
    ``factories.array(gres, split=a.split)``."""
    dim = sanitize_axis(a.shape, dim)
    if k > a.shape[dim]:
        raise ValueError(
            f"selected index k={k} out of range for dimension of size {a.shape[dim]}"
        )
    if dim == a.split and a.comm.size > 1:
        from ..parallel.dtopk import distributed_topk

        values, indices = distributed_topk(
            a.larray, a.gshape, dim, k, a.comm, largest=largest
        )
    else:
        arr = a._logical()
        moved = jnp.moveaxis(arr, dim, -1)
        if largest:
            values, indices = jax.lax.top_k(moved, k)
        else:
            values, indices = jax.lax.top_k(-moved, k)
            values = -values
        values = jnp.moveaxis(values, -1, dim)
        indices = jnp.moveaxis(indices, -1, dim)
    split = a.split
    res_v = _wrap(values, a, split)
    res_i = DNDarray(indices.astype(jnp.int64), dtype=types.int64, split=split, device=a.device, comm=a.comm)
    if out is not None:
        _write = __import__("heat_tpu.core._operations", fromlist=["_write_out"])._write_out
        _write(out[0], res_v)
        _write(out[1], res_i)
        return out
    return res_v, res_i


def unfold(a: DNDarray, axis: int, size: int, step: int = 1) -> DNDarray:
    """Sliding windows along an axis (reference ``manipulations.py`` unfold;
    torch.Tensor.unfold semantics: window dim appended last)."""
    axis = sanitize_axis(a.shape, axis)
    if size < 1 or step < 1:
        raise ValueError(f"size and step must be >= 1, got {size}, {step}")
    length = a.shape[axis]
    if size > length:
        raise ValueError(f"size {size} exceeds dimension {length}")
    if a.split is not None and a.comm.size > 1:
        # one jitted sharded program of static strided slices — GSPMD
        # keeps it at O(n/P) per device with collective-permutes only
        # (the vmap-of-dynamic-slice form all-gathers; HLO-proven in
        # tests/test_distribution_proofs.py)
        from ._movement import unfold_padded

        buf, out_shape = unfold_padded(
            a.larray, a.gshape, a.split, axis, size, step, a.comm
        )
        return DNDarray._from_buffer(
            buf, out_shape, a.dtype, a.split, device=a.device, comm=a.comm
        )
    n_windows = (length - size) // step + 1
    starts = jnp.arange(n_windows) * step
    moved = jnp.moveaxis(a._logical(), axis, 0)
    windows = jax.vmap(lambda s: jax.lax.dynamic_slice_in_dim(moved, s, size, axis=0))(starts)
    # windows: (n_windows, size, ...) -> restore axis order, window dim last
    windows = jnp.moveaxis(windows, 1, -1)  # (n_windows, ..., size)
    result = jnp.moveaxis(windows, 0, axis)
    # windows stay distributed along the unfolded axis
    return _wrap(result, a, a.split)


def unique(a: DNDarray, sorted: bool = False, return_inverse: bool = False, axis: Optional[int] = None):
    """Unique elements (reference ``manipulations.py:3055``: local
    ``torch.unique`` per rank, Allgatherv of the *deduplicated candidates*,
    then a final re-unique — never a gather of the raw data).

    Same shape here: the per-device dedup runs as ONE compiled shard_map
    scan for the flat case (:mod:`heat_tpu.parallel.dscan` — candidates
    compacted to an O(block) buffer + counts, the dtopk output pattern;
    round 3's host loop over shards serialized P dispatches), only the
    per-shard candidate sets travel to the host for the final merge, and
    the inverse map is recovered with a replicated ``searchsorted``
    against the merged table instead of gathering the input. Per-device
    temp stays O(shard); host temp is the candidate union (worst case
    O(n), exactly the reference's Allgatherv bound)."""
    if axis is not None:
        axis = sanitize_axis(a.shape, axis)
    distributed = a.split is not None and a.comm.size > 1
    flat_case = axis is None
    rows_case = axis is not None and axis == a.split
    local_first = distributed and (flat_case or (rows_case and not return_inverse))
    if local_first:
        if flat_case and not types.issubdtype(a.dtype, types.complexfloating):
            from ..parallel.dscan import unique_scan

            cands = unique_scan(a.larray, a.split, a.gshape[a.split], a.comm)
        else:
            # axis-unique (and complex, which jnp.sort orders differently
            # than np.unique's lexicographic rule): per-shard eager dedup
            cands = []
            for shard in a.local_shards:
                if shard.size == 0:
                    continue
                cands.append(np.asarray(jnp.unique(shard, axis=axis)))
        if cands:
            merged = np.unique(np.concatenate(cands, axis=0 if flat_case else axis), axis=axis)
        else:
            eshape = (0,) if flat_case else tuple(
                0 if d == axis else s for d, s in enumerate(a.shape)
            )
            merged = np.empty(eshape, dtype=np.dtype(a.larray.dtype))
        if jax.process_count() > 1:
            # exchange only the deduplicated candidate sets across hosts
            # (the reference's Allgatherv of local uniques) — local_shards
            # covers this process's devices only, and every process must
            # agree on the result
            merged = _merge_unique_across_processes(merged, axis if not flat_case else None)
        vals = jnp.asarray(merged)
        if return_inverse:
            # merged is sorted: positions via searchsorted against the
            # replicated table — O(U + shard) per device, no gather
            inverse = jnp.searchsorted(vals, a._logical().ravel()).reshape(a.shape)
    else:
        if return_inverse:
            vals, inverse = jnp.unique(a._logical(), return_inverse=True, axis=axis)
        else:
            vals = jnp.unique(a._logical(), axis=axis)
    split = 0 if a.split is not None else None
    res = DNDarray(vals, dtype=a.dtype, split=split, device=a.device, comm=a.comm)
    if return_inverse:
        return res, DNDarray(inverse.astype(jnp.int64), dtype=types.int64, split=None, device=a.device, comm=a.comm)
    return res


def vsplit(x: DNDarray, indices_or_sections) -> List[DNDarray]:
    return split(x, indices_or_sections, 0)


def vstack(arrays: Sequence[DNDarray]) -> DNDarray:
    dnd = [a if isinstance(a, DNDarray) else DNDarray(jnp.asarray(a)) for a in arrays]
    dnd = [a if a.ndim > 1 else reshape(a, (1, a.shape[0])) for a in dnd]
    return concatenate(dnd, axis=0)
