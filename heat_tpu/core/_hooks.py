"""Fault-injection hook points (consumed by :mod:`heat_tpu.resilience.chaos`).

Production code calls :func:`fault_point` at the places where real
deployments fail — file opens/writes/commits in :mod:`heat_tpu.core.io`,
shard assembly and host allgathers in :mod:`heat_tpu.core.communication`,
checkpoint shard serialization — and the call is a no-op unless an
injector has been installed. ``resilience.chaos(...)`` installs a seeded
injector for the duration of a ``with`` block, which lets every recovery
path (retry, atomic rename, checksum verification) be exercised
deterministically on CPU.

This module is dependency-free on purpose: ``core`` must not import
``resilience`` at module scope (resilience sits above core), so the
registry lives down here and chaos reaches down to install itself.
"""
from __future__ import annotations

import threading as _threading
from typing import Callable, Dict, Optional

# the active injector: fn(name, ctx) -> None, may raise to simulate a
# fault and may mutate ``ctx`` values in place (e.g. corrupt a byte
# buffer). None means fault injection is off (the production state).
_INJECTOR: Optional[Callable[[str, Dict], None]] = None


def set_injector(injector: Optional[Callable[[str, Dict], None]]):
    """Install (or with ``None`` remove) the process-wide fault injector.

    Returns the previous injector so callers can restore it (the chaos
    context manager nests correctly).
    """
    global _INJECTOR
    prev = _INJECTOR
    _INJECTOR = injector
    return prev


def get_injector() -> Optional[Callable[[str, Dict], None]]:
    return _INJECTOR


def fault_point(name: str, **ctx) -> Dict:
    """Declare a fault-injection site.

    ``name`` is a dotted site id (``"io.open"``, ``"io.commit"``,
    ``"collective.assemble"``, ``"checkpoint.shard_bytes"``,
    ``"supervisor.step"`` — the last fires before every supervised step,
    the injection point for step-level faults including simulated device
    loss). The installed injector may raise (OSError, TimeoutError, ...)
    to simulate a failure at this site, or mutate mutable ``ctx`` entries
    (e.g. a ``bytearray`` payload) to simulate corruption. Returns ``ctx``
    so call sites can read mutated values back.
    """
    if _OBSERVERS:
        # the existing fault sites double as instrumentation points: every
        # collective/io/checkpoint site is reported to passive observers
        # (see ``observe`` below) before any injected fault can fire
        for fn in tuple(_OBSERVERS):
            fn(name, ctx)
    if _INJECTOR is not None:
        _INJECTOR(name, ctx)
    return ctx


# the active deadline runner: fn(label, callable, args, kwargs) -> result.
# None (the production default) means blocking host-side paths run inline
# with zero overhead; ``resilience.watchdog.deadlines(...)`` installs a
# runner that bounds each labeled call and raises CollectiveTimeout
# instead of hanging forever. Same layering trick as the injector: the
# slot lives down here so core never imports resilience.
_DEADLINE_RUNNER = None


def set_deadline_runner(runner):
    """Install (or with ``None`` remove) the process-wide deadline runner.

    Returns the previous runner so contexts nest correctly.
    """
    global _DEADLINE_RUNNER
    prev = _DEADLINE_RUNNER
    _DEADLINE_RUNNER = runner
    return prev


def get_deadline_runner():
    return _DEADLINE_RUNNER


def guarded_call(label: str, fn, *args, **kwargs):
    """Run a blocking host-side operation under the active deadline runner.

    ``label`` names the operation in any timeout raised
    (``"collective.assemble"``, ``"flatmove.ragged"``, ...). With no
    runner installed this is a direct call — the hot path pays one global
    read and nothing else.
    """
    if _DEADLINE_RUNNER is None:
        return fn(*args, **kwargs)
    return _DEADLINE_RUNNER(label, fn, args, kwargs)


# trace-safe mode: a PER-THREAD depth counter armed by the lazy-fusion
# subsystem (:mod:`heat_tpu.core.lazy`) while it replays DNDarray ops under
# a jax trace (``jax.eval_shape`` metadata probes and the fused-program
# ``jax.jit``). Two effects, both consulted from core with one integer
# read: placement helpers (``dndarray._place`` / ``_from_ragged``) skip
# ``jax.device_put`` — tracers cannot be placed, shardings are pinned via
# the jit's ``out_shardings`` instead — and host-side data movement
# (``balance_``, ``flatmove.ragged_move``) raises :class:`TraceBarrierError`
# so an op that would need a collective exchange under trace is declined
# at capture time rather than miscompiled. Same layering trick as the
# slots above: the flag lives down here so core never imports the lazy
# package at module scope. The depth is THREAD-LOCAL: a serving
# dispatcher thread replaying a fused program must not flip eager
# client threads into trace-safe mode (and vice versa) — each thread
# carries its own capture/replay state.
_TRACE_SAFE = _threading.local()


class TraceBarrierError(RuntimeError):
    """Raised by host-side data-movement paths entered under trace-safe
    mode — the signal that an op cannot be captured into a fused program
    and must take the eager path instead."""


def enter_trace_safe() -> None:
    _TRACE_SAFE.depth = getattr(_TRACE_SAFE, "depth", 0) + 1


def exit_trace_safe() -> None:
    _TRACE_SAFE.depth = getattr(_TRACE_SAFE, "depth", 0) - 1


def in_trace_safe() -> bool:
    """True while lazy fusion is replaying ops under a jax trace (on the
    CALLING thread; other threads' replays are invisible here)."""
    return getattr(_TRACE_SAFE, "depth", 0) > 0


def trace_barrier(label: str) -> None:
    """Declare a host-side data-movement site that cannot run under a jax
    trace (``"balance_"``, ``"ragged_move"``, ...). No-op in normal eager
    execution; under trace-safe mode raises :class:`TraceBarrierError` so
    the lazy capture layer falls back to eager for the offending op."""
    if getattr(_TRACE_SAFE, "depth", 0) > 0:
        raise TraceBarrierError(
            f"{label} moves data host-side and cannot run under a jax trace"
        )


# passive event observers: fn(event, ctx) -> None, must not raise. Unlike
# the injector (which simulates faults) and the deadline runner (which
# bounds calls), observers only *record*: ``analysis.sanitizer`` registers
# one to attribute cache insertions, host transfers, and collective
# dispatches to a code region, and ``analysis.lockstep`` registers one to
# digest the ORDER of ``collective.*`` sites (observers fire before any
# injected fault, so a chaos-dropped event was recorded first — the
# property the ``lockstep_divergence`` fault kind relies on). Same
# layering trick again — the list lives down here so core never imports
# analysis.
_OBSERVERS = []


def add_observer(fn):
    """Register a process-wide event observer; returns ``fn``."""
    _OBSERVERS.append(fn)
    return fn


def remove_observer(fn):
    """Remove a previously registered observer (no error if absent)."""
    try:
        _OBSERVERS.remove(fn)
    except ValueError:
        pass


def observe(event: str, **ctx) -> None:
    """Report an instrumentation event (``"cache.insert"``,
    ``"host.gather"``, ... — plus the ``"recovery.*"`` family emitted by
    :mod:`heat_tpu.resilience.supervisor`, which its ``RECOVERY_STATS``
    observer counts, and the ``"stream.*"`` family — ``stream.chunk``
    (``rows``, ``nbytes``), ``stream.prefetch_hit``, ``stream.stall``,
    ``stream.overlap`` (``seconds``) — emitted by the chunked pipeline
    layer and folded into ``STREAM_STATS`` by
    :mod:`heat_tpu.stream._stats`). Free when no observer is installed:
    one falsy check on the hot path."""
    if _OBSERVERS:
        for fn in tuple(_OBSERVERS):
            fn(event, ctx)
