"""Generic operation machinery (reference ``heat/core/_operations.py``).

The reference's quartet — ``__binary_op`` / ``__local_op`` / ``__reduce_op``
/ ``__cum_op`` — mixed dtype promotion with hand-written communication
(operand redistribution, Allreduce of partials, Exscan prefix carries).
On TPU the communication half vanishes: every function here applies a
``jax.numpy`` op to global arrays and lets GSPMD insert collectives. What
remains is exactly the *semantic* layer:

- numpy/heat type-promotion (reference ``_operations.py:42-77``),
- broadcast + split-axis compatibility and propagation,
- reduction split bookkeeping (reference ``_operations.py:462-472``),
- **padding discipline**: buffers are tail-padded along the split axis
  (see :mod:`heat_tpu.core.dndarray`), so binary ops align operand buffers,
  and reductions that cross the split axis mask the padding with the op's
  neutral element (the analogue of the reference's neutral-element fill for
  empty shards, ``_operations.py:424-436``),
- **ragged discipline**: arrays left in a ragged layout by ``redistribute_``
  (per-shard ``lcounts``, data at offset 0 of each block) compute in place,
  like the reference's unbalanced arrays (``_operations.py:72-77``) — the
  invalid region of each block is masked exactly like tail padding (valid
  iff ``pos % block < lcounts[pos // block]``). Binary operands with
  identical ``lcounts`` compute directly; mismatched layouts align with ONE
  bounded ``flatmove`` exchange into the first ragged operand's layout
  (cheaper than rebalancing both); results inherit the ragged layout.
  ``balance_()`` is reserved for ops that need the canonical ceil-div map.
- ``out=`` rewriting.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = [
    "_binary_op",
    "_local_op",
    "_reduce_op",
    "_cum_op",
    "_mask_padding",
    "_mask_ragged",
    "_ragged_valid_mask",
]

Scalar = (int, float, bool, complex, np.number, np.bool_)

# Capture hook for the lazy-fusion subsystem: heat_tpu.core.lazy installs
# its capture module here on import. While a ht.lazy() scope is open the
# dispatchers below offer each call for capture first; NotImplemented
# means "not capturable — run eagerly". None (the default, and whenever
# the lazy package is absent) keeps dispatch on the plain eager path.
_capture = None


def _as_dndarray(x, device=None, comm=None) -> DNDarray:
    from . import factories

    if isinstance(x, DNDarray):
        return x
    return factories.array(x, device=device, comm=comm)


def _out_split_after_broadcast(ndim_out: int, operand: DNDarray) -> Optional[int]:
    """Where an operand's split axis lands in the broadcast output."""
    if operand.split is None:
        return None
    return operand.split + (ndim_out - operand.ndim)


def _neutral_value(neutral, dtype):
    """Resolve a neutral-element spec to a concrete scalar for ``dtype``.

    ``neutral`` may be a scalar; one of the strings ``"min"``/``"max"``
    (the dtype's most negative / most positive value — the identity of
    max/min reductions); ``"nan"`` (the ignored element of the jnp.nan*
    reductions, inexact dtypes only); or a pair ``(inexact_spec, int_spec)``
    choosing by dtype class (e.g. ``("nan", 0)`` for nansum, where integer
    inputs degenerate to a plain sum). Returns None when the spec has no
    value for this dtype — the caller then reduces the exact logical array.
    """
    d = jnp.dtype(dtype)
    if isinstance(neutral, tuple):
        neutral = neutral[0] if jnp.issubdtype(d, jnp.inexact) else neutral[1]
    if isinstance(neutral, str):
        if neutral == "nan":
            return jnp.nan if jnp.issubdtype(d, jnp.inexact) else None
        if jnp.issubdtype(d, jnp.inexact):
            return -jnp.inf if neutral == "min" else jnp.inf
        if d == np.bool_:
            return neutral == "max"
        info = jnp.iinfo(d)
        return info.min if neutral == "min" else info.max
    return neutral


def _mask_padding(buffer: jax.Array, gshape, split: int, fill) -> jax.Array:
    """Overwrite the tail padding along ``split`` with ``fill``."""
    n = gshape[split]
    if buffer.shape[split] == n:
        return buffer
    fill = _neutral_value(fill, buffer.dtype)
    if fill is None:
        raise ValueError("no neutral value for this dtype; reduce the logical array instead")
    iota = jax.lax.broadcasted_iota(jnp.int32, buffer.shape, split)
    return jnp.where(iota < n, buffer, jnp.asarray(fill, dtype=buffer.dtype))


# --------------------------------------------------------------- ragged layout
def _ragged_layout(x) -> Optional[Tuple[int, Tuple[int, ...]]]:
    """``(block, lcounts)`` of a ragged-layout DNDarray, else None.

    Hashable on purpose: the pair keys the jitted-reduce cache, so every
    distinct ragged map compiles once and is reused (no per-call closures
    — the statistics.py recompile bug must stay dead)."""
    lcounts = getattr(x, "lcounts", None)
    if lcounts is None:
        return None
    return (x._raw.shape[x.split] // x.comm.size, tuple(lcounts))


def _ragged_valid_mask(shape, split: int, block: int, lcounts) -> jax.Array:
    """Boolean buffer-shaped mask of the VALID positions of a ragged
    layout: position ``k`` of block ``r`` is valid iff ``k < lcounts[r]``.
    Traceable (``lcounts`` is a static tuple, so the limits are an XLA
    constant); the generalization of the tail-padding ``iota < n`` test to
    per-block valid extents."""
    iota = jax.lax.broadcasted_iota(jnp.int32, shape, split)
    limits = jnp.take(jnp.asarray(lcounts, dtype=jnp.int32), iota // block)
    return (iota % block) < limits


def _mask_ragged(buffer: jax.Array, split: int, block: int, lcounts, fill) -> jax.Array:
    """Overwrite the ragged-invalid region of every block with ``fill``
    (the ragged analogue of :func:`_mask_padding`)."""
    fill = _neutral_value(fill, buffer.dtype)
    if fill is None:
        raise ValueError("no neutral value for this dtype on a ragged layout")
    mask = _ragged_valid_mask(buffer.shape, split, block, lcounts)
    return jnp.where(mask, buffer, jnp.asarray(fill, dtype=buffer.dtype))


def _aligned_operand_buffer(
    op: DNDarray, jt, out_shape, out_split: Optional[int], out_pshape
) -> jax.Array:
    """Operand buffer cast to ``jt`` and physically broadcast-compatible
    with the (possibly padded) output buffer."""
    buf = op.larray.astype(jt)
    if out_split is None or out_shape == tuple(out_pshape):
        # unpadded output: any padded operand must be trimmed (only happens
        # for a size-1 split dim padded to the mesh size)
        return op._logical().astype(jt) if op.padded else buf
    j = out_split - (len(out_shape) - op.ndim)
    if j < 0:
        return buf  # operand has no dim at the output split axis
    d = op.gshape[j]
    if d == 1:
        # broadcasts against the padded extent; drop any padding of its own
        return op._logical().astype(jt) if op.padded else buf
    if op.split == j:
        return buf  # padded identically to the output by construction
    # replicated (or differently laid out) operand at full logical extent:
    # zero-pad to the output's buffer extent
    pad = [(0, 0)] * op.ndim
    pad[j] = (0, out_pshape[out_split] - d)
    base = op._logical() if op.padded else op.larray
    return jnp.pad(base.astype(jt), pad)


def _ragged_aligned_buffer(
    op: DNDarray, jt, out_shape, j: int, lcounts, block: int, comm
) -> Optional[jax.Array]:
    """Operand buffer cast to ``jt`` and broadcast-compatible with the
    target ragged layout ``(block, lcounts)`` at output axis ``j``.

    At most ONE bounded flatmove exchange (a split operand in a different
    layout); a replicated full-extent operand is re-indexed locally (its
    data is everywhere already — a constant gather, no collective).
    Returns None when the operand cannot be aligned cheaply (caller falls
    back to the canonical path)."""
    jo = j - (len(out_shape) - op.ndim)
    if jo < 0 or op.gshape[jo] == 1:
        # no dim / size-1 dim at the split axis: broadcasts against the
        # padded block extent untouched
        return (op._logical() if op.padded else op._raw).astype(jt)
    if op.gshape[jo] != out_shape[j]:  # pragma: no cover - defensive
        return None
    # ``lcounts`` here is REPLICATED layout metadata: the full per-shard
    # counts tuple is identical on every process (set at construction from
    # global layout decisions), so all ranks take the same branches and the
    # one-sided ragged_move below is dispatched by everyone or no one.
    # graftflow taints .lcounts by policy (user code can stuff
    # process-local counts into it) — this reviewed site is the sanctioned
    # exception.
    # graftflow: F001 - lcounts replicated by construction here
    if op.lcounts is not None:
        if op.split != jo:  # pragma: no cover - defensive
            return None  # graftflow: F004 - replicated lcounts, see block above
        own_block = op._raw.shape[jo] // comm.size
        if tuple(op.lcounts) == tuple(lcounts) and own_block == block:
            # identical layout: compute in place  # graftflow: F004 - replicated lcounts
            return op._raw.astype(jt)
        from ..parallel.flatmove import ragged_move

        return ragged_move(  # graftflow: F004 - replicated lcounts, see block above
            op._raw, jo, op.lcounts, lcounts, block, comm
        ).astype(jt)
    if op.split == jo:
        # canonical split operand — a canonical buffer IS a ragged layout
        # (ceil-div counts, data at offset 0 per block): one exchange
        from ..parallel.flatmove import ragged_move

        counts = tuple(comm.counts_displs_shape(op.gshape, jo)[0])
        return ragged_move(op._raw, jo, counts, lcounts, block, comm).astype(jt)
    if op.split is not None:  # pragma: no cover - defensive (sa == sb checked)
        return None
    # replicated at full extent: scatter the logical rows into the target
    # block layout with a constant index map (local gather, no collective)
    n = op.gshape[jo]
    displs = np.concatenate([[0], np.cumsum(lcounts)[:-1]])
    rows = np.concatenate(
        [
            displs[r] + np.minimum(np.arange(block), max(int(lcounts[r]) - 1, 0))
            for r in range(comm.size)
        ]
    )
    rows = np.clip(rows, 0, n - 1)
    return jnp.take(op._logical().astype(jt), jnp.asarray(rows), axis=jo)


def _ragged_binary(
    operation, a: DNDarray, b: DNDarray, out_shape, j: int, jt, device, comm, fn_kwargs
) -> Optional[DNDarray]:
    """Binary op computed directly in a ragged layout (no rebalance).

    The target layout is the first ragged operand's (its ``lcounts``
    survive into the result); the other operand aligns with at most one
    bounded exchange. Returns None when the pair needs the canonical
    path."""
    target = a if a.lcounts is not None else b
    jt_axis = j - (len(out_shape) - target.ndim)
    if jt_axis < 0 or target.gshape[jt_axis] != out_shape[j]:
        return None  # ragged operand broadcasts at the split axis: rare, rebalance
    lcounts = tuple(target.lcounts)
    block = target._raw.shape[target.split] // comm.size
    buf_a = _ragged_aligned_buffer(a, jt, out_shape, j, lcounts, block, comm)
    buf_b = _ragged_aligned_buffer(b, jt, out_shape, j, lcounts, block, comm)
    if buf_a is None or buf_b is None:
        return None
    result = operation(buf_a, buf_b, **fn_kwargs)
    return DNDarray._from_ragged(
        result,
        out_shape,
        types.canonical_heat_type(result.dtype),
        j,
        lcounts,
        device,
        comm,
    )


def _write_out(out: DNDarray, result: DNDarray) -> DNDarray:
    """Rewrite ``out`` in place with ``result`` (reference out= semantics)."""
    if tuple(out.shape) != tuple(result.shape):
        raise ValueError(f"output shape {out.shape} does not match result shape {result.shape}")
    target_t = out.dtype.jax_type()
    if out.split == result.split:
        out._set_buffer(result.larray.astype(target_t), result.gshape)
    else:
        out.larray = result._logical().astype(target_t)
    return out


def _binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=True,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Apply a binary jnp op with heat promotion/broadcast/split rules
    (reference ``_operations.py:24-205``)."""
    if _capture is not None and _capture.active():
        res = _capture.binary(operation, t1, t2, out, where, fn_kwargs)
        if res is not NotImplemented:
            return res
    fn_kwargs = fn_kwargs or {}
    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(
            f"Only DNDarrays and numeric scalars are supported, but input was {type(t1)}, {type(t2)}"
        )
    anchor = t1 if isinstance(t1, DNDarray) else t2
    device, comm = anchor.device, anchor.comm
    if (
        isinstance(t1, DNDarray)
        and isinstance(t2, DNDarray)
        and t1.comm != t2.comm
    ):
        # the reference raises on mismatched communicators
        # (_operations.py binary path); relying on a sharding clash to
        # fail is world-size-dependent
        raise ValueError("operands live on different communicators")
    promoted = types.result_type(t1, t2)

    a = _as_dndarray(t1, device, comm)
    b = _as_dndarray(t2, device, comm)
    out_shape = broadcast_shape(a.shape, b.shape)
    ndim_out = len(out_shape)

    sa = _out_split_after_broadcast(ndim_out, a)
    sb = _out_split_after_broadcast(ndim_out, b)
    if sa is not None and sb is not None and sa != sb:
        raise ValueError(
            f"DNDarrays must have the same split axes, found {a.split} and {b.split}"
        )
    out_split = sa if sa is not None else sb
    out_pshape = comm.padded_shape(out_shape, out_split)

    jt = promoted.jax_type()
    if (
        out is None
        and where is True
        and out_split is not None
        and (a.lcounts is not None or b.lcounts is not None)
    ):
        # ragged fast path: compute in the ragged layout, no rebalance
        res = _ragged_binary(
            operation, a, b, out_shape, out_split, jt, device, comm, fn_kwargs
        )
        if res is not None:
            return res
    buf_a = _aligned_operand_buffer(a, jt, out_shape, out_split, out_pshape)
    buf_b = _aligned_operand_buffer(b, jt, out_shape, out_split, out_pshape)
    result = operation(buf_a, buf_b, **fn_kwargs)
    if where is not True:
        where_nd = _as_dndarray(where, device, comm)
        where_arr = _aligned_operand_buffer(
            where_nd, where_nd.dtype.jax_type(), out_shape, out_split, out_pshape
        )
        if out is not None:
            base = _aligned_operand_buffer(
                out, result.dtype, out_shape, out_split, out_pshape
            )
        else:
            base = jnp.zeros(out_pshape, dtype=result.dtype)
        result = jnp.where(where_arr, result, base)

    res = DNDarray._from_buffer(
        result,
        out_shape,
        types.canonical_heat_type(result.dtype),
        out_split,
        device,
        comm,
    )
    if out is not None:
        return _write_out(out, res)
    return res


def _local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    out_dtype=None,
    **kwargs,
) -> DNDarray:
    """Embarrassingly-parallel elementwise op (reference
    ``_operations.py:305-376``). Split, sharding, padding AND raggedness
    are inherited: the op runs on the stored buffer (pad / ragged-invalid
    content stays unspecified), so a ragged array never rebalances here."""
    if _capture is not None and _capture.active():
        res = _capture.local(operation, x, out, no_cast, out_dtype, kwargs)
        if res is not NotImplemented:
            return res
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    arr = x._raw if x.lcounts is not None else x.larray
    if not no_cast and not jnp.issubdtype(arr.dtype, jnp.inexact) and not jnp.issubdtype(
        arr.dtype, jnp.complexfloating
    ):
        # float-promoting math functions: int input -> float output
        if out_dtype is None:
            arr = arr.astype(types.promote_types(x.dtype, types.float32).jax_type())
    result = operation(arr, **kwargs)
    dtype = out_dtype if out_dtype is not None else types.canonical_heat_type(result.dtype)
    if x.lcounts is not None:
        if tuple(result.shape) != tuple(arr.shape):
            # shape-changing op: ragged block coordinates would be
            # misinterpreted — recompute through the canonical layout
            x.balance_()
            return _local_op(
                operation, x, out=out, no_cast=no_cast, out_dtype=out_dtype, **kwargs
            )
        res = DNDarray._from_ragged(
            result.astype(dtype.jax_type()),
            x.gshape, dtype, x.split, x.lcounts, x.device, x.comm,
        )
    elif tuple(result.shape) == x.pshape:
        res = DNDarray._from_buffer(
            result.astype(dtype.jax_type()), x.gshape, dtype, x.split, x.device, x.comm
        )
    else:
        res = DNDarray(
            result.astype(dtype.jax_type()),
            dtype=dtype,
            split=x.split if result.ndim == x.ndim else None,
            device=x.device,
            comm=x.comm,
        )
    if out is not None:
        return _write_out(out, res)
    return res


def _axis_key(axis):
    """Hashable form of a sanitized axis (int, None, or tuple)."""
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def _kwargs_key(kwargs: dict):
    """Hashable form of reduce kwargs, or None when unhashable."""
    try:
        return tuple(sorted((k, v) for k, v in kwargs.items()))
    except TypeError:
        return None


@lru_cache(maxsize=256)
def _jitted_reduce_cached(
    operation, axis, keepdims, pad_mode, pad_n, pad_split, fill, kwargs_items, ragged=None
):
    kwargs = dict(kwargs_items)

    fill_val = float("nan") if fill == "__nan__" else fill

    def run(arr):
        if pad_mode == "mask":
            iota = jax.lax.broadcasted_iota(jnp.int32, arr.shape, pad_split)
            arr = jnp.where(iota < pad_n, arr, jnp.asarray(fill_val, dtype=arr.dtype))
        elif pad_mode == "trim":
            sl = [slice(None)] * arr.ndim
            sl[pad_split] = slice(0, pad_n)
            arr = arr[tuple(sl)]
        elif pad_mode == "ragged_mask":
            block, lcounts = ragged
            mask = _ragged_valid_mask(arr.shape, pad_split, block, lcounts)
            arr = jnp.where(mask, arr, jnp.asarray(fill_val, dtype=arr.dtype))
        elif pad_mode == "ragged_where":
            # no neutral element (mean/std/var family): the op normalizes
            # by the selected count itself, so pass the validity mask
            block, lcounts = ragged
            mask = _ragged_valid_mask(arr.shape, pad_split, block, lcounts)
            return operation(arr, axis=axis, keepdims=keepdims, where=mask, **kwargs)
        return operation(arr, axis=axis, keepdims=keepdims, **kwargs)

    return jax.jit(run)


def _jitted_reduce(
    operation, axis, keepdims, pad_mode, pad_n, pad_split, fill, kwargs_items, ragged=None
):
    """Cached jitted reduce program, or None when any static is unhashable.

    A nan fill is tokenized ("__nan__") before keying: nan != nan would
    make every lookup miss and retrace.

    A closure created inside a function (``<locals>`` in its qualname)
    keys the cache by a fresh object identity on every call — each call
    recompiles AND permanently parks the dead executable in the cache.
    Those take the eager fallback instead, unless the caller hoisted the
    closure to module level and marked it ``_cache_stable = True`` (one
    identity forever — see ``statistics._NANPROP_MAX``)."""
    if kwargs_items is None:
        return None
    if "<locals>" in getattr(operation, "__qualname__", "") and not getattr(
        operation, "_cache_stable", False
    ):
        return None
    if isinstance(fill, float) and fill != fill:
        fill = "__nan__"
    try:
        return _jitted_reduce_cached(
            operation, axis, keepdims, pad_mode, pad_n, pad_split, fill, kwargs_items, ragged
        )
    except TypeError:
        return None


def _reduce_op(
    operation: Callable,
    x: DNDarray,
    axis=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    out_dtype=None,
    neutral=None,
    **kwargs,
) -> DNDarray:
    """Global reduction (reference ``_operations.py:379-505``).

    The reference computed a local partial then Allreduced with a custom MPI
    op when the split axis was reduced; XLA compiles ``jnp`` reductions over
    sharded inputs to the identical partial+all-reduce schedule on ICI.

    ``neutral`` is the op's identity element (scalar, ``"min"``/``"max"``,
    or ``"nan"``): tail padding is overwritten with it before reducing — the
    analogue of the reference's neutral fill for empty chunks
    (``_operations.py:424-436``). A padded input with no neutral given falls
    back to reducing the exact logical array.

    A ragged-layout input reduces IN PLACE, no rebalance: when the split
    axis is not reduced the op runs per-row and the result inherits the
    ragged layout; when it is reduced, ragged-invalid positions are masked
    with the neutral (``ragged_mask``) or, for the self-normalizing
    mean/std/var family with no neutral, excluded via the op's ``where=``
    mask (``ragged_where``). Both modes key the jitted cache by the
    hashable ``(block, lcounts)`` pair — one compile per ragged map.
    """
    if _capture is not None and _capture.active():
        res = _capture.reduce(operation, x, axis, out, keepdims, out_dtype, neutral, kwargs)
        if res is not NotImplemented:
            return res
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    axis = sanitize_axis(x.shape, axis)
    ragged = _ragged_layout(x)
    split_reduced = axis is None or (
        x.split in ((axis,) if isinstance(axis, int) else tuple(axis))
        if x.split is not None
        else False
    )
    if ragged is not None:
        arr = x._raw
        if not split_reduced:
            # per-row reduction: invalid rows stay garbage, result ragged
            pad_mode, pad_n, pad_split, fill = "none", 0, 0, None
            ragged = None
        else:
            fill = None if neutral is None else _neutral_value(neutral, arr.dtype)
            pad_mode = "ragged_mask" if fill is not None else "ragged_where"
            pad_n, pad_split = x.gshape[x.split], x.split
    else:
        arr = x.larray
        if x.padded:
            fill = None if neutral is None else _neutral_value(neutral, arr.dtype)
            pad_mode = "mask" if fill is not None else "trim"
            pad_n, pad_split = x.gshape[x.split], x.split
        else:
            pad_mode, pad_n, pad_split, fill = "none", 0, 0, None
    # One fused jitted program per (op, axis, padding) combination: the
    # composite reductions (std/var/nanmean) otherwise run as eager
    # per-primitive programs that materialize every (n, f) intermediate in
    # HBM — 3-4x the traffic of the fused program — and the padding
    # mask/trim fuses into the reduction read instead of writing a copy.
    fn = _jitted_reduce(
        operation, _axis_key(axis), keepdims, pad_mode, pad_n, pad_split,
        fill if pad_mode in ("mask", "ragged_mask") else None, _kwargs_key(kwargs),
        ragged,
    )
    try:
        if fn is not None:
            result = fn(arr)
        else:  # unhashable op/kwargs: eager fallback, semantics identical
            if pad_mode == "mask":
                arr = _mask_padding(arr, x.gshape, x.split, fill)
            elif pad_mode == "trim":
                arr = x._logical()
            elif pad_mode == "ragged_mask":
                arr = jnp.where(
                    _ragged_valid_mask(arr.shape, pad_split, ragged[0], ragged[1]),
                    arr,
                    jnp.asarray(fill, dtype=arr.dtype),
                )
            if pad_mode == "ragged_where":
                mask = _ragged_valid_mask(arr.shape, pad_split, ragged[0], ragged[1])
                result = operation(arr, axis=axis, keepdims=keepdims, where=mask, **kwargs)
            else:
                result = operation(arr, axis=axis, keepdims=keepdims, **kwargs)
    except TypeError:
        if pad_mode != "ragged_where":
            raise
        # op takes no where= mask: last resort, reduce the canonical
        # logical array (one rebalance — correctness over layout)
        result = operation(x._logical(), axis=axis, keepdims=keepdims, **kwargs)
    out_split = _reduced_split(x.split, axis, x.ndim, keepdims)
    dtype = out_dtype if out_dtype is not None else types.canonical_heat_type(result.dtype)
    result = jnp.asarray(result).astype(dtype.jax_type())
    out_gshape = _reduced_shape(x.gshape, axis, keepdims)
    if x.lcounts is not None and out_split is not None and not split_reduced:
        # split axis survives: the result keeps the ragged layout
        res = DNDarray._from_ragged(
            result, out_gshape, dtype, out_split, x.lcounts, x.device, x.comm
        )
    elif out_split is not None and tuple(result.shape) != out_gshape:
        res = DNDarray._from_buffer(result, out_gshape, dtype, out_split, x.device, x.comm)
    else:
        res = DNDarray(
            result, gshape=out_gshape, dtype=dtype, split=out_split,
            device=x.device, comm=x.comm,
        )
    if out is not None:
        return _write_out(out, res)
    return res


def _reduced_shape(gshape, axis, keepdims: bool) -> Tuple[int, ...]:
    """Logical shape after reducing ``axis``."""
    if axis is None:
        axes = tuple(range(len(gshape)))
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
    if keepdims:
        return tuple(1 if i in axes else s for i, s in enumerate(gshape))
    return tuple(s for i, s in enumerate(gshape) if i not in axes)


def _reduced_split(
    split: Optional[int], axis, ndim: int, keepdims: bool
) -> Optional[int]:
    """Output split of a reduction (reference ``_operations.py:462-472``)."""
    if split is None:
        return None
    if axis is None:
        return None
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    if split in axes:
        # reduced over the split axis -> every device holds the result
        return None
    if keepdims:
        return split
    return split - sum(1 for a in axes if a < split)


def _cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
    neutral=None,
) -> DNDarray:
    """Cumulative op along an axis (reference ``_operations.py:208-302``).

    The reference's local-cumop + ``Exscan`` + combine pattern is exactly
    what XLA generates for a cumulative op over a sharded axis; a single
    global ``jnp`` call suffices. Tail padding is harmless here: it sits
    strictly *after* every valid element along the split axis, so valid
    prefixes never include it.

    A ragged layout computes in place too: along a non-split axis the scan
    runs per-row; along the split axis the ragged-invalid slots are filled
    with the op's identity (``neutral``) first — block order restricted to
    valid positions IS logical order, so every valid prefix is exact.
    """
    if _capture is not None and _capture.active():
        res = _capture.cum(operation, x, axis, out, dtype, neutral)
        if res is not NotImplemented:
            return res
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative ops require an explicit axis")
    lcounts = x.lcounts
    if lcounts is not None and axis == x.split and neutral is None:
        x.balance_()  # no identity to fill invalid slots with
        lcounts = None
    arr = x._raw if lcounts is not None else x.larray
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        arr = arr.astype(dtype.jax_type())
    if lcounts is not None and axis == x.split:
        block = arr.shape[x.split] // x.comm.size
        arr = _mask_ragged(arr, x.split, block, lcounts, neutral)
    result = operation(arr, axis=axis)
    if lcounts is not None:
        res = DNDarray._from_ragged(
            result,
            x.gshape,
            types.canonical_heat_type(result.dtype),
            x.split,
            lcounts,
            x.device,
            x.comm,
        )
    else:
        res = DNDarray._from_buffer(
            result,
            x.gshape,
            types.canonical_heat_type(result.dtype),
            x.split,
            x.device,
            x.comm,
        )
    if out is not None:
        return _write_out(out, res)
    return res
