"""Generic operation machinery (reference ``heat/core/_operations.py``).

The reference's quartet — ``__binary_op`` / ``__local_op`` / ``__reduce_op``
/ ``__cum_op`` — mixed dtype promotion with hand-written communication
(operand redistribution, Allreduce of partials, Exscan prefix carries).
On TPU the communication half vanishes: every function here applies a
``jax.numpy`` op to global arrays and lets GSPMD insert collectives. What
remains is exactly the *semantic* layer:

- numpy/heat type-promotion (reference ``_operations.py:42-77``),
- broadcast + split-axis compatibility and propagation,
- reduction split bookkeeping (reference ``_operations.py:462-472``),
- **padding discipline**: buffers are tail-padded along the split axis
  (see :mod:`heat_tpu.core.dndarray`), so binary ops align operand buffers,
  and reductions that cross the split axis mask the padding with the op's
  neutral element (the analogue of the reference's neutral-element fill for
  empty shards, ``_operations.py:424-436``),
- ``out=`` rewriting.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = ["_binary_op", "_local_op", "_reduce_op", "_cum_op", "_mask_padding"]

Scalar = (int, float, bool, complex, np.number, np.bool_)


def _as_dndarray(x, device=None, comm=None) -> DNDarray:
    from . import factories

    if isinstance(x, DNDarray):
        return x
    return factories.array(x, device=device, comm=comm)


def _out_split_after_broadcast(ndim_out: int, operand: DNDarray) -> Optional[int]:
    """Where an operand's split axis lands in the broadcast output."""
    if operand.split is None:
        return None
    return operand.split + (ndim_out - operand.ndim)


def _neutral_value(neutral, dtype):
    """Resolve a neutral-element spec to a concrete scalar for ``dtype``.

    ``neutral`` may be a scalar; one of the strings ``"min"``/``"max"``
    (the dtype's most negative / most positive value — the identity of
    max/min reductions); ``"nan"`` (the ignored element of the jnp.nan*
    reductions, inexact dtypes only); or a pair ``(inexact_spec, int_spec)``
    choosing by dtype class (e.g. ``("nan", 0)`` for nansum, where integer
    inputs degenerate to a plain sum). Returns None when the spec has no
    value for this dtype — the caller then reduces the exact logical array.
    """
    d = jnp.dtype(dtype)
    if isinstance(neutral, tuple):
        neutral = neutral[0] if jnp.issubdtype(d, jnp.inexact) else neutral[1]
    if isinstance(neutral, str):
        if neutral == "nan":
            return jnp.nan if jnp.issubdtype(d, jnp.inexact) else None
        if jnp.issubdtype(d, jnp.inexact):
            return -jnp.inf if neutral == "min" else jnp.inf
        if d == np.bool_:
            return neutral == "max"
        info = jnp.iinfo(d)
        return info.min if neutral == "min" else info.max
    return neutral


def _mask_padding(buffer: jax.Array, gshape, split: int, fill) -> jax.Array:
    """Overwrite the tail padding along ``split`` with ``fill``."""
    n = gshape[split]
    if buffer.shape[split] == n:
        return buffer
    fill = _neutral_value(fill, buffer.dtype)
    if fill is None:
        raise ValueError("no neutral value for this dtype; reduce the logical array instead")
    iota = jax.lax.broadcasted_iota(jnp.int32, buffer.shape, split)
    return jnp.where(iota < n, buffer, jnp.asarray(fill, dtype=buffer.dtype))


def _aligned_operand_buffer(
    op: DNDarray, jt, out_shape, out_split: Optional[int], out_pshape
) -> jax.Array:
    """Operand buffer cast to ``jt`` and physically broadcast-compatible
    with the (possibly padded) output buffer."""
    buf = op.larray.astype(jt)
    if out_split is None or out_shape == tuple(out_pshape):
        # unpadded output: any padded operand must be trimmed (only happens
        # for a size-1 split dim padded to the mesh size)
        return op._logical().astype(jt) if op.padded else buf
    j = out_split - (len(out_shape) - op.ndim)
    if j < 0:
        return buf  # operand has no dim at the output split axis
    d = op.gshape[j]
    if d == 1:
        # broadcasts against the padded extent; drop any padding of its own
        return op._logical().astype(jt) if op.padded else buf
    if op.split == j:
        return buf  # padded identically to the output by construction
    # replicated (or differently laid out) operand at full logical extent:
    # zero-pad to the output's buffer extent
    pad = [(0, 0)] * op.ndim
    pad[j] = (0, out_pshape[out_split] - d)
    base = op._logical() if op.padded else op.larray
    return jnp.pad(base.astype(jt), pad)


def _write_out(out: DNDarray, result: DNDarray) -> DNDarray:
    """Rewrite ``out`` in place with ``result`` (reference out= semantics)."""
    if tuple(out.shape) != tuple(result.shape):
        raise ValueError(f"output shape {out.shape} does not match result shape {result.shape}")
    target_t = out.dtype.jax_type()
    if out.split == result.split:
        out._set_buffer(result.larray.astype(target_t), result.gshape)
    else:
        out.larray = result._logical().astype(target_t)
    return out


def _binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=True,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Apply a binary jnp op with heat promotion/broadcast/split rules
    (reference ``_operations.py:24-205``)."""
    fn_kwargs = fn_kwargs or {}
    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(
            f"Only DNDarrays and numeric scalars are supported, but input was {type(t1)}, {type(t2)}"
        )
    anchor = t1 if isinstance(t1, DNDarray) else t2
    device, comm = anchor.device, anchor.comm
    if (
        isinstance(t1, DNDarray)
        and isinstance(t2, DNDarray)
        and t1.comm != t2.comm
    ):
        # the reference raises on mismatched communicators
        # (_operations.py binary path); relying on a sharding clash to
        # fail is world-size-dependent
        raise ValueError("operands live on different communicators")
    promoted = types.result_type(t1, t2)

    a = _as_dndarray(t1, device, comm)
    b = _as_dndarray(t2, device, comm)
    out_shape = broadcast_shape(a.shape, b.shape)
    ndim_out = len(out_shape)

    sa = _out_split_after_broadcast(ndim_out, a)
    sb = _out_split_after_broadcast(ndim_out, b)
    if sa is not None and sb is not None and sa != sb:
        raise ValueError(
            f"DNDarrays must have the same split axes, found {a.split} and {b.split}"
        )
    out_split = sa if sa is not None else sb
    out_pshape = comm.padded_shape(out_shape, out_split)

    jt = promoted.jax_type()
    buf_a = _aligned_operand_buffer(a, jt, out_shape, out_split, out_pshape)
    buf_b = _aligned_operand_buffer(b, jt, out_shape, out_split, out_pshape)
    result = operation(buf_a, buf_b, **fn_kwargs)
    if where is not True:
        where_nd = _as_dndarray(where, device, comm)
        where_arr = _aligned_operand_buffer(
            where_nd, where_nd.dtype.jax_type(), out_shape, out_split, out_pshape
        )
        if out is not None:
            base = _aligned_operand_buffer(
                out, result.dtype, out_shape, out_split, out_pshape
            )
        else:
            base = jnp.zeros(out_pshape, dtype=result.dtype)
        result = jnp.where(where_arr, result, base)

    res = DNDarray._from_buffer(
        result,
        out_shape,
        types.canonical_heat_type(result.dtype),
        out_split,
        device,
        comm,
    )
    if out is not None:
        return _write_out(out, res)
    return res


def _local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    out_dtype=None,
    **kwargs,
) -> DNDarray:
    """Embarrassingly-parallel elementwise op (reference
    ``_operations.py:305-376``). Split, sharding and padding are inherited:
    the op runs on the padded buffer (pad content stays unspecified)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    arr = x.larray
    if not no_cast and not jnp.issubdtype(arr.dtype, jnp.inexact) and not jnp.issubdtype(
        arr.dtype, jnp.complexfloating
    ):
        # float-promoting math functions: int input -> float output
        if out_dtype is None:
            arr = arr.astype(types.promote_types(x.dtype, types.float32).jax_type())
    result = operation(arr, **kwargs)
    dtype = out_dtype if out_dtype is not None else types.canonical_heat_type(result.dtype)
    if tuple(result.shape) == x.pshape:
        res = DNDarray._from_buffer(
            result.astype(dtype.jax_type()), x.gshape, dtype, x.split, x.device, x.comm
        )
    else:
        res = DNDarray(
            result.astype(dtype.jax_type()),
            dtype=dtype,
            split=x.split if result.ndim == x.ndim else None,
            device=x.device,
            comm=x.comm,
        )
    if out is not None:
        return _write_out(out, res)
    return res


def _axis_key(axis):
    """Hashable form of a sanitized axis (int, None, or tuple)."""
    return tuple(axis) if isinstance(axis, (list, tuple)) else axis


def _kwargs_key(kwargs: dict):
    """Hashable form of reduce kwargs, or None when unhashable."""
    try:
        return tuple(sorted((k, v) for k, v in kwargs.items()))
    except TypeError:
        return None


@lru_cache(maxsize=256)
def _jitted_reduce_cached(operation, axis, keepdims, pad_mode, pad_n, pad_split, fill, kwargs_items):
    kwargs = dict(kwargs_items)

    fill_val = float("nan") if fill == "__nan__" else fill

    def run(arr):
        if pad_mode == "mask":
            iota = jax.lax.broadcasted_iota(jnp.int32, arr.shape, pad_split)
            arr = jnp.where(iota < pad_n, arr, jnp.asarray(fill_val, dtype=arr.dtype))
        elif pad_mode == "trim":
            sl = [slice(None)] * arr.ndim
            sl[pad_split] = slice(0, pad_n)
            arr = arr[tuple(sl)]
        return operation(arr, axis=axis, keepdims=keepdims, **kwargs)

    return jax.jit(run)


def _jitted_reduce(operation, axis, keepdims, pad_mode, pad_n, pad_split, fill, kwargs_items):
    """Cached jitted reduce program, or None when any static is unhashable.

    A nan fill is tokenized ("__nan__") before keying: nan != nan would
    make every lookup miss and retrace.

    A closure created inside a function (``<locals>`` in its qualname)
    keys the cache by a fresh object identity on every call — each call
    recompiles AND permanently parks the dead executable in the cache.
    Those take the eager fallback instead, unless the caller hoisted the
    closure to module level and marked it ``_cache_stable = True`` (one
    identity forever — see ``statistics._NANPROP_MAX``)."""
    if kwargs_items is None:
        return None
    if "<locals>" in getattr(operation, "__qualname__", "") and not getattr(
        operation, "_cache_stable", False
    ):
        return None
    if isinstance(fill, float) and fill != fill:
        fill = "__nan__"
    try:
        return _jitted_reduce_cached(
            operation, axis, keepdims, pad_mode, pad_n, pad_split, fill, kwargs_items
        )
    except TypeError:
        return None


def _reduce_op(
    operation: Callable,
    x: DNDarray,
    axis=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    out_dtype=None,
    neutral=None,
    **kwargs,
) -> DNDarray:
    """Global reduction (reference ``_operations.py:379-505``).

    The reference computed a local partial then Allreduced with a custom MPI
    op when the split axis was reduced; XLA compiles ``jnp`` reductions over
    sharded inputs to the identical partial+all-reduce schedule on ICI.

    ``neutral`` is the op's identity element (scalar, ``"min"``/``"max"``,
    or ``"nan"``): tail padding is overwritten with it before reducing — the
    analogue of the reference's neutral fill for empty chunks
    (``_operations.py:424-436``). A padded input with no neutral given falls
    back to reducing the exact logical array.
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    axis = sanitize_axis(x.shape, axis)
    arr = x.larray
    if x.padded:
        fill = None if neutral is None else _neutral_value(neutral, arr.dtype)
        pad_mode = "mask" if fill is not None else "trim"
        pad_n, pad_split = x.gshape[x.split], x.split
    else:
        pad_mode, pad_n, pad_split, fill = "none", 0, 0, None
    # One fused jitted program per (op, axis, padding) combination: the
    # composite reductions (std/var/nanmean) otherwise run as eager
    # per-primitive programs that materialize every (n, f) intermediate in
    # HBM — 3-4x the traffic of the fused program — and the padding
    # mask/trim fuses into the reduction read instead of writing a copy.
    fn = _jitted_reduce(
        operation, _axis_key(axis), keepdims, pad_mode, pad_n, pad_split,
        fill if pad_mode == "mask" else None, _kwargs_key(kwargs),
    )
    if fn is not None:
        result = fn(arr)
    else:  # unhashable op/kwargs: eager fallback, semantics identical
        if pad_mode == "mask":
            arr = _mask_padding(arr, x.gshape, x.split, fill)
        elif pad_mode == "trim":
            arr = x._logical()
        result = operation(arr, axis=axis, keepdims=keepdims, **kwargs)
    out_split = _reduced_split(x.split, axis, x.ndim, keepdims)
    dtype = out_dtype if out_dtype is not None else types.canonical_heat_type(result.dtype)
    result = jnp.asarray(result).astype(dtype.jax_type())
    out_gshape = _reduced_shape(x.gshape, axis, keepdims)
    if out_split is not None and tuple(result.shape) != out_gshape:
        res = DNDarray._from_buffer(result, out_gshape, dtype, out_split, x.device, x.comm)
    else:
        res = DNDarray(
            result, gshape=out_gshape, dtype=dtype, split=out_split,
            device=x.device, comm=x.comm,
        )
    if out is not None:
        return _write_out(out, res)
    return res


def _reduced_shape(gshape, axis, keepdims: bool) -> Tuple[int, ...]:
    """Logical shape after reducing ``axis``."""
    if axis is None:
        axes = tuple(range(len(gshape)))
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
    if keepdims:
        return tuple(1 if i in axes else s for i, s in enumerate(gshape))
    return tuple(s for i, s in enumerate(gshape) if i not in axes)


def _reduced_split(
    split: Optional[int], axis, ndim: int, keepdims: bool
) -> Optional[int]:
    """Output split of a reduction (reference ``_operations.py:462-472``)."""
    if split is None:
        return None
    if axis is None:
        return None
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    if split in axes:
        # reduced over the split axis -> every device holds the result
        return None
    if keepdims:
        return split
    return split - sum(1 for a in axes if a < split)


def _cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Cumulative op along an axis (reference ``_operations.py:208-302``).

    The reference's local-cumop + ``Exscan`` + combine pattern is exactly
    what XLA generates for a cumulative op over a sharded axis; a single
    global ``jnp`` call suffices. Tail padding is harmless here: it sits
    strictly *after* every valid element along the split axis, so valid
    prefixes never include it.
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative ops require an explicit axis")
    arr = x.larray
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        arr = arr.astype(dtype.jax_type())
    result = operation(arr, axis=axis)
    res = DNDarray._from_buffer(
        result,
        x.gshape,
        types.canonical_heat_type(result.dtype),
        x.split,
        x.device,
        x.comm,
    )
    if out is not None:
        return _write_out(out, res)
    return res
