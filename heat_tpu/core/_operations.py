"""Generic operation machinery (reference ``heat/core/_operations.py``).

The reference's quartet — ``__binary_op`` / ``__local_op`` / ``__reduce_op``
/ ``__cum_op`` — mixed dtype promotion with hand-written communication
(operand redistribution, Allreduce of partials, Exscan prefix carries).
On TPU the communication half vanishes: every function here applies a
``jax.numpy`` op to global arrays and lets GSPMD insert collectives. What
remains is exactly the *semantic* layer:

- numpy/heat type-promotion (reference ``_operations.py:42-77``),
- broadcast + split-axis compatibility and propagation,
- reduction split bookkeeping (reference ``_operations.py:462-472``),
- ``out=`` rewriting.
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .communication import sanitize_comm
from .dndarray import DNDarray
from .stride_tricks import broadcast_shape, sanitize_axis

__all__ = ["_binary_op", "_local_op", "_reduce_op", "_cum_op"]

Scalar = (int, float, bool, complex, np.number, np.bool_)


def _as_dndarray(x, device=None, comm=None) -> DNDarray:
    from . import factories

    if isinstance(x, DNDarray):
        return x
    return factories.array(x, device=device, comm=comm)


def _out_split_after_broadcast(ndim_out: int, operand: DNDarray) -> Optional[int]:
    """Where an operand's split axis lands in the broadcast output."""
    if operand.split is None:
        return None
    return operand.split + (ndim_out - operand.ndim)


def _write_out(out: DNDarray, result: DNDarray) -> DNDarray:
    """Rewrite ``out`` in place with ``result`` (reference out= semantics)."""
    if tuple(out.shape) != tuple(result.shape):
        raise ValueError(f"output shape {out.shape} does not match result shape {result.shape}")
    out.larray = result.larray.astype(out.dtype.jax_type())
    return out


def _binary_op(
    operation: Callable,
    t1,
    t2,
    out: Optional[DNDarray] = None,
    where=True,
    fn_kwargs: Optional[dict] = None,
) -> DNDarray:
    """Apply a binary jnp op with heat promotion/broadcast/split rules
    (reference ``_operations.py:24-205``)."""
    fn_kwargs = fn_kwargs or {}
    if not isinstance(t1, DNDarray) and not isinstance(t2, DNDarray):
        raise TypeError(
            f"Only DNDarrays and numeric scalars are supported, but input was {type(t1)}, {type(t2)}"
        )
    anchor = t1 if isinstance(t1, DNDarray) else t2
    device, comm = anchor.device, anchor.comm
    promoted = types.result_type(t1, t2)

    a = _as_dndarray(t1, device, comm)
    b = _as_dndarray(t2, device, comm)
    out_shape = broadcast_shape(a.shape, b.shape)
    ndim_out = len(out_shape)

    sa = _out_split_after_broadcast(ndim_out, a)
    sb = _out_split_after_broadcast(ndim_out, b)
    if sa is not None and sb is not None and sa != sb:
        raise ValueError(
            f"DNDarrays must have the same split axes, found {a.split} and {b.split}"
        )
    out_split = sa if sa is not None else sb

    jt = promoted.jax_type()
    result = operation(a.larray.astype(jt), b.larray.astype(jt), **fn_kwargs)
    if where is not True:
        where_arr = where.larray if isinstance(where, DNDarray) else jnp.asarray(where)
        base = out.larray if out is not None else jnp.zeros(out_shape, dtype=result.dtype)
        result = jnp.where(where_arr, result, base)

    res = DNDarray(
        result,
        dtype=types.canonical_heat_type(result.dtype),
        split=out_split,
        device=device,
        comm=comm,
    )
    if out is not None:
        return _write_out(out, res)
    return res


def _local_op(
    operation: Callable,
    x: DNDarray,
    out: Optional[DNDarray] = None,
    no_cast: bool = False,
    out_dtype=None,
    **kwargs,
) -> DNDarray:
    """Embarrassingly-parallel elementwise op (reference
    ``_operations.py:305-376``). Split and sharding are inherited."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    arr = x.larray
    if not no_cast and not jnp.issubdtype(arr.dtype, jnp.inexact) and not jnp.issubdtype(
        arr.dtype, jnp.complexfloating
    ):
        # float-promoting math functions: int input -> float output
        if out_dtype is None:
            arr = arr.astype(types.promote_types(x.dtype, types.float32).jax_type())
    result = operation(arr, **kwargs)
    dtype = out_dtype if out_dtype is not None else types.canonical_heat_type(result.dtype)
    res = DNDarray(
        result.astype(dtype.jax_type()),
        dtype=dtype,
        split=x.split if result.ndim == x.ndim else None,
        device=x.device,
        comm=x.comm,
    )
    if out is not None:
        return _write_out(out, res)
    return res


def _reduce_op(
    operation: Callable,
    x: DNDarray,
    axis=None,
    out: Optional[DNDarray] = None,
    keepdims: bool = False,
    out_dtype=None,
    **kwargs,
) -> DNDarray:
    """Global reduction (reference ``_operations.py:379-505``).

    The reference computed a local partial then Allreduced with a custom MPI
    op when the split axis was reduced; XLA compiles ``jnp`` reductions over
    sharded inputs to the identical partial+all-reduce schedule on ICI.
    Split bookkeeping follows reference ``_operations.py:462-472``.
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    axis = sanitize_axis(x.shape, axis)
    result = operation(x.larray, axis=axis, keepdims=keepdims, **kwargs)
    out_split = _reduced_split(x.split, axis, x.ndim, keepdims)
    dtype = out_dtype if out_dtype is not None else types.canonical_heat_type(result.dtype)
    res = DNDarray(
        jnp.asarray(result).astype(dtype.jax_type()),
        dtype=dtype,
        split=out_split,
        device=x.device,
        comm=x.comm,
    )
    if out is not None:
        return _write_out(out, res)
    return res


def _reduced_split(
    split: Optional[int], axis, ndim: int, keepdims: bool
) -> Optional[int]:
    """Output split of a reduction (reference ``_operations.py:462-472``)."""
    if split is None:
        return None
    if axis is None:
        return None
    axes = (axis,) if isinstance(axis, int) else tuple(axis)
    if split in axes:
        # reduced over the split axis -> every device holds the result
        return None
    if keepdims:
        return split
    return split - sum(1 for a in axes if a < split)


def _cum_op(
    operation: Callable,
    x: DNDarray,
    axis: int,
    out: Optional[DNDarray] = None,
    dtype=None,
) -> DNDarray:
    """Cumulative op along an axis (reference ``_operations.py:208-302``).

    The reference's local-cumop + ``Exscan`` + combine pattern is exactly
    what XLA generates for a cumulative op over a sharded axis; a single
    global ``jnp`` call suffices.
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    axis = sanitize_axis(x.shape, axis)
    if axis is None:
        raise NotImplementedError("cumulative ops require an explicit axis")
    arr = x.larray
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        arr = arr.astype(dtype.jax_type())
    result = operation(arr, axis=axis)
    res = DNDarray(
        result,
        dtype=types.canonical_heat_type(result.dtype),
        split=x.split,
        device=x.device,
        comm=x.comm,
    )
    if out is not None:
        return _write_out(out, res)
    return res
