"""Mathematical constants (reference ``heat/core/constants.py``)."""
import numpy as np

__all__ = [
    "e",
    "Euler",
    "inf",
    "Inf",
    "Infty",
    "Infinity",
    "nan",
    "NaN",
    "pi",
    "PI",
    "E",
    "INF",
    "NINF",
    "NAN",
]

e = float(np.e)
pi = float(np.pi)
inf = float("inf")
nan = float("nan")

# aliases (reference ``constants.py``)
Euler = e
Inf = inf
Infty = inf
Infinity = inf
NaN = nan

# uppercase source names (reference ``constants.py:10-18``)
PI = pi
E = e
INF = inf
NINF = -inf
NAN = nan
