"""Indexing operations (reference ``heat/core/indexing.py``)."""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from . import types
from ._operations import _binary_op
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> Tuple[DNDarray, ...]:
    """Indices of nonzero elements, one 1-D array per dimension (reference
    ``indexing.py:16`` — local nonzero + global offset; a global jnp call
    here). Result is split=0 when the input was distributed."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    result = jnp.nonzero(x.larray)
    split = 0 if x.split is not None else None
    return tuple(
        DNDarray(r.astype(jnp.int64), dtype=types.int64, split=split, device=x.device, comm=x.comm)
        for r in result
    )


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Ternary where / nonzero dispatch (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    xs = x.larray if isinstance(x, DNDarray) else x
    ys = y.larray if isinstance(y, DNDarray) else y
    result = jnp.where(cond.larray.astype(jnp.bool_), xs, ys)
    split = cond.split
    if isinstance(x, DNDarray) and x.split is not None:
        split = x.split if split is None else split
    return DNDarray(result, split=split if result.ndim == cond.ndim else None, device=cond.device, comm=cond.comm)
