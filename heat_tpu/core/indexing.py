"""Indexing operations (reference ``heat/core/indexing.py``)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of nonzero elements as one (n, ndim) coordinate array
    (reference ``indexing.py:16`` — torch-style, *not* the numpy tuple).

    For 1-D input the result is 1-D (reference squeezes the trailing dim).
    The result is split=0 when the input was distributed; ``x[nonzero(x)]``
    recovers the nonzero values (coordinate-list indexing, handled by
    ``DNDarray.__getitem__``).

    Distributed inputs run ONE compiled shard_map scan (the reference's
    local ``torch.nonzero`` + rank offset, ``indexing.py:16-78``): every
    device compacts its hits' coordinates to the front of an O(block)
    buffer in parallel (:mod:`heat_tpu.parallel.dscan` — round 3's host
    loop over shards serialized P dispatches), and only the found
    coordinates travel — never the operand (``jnp.nonzero`` on the
    logical view would gather it).
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    if x.split is not None and x.comm.size > 1:
        from ..parallel.dscan import nonzero_scan

        if x.lcounts is not None:
            # ragged layout: scan in place (validity = per-block lcounts,
            # offsets = running displacements) — no rebalance
            counts, displs = x.counts_displs()
            parts = nonzero_scan(
                x._raw, x.split, x.gshape[x.split], x.comm, ragged=(counts, displs)
            )
        else:
            parts = nonzero_scan(x.larray, x.split, x.gshape[x.split], x.comm)
        coords = (
            np.concatenate(parts, axis=0)
            if parts
            else np.empty((0, x.ndim), np.int64)
        )
        if jax.process_count() > 1:
            coords = _allgather_ordered_rows(coords)
        if coords.shape[0] > 1:
            # row-major order AND cross-process replica dedup in one step
            # (nonzero coordinates are unique by construction, so unique
            # only removes replica double-counts from process-spanning
            # replicated meshes)
            coords = np.unique(coords, axis=0)
        result = jnp.asarray(coords, dtype=jnp.int64)
    else:
        result = jnp.stack(jnp.nonzero(x._logical()), axis=1)
    if x.ndim == 1:
        result = result.reshape(-1)
    split = 0 if x.split is not None else None
    return DNDarray(
        result.astype(jnp.int64), dtype=types.int64, split=split, device=x.device, comm=x.comm
    )


def _allgather_ordered_rows(rows: np.ndarray) -> np.ndarray:
    """Concatenate each process's row block in process order (ragged
    allgather) — every process's local shards cover a contiguous rank
    range, so process-order concat preserves global shard order."""
    from .communication import ragged_process_allgather

    return np.concatenate(ragged_process_allgather(rows, axis=0), axis=0)


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Ternary where / nonzero dispatch (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    xs = x._logical() if isinstance(x, DNDarray) else x
    ys = y._logical() if isinstance(y, DNDarray) else y
    result = jnp.where(cond._logical().astype(jnp.bool_), xs, ys)
    split = cond.split
    if isinstance(x, DNDarray) and x.split is not None:
        split = x.split if split is None else split
    return DNDarray(
        result,
        split=split if result.ndim == cond.ndim else None,
        device=cond.device,
        comm=cond.comm,
    )
