"""Indexing operations (reference ``heat/core/indexing.py``)."""
from __future__ import annotations

import jax.numpy as jnp

from . import types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of nonzero elements as one (n, ndim) coordinate array
    (reference ``indexing.py:16`` — torch-style, *not* the numpy tuple).

    For 1-D input the result is 1-D (reference squeezes the trailing dim).
    The result is split=0 when the input was distributed; ``x[nonzero(x)]``
    recovers the nonzero values (coordinate-list indexing, handled by
    ``DNDarray.__getitem__``).
    """
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    result = jnp.stack(jnp.nonzero(x._logical()), axis=1)
    if x.ndim == 1:
        result = result.reshape(-1)
    split = 0 if x.split is not None else None
    return DNDarray(
        result.astype(jnp.int64), dtype=types.int64, split=split, device=x.device, comm=x.comm
    )


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Ternary where / nonzero dispatch (reference ``indexing.py:91``)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y should be given")
    xs = x._logical() if isinstance(x, DNDarray) else x
    ys = y._logical() if isinstance(y, DNDarray) else y
    result = jnp.where(cond._logical().astype(jnp.bool_), xs, ys)
    split = cond.split
    if isinstance(x, DNDarray) and x.split is not None:
        split = x.split if split is None else split
    return DNDarray(
        result,
        split=split if result.ndim == cond.ndim else None,
        device=cond.device,
        comm=cond.comm,
    )
