"""Trigonometric functions (reference ``heat/core/trigonometrics.py``).

Pure ``_local_op`` wrappers: elementwise, split-preserving, fused by XLA
into surrounding computations.
"""
from __future__ import annotations

import jax.numpy as jnp

from ._operations import _binary_op, _local_op
from .dndarray import DNDarray

__all__ = [
    "acos",
    "arccos",
    "acosh",
    "arccosh",
    "asin",
    "arcsin",
    "asinh",
    "arcsinh",
    "atan",
    "arctan",
    "atan2",
    "arctan2",
    "atanh",
    "arctanh",
    "cos",
    "cosh",
    "deg2rad",
    "degrees",
    "rad2deg",
    "radians",
    "sin",
    "sinc",
    "sinh",
    "tan",
    "tanh",
]


def acos(x, out=None) -> DNDarray:
    """Elementwise arccos."""
    return _local_op(jnp.arccos, x, out=out)


arccos = acos


def acosh(x, out=None) -> DNDarray:
    return _local_op(jnp.arccosh, x, out=out)


arccosh = acosh


def asin(x, out=None) -> DNDarray:
    return _local_op(jnp.arcsin, x, out=out)


arcsin = asin


def asinh(x, out=None) -> DNDarray:
    return _local_op(jnp.arcsinh, x, out=out)


arcsinh = asinh


def atan(x, out=None) -> DNDarray:
    return _local_op(jnp.arctan, x, out=out)


arctan = atan


def atanh(x, out=None) -> DNDarray:
    return _local_op(jnp.arctanh, x, out=out)


arctanh = atanh


def atan2(x1, x2) -> DNDarray:
    """Elementwise two-argument arctangent."""
    from . import types

    res = _binary_op(jnp.arctan2, x1, x2)
    if types.heat_type_is_exact(res.dtype):
        res = res.astype(types.float32)
    return res


arctan2 = atan2


def cos(x, out=None) -> DNDarray:
    return _local_op(jnp.cos, x, out=out)


def cosh(x, out=None) -> DNDarray:
    return _local_op(jnp.cosh, x, out=out)


def deg2rad(x, out=None) -> DNDarray:
    return _local_op(jnp.deg2rad, x, out=out)


radians = deg2rad


def rad2deg(x, out=None) -> DNDarray:
    return _local_op(jnp.rad2deg, x, out=out)


degrees = rad2deg


def sin(x, out=None) -> DNDarray:
    return _local_op(jnp.sin, x, out=out)


def sinc(x, out=None) -> DNDarray:
    return _local_op(jnp.sinc, x, out=out)


def sinh(x, out=None) -> DNDarray:
    return _local_op(jnp.sinh, x, out=out)


def tan(x, out=None) -> DNDarray:
    return _local_op(jnp.tan, x, out=out)


def tanh(x, out=None) -> DNDarray:
    return _local_op(jnp.tanh, x, out=out)
