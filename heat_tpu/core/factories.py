"""Array factories (reference ``heat/core/factories.py``).

Every factory builds the array *directly in its target sharding* via
``jax.jit(..., out_shardings=...)`` where possible, so large distributed
arrays never materialize on one device. The reference's ``is_split=``
global-shape inference (neighbor Isend/Probe/Recv, ``factories.py:383-426``)
is only meaningful multi-host; under multi-process JAX it maps onto
``communication.assemble_local_shards`` (allgathered shape inference +
padded per-device assembly, with an allgather-of-data fallback for uneven
local extents).
"""
from __future__ import annotations

from typing import Iterable, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import devices, types
from ._cache import ExecutableCache
from .communication import MeshCommunication, sanitize_comm
from .devices import Device
from .dndarray import DNDarray
from .stride_tricks import sanitize_axis, sanitize_shape

__all__ = [
    "arange",
    "array",
    "asarray",
    "empty",
    "empty_like",
    "eye",
    "full",
    "full_like",
    "linspace",
    "logspace",
    "meshgrid",
    "ones",
    "ones_like",
    "zeros",
    "zeros_like",
]


def array(
    obj,
    dtype=None,
    copy: bool = True,
    ndmin: int = 0,
    order: str = "C",
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device: Optional[Union[str, Device]] = None,
    comm: Optional[MeshCommunication] = None,
) -> DNDarray:
    """The main constructor (reference ``factories.py:150-431``).

    ``split=k`` shards the global input along axis ``k`` over the mesh.
    ``is_split=k`` declares the input to be this *process's* local shard;
    with one controlling process the local data is the global data, and
    multi-host processes are assembled with
    ``communication.assemble_local_shards`` (uneven extents supported).
    """
    if split is not None and is_split is not None:
        raise ValueError(f"split and is_split are mutually exclusive, got {split}, {is_split}")
    comm = sanitize_comm(comm)
    device = devices.sanitize_device(device)

    if isinstance(obj, DNDarray):
        if dtype is None:
            dtype = obj.dtype
        data = obj._logical()
    else:
        data = obj

    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        jt = dtype.jax_type()
    else:
        jt = None

    if not isinstance(data, jax.Array):
        np_data = np.asarray(data)
        if np_data.dtype == np.float64 and jt is None and not isinstance(data, np.ndarray):
            # python floats default to float32, matching the reference/torch
            np_data = np_data.astype(np.float32)
        data = jnp.asarray(np_data, dtype=jt)
    elif jt is not None and data.dtype != np.dtype(jt):
        data = data.astype(jt)

    while data.ndim < ndmin:
        data = data[jnp.newaxis]

    if is_split is not None:
        is_split = sanitize_axis(data.shape, is_split)
        if jax.process_count() > 1:
            from .communication import assemble_local_shards

            buf, gshape = assemble_local_shards(np.asarray(data), is_split, comm)
            if dtype is None:
                dtype = types.canonical_heat_type(buf.dtype)
            return DNDarray._from_buffer(buf, gshape, dtype, is_split, device, comm)
        split = is_split

    return DNDarray(data, dtype=dtype, split=split, device=device, comm=comm)


def asarray(obj, dtype=None, copy=None, order="C", is_split=None, device=None) -> DNDarray:
    """Convert to DNDarray without copy when possible (reference
    ``factories.py:434``). ``is_split`` marks ``obj`` as this process's
    local shard of a larger array (reference is_split semantics)."""
    if order is not None and order not in ("C", "K", "A"):
        raise NotImplementedError("only C-order memory layout is supported on TPU")
    if isinstance(obj, DNDarray) and is_split is None and (
        dtype is None or obj.dtype == types.canonical_heat_type(dtype)
    ):
        return obj
    return array(obj, dtype=dtype, is_split=is_split, device=device)


# compiled fill programs keyed by (fill statics, pshape, sharding): the fill
# closures below are rebuilt per call, so keying by their identity (what a
# bare jax.jit would do) made every factory call a retrace; the token key
# makes a repeated zeros/arange/... a cache hit instead
_FILL_CACHE = ExecutableCache()


def _sharded_factory(shape, split, comm, fill, fill_key) -> jax.Array:
    """jit a fill function straight into the target sharding (no host pass).

    ``fill`` receives the *physical* (padded) shape to build; the result is
    born in its final even sharding, so large distributed arrays never
    materialize on one device.  ``fill_key`` must be a hashable token that
    fully determines ``fill``'s behavior (name + every baked-in static);
    it — not the closure object — keys the executable cache.
    """
    pshape = comm.padded_shape(shape, split)
    sharding = comm.array_sharding(pshape, split)
    key = (fill_key, tuple(pshape), sharding)
    try:
        fn = _FILL_CACHE.get(key)
    except TypeError:  # unhashable static (e.g. array fill_value): rare, uncached
        return jax.jit(lambda: fill(pshape), out_shardings=sharding)()  # graftlint: retrace
    if fn is None:
        fn = _FILL_CACHE[key] = jax.jit(lambda: fill(pshape), out_shardings=sharding)
    return fn()


def _build(shape, split, comm, dtype, device, fill, fill_key) -> DNDarray:
    """Run a padded-shape fill and wrap it with logical-gshape metadata."""
    data = _sharded_factory(shape, split, comm, fill, fill_key)
    return DNDarray._from_buffer(
        data, shape, dtype, split, devices.sanitize_device(device), comm
    )


def __factory(shape, dtype, split, device, comm, fill_name) -> DNDarray:
    shape = sanitize_shape(shape)
    dtype = types.canonical_heat_type(dtype)
    split = sanitize_axis(shape, split)
    comm = sanitize_comm(comm)
    jt = dtype.jax_type()
    if fill_name == "zeros":
        fill = lambda ps: jnp.zeros(ps, dtype=jt)
    elif fill_name == "ones":
        fill = lambda ps: jnp.ones(ps, dtype=jt)
    else:
        raise ValueError(fill_name)
    return _build(shape, split, comm, dtype, device, fill, (fill_name, jt))


def zeros(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """reference ``factories.py:1225``"""
    return __factory(shape, dtype, split, device, comm, "zeros")


def ones(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """reference ``factories.py:1128``"""
    return __factory(shape, dtype, split, device, comm, "ones")


def empty(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """reference ``factories.py:488``. XLA has no uninitialized alloc; zeros."""
    return __factory(shape, dtype, split, device, comm, "zeros")


def full(shape, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    """reference ``factories.py:789``: dtype defaults to float32 — it is
    never inferred from the fill value — except complex fills, which
    default to complex64 (``factories.py:840-841``). Unlike the reference,
    an explicitly passed dtype always wins (the reference's unconditional
    complex override silently halves an explicit complex128)."""
    shape = sanitize_shape(shape)
    if dtype is None:
        dtype = (
            types.complex64
            if isinstance(fill_value, (complex, np.complexfloating))
            else types.float32
        )
    dtype = types.canonical_heat_type(dtype)
    comm = sanitize_comm(comm)
    split = sanitize_axis(shape, split)
    jt = dtype.jax_type()
    if isinstance(fill_value, np.ndarray) and fill_value.ndim == 0:
        fill_value = fill_value.item()
    return _build(
        shape, split, comm, dtype, device,
        lambda ps: jnp.full(ps, fill_value, dtype=jt),
        ("full", jt, fill_value),
    )


def _like_meta(a: DNDarray, dtype, split, device, comm):
    return (
        a.shape,
        dtype if dtype is not None else a.dtype,
        split if split is not None else a.split,
        device if device is not None else a.device,
        comm if comm is not None else a.comm,
    )


def zeros_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return zeros(*_like_meta(a, dtype, split, device, comm))


def ones_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return ones(*_like_meta(a, dtype, split, device, comm))


def empty_like(a, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    return empty(*_like_meta(a, dtype, split, device, comm))


def full_like(a, fill_value, dtype=None, split=None, device=None, comm=None, order="C") -> DNDarray:
    # dtype deliberately does NOT inherit a.dtype: the reference's full_like
    # defaults to float32 (``factories.py:846-849``), via full()'s own default
    shape, _, split_, device_, comm_ = _like_meta(a, dtype, split, device, comm)
    return full(shape, fill_value, dtype=dtype, split=split_, device=device_, comm=comm_)


def arange(*args, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """reference ``factories.py:40``"""
    if len(args) == 1:
        start, stop, step = 0, args[0], 1
    elif len(args) == 2:
        start, stop, step = args[0], args[1], 1
    elif len(args) == 3:
        start, stop, step = args
    else:
        raise TypeError(f"function takes 1 to 3 positional arguments but {len(args)} were given")
    if dtype is None:
        if all(isinstance(a, (int, np.integer)) for a in (start, stop, step)):
            dtype = types.int32
        else:
            dtype = types.float32
    dtype = types.canonical_heat_type(dtype)
    comm = sanitize_comm(comm)
    n = int(max(0, -(-(stop - start) // step))) if step != 0 else 0
    split = sanitize_axis((n,), split)
    jt = dtype.jax_type()
    return _build(
        (n,),
        split,
        comm,
        dtype,
        device,
        # fill the physical extent by extending the progression; the tail
        # (indices >= n) is padding and never observed
        lambda ps: (start + step * jnp.arange(ps[0])).astype(jt),
        ("arange", jt, start, step),
    )


def linspace(
    start,
    stop,
    num: int = 50,
    endpoint: bool = True,
    retstep: bool = False,
    dtype=None,
    split=None,
    device=None,
    comm=None,
):
    """reference ``factories.py:896``"""
    num = int(num)
    comm = sanitize_comm(comm)
    split = sanitize_axis((num,), split)
    dtype = types.canonical_heat_type(dtype) if dtype is not None else types.float32
    jt = dtype.jax_type()

    def _fill(ps):
        vals = jnp.linspace(start, stop, num, endpoint=endpoint).astype(jt)
        return jnp.pad(vals, (0, ps[0] - num))

    res = _build((num,), split, comm, dtype, device, _fill,
                 ("linspace", jt, start, stop, num, endpoint))
    if retstep:
        step = (stop - start) / max(1, (num - 1 if endpoint else num))
        return res, step
    return res


def logspace(start, stop, num=50, endpoint=True, base=10.0, dtype=None, split=None, device=None, comm=None) -> DNDarray:
    """reference ``factories.py:982``"""
    from . import exponential, arithmetics

    y = linspace(start, stop, num=num, endpoint=endpoint, split=split, device=device, comm=comm)
    res = arithmetics.pow(float(base), y)
    if dtype is not None:
        return res.astype(dtype)
    return res


def eye(shape, dtype=types.float32, split=None, device=None, comm=None, order="C") -> DNDarray:
    """reference ``factories.py:586``"""
    if order != "C":
        raise NotImplementedError("only C-order memory layout is supported on TPU")
    if isinstance(shape, (int, np.integer)):
        n, m = int(shape), int(shape)
    else:
        shape = tuple(shape)
        n, m = (int(shape[0]), int(shape[0])) if len(shape) == 1 else (int(shape[0]), int(shape[1]))
    dtype = types.canonical_heat_type(dtype)
    comm = sanitize_comm(comm)
    split = sanitize_axis((n, m), split)
    jt = dtype.jax_type()
    return _build(
        (n, m), split, comm, dtype, device,
        lambda ps: jnp.eye(ps[0], ps[1], dtype=jt),
        ("eye", jt),
    )


def meshgrid(*arrays, indexing: str = "xy") -> List[DNDarray]:
    """reference ``factories.py:1045``. Outputs inherit the split of the
    corresponding input dimension where possible."""
    if indexing not in ("xy", "ij"):
        raise ValueError(f"indexing must be 'xy' or 'ij', got {indexing}")
    dnd = [a if isinstance(a, DNDarray) else array(a) for a in arrays]
    if len(dnd) == 0:
        return []
    comm = dnd[0].comm
    device = dnd[0].device
    splits = [a.split for a in dnd]
    grids = jnp.meshgrid(*[a._logical() for a in dnd], indexing=indexing)
    # determine output split: if any input was split, shard outputs along the
    # dimension that input occupies in the grid
    out_split = None
    for i, s in enumerate(splits):
        if s is not None:
            dim = i
            if indexing == "xy" and i < 2 and len(dnd) >= 2:
                dim = 1 - i
            out_split = dim
            break
    return [
        DNDarray(g, dtype=types.canonical_heat_type(g.dtype), split=out_split, device=device, comm=comm)
        for g in grids
    ]
