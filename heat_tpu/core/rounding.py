"""Rounding and sign operations (reference ``heat/core/rounding.py``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import types
from ._operations import _aligned_operand_buffer, _local_op
from .dndarray import DNDarray

__all__ = [
    "abs",
    "absolute",
    "ceil",
    "clip",
    "fabs",
    "floor",
    "modf",
    "nan_to_num",
    "round",
    "sgn",
    "sign",
    "trunc",
]


def abs(x, out=None, dtype=None) -> DNDarray:
    """Elementwise absolute value (reference ``rounding.py``)."""
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
    res = _local_op(jnp.abs, x, out=None if dtype else out, no_cast=True)
    if dtype is not None:
        res = res.astype(dtype)
        if out is not None:
            from ._operations import _write_out

            return _write_out(out, res)
    return res


absolute = abs


def fabs(x, out=None) -> DNDarray:
    """Float absolute value."""
    return _local_op(jnp.fabs, x, out=out)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, out=None) -> DNDarray:
    """Replace NaN/±inf with finite numbers (numpy extra beyond the reference)."""
    return _local_op(
        lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
        x,
        out=out,
        no_cast=True,
    )


def ceil(x, out=None) -> DNDarray:
    return _local_op(jnp.ceil, x, out=out)


def floor(x, out=None) -> DNDarray:
    return _local_op(jnp.floor, x, out=out)


def clip(x, min=None, max=None, out=None, *, a_min=None, a_max=None) -> DNDarray:
    """Clamp values to [min, max] (reference ``rounding.py:126`` spells the
    bounds ``min``/``max``; numpy's ``a_min``/``a_max`` also accepted)."""
    lo = a_min if a_min is not None else min
    hi = a_max if a_max is not None else max
    if lo is None and hi is None:
        raise ValueError("either min or max must be set")
    # DNDarray bounds must be aligned to x's (possibly padded) buffer the
    # same way _binary_op aligns operands: a bare pshape match can be a
    # coincidence of different logical layouts, and a logical view cannot
    # broadcast against a padded buffer
    if isinstance(lo, DNDarray):
        lo = _aligned_operand_buffer(lo, lo.dtype.jax_type(), x.gshape, x.split, x.pshape)
    if isinstance(hi, DNDarray):
        hi = _aligned_operand_buffer(hi, hi.dtype.jax_type(), x.gshape, x.split, x.pshape)
    return _local_op(lambda t: jnp.clip(t, lo, hi), x, out=out, no_cast=True)


def modf(x, out=None):
    """Fractional and integral parts (reference ``rounding.py``)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    frac = _local_op(lambda t: jnp.modf(t)[0], x)
    integ = _local_op(lambda t: jnp.modf(t)[1], x)
    if out is not None:
        if not isinstance(out, tuple) or len(out) != 2:
            raise TypeError("out must be a 2-tuple of DNDarrays")
        from ._operations import _write_out

        return _write_out(out[0], frac), _write_out(out[1], integ)
    return frac, integ


def round(x, decimals: int = 0, out=None, dtype=None) -> DNDarray:
    """Round to the given number of decimals (reference ``rounding.py``)."""
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
    res = _local_op(lambda t: jnp.round(t, decimals=decimals), x, out=out)
    if dtype is not None:
        res = res.astype(dtype)
    return res


def sgn(x, out=None) -> DNDarray:
    """Sign (complex: x/|x|) — reference ``rounding.py``."""
    return _local_op(jnp.sign, x, out=out, no_cast=True)


def sign(x, out=None) -> DNDarray:
    """Sign; for complex input, the sign of the real part (torch semantics
    in the reference)."""
    if isinstance(x, DNDarray) and types.heat_type_is_complexfloating(x.dtype):
        return _local_op(lambda t: jnp.sign(jnp.real(t)).astype(t.dtype), x, out=out, no_cast=True)
    return _local_op(jnp.sign, x, out=out, no_cast=True)


def trunc(x, out=None) -> DNDarray:
    return _local_op(jnp.trunc, x, out=out)
