"""Heat-TPU core: array API over JAX/XLA (reference ``heat/core/``)."""
from . import _jax_compat  # noqa: F401  (installs jax.shard_map on older jax)
import jax as _jax

# float64/int64 parity with the reference's torch semantics. TPU computes
# f32/bf16 natively; f64 arrays are supported for API parity (XLA emulates
# or the user stays in f32 for MXU speed).
_jax.config.update("jax_enable_x64", True)

from . import communication, devices, types, version
from .communication import *
from .devices import *
from .types import *
from .dndarray import *
from .factories import *
from .constants import *
from .memory import *
from .printing import *
from .stride_tricks import *
from .sanitation import *
from . import tiling
from .tiling import *
from ._operations import *
from .arithmetics import *
from .complex_math import *
from .exponential import *
from .indexing import *
from .logical import *
from .manipulations import *
from .relational import *
from .rounding import *
from .statistics import *
from .trigonometrics import *
from . import linalg
from .linalg.basics import *
from . import random
from .random import *
from . import signal
from .signal import *
from . import io
from .io import *
from . import lazy as _lazy_pkg  # installs the _operations capture hook
from .lazy import lazy, fuse, LazyDNDarray, FUSE_STATS, reset_fuse_stats
from .base import *
from .version import __version__


def __getattr__(name: str):
    # accelerator device singletons (tpu / gpu) resolve lazily in
    # heat_tpu.core.devices so importing never initializes the XLA backend
    from . import devices as _devices_mod

    if name in _devices_mod.ACCEL_NAMES:
        return getattr(_devices_mod, name)
    raise AttributeError(f"module has no attribute {name!r} (heat_tpu namespace)")
