"""Logical operations (reference ``heat/core/logical.py``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from . import types
from ._operations import _binary_op, _local_op, _reduce_op
from .dndarray import DNDarray

__all__ = [
    "all",
    "allclose",
    "any",
    "isclose",
    "isfinite",
    "isinf",
    "isnan",
    "isneginf",
    "isposinf",
    "logical_and",
    "logical_not",
    "logical_or",
    "logical_xor",
    "signbit",
]


def all(x, axis=None, out=None, keepdim=False, keepdims=None) -> DNDarray:
    """Whether all elements are truthy (reference ``logical.py:38`` —
    MPI.LAND reduce; XLA emits the equivalent all-reduce). ``keepdim`` is
    the reference spelling; ``keepdims`` accepted for numpy users."""
    return _reduce_op(jnp.all, x, axis=axis, out=out, keepdims=bool(keepdim or keepdims), out_dtype=types.bool, neutral=True)


def any(x, axis=None, out=None, keepdim=False, keepdims=None) -> DNDarray:
    """Whether any element is truthy (reference ``logical.py:157``)."""
    return _reduce_op(jnp.any, x, axis=axis, out=out, keepdims=bool(keepdim or keepdims), out_dtype=types.bool, neutral=False)


def allclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> bool:
    """Global closeness check to one python bool (reference ``logical.py:105``)."""
    close = isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)
    return bool(jnp.all(close._logical()))


def isclose(x, y, rtol: float = 1e-05, atol: float = 1e-08, equal_nan: bool = False) -> DNDarray:
    """Elementwise closeness (reference ``logical.py:210``)."""
    res = _binary_op(
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), x, y
    )
    return res.astype(types.bool) if res.dtype != types.bool else res


def isfinite(x) -> DNDarray:
    return _local_op(jnp.isfinite, x, no_cast=True, out_dtype=types.bool)


def isinf(x) -> DNDarray:
    return _local_op(jnp.isinf, x, no_cast=True, out_dtype=types.bool)


def isnan(x) -> DNDarray:
    return _local_op(jnp.isnan, x, no_cast=True, out_dtype=types.bool)


def isneginf(x, out=None) -> DNDarray:
    return _local_op(jnp.isneginf, x, out=out, no_cast=True, out_dtype=types.bool)


def isposinf(x, out=None) -> DNDarray:
    return _local_op(jnp.isposinf, x, out=out, no_cast=True, out_dtype=types.bool)


def logical_and(x, y) -> DNDarray:
    return _binary_op(jnp.logical_and, _as_bool(x), _as_bool(y))


def logical_not(x, out=None) -> DNDarray:
    return _local_op(jnp.logical_not, x, out=out, no_cast=True, out_dtype=types.bool)


def logical_or(x, y) -> DNDarray:
    return _binary_op(jnp.logical_or, _as_bool(x), _as_bool(y))


def logical_xor(x, y) -> DNDarray:
    return _binary_op(jnp.logical_xor, x, y)


def signbit(x, out=None) -> DNDarray:
    return _local_op(jnp.signbit, x, out=out, no_cast=True, out_dtype=types.bool)


def _as_bool(t):
    if isinstance(t, DNDarray) and t.dtype != types.bool:
        return t.astype(types.bool)
    return t
