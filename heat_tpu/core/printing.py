"""Printing (reference ``heat/core/printing.py``).

The reference gathers shards to rank 0 and formats torch-style
(``printing.py:62-100,184-295``). Under single-controller JAX the global
array is directly addressable; formatting uses numpy with torch-like
thresholds.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "get_printoptions",
    "global_printing",
    "local_printing",
    "print0",
    "set_printoptions",
]

# torch-like defaults (reference ``printing.py:14-28``)
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)

# mode flag (reference ``printing.py:16``): True prints process-local shards
LOCAL_PRINT = False


def get_printoptions() -> dict:
    """Current print options (reference ``printing.py:42``)."""
    return dict(__PRINT_OPTIONS)


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure printing (reference ``printing.py:150``)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=float("inf"), edgeitems=3, linewidth=120)
    for key, value in dict(
        precision=precision, threshold=threshold, edgeitems=edgeitems, linewidth=linewidth, sci_mode=sci_mode
    ).items():
        if value is not None:
            __PRINT_OPTIONS[key] = value


def local_printing() -> None:
    """Print only process-local data (reference ``printing.py:30``)."""
    global LOCAL_PRINT
    LOCAL_PRINT = True


def global_printing() -> None:
    """Print the full global array (default; reference ``printing.py:62``)."""
    global LOCAL_PRINT
    LOCAL_PRINT = False


def print0(*args, **kwargs) -> None:
    """Print once (on the controller) — reference ``printing.py:100``."""
    import jax

    if jax.process_index() == 0:
        print(*args, **kwargs)


def __str__(dndarray) -> str:
    """Format a DNDarray (reference ``printing.py:184``); in local-print
    mode only the process-addressable shard data is shown."""
    opts = __PRINT_OPTIONS
    if LOCAL_PRINT:
        shards = dndarray.larray.addressable_shards
        split = dndarray.split
        # on a multi-axis mesh each unique shard appears once per replica
        # and device order need not follow index order: keep one shard per
        # distinct index, ordered by position along the split axis
        def _index_key(index):
            # slices are unhashable before Python 3.12: normalize to tuples
            return tuple(
                (sl.start or 0, sl.stop) if isinstance(sl, slice) else (sl, sl)
                for sl in index
            )

        unique = {_index_key(s.index): s for s in shards}
        ordered = [unique[k] for k in sorted(unique)]
        if split is not None and len(ordered) > 1:
            data = np.concatenate([np.asarray(s.data) for s in ordered], axis=split)
            if dndarray.padded:  # drop the tail padding of the last shard
                sl = [slice(None)] * data.ndim
                sl[split] = slice(0, dndarray.gshape[split])
                data = data[tuple(sl)]
        else:
            data = np.asarray(ordered[0].data)
    else:
        data = np.asarray(dndarray.numpy())
    with np.printoptions(
        precision=opts["precision"],
        threshold=opts["threshold"] if np.isfinite(opts["threshold"]) else data.size + 1,
        edgeitems=opts["edgeitems"],
        linewidth=opts["linewidth"],
    ):
        body = np.array2string(data, separator=", ", prefix="DNDarray(")
    return (
        f"DNDarray({body}, dtype=ht.{dndarray.dtype.__name__}, "
        f"device={dndarray.device}, split={dndarray.split})"
    )
