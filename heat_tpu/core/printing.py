"""Printing (reference ``heat/core/printing.py``).

The reference gathers shards to rank 0 and formats torch-style
(``printing.py:62-100,184-295``). Under single-controller JAX the global
array is directly addressable; formatting uses numpy with torch-like
thresholds.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "get_printoptions",
    "global_printing",
    "local_printing",
    "print0",
    "set_printoptions",
]

# torch-like defaults (reference ``printing.py:14-28``)
__PRINT_OPTIONS = dict(precision=4, threshold=1000, edgeitems=3, linewidth=120, sci_mode=None)

# mode flag (reference ``printing.py:16``): True prints process-local shards
LOCAL_PRINT = False


def get_printoptions() -> dict:
    """Current print options (reference ``printing.py:42``)."""
    return dict(__PRINT_OPTIONS)


def set_printoptions(precision=None, threshold=None, edgeitems=None, linewidth=None, profile=None, sci_mode=None):
    """Configure printing (reference ``printing.py:150``)."""
    if profile == "default":
        __PRINT_OPTIONS.update(precision=4, threshold=1000, edgeitems=3, linewidth=120)
    elif profile == "short":
        __PRINT_OPTIONS.update(precision=2, threshold=1000, edgeitems=2, linewidth=120)
    elif profile == "full":
        __PRINT_OPTIONS.update(precision=4, threshold=float("inf"), edgeitems=3, linewidth=120)
    for key, value in dict(
        precision=precision, threshold=threshold, edgeitems=edgeitems, linewidth=linewidth
    ).items():
        if value is not None:
            __PRINT_OPTIONS[key] = value
    # torch semantics (the reference delegates to torch.set_printoptions,
    # which resets sci_mode to auto on EVERY non-profile call unless the
    # caller passes it explicitly) — assign unconditionally
    __PRINT_OPTIONS["sci_mode"] = sci_mode


def local_printing() -> None:
    """Print only process-local data (reference ``printing.py:30``)."""
    global LOCAL_PRINT
    LOCAL_PRINT = True


def global_printing() -> None:
    """Print the full global array (default; reference ``printing.py:62``)."""
    global LOCAL_PRINT
    LOCAL_PRINT = False


def print0(*args, **kwargs) -> None:
    """Print once (on the controller) — reference ``printing.py:100``."""
    import jax

    if jax.process_index() == 0:
        print(*args, **kwargs)


def _edge_data(dndarray, edgeitems: int) -> np.ndarray:
    """Bounded gather for summarized printing (reference
    ``printing.py:208-265``: when the output will be ellipsed, only the
    ``edgeitems + 1`` head/tail slices of each large axis travel to rank
    0, never the full array).

    TPU-native shape of the same idea: slice the logical (sharded) array
    device-side — the head/tail of the split axis touch only the first
    and last shards, XLA moves at most ``2 * (edgeitems + 1)`` rows per
    axis — and transfer just that reduced block to the host. Axes no
    longer than ``2 * edgeitems + 2`` are kept whole (numpy's own
    summarizer prints short axes in full, so the edges line up exactly
    with what formatting the full array would have shown)."""
    import jax
    import jax.numpy as jnp

    data = dndarray._logical()
    for axis, extent in enumerate(dndarray.gshape):
        if extent <= 2 * edgeitems + 2:
            continue
        head = [slice(None)] * data.ndim
        tail = [slice(None)] * data.ndim
        head[axis] = slice(0, edgeitems + 1)
        tail[axis] = slice(extent - (edgeitems + 1), extent)
        data = jnp.concatenate([data[tuple(head)], data[tuple(tail)]], axis=axis)
    if not getattr(data, "is_fully_addressable", True):
        # multi-process: replicate the (small) edge block so every
        # process can format it — the only cross-host traffic of the
        # whole print (reference gathers the same slices, printing.py:259).
        # device_put reshards without tracing, so repeated prints don't
        # recompile anything.
        comm = dndarray.comm
        data = jax.device_put(data, comm.sharding(data.ndim, None))
        data = data.addressable_shards[0].data
    return np.asarray(jax.device_get(data))


def _array2string(data: np.ndarray, opts: dict, force_summary: bool = False) -> str:
    """numpy formatting honoring ``sci_mode`` (reference
    ``printing.py:150-182``: ``None`` lets the formatter decide, ``True``
    forces scientific notation, ``False`` suppresses it)."""
    threshold = opts["threshold"] if np.isfinite(opts["threshold"]) else data.size + 1
    if force_summary:
        # the caller already reduced each large axis to its edge slices;
        # force the summarizer on so numpy emits the "..." separators
        threshold = max(data.size - 1, 0)
    kwargs = dict(
        precision=opts["precision"],
        threshold=threshold,
        edgeitems=opts["edgeitems"],
        linewidth=opts["linewidth"],
    )
    if opts.get("sci_mode") is True:
        precision = opts["precision"]

        def _sci(x):
            return np.format_float_scientific(x, precision=precision)

        kwargs["formatter"] = {
            "float_kind": _sci,
            # numpy consults complex_kind for complex floats — torch's
            # sci_mode applies there too
            "complex_kind": lambda z: (
                f"{_sci(z.real)}{'+' if z.imag >= 0 else '-'}{_sci(abs(z.imag))}j"
            ),
        }
    elif opts.get("sci_mode") is False:
        kwargs["suppress"] = True
    with np.printoptions(**kwargs):
        return np.array2string(data, separator=", ", prefix="DNDarray(")


def __str__(dndarray) -> str:
    """Format a DNDarray (reference ``printing.py:184``); in local-print
    mode only the process-addressable shard data is shown."""
    opts = __PRINT_OPTIONS
    if LOCAL_PRINT:
        shards = dndarray.larray.addressable_shards
        split = dndarray.split
        # on a multi-axis mesh each unique shard appears once per replica
        # and device order need not follow index order: keep one shard per
        # distinct index, ordered by position along the split axis
        def _index_key(index):
            # slices are unhashable before Python 3.12: normalize to tuples
            return tuple(
                (sl.start or 0, sl.stop) if isinstance(sl, slice) else (sl, sl)
                for sl in index
            )

        unique = {_index_key(s.index): s for s in shards}
        ordered = [unique[k] for k in sorted(unique)]
        if split is not None and len(ordered) > 1:
            data = np.concatenate([np.asarray(s.data) for s in ordered], axis=split)
            if dndarray.padded:  # drop the tail padding of the last shard
                sl = [slice(None)] * data.ndim
                sl[split] = slice(0, dndarray.gshape[split])
                data = data[tuple(sl)]
        else:
            data = np.asarray(ordered[0].data)
        body = _array2string(data, opts)
    else:
        size = int(np.prod(dndarray.gshape)) if dndarray.gshape else 1
        summarize = np.isfinite(opts["threshold"]) and size > opts["threshold"]
        if summarize and dndarray.split is not None:
            # ellipsed output: gather only the edge slices (reference
            # ``printing.py:208`` gathers edgeitems+1 per axis, not all)
            body = _array2string(_edge_data(dndarray, opts["edgeitems"]), opts, force_summary=True)
        else:
            body = _array2string(np.asarray(dndarray.numpy()), opts)
    return (
        f"DNDarray({body}, dtype=ht.{dndarray.dtype.__name__}, "
        f"device={dndarray.device}, split={dndarray.split})"
    )
