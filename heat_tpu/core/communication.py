"""Device-mesh "communication" layer — the TPU-native replacement for MPI.

The reference implements a 1941-line MPI wrapper
(``heat/core/communication.py``): tensor-aware buffers, derived datatypes,
GPU staging, axis-permuting collectives. On TPU none of that machinery is
needed — a ``jax.sharding.Mesh`` plus ``NamedSharding`` annotations *is* the
communication backend: XLA GSPMD inserts all-reduce / all-gather /
all-to-all / collective-permute on ICI automatically, and explicit
collectives are expressed with ``jax.lax`` primitives inside ``shard_map``.

What survives here is the *bookkeeping* interface the rest of the library
speaks (reference ``communication.py:120,161-239,1886-1937``):

- ``MPICommunication`` -> :class:`MeshCommunication`: holds the device mesh,
  knows the world ``size``/``rank``, computes ``chunk()`` partitions and
  ``counts_displs_shape()``.
- ``MPI_WORLD``/``MPI_SELF`` singletons and ``get_comm``/``use_comm``/
  ``sanitize_comm``.

Partitioning note: the reference balances remainders across the first ranks
(``communication.py:161-209``); XLA shards an axis in ceil-div blocks (the
last shard may be short or empty). ``chunk()`` follows the XLA convention so
that ``lshape_map`` always reflects the true on-device layout.
"""
from __future__ import annotations

import math
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "Communication",
    "MeshCommunication",
    "MPI_WORLD",
    "MPI_SELF",
    "WORLD",
    "SELF",
    "get_comm",
    "init_distributed",
    "use_comm",
    "sanitize_comm",
    "SPLIT_AXIS",
    "MPICommunication",
    "CUDA_AWARE_MPI",
    "collective_lockstep",
    "replicated_decision",
    "replicated_ids",
    "replicated_frame",
    "tree_merge",
    "tree_merge_rounds",
]

# canonical mesh-axis name carrying the DNDarray ``split`` dimension
SPLIT_AXIS = "split"


class Communication:
    """Base class for communication backends (reference ``communication.py:88``)."""

    @staticmethod
    def is_distributed() -> bool:
        raise NotImplementedError()

    def chunk(self, shape, split, rank=None) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        raise NotImplementedError()


class MeshCommunication(Communication):
    """A communicator backed by a JAX device mesh.

    Parameters
    ----------
    devices : list of jax.Device, optional
        Devices forming the mesh. Defaults to all devices of the default
        backend.
    mesh : jax.sharding.Mesh, optional
        Pre-built mesh. Must contain the axis ``split``; additional axes
        (e.g. a slow DCN axis for hierarchical data-parallelism) are allowed
        and are used by :mod:`heat_tpu.optim`.
    """

    def __init__(self, devices: Optional[List] = None, mesh: Optional[Mesh] = None):
        if mesh is not None:
            if SPLIT_AXIS not in mesh.axis_names:
                raise ValueError(f"mesh must contain axis {SPLIT_AXIS!r}, got {mesh.axis_names}")
            self._mesh = mesh
        elif devices is not None:
            self._mesh = Mesh(np.array(devices), axis_names=(SPLIT_AXIS,))
        else:
            # defer jax.devices() so that `import heat_tpu` does not
            # initialize the XLA backend — a prerequisite for
            # init_distributed(), which must run before first backend use
            self._mesh = None

    # -- world-style properties ------------------------------------------------
    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            self._mesh = Mesh(np.array(jax.devices()), axis_names=(SPLIT_AXIS,))
        return self._mesh

    @property
    def size(self) -> int:
        """Number of shards along the split axis (MPI world-size analogue)."""
        return self.mesh.shape[SPLIT_AXIS]

    @property
    def rank(self) -> int:
        """Index of the controlling process (multi-host: ``jax.process_index``).

        Under single-controller JAX every process sees the *global* array, so
        unlike MPI code the library almost never branches on ``rank``.
        """
        return jax.process_index()

    def is_distributed(self) -> bool:
        return self.size > 1

    # -- sharding construction -------------------------------------------------
    def spec(self, ndim: int, split: Optional[int]) -> PartitionSpec:
        """PartitionSpec placing the mesh split-axis at dimension ``split``."""
        if split is None:
            return PartitionSpec()
        if not 0 <= split < max(ndim, 1):
            raise ValueError(f"split {split} out of range for ndim {ndim}")
        parts = [None] * ndim
        parts[split] = SPLIT_AXIS
        return PartitionSpec(*parts)

    def sharding(self, ndim: int, split: Optional[int]) -> NamedSharding:
        """NamedSharding for an ``ndim``-dim array split along ``split``."""
        return NamedSharding(self.mesh, self.spec(ndim, split))

    def padded_dim(self, n: int) -> int:
        """Physical size of a split dimension of logical size ``n``: the
        smallest multiple of the mesh size >= ``n`` (ceil-div padding).

        JAX rejects uneven ``NamedSharding``s at every array boundary
        (``device_put``/jit in/out); the TPU-native answer is static even
        shards + tail padding, with validity masks at reductions. The
        reference instead allowed ragged per-rank chunks
        (``communication.py:161-209``) — same logical layout, since the
        ceil-div chunks here are exactly the valid prefixes of the padded
        blocks.
        """
        n = int(n)
        block = -(-n // self.size) if n else 0
        return max(block, 1) * self.size

    def padded_shape(self, shape, split: Optional[int]) -> Tuple[int, ...]:
        """Physical (buffer) shape for a logical ``shape`` split at ``split``."""
        shape = tuple(int(s) for s in shape)
        if split is None:
            return shape
        out = list(shape)
        out[split] = self.padded_dim(shape[split])
        return tuple(out)

    def array_sharding(self, shape, split: Optional[int]) -> NamedSharding:
        """Sharding applied to a physical buffer of ``shape``. The split dim
        must already be padded to a multiple of the mesh size."""
        if split is not None and shape[split] % self.size != 0:
            raise ValueError(
                f"buffer dim {split} of shape {tuple(shape)} is not a multiple of the "
                f"mesh size {self.size}; pad with padded_shape() first"
            )
        return self.sharding(len(shape), split)

    # -- partition bookkeeping (reference communication.py:161-239) -----------
    def chunk(
        self, shape, split: Optional[int], rank: Optional[int] = None
    ) -> Tuple[int, Tuple[int, ...], Tuple[slice, ...]]:
        """Compute the shard of ``shape`` owned by ``rank`` along ``split``.

        Returns ``(offset, local_shape, slices)`` like the reference
        (``communication.py:161-209``), but using XLA's ceil-div layout.
        """
        shape = tuple(int(s) for s in shape)
        if split is None:
            return 0, shape, tuple(slice(0, s) for s in shape)
        rank = self.rank if rank is None else rank
        n = shape[split]
        block = -(-n // self.size) if n else 0  # ceil div
        start = min(rank * block, n)
        end = min(start + block, n)
        lshape = list(shape)
        lshape[split] = end - start
        slices = tuple(
            slice(start, end) if i == split else slice(0, s) for i, s in enumerate(shape)
        )
        return start, tuple(lshape), slices

    def counts_displs_shape(self, shape, split: int):
        """Per-rank counts/displacements along ``split`` (ref ``:211-239``)."""
        shape = tuple(int(s) for s in shape)
        n = shape[split]
        block = -(-n // self.size) if n else 0
        counts, displs = [], []
        for r in range(self.size):
            start = min(r * block, n)
            end = min(start + block, n)
            counts.append(end - start)
            displs.append(start)
        output_shape = list(shape)
        output_shape[split] = block
        return tuple(counts), tuple(displs), tuple(output_shape)

    def lshape_map(self, shape, split: Optional[int]) -> np.ndarray:
        """(size, ndim) array of every shard's local shape (ref ``dndarray.py:569``).

        Pure metadata on TPU — no Allreduce needed.
        """
        shape = tuple(int(s) for s in shape)
        ndim = max(len(shape), 1)
        out = np.empty((self.size, len(shape)), dtype=np.int64)
        for r in range(self.size):
            _, lshape, _ = self.chunk(shape, split, rank=r)
            out[r] = lshape if len(shape) else ()
        return out

    # -- misc -----------------------------------------------------------------
    def __repr__(self) -> str:
        # must not force lazy mesh resolution (would initialize the backend
        # and break a subsequent init_distributed)
        if self._mesh is None:
            return "MeshCommunication(<world, unresolved>)"
        return f"MeshCommunication(size={self.size}, mesh={self._mesh!r})"

    def __eq__(self, other) -> bool:
        # resolution-free: two unresolved communicators are equal only when
        # they are the same kind (unresolved SELF != unresolved WORLD)
        return type(self) is type(other) and self._mesh == other._mesh

    def __hash__(self):
        # constant per class: stable across lazy resolution (eq still
        # discriminates; collisions only cost dict-probe time)
        return hash(MeshCommunication)


class _SelfCommunication(MeshCommunication):
    """Single-device communicator (MPI_SELF analogue); resolves its device
    lazily so importing the package does not initialize the backend."""

    def __init__(self):
        self._mesh = None

    @property
    def mesh(self) -> Mesh:
        if self._mesh is None:
            # first LOCAL device: on a multi-host pod every process must
            # pick a device it can address (jax.devices()[0] lives on host 0)
            self._mesh = Mesh(np.array([jax.local_devices()[0]]), axis_names=(SPLIT_AXIS,))
        return self._mesh


# module-level singletons (reference communication.py:1886-1937)
WORLD = MeshCommunication()
SELF = _SelfCommunication()
# Names kept for reference-API familiarity; there is no MPI underneath.
MPI_WORLD = WORLD
MPI_SELF = SELF

_default_comm = WORLD


def get_comm() -> MeshCommunication:
    """The current default communicator (reference ``communication.py:1907``)."""
    return _default_comm


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids=None,
) -> MeshCommunication:
    """Initialize the multi-host runtime and rebuild the world communicator.

    The reference initializes at import under ``mpirun`` (MPI_Init inside
    ``import heat``, reference ``communication.py:1886-1891``). The TPU
    analogue is ``jax.distributed.initialize`` — on Cloud TPU pods every
    argument auto-detects from the metadata server, so a bare
    ``ht.init_distributed()`` at the top of the SPMD script is the whole
    story; on other clusters pass coordinator/process arguments explicitly.

    Importing ``heat_tpu`` does NOT initialize the XLA backend (the world
    communicators resolve their device mesh lazily), so this must be the
    first device-touching call of the program::

        import heat_tpu as ht
        ht.init_distributed()          # before any array is created
        x = ht.zeros((N, F), split=0)  # sharded over the whole pod

    After initialization the default communicator spans ALL global devices:
    intra-host collectives ride ICI, inter-host DCN (XLA routes per edge).
    """
    global _default_comm
    # Multi-process groups on the CPU platform (tests, local smoke runs)
    # need a host-side collectives layer armed BEFORE the backend comes up:
    # XLA's bare CPU client rejects cross-process programs outright
    # ("Multiprocess computations aren't implemented on the CPU backend").
    # TPU/GPU platforms never enter this branch, and an explicit user
    # choice (e.g. "mpi") is left alone.
    try:
        if (
            jax.config.jax_platforms == "cpu"
            and jax.config._read("jax_cpu_collectives_implementation") == "none"
        ):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        pass  # this jax build predates (or renamed) the flag: nothing to arm
    kwargs = {
        k: v
        for k, v in dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            local_device_ids=local_device_ids,
        ).items()
        if v is not None
    }
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "must be called before" in str(e):
            raise RuntimeError(
                "the XLA backend is already initialized: call "
                "ht.init_distributed() before creating any array (or call "
                "jax.distributed.initialize() before importing anything "
                "that touches devices)"
            ) from e
        raise
    # drop any lazily-cached single-host mesh so WORLD/SELF re-resolve over
    # the now-global device set; aliases (MPI_WORLD, ht.WORLD, ...) keep
    # pointing at the same objects, so they refresh too
    WORLD._mesh = None
    SELF._mesh = None
    _default_comm = WORLD
    return WORLD


def use_comm(comm: Optional[MeshCommunication] = None) -> None:
    """Set the default communicator (reference ``communication.py:1927``)."""
    global _default_comm
    if comm is None:
        comm = WORLD
    if not isinstance(comm, Communication):
        raise TypeError(f"expected a Communication object, got {type(comm)}")
    _default_comm = comm


def sanitize_comm(comm) -> MeshCommunication:
    """Default-or-validate a communicator (reference ``communication.py:1917``)."""
    if comm is None:
        return get_comm()
    if not isinstance(comm, Communication):
        raise TypeError(f"expected a Communication object, got {type(comm)}")
    return comm


@contextmanager
def comm_context(comm: MeshCommunication):
    """Temporarily swap the default communicator."""
    global _default_comm
    prev = _default_comm
    _default_comm = comm
    try:
        yield comm
    finally:
        _default_comm = prev


# name-parity aliases: the reference's MPI backend class (``communication.py:120``)
# maps onto the mesh-collective backend here; there is no CUDA staging on TPU.
MPICommunication = MeshCommunication
CUDA_AWARE_MPI = False


def _assemble_from_chunks(read_chunk, gshape, split, comm, np_dtype):
    """Build the padded global buffer from per-device chunk reads.

    ``read_chunk(slices) -> np.ndarray`` returns the data for one device's
    valid chunk, addressed in GLOBAL coordinates (``comm.chunk`` layout).
    Each process materializes only its addressable devices' blocks,
    zero-padded to the even block size; the global ``jax.Array`` is
    stitched with ``make_array_from_single_device_arrays`` — the analogue
    of the reference's per-rank parallel reads (``io.py:57-147``). No
    device and no host ever holds the full array.

    Runs under the collective watchdog when one is installed
    (``resilience.deadlines``): a wedged chunk read or device transfer
    raises ``CollectiveTimeout`` instead of hanging the job.
    """
    from . import _hooks

    return _hooks.guarded_call(
        "collective.assemble",
        _assemble_from_chunks_impl,
        read_chunk, gshape, split, comm, np_dtype,
    )


def _assemble_from_chunks_impl(read_chunk, gshape, split, comm, np_dtype):
    from . import _hooks

    _hooks.fault_point(
        "collective.assemble",
        gshape=tuple(gshape),
        split=split,
        dtype=str(np.dtype(np_dtype)),
    )
    pshape = comm.padded_shape(gshape, split)
    sharding = comm.array_sharding(pshape, split)
    block_shape = list(pshape)
    block_shape[split] = pshape[split] // comm.size
    pid = jax.process_index()
    arrays = []
    blocks = {}  # split-rank -> host block, shared by replicated devices
    for rank, dev in _split_ranks(comm):
        if dev.process_index != pid:
            continue
        if rank not in blocks:
            _, lshape, slices = comm.chunk(gshape, split, rank=rank)
            buf = np.zeros(tuple(block_shape), dtype=np_dtype)
            if all(s > 0 for s in lshape):
                buf[tuple(slice(0, s) for s in lshape)] = read_chunk(slices)
                # chaos can plant NaNs here — the simulated silently-
                # corrupted shard that validate()/health_check() must catch
                _hooks.fault_point("collective.shard", array=buf, rank=rank)
            blocks[rank] = buf
        arrays.append(jax.device_put(blocks[rank], dev))
    return jax.make_array_from_single_device_arrays(pshape, sharding, arrays)


def ragged_process_allgather(arr: np.ndarray, axis: int = 0):
    """Allgather per-process host arrays whose extent along ``axis`` may
    differ: sizes are exchanged first, payloads padded to the max, and
    each process's block trimmed on receipt. Returns the list of blocks
    in process order. THE one implementation of this subtle protocol —
    ``assemble_local_shards``'s uneven path, ``unique``'s candidate
    merge, and ``nonzero``'s coordinate concat all route through it.

    The blocking host allgather is THE place a straggling or dead peer
    wedges every process; under an installed watchdog
    (``resilience.deadlines``) the wait is bounded and surfaces as
    ``CollectiveTimeout('collective.allgather')``."""
    from . import _hooks

    return _hooks.guarded_call(
        "collective.allgather", _ragged_process_allgather_impl, arr, axis
    )


def _ragged_process_allgather_impl(arr: np.ndarray, axis: int = 0):
    from jax.experimental import multihost_utils

    from . import _hooks

    nproc = jax.process_count()
    moved = np.moveaxis(np.asarray(arr), axis, 0)
    # per-rank extents along ``axis`` are allowed to differ — that is
    # this protocol's entire contract — so the lockstep fingerprint must
    # carry only the rank-invariant context (trailing dims, dtype, axis);
    # including the local extent would make every legal ragged gather
    # self-report as a divergence
    _hooks.fault_point(
        "collective.allgather",
        shape=tuple(moved.shape[1:]),
        axis=int(axis),
        dtype=str(moved.dtype),
    )
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([moved.shape[0]], np.int64))
    ).reshape(-1)
    cap = int(counts.max()) if counts.size else 0
    if cap == 0:
        return [np.moveaxis(moved, 0, axis) for _ in range(nproc)]
    padded = np.zeros((cap,) + moved.shape[1:], moved.dtype)
    padded[: moved.shape[0]] = moved
    gathered = np.asarray(multihost_utils.process_allgather(padded)).reshape(
        (nproc, cap) + padded.shape[1:]
    )
    return [
        np.moveaxis(gathered[p, : int(counts[p])], 0, axis) for p in range(nproc)
    ]


def replicated_decision(flag, *, active: bool = True) -> bool:
    """Make a host-side boolean rendezvous-safe: every process returns the
    OR of all processes' flags, so a branch guarded by the result is
    taken identically everywhere even when the local inputs (wall clocks,
    filesystem probes) disagree.  THE sanctioned way to branch a
    collective-dispatching path on a process-local predicate.

    ``active=False`` — or a single-process world — returns ``bool(flag)``
    without dispatching anything, so callers whose predicate is already
    replicated (step counters, global metadata) pay nothing.  graftflow
    models this call as laundering taint (its summary table), which is
    exactly its contract."""
    flag = bool(flag)
    if not active or jax.process_count() == 1:
        return flag
    from . import _hooks

    return _hooks.guarded_call(
        "collective.replicated_decision", _replicated_decision_impl, flag
    )


def _replicated_decision_impl(flag: bool) -> bool:
    from jax.experimental import multihost_utils

    from . import _hooks

    _hooks.fault_point(
        "collective.replicated_decision", shape=(1,), dtype="bool"
    )
    votes = multihost_utils.process_allgather(np.asarray([flag], dtype=np.bool_))
    return bool(np.asarray(votes).any())


def replicated_ids(ids, *, cap: int = 64, active: bool = True) -> frozenset:
    """Union a small process-local set of integer ids across every
    process — the set-valued sibling of :func:`replicated_decision`, for
    decisions that need consensus on WHICH members, not just whether any.

    The motivating caller is elastic shrink under multiple controllers:
    ``probe`` only sees this process's addressable devices, so each rank
    holds a partial unhealthy set; building survivor meshes from partial
    sets would give every rank a DIFFERENT mesh. One fixed-width
    allgather (``cap`` slots, -1 padded — rank-invariant shape, so the
    collective itself is lockstep-safe) returns the identical union
    everywhere. ``active=False`` — or a single-process world — returns
    the local set without dispatching anything."""
    local = frozenset(int(i) for i in ids)
    if not active or jax.process_count() == 1:
        return local
    if len(local) > cap:
        raise ValueError(
            f"replicated_ids: {len(local)} ids exceed the {cap}-slot frame"
        )
    from . import _hooks

    def impl() -> frozenset:
        from jax.experimental import multihost_utils

        _hooks.fault_point("collective.replicated_ids", shape=(cap,), dtype="int32")
        frame = np.full((cap,), -1, dtype=np.int32)
        frame[: len(local)] = sorted(local)
        gathered = np.asarray(multihost_utils.process_allgather(frame)).ravel()
        return frozenset(int(i) for i in gathered if i >= 0)

    return _hooks.guarded_call("collective.replicated_ids", impl)


def replicated_frame(
    frame, *, label: str = "collective.replicated_frame", active: bool = True
) -> np.ndarray:
    """Exchange a small fixed-width int64 metadata frame: every process
    contributes one ``frame`` (identical shape/dtype everywhere by
    contract — a rank-dependent shape would desync the allgather itself)
    and receives the stacked ``(nproc, *frame.shape)`` array, identical
    on every rank.  The array-valued sibling of
    :func:`replicated_decision` / :func:`replicated_ids`: any pure
    function of the gathered frames computes the SAME value on every
    process, so its result may gate collectives — graftflow models this
    call as laundering taint, which is exactly that contract.

    ``label`` names the guarded-call site (and its fault point) so
    distinct frame protocols — the health monitor's EWMA frame, the
    serve dispatch tick — stay separately addressable under chaos
    schedules.  ``active=False`` — or a single-process world — returns
    ``frame[None]`` without dispatching anything, so single-controller
    callers run the identical decode path over a one-row gather."""
    frame = np.ascontiguousarray(frame, dtype=np.int64)
    if not active or jax.process_count() == 1:
        return frame[None]
    from . import _hooks

    def impl() -> np.ndarray:
        from jax.experimental import multihost_utils

        _hooks.fault_point(label, shape=frame.shape, dtype="int64")
        gathered = np.asarray(multihost_utils.process_allgather(frame))
        return gathered.reshape((jax.process_count(),) + frame.shape)

    return _hooks.guarded_call(label, impl)


def collective_lockstep(tree):
    """Pin a collective-bearing dispatch to completion under
    multi-controller execution; a transparent pass-through otherwise.

    XLA matches cross-process collectives by launch order per device, but
    two *independent* programs (no data dependency — e.g. the moments and
    cov folds of the same streamed chunk) may execute concurrently on the
    runtime thread pool, interleaving their collectives differently on
    each process: the rendezvous then deadlocks or silently mixes data
    across programs. Blocking on each such program before launching the
    next independent one restores a total cross-process order. Eager op
    *chains* don't need this — data dependencies already serialize them —
    and with one process there is no rendezvous, so this returns
    immediately and full async dispatch is preserved."""
    if jax.process_count() > 1:
        jax.block_until_ready(tree)
    return tree


def tree_merge_rounds(nproc: int) -> int:
    """Exchange rounds :func:`tree_merge` dispatches for ``nproc``
    processes: ``ceil(log2 P)`` on the butterfly path, 0 when it falls
    back (P == 1, or a non-power-of-two world). Host-pure — the counter
    oracle the multihost tests assert against."""
    nproc = int(nproc)
    if nproc <= 1 or nproc & (nproc - 1):
        return 0
    return nproc.bit_length() - 1


# one jitted butterfly program per (combine, state structure, mesh) —
# every fold-then-merge epoch re-dispatches the same executable
_TREE_PROGRAMS: Optional[object] = None
_PROCESS_MESH: Optional[Mesh] = None


def _process_mesh() -> Mesh:
    """One-device-per-process mesh (split axis = process index): the
    substrate for replicated-state collectives. Each process contributes
    its first addressable device, ordered by process index, so rank ==
    ``jax.process_index`` on every controller."""
    global _PROCESS_MESH
    if _PROCESS_MESH is not None and _PROCESS_MESH.devices.size == jax.process_count():
        return _PROCESS_MESH
    first: Dict[int, object] = {}
    for d in jax.devices():
        first.setdefault(d.process_index, d)
    devs = [first[i] for i in range(jax.process_count())]
    _PROCESS_MESH = Mesh(np.array(devs), axis_names=(SPLIT_AXIS,))
    return _PROCESS_MESH


def tree_merge(state, combine, *, label: str = "collective.tree_merge", active: bool = True):
    """Merge one replicated-state pytree per process into the identical
    global state on EVERY process in ``ceil(log2 P)`` ``ppermute`` rounds
    — the log-depth alternative to allgathering all P states and folding
    them serially.

    ``state`` is a pytree of (host or device) arrays — one streaming
    estimator's state as held by THIS process; every process must pass
    the same tree structure, leaf shapes, and dtypes (a rank-dependent
    shape would desync the exchange itself). ``combine`` is a pure,
    jax-traceable, associative function ``(tree_a, tree_b) -> tree`` with
    ``tree_a`` the lower-rank operand; it must preserve leaf shapes and
    dtypes. The result on every process is the rank-ordered combination
    ``s_0 ⊕ s_1 ⊕ ... ⊕ s_{P-1}`` with the SAME balanced-tree bracketing
    everywhere, so the merged state is bit-identical across processes —
    replicated-state discipline holds by construction.

    Rounds: an XOR butterfly over a one-device-per-process mesh — round
    ``d`` pairs rank ``r`` with ``r ^ d`` (one full-permutation
    ``ppermute`` each), ``log2 P`` rounds total, counted in
    ``MOVE_STATS["tree_merge_rounds"]``. A non-power-of-two world has no
    single-permutation butterfly; it falls back to one flat
    ``process_allgather`` + a rank-ordered serial fold (still identical
    on every process, rounds counted as 0). ``active=False`` — or a
    single-process world — returns ``state`` unchanged.

    The dispatch runs under the collective watchdog (``label``) and is
    pinned with :func:`collective_lockstep`, so independent merges of
    several estimators stay rendezvous-ordered across controllers.
    """
    nproc = jax.process_count()
    if not active or nproc == 1:
        return state
    from . import _hooks

    leaves, treedef = jax.tree_util.tree_flatten(state)
    np_leaves = [np.asarray(x) for x in leaves]

    def impl():
        _hooks.fault_point(
            label,
            leaves=len(np_leaves),
            shapes=tuple(tuple(x.shape) for x in np_leaves),
            dtypes=tuple(str(x.dtype) for x in np_leaves),
        )
        if nproc & (nproc - 1):  # no butterfly off powers of two
            out = _flat_state_merge(np_leaves, treedef, combine, nproc)
        else:
            out = _butterfly_state_merge(np_leaves, treedef, combine, nproc)
        from ..parallel.flatmove import MOVE_STATS

        MOVE_STATS["tree_merges"] += 1
        MOVE_STATS["tree_merge_rounds"] += tree_merge_rounds(nproc)
        return out

    merged = _hooks.guarded_call(label, impl)
    return collective_lockstep(merged)


def _flat_state_merge(np_leaves, treedef, combine, nproc):
    """Fallback: allgather every process's leaves, fold in rank order.
    Serial (P-1 combines) but structurally identical output on all
    ranks; used off power-of-two worlds."""
    from jax.experimental import multihost_utils

    gathered = [
        np.asarray(multihost_utils.process_allgather(x)).reshape((nproc,) + x.shape)
        for x in np_leaves
    ]
    acc = jax.tree_util.tree_unflatten(
        treedef, [jnp.asarray(g[0]) for g in gathered]
    )
    for r in range(1, nproc):
        nxt = jax.tree_util.tree_unflatten(
            treedef, [jnp.asarray(g[r]) for g in gathered]
        )
        acc = combine(acc, nxt)
    return acc


def _butterfly_state_merge(np_leaves, treedef, combine, nproc):
    from jax import lax, shard_map

    from ._cache import ExecutableCache

    global _TREE_PROGRAMS
    if _TREE_PROGRAMS is None:
        _TREE_PROGRAMS = ExecutableCache(maxsize=32)
    pmesh = _process_mesh()
    pid = jax.process_index()
    my_dev = pmesh.devices.ravel()[pid]

    # each process donates its own row of the (P, *shape) stacked state
    stacked = []
    for x in np_leaves:
        pshape = (nproc,) + x.shape
        sharding = NamedSharding(
            pmesh, PartitionSpec(SPLIT_AXIS, *([None] * x.ndim))
        )
        local = jax.device_put(x[None], my_dev)
        stacked.append(
            jax.make_array_from_single_device_arrays(pshape, sharding, [local])
        )

    key = (
        "tree_merge",
        combine,
        treedef,
        tuple((tuple(x.shape), str(x.dtype)) for x in np_leaves),
        pmesh,
    )
    fn = _TREE_PROGRAMS.get(key)
    if fn is None:

        def kernel(*blocks):  # each (1, *shape): this rank's state
            r = lax.axis_index(SPLIT_AXIS)
            acc = [b[0] for b in blocks]
            d = 1
            while d < nproc:
                perm = [(i, i ^ d) for i in range(nproc)]
                recv = [lax.ppermute(a, SPLIT_AXIS, perm) for a in acc]
                own_t = jax.tree_util.tree_unflatten(treedef, acc)
                rec_t = jax.tree_util.tree_unflatten(treedef, recv)
                # rank-ordered operands: the lower rank of each pair goes
                # first, so every rank applies the same balanced tree
                lo = jax.tree_util.tree_leaves(combine(own_t, rec_t))
                hi = jax.tree_util.tree_leaves(combine(rec_t, own_t))
                low_first = (r & d) == 0
                acc = [jnp.where(low_first, a, b) for a, b in zip(lo, hi)]
                d <<= 1
            return tuple(a[None] for a in acc)

        specs = tuple(
            PartitionSpec(SPLIT_AXIS, *([None] * x.ndim)) for x in np_leaves
        )
        # every rank's block carries the identical merged state by
        # construction, which the varying-mesh-axes analysis cannot infer
        prog = shard_map(
            kernel, mesh=pmesh, in_specs=specs, out_specs=specs, check_vma=False
        )
        fn = _TREE_PROGRAMS[key] = jax.jit(prog)
    outs = fn(*stacked)
    # read this process's (identical) copy back off the process mesh; host
    # round-trip decommits the leaf so downstream arithmetic is free to
    # place results wherever the estimator's other arrays live
    merged_leaves = [
        jnp.asarray(np.asarray(o.addressable_shards[0].data)[0]) for o in outs
    ]
    return jax.tree_util.tree_unflatten(treedef, merged_leaves)


def _split_ranks(comm: MeshCommunication):
    """(split_rank, device) for every mesh device.

    A device's shard rank is its COORDINATE along the split mesh axis —
    not its position in ``devices.ravel()``, which diverges on multi-axis
    meshes (e.g. a 2-D DASO mesh, where devices sharing a split coordinate
    replicate the same shard)."""
    devs = comm.mesh.devices
    axis_idx = list(comm.mesh.axis_names).index(SPLIT_AXIS)
    for coords in np.ndindex(devs.shape):
        yield coords[axis_idx], devs[coords]


def assemble_local_shards(local: np.ndarray, split: int, comm: MeshCommunication):
    """Infer the global shape from per-process ``is_split`` shards and build
    the padded global buffer (reference ``factories.py:383-426``: neighbor
    Isend/Probe/Recv shape exchange + Allreduce consistency checks).

    Returns ``(buffer, gshape)``. Non-split dims must agree across
    processes; the split dim is the sum of the local extents. When every
    process holds the same extent and it divides evenly over the local
    devices, blocks align with process boundaries and assembly is
    local-only; otherwise the shards are allgathered once (O(n) host
    memory — the uneven path, like the reference's staged Recv).

    Bounded end-to-end by the collective watchdog when installed
    (``resilience.deadlines``), label ``collective.assemble_local``.
    """
    from . import _hooks

    return _hooks.guarded_call(
        "collective.assemble_local", _assemble_local_shards_impl, local, split, comm
    )


def _assemble_local_shards_impl(local: np.ndarray, split: int, comm: MeshCommunication):
    from jax.experimental import multihost_utils

    nproc = jax.process_count()
    pid = jax.process_index()
    shapes = multihost_utils.process_allgather(np.asarray(local.shape, dtype=np.int64))
    shapes = np.asarray(shapes).reshape(nproc, local.ndim)
    for d in range(local.ndim):
        if d != split and len(set(int(s) for s in shapes[:, d])) != 1:
            raise ValueError(
                f"local shards disagree on non-split dim {d}: {sorted(set(int(s) for s in shapes[:, d]))}"
            )
    sizes = [int(s) for s in shapes[:, split]]
    gshape = list(local.shape)
    gshape[split] = sum(sizes)
    gshape = tuple(gshape)

    block = comm.padded_shape(gshape, split)[split] // comm.size
    # is_split semantics: the global array is the pid-ordered concatenation
    # of the local shards. The local-only fast path requires every device
    # block (rank r covers global rows [r*block, (r+1)*block)) to fall
    # inside its OWN process's rows — true for equal, divisible extents on
    # a process-major mesh. The decision is computed from the REPLICATED
    # (rank, device) placement of the whole mesh, never from this
    # process's local view: a per-host check here diverges on a partially
    # permuted mesh, stranding the aligned hosts while the misaligned
    # ones enter the allgather below (graftflow F001).
    placement = _split_ranks(comm)
    per_proc: Dict[int, int] = {}
    for _r, d in placement:
        per_proc[d.process_index] = per_proc.get(d.process_index, 0) + 1
    dpp = next(iter(per_proc.values()))
    aligned = (
        len(set(sizes)) == 1
        and len(set(per_proc.values())) == 1
        and sizes[0] % dpp == 0
        and sizes[0] // dpp == block
        and all(r * block // sizes[0] == d.process_index for r, d in placement)
    )
    if aligned:
        offset = pid * sizes[0]  # this process's rows in global coordinates

        def read_chunk(slices):
            local_slices = list(slices)
            s = slices[split]
            local_slices[split] = slice(s.start - offset, s.stop - offset)
            return local[tuple(local_slices)]

    else:
        full = np.concatenate(ragged_process_allgather(local, axis=split), axis=split)

        def read_chunk(slices):
            return full[slices]

    buf = _assemble_from_chunks(read_chunk, gshape, split, comm, local.dtype)
    return buf, gshape
