"""Arithmetic operations (reference ``heat/core/arithmetics.py``).

All binary ops ride :func:`_operations._binary_op` (promotion + broadcast +
split propagation); reductions and cumops compile to partial+collective
schedules by XLA. The reference's hand-rolled ``diff`` neighbor exchange
(``arithmetics.py:293``) is a single global ``jnp.diff``.
"""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp
import numpy as np

from . import types
from ._operations import _binary_op, _cum_op, _local_op, _reduce_op
from .dndarray import DNDarray

__all__ = [
    "add",
    "bitwise_and",
    "bitwise_not",
    "bitwise_or",
    "bitwise_xor",
    "cumprod",
    "cumproduct",
    "copysign",
    "cumsum",
    "diff",
    "div",
    "divide",
    "floordiv",
    "floor_divide",
    "fmod",
    "hypot",
    "invert",
    "left_shift",
    "mod",
    "mul",
    "multiply",
    "nanprod",
    "nansum",
    "neg",
    "negative",
    "pos",
    "positive",
    "pow",
    "power",
    "prod",
    "remainder",
    "right_shift",
    "sub",
    "subtract",
    "sum",
]


def add(t1, t2, out=None, where=True) -> DNDarray:
    """Elementwise addition (reference ``arithmetics.py:63``)."""
    return _binary_op(jnp.add, t1, t2, out=out, where=where)


def sub(t1, t2, out=None, where=True) -> DNDarray:
    """Elementwise subtraction (reference ``arithmetics.py``)."""
    return _binary_op(jnp.subtract, t1, t2, out=out, where=where)


subtract = sub


def mul(t1, t2, out=None, where=True) -> DNDarray:
    """Elementwise multiplication."""
    return _binary_op(jnp.multiply, t1, t2, out=out, where=where)


multiply = mul


def div(t1, t2, out=None, where=True) -> DNDarray:
    """Elementwise true division."""
    res = _binary_op(jnp.true_divide, t1, t2, out=out, where=where)
    return res


divide = div


def floordiv(t1, t2) -> DNDarray:
    """Elementwise floor division."""
    return _binary_op(jnp.floor_divide, t1, t2)


floor_divide = floordiv


def fmod(t1, t2) -> DNDarray:
    """Elementwise C-style remainder (sign of the dividend)."""
    return _binary_op(jnp.fmod, t1, t2)


def hypot(t1, t2) -> DNDarray:
    """Elementwise ``sqrt(t1**2 + t2**2)`` (numpy extra beyond the reference)."""
    return _binary_op(jnp.hypot, t1, t2)


def copysign(t1, t2) -> DNDarray:
    """Magnitude of ``t1`` with the sign of ``t2`` (numpy extra beyond the reference)."""
    return _binary_op(jnp.copysign, t1, t2)


def mod(t1, t2) -> DNDarray:
    """Elementwise python-style modulo (sign of the divisor)."""
    return _binary_op(jnp.mod, t1, t2)


remainder = mod


def pow(t1, t2, out=None, where=True) -> DNDarray:
    """Elementwise exponentiation."""
    return _binary_op(jnp.power, t1, t2, out=out, where=where)


power = pow


def neg(a, out=None) -> DNDarray:
    """Elementwise negation."""
    return _local_op(jnp.negative, a, out=out, no_cast=True)


negative = neg


def pos(a, out=None) -> DNDarray:
    """Elementwise unary plus."""
    return _local_op(jnp.positive, a, out=out, no_cast=True)


positive = pos


def _check_int_or_bool(*tensors):
    for t in tensors:
        if isinstance(t, DNDarray) and not types.heat_type_is_exact(t.dtype):
            raise TypeError(f"Operation not supported for float types, got {t.dtype}")
        if isinstance(t, (float, complex)) and not isinstance(t, bool):
            raise TypeError("Operation not supported for float scalars")


def bitwise_and(t1, t2) -> DNDarray:
    """Elementwise AND of integer/boolean arrays."""
    _check_int_or_bool(t1, t2)
    return _binary_op(jnp.bitwise_and, t1, t2)


def bitwise_or(t1, t2) -> DNDarray:
    _check_int_or_bool(t1, t2)
    return _binary_op(jnp.bitwise_or, t1, t2)


def bitwise_xor(t1, t2) -> DNDarray:
    _check_int_or_bool(t1, t2)
    return _binary_op(jnp.bitwise_xor, t1, t2)


def invert(a, out=None) -> DNDarray:
    """Elementwise bitwise NOT (reference ``arithmetics.py``)."""
    _check_int_or_bool(a)
    return _local_op(jnp.invert, a, out=out, no_cast=True)


bitwise_not = invert


def left_shift(t1, t2) -> DNDarray:
    _check_int_or_bool(t1, t2)
    return _binary_op(jnp.left_shift, t1, t2)


def right_shift(t1, t2) -> DNDarray:
    _check_int_or_bool(t1, t2)
    return _binary_op(jnp.right_shift, t1, t2)


def cumsum(a, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative sum (reference ``arithmetics.py:261`` — local cumsum +
    Exscan; on TPU one jnp.cumsum, XLA inserts the scan collective)."""
    return _cum_op(jnp.cumsum, a, axis, out=out, dtype=dtype, neutral=0)


def cumprod(a, axis: int, dtype=None, out=None) -> DNDarray:
    """Cumulative product (reference ``arithmetics.py:224``)."""
    return _cum_op(jnp.cumprod, a, axis, out=out, dtype=dtype, neutral=1)


cumproduct = cumprod


def diff(a: DNDarray, n: int = 1, axis: int = -1, prepend=None, append=None) -> DNDarray:
    """n-th discrete difference along an axis (reference
    ``arithmetics.py:293`` hand-rolled the split-axis neighbor send; the
    global jnp.diff compiles to a halo exchange automatically)."""
    if n == 0:
        return a
    if n < 0:
        raise ValueError(f"diff requires that n be a positive number, got {n}")
    from .stride_tricks import sanitize_axis

    axis = sanitize_axis(a.shape, axis)

    def _edge(v):
        if v is None:
            return None
        arr = v._logical() if isinstance(v, DNDarray) else jnp.asarray(v)
        if arr.ndim == 0:
            shape = list(a.shape)
            shape[axis] = 1
            arr = jnp.broadcast_to(arr, shape)
        return arr

    pre, app = _edge(prepend), _edge(append)
    if a.split is not None and a.comm.is_distributed():
        from ._movement import diff_padded
        from .dndarray import DNDarray as _D

        buf, out_shape = diff_padded(a.larray, a.gshape, a.split, n, axis, pre, app, a.comm)
        return _D._from_buffer(
            buf, out_shape, types.canonical_heat_type(buf.dtype), a.split, a.device, a.comm
        )
    result = jnp.diff(a._logical(), n=n, axis=axis, prepend=pre, append=app)
    return DNDarray(
        result,
        dtype=types.canonical_heat_type(result.dtype),
        split=a.split,
        device=a.device,
        comm=a.comm,
    )


def _int_to_int64(x: DNDarray):
    # reference sum/prod accumulate small ints in int64 (torch semantics)
    if types.heat_type_is_exact(x.dtype) and x.dtype not in (types.int64,):
        return types.int64
    return None


def _merge_keepdim(keepdim, keepdims) -> bool:
    """The reference spells this kwarg ``keepdim`` (torch-style,
    ``arithmetics.py:960``); numpy users expect ``keepdims``. Accept both."""
    if keepdim is not None:
        return bool(keepdim)
    return bool(keepdims)


def sum(a: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Sum over axis (reference ``arithmetics.py:960``)."""
    kd = _merge_keepdim(keepdim, keepdims)
    return _reduce_op(jnp.sum, a, axis=axis, out=out, keepdims=kd, out_dtype=_int_to_int64(a), neutral=0)


def prod(a: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Product over axis (reference ``arithmetics.py:870``)."""
    kd = _merge_keepdim(keepdim, keepdims)
    return _reduce_op(jnp.prod, a, axis=axis, out=out, keepdims=kd, out_dtype=_int_to_int64(a), neutral=1)


def nansum(a: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Sum ignoring NaNs."""
    return _reduce_op(jnp.nansum, a, axis=axis, out=out, keepdims=_merge_keepdim(keepdim, keepdims), neutral=("nan", 0))


def nanprod(a: DNDarray, axis=None, out=None, keepdim=None, keepdims=None) -> DNDarray:
    """Product ignoring NaNs."""
    return _reduce_op(jnp.nanprod, a, axis=axis, out=out, keepdims=_merge_keepdim(keepdim, keepdims), neutral=("nan", 1))
