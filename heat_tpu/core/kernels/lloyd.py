"""Fused distance + argmin + centroid-update Pallas kernel (Lloyd step).

The XLA Lloyd iteration (``cluster.kmeans._assign_stats``) is two HBM
passes over the data: the fused distance+argmin pass, then — because the
argmin→one-hot dependency blocks fusion — a separate ``onehotᵀ @ X``
update matmul that re-reads X. At k=8 that matmul also drives the MXU at
8-of-128 output lanes (the BENCH_r05 floor probe's bound). This kernel
streams X row tiles through VMEM ONCE: distances, argmin, the one-hot
update matmul, per-cluster counts and the inertia all happen while the
tile is resident, accumulating (sums, counts, inertia) across the
sequential TPU grid. Centers are padded to 128 rows so the per-tile
update matmul runs at full MXU width on operands already in VMEM.

Roofline: one read of the (n, f) buffer + O(n) label writes per Lloyd
iteration — half the unfused path's traffic. Comparator: the fused-XLA
``_assign_stats`` program (``kmeans_floor_probe``'s decomposition floor
is the unfused treatment both beat).

Parity: distances use the same quadratic expansion as
``spatial.distance._quadratic_expand`` and ties break toward the lower
index (matching ``jnp.argmin``), so labels are bit-identical; sums and
inertia accumulate per tile, so centroids match the XLA path to float32
re-association (~1e-6 relative, the documented tolerance).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._dispatch import register_kernel

try:  # pallas TPU backend is optional at import time (CPU test meshes)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["lloyd_local", "lloyd_sharded", "LLOYD_KERNEL"]

_INT_MAX = 2**31 - 1

LLOYD_KERNEL = register_kernel(
    "lloyd_fused",
    fallback="fallback",
    comparator="fused-XLA _assign_stats (distance pass + separate update matmul)",
    roofline="one HBM read of X per Lloyd iteration vs two unfused — bandwidth bound",
)


def _lloyd_kernel(nv_ref, x_ref, c_ref, labels_ref, sums_ref, cnt_ref, in_ref,
                  *, k: int, tile_n: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[:] = jnp.zeros(sums_ref.shape, sums_ref.dtype)
        cnt_ref[:] = jnp.zeros(cnt_ref.shape, cnt_ref.dtype)
        in_ref[:] = jnp.zeros(in_ref.shape, in_ref.dtype)

    x = x_ref[:]
    c = c_ref[:]
    xc = jnp.dot(x, c.T, preferred_element_type=jnp.float32)
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)[None, :]
    d2 = jnp.maximum(x2 + c2 - 2.0 * xc, 0.0)
    col = jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
    d2 = jnp.where(col < k, d2, jnp.inf)  # padded center rows can never win
    mval = jnp.min(d2, axis=1, keepdims=True)
    # argmin with ties toward the lower index, matching jnp.argmin
    labels = jnp.min(
        jnp.where(d2 == mval, col, jnp.int32(_INT_MAX)), axis=1, keepdims=True
    )
    labels_ref[:] = labels
    row = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0) + i * tile_n
    valid = row < nv_ref[0, 0]
    # zero both factors for padded rows: 0-weight x garbage would be nan
    onehot = jnp.where(valid & (col == labels), 1.0, 0.0).astype(x.dtype)
    xs = jnp.where(valid, x, 0.0)
    sums_ref[:] += jnp.dot(onehot.T, xs, preferred_element_type=jnp.float32)
    cnt_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)
    in_ref[0, 0] += jnp.sum(jnp.where(valid[:, 0], mval[:, 0], 0.0))


@functools.partial(jax.jit, static_argnames=("k", "tile_n", "interpret"))
def _lloyd_call(xa, centers, n_valid, k: int, tile_n: int, interpret: bool):
    n, f = xa.shape
    kp = ((k + 127) // 128) * 128  # full MXU width for the update matmul
    fp = (-f) % 128
    xp = jnp.pad(xa, ((0, (-n) % tile_n), (0, fp)))
    cp = jnp.pad(centers, ((0, kp - k), (0, fp)))
    grid = (xp.shape[0] // tile_n,)
    if pltpu is not None and not interpret:
        vmem = pltpu.VMEM
    else:  # interpreter path (CPU test meshes) has no TPU memory spaces
        vmem = pl.ANY
    # zero index-map components derive from the grid arg (i - i): this
    # Mosaic build mis-legalizes i64 index-map constants (see topk_distance)
    amap = lambda i: (i - i, i - i)
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024
        )
    labels, sums, cnt, inertia = pl.pallas_call(
        functools.partial(_lloyd_kernel, k=k, tile_n=tile_n),
        grid=grid,
        **kwargs,
        in_specs=[
            pl.BlockSpec((1, 1), amap, memory_space=vmem),
            pl.BlockSpec((tile_n, xp.shape[1]), lambda i: (i, i - i), memory_space=vmem),
            pl.BlockSpec((kp, xp.shape[1]), amap, memory_space=vmem),
        ],
        out_specs=[
            pl.BlockSpec((tile_n, 1), lambda i: (i, i - i), memory_space=vmem),
            pl.BlockSpec((kp, xp.shape[1]), amap, memory_space=vmem),
            pl.BlockSpec((1, kp), amap, memory_space=vmem),
            pl.BlockSpec((1, 1), amap, memory_space=vmem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((xp.shape[0], 1), jnp.int32),
            jax.ShapeDtypeStruct((kp, xp.shape[1]), jnp.float32),
            jax.ShapeDtypeStruct((1, kp), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(jnp.asarray(n_valid, jnp.int32).reshape(1, 1), xp, cp)
    return sums[:k, :f], cnt[0, :k], labels[:n, 0], inertia[0, 0]


def lloyd_local(
    xa: jnp.ndarray,
    centers: jnp.ndarray,
    n_valid=None,
    *,
    tile_n: int = 512,
    interpret: bool | None = None,
):
    """Fused Lloyd assignment statistics of a local (n, f) buffer.

    Returns ``(sums, counts, labels, inertia)`` with the exact contract
    of ``cluster.kmeans._assign_stats``: per-cluster sums (k, f), counts
    (k,), per-row labels (n,) int32 and the summed min-distance inertia.
    """
    if xa.ndim != 2 or centers.ndim != 2 or xa.shape[1] != centers.shape[1]:
        raise ValueError(f"bad operand shapes {xa.shape} x {centers.shape}")
    from ._dispatch import pallas_supported

    if interpret is None:
        interpret = not pallas_supported(LLOYD_KERNEL)
    xa = xa.astype(jnp.float32)
    centers = centers.astype(jnp.float32)
    if n_valid is None:
        n_valid = xa.shape[0]
    # keep the tile a multiple of 8: unaligned block shapes break Mosaic
    tile_n = max(8, min(tile_n, -(-xa.shape[0] // 8) * 8))
    return _lloyd_call(xa, centers, n_valid, centers.shape[0], tile_n, interpret)


def lloyd_sharded(
    xa,
    centers,
    n_valid,
    mesh,
    *,
    tile_n: int = 512,
    interpret: bool | None = None,
):
    """Fused Lloyd assignment statistics of a split-0 sharded buffer.

    Each shard runs :func:`lloyd_local` over its rows (validity window
    derived from the shard's position and the GLOBAL ``n_valid``); sums,
    counts and inertia psum over the mesh axis, labels stay sharded.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..communication import SPLIT_AXIS

    p = mesh.devices.size
    mi = xa.shape[0] // p

    def local(xs, cs, nv_g):
        r = jax.lax.axis_index(SPLIT_AXIS)
        nv = jnp.clip(nv_g - r * mi, 0, mi)
        sums, cnt, labels, inertia = lloyd_local(
            xs, cs, nv, tile_n=tile_n, interpret=interpret
        )
        return (
            jax.lax.psum(sums, SPLIT_AXIS),
            jax.lax.psum(cnt, SPLIT_AXIS),
            labels,
            jax.lax.psum(inertia, SPLIT_AXIS),
        )

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(SPLIT_AXIS, None), P(None, None), P()),
        out_specs=(P(), P(), P(SPLIT_AXIS), P()),
        check_vma=False,  # pallas_call out_shapes carry no vma info
    )(xa, centers, jnp.asarray(n_valid, jnp.int32))
