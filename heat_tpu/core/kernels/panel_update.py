"""Blocked panel-fused Cholesky kernel (panel factor + trailing update).

The single-device blocked factorization path runs the panel factor and
the O(bs·n) trailing GEMM as separate XLA ops, round-tripping the
trailing submatrix through HBM once per panel — O(n²·nb) bytes. This
kernel keeps the (padded) matrix resident in VMEM across a sequential
grid over panels: each step factors the bs×bs diagonal block (masked
unblocked Cholesky — no LAPACK call exists inside a Mosaic kernel),
forward-substitutes the full-height panel against it, and applies the
trailing syrk while everything is still on-chip. HBM traffic: one read
of A and one write of L, total — the floor.

The trailing update needs no region mask: the panel is zeroed above the
diagonal block before the ``Lm @ Lmᵀ`` product, so the product is
already zero outside the trailing submatrix.

Scope: real float32, n ≤ ``MAX_FUSED_N`` (the whole matrix must fit
VMEM). The distributed (p > 1) factorization keeps the shard_map path —
its per-panel all_gather between the solve and the trailing update
cannot live inside one kernel. LU keeps the XLA path too: tournament
pivoting is collective-bound, not fusion-bound (see docs/PERFORMANCE.md).

Comparator: ``jnp.linalg.cholesky`` on the same buffer. Parity: same
factor up to float32 re-association (~1e-6 relative); non-SPD inputs
propagate NaNs like ``jnp.linalg.cholesky``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._dispatch import register_kernel

try:  # pallas TPU backend is optional at import time (CPU test meshes)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["cholesky_blocked", "CHOL_KERNEL", "MAX_FUSED_N"]

# (n_pad, n_pad) working copy + the input block must fit scoped VMEM
MAX_FUSED_N = 1024

CHOL_KERNEL = register_kernel(
    "chol_panel_fused",
    fallback="fallback",
    comparator="jnp.linalg.cholesky (separate XLA panel + trailing-update ops)",
    roofline="one HBM read of A + one write of L; trailing updates stay in VMEM",
)


def _chol_unblocked(Akk: jnp.ndarray, bs: int) -> jnp.ndarray:
    """Unblocked right-looking Cholesky of a bs×bs block, mask-based
    (no dynamic indexing — Mosaic-friendly column selection via iota)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 1)
    ridx = rows[:, 0]

    def body(j, A):
        djj = jnp.sum(jnp.where((rows == j) & (cols == j), A, 0.0))
        d = jnp.sqrt(djj)
        colj = jnp.sum(jnp.where(cols == j, A, 0.0), axis=1)
        lcol = jnp.where(ridx > j, colj / d, 0.0)
        newcol = jnp.where(ridx == j, d, lcol)
        A = jnp.where(cols == j, newcol[:, None], A)
        upd = lcol[:, None] * lcol[None, :]
        return A - jnp.where((rows > j) & (cols > j), upd, 0.0)

    A = jax.lax.fori_loop(0, bs, body, Akk)
    return jnp.where(rows >= cols, A, 0.0)


def _panel_solve(Lkk: jnp.ndarray, Pfull: jnp.ndarray, bs: int) -> jnp.ndarray:
    """X with ``X @ Lkkᵀ = Pfull`` (forward substitution over columns,
    mask-based row selection — runs on the full-height panel)."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 0)
    cidx = jax.lax.broadcasted_iota(jnp.int32, (bs, bs), 1)[0]
    pcols = jax.lax.broadcasted_iota(jnp.int32, Pfull.shape, 1)

    def body(j, X):
        lrow = jnp.sum(jnp.where(rows == j, Lkk, 0.0), axis=0)  # Lkk[j, :]
        w = jnp.where(cidx < j, lrow, 0.0)
        pj = jnp.sum(jnp.where(pcols == j, Pfull, 0.0), axis=1)
        acc = jnp.dot(X, w[:, None], preferred_element_type=jnp.float32)[:, 0]
        ljj = jnp.sum(jnp.where(cidx == j, lrow, 0.0))
        xj = (pj - acc) / ljj
        return jnp.where(pcols == j, xj[:, None], X)

    return jax.lax.fori_loop(0, bs, body, jnp.zeros_like(Pfull))


def _chol_kernel(a_ref, L_ref, *, bs: int, n_pad: int):
    kb = pl.program_id(0)

    @pl.when(kb == 0)
    def _():
        L_ref[:] = a_ref[:]  # working copy; panels overwrite it in place

    off = (kb * bs).astype(jnp.int32)  # multiple of bs — aligned slices
    top = off - off  # int32 zero (mixed python-int/traced starts mis-type)
    Akk = pl.load(L_ref, (pl.ds(off, bs), pl.ds(off, bs)))
    Lkk = _chol_unblocked(Akk, bs)
    Pfull = pl.load(L_ref, (pl.ds(top, n_pad), pl.ds(off, bs)))
    X = _panel_solve(Lkk, Pfull, bs)
    rown = jax.lax.broadcasted_iota(jnp.int32, (n_pad, 1), 0)
    below = rown >= off + bs
    Lm = jnp.where(below, X, 0.0)
    # panel columns are final: zeros above, Lkk on the block, solve below
    pl.store(L_ref, (pl.ds(top, n_pad), pl.ds(off, bs)), Lm)
    pl.store(L_ref, (pl.ds(off, bs), pl.ds(off, bs)), Lkk)
    # Lm is zero outside the trailing rows, so Lm @ Lmᵀ is already zero
    # outside the trailing submatrix — subtract without a region mask
    L_ref[:] = L_ref[:] - jnp.dot(Lm, Lm.T, preferred_element_type=jnp.float32)


@functools.partial(jax.jit, static_argnames=("bs", "interpret"))
def _chol_call(a, bs: int, interpret: bool):
    n = a.shape[0]
    n_pad = -(-n // bs) * bs
    ap = jnp.pad(a, ((0, n_pad - n), (0, n_pad - n)))
    # identity-extend the padding diagonal: chol([[A, 0], [0, I]]) keeps
    # the logical factor unchanged and the padded system SPD
    idx = jnp.arange(n_pad)
    pad_diag = (idx[:, None] == idx[None, :]) & (idx[:, None] >= n)
    ap = jnp.where(pad_diag, 1.0, ap)
    if pltpu is not None and not interpret:
        vmem = pltpu.VMEM
    else:  # interpreter path (CPU test meshes) has no TPU memory spaces
        vmem = pl.ANY
    amap = lambda i: (i - i, i - i)  # Mosaic i64 index-map workaround
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024
        )
    L = pl.pallas_call(
        functools.partial(_chol_kernel, bs=bs, n_pad=n_pad),
        grid=(n_pad // bs,),
        **kwargs,
        in_specs=[pl.BlockSpec((n_pad, n_pad), amap, memory_space=vmem)],
        out_specs=pl.BlockSpec((n_pad, n_pad), amap, memory_space=vmem),
        out_shape=jax.ShapeDtypeStruct((n_pad, n_pad), jnp.float32),
        interpret=interpret,
    )(ap)
    return L[:n, :n]


def cholesky_blocked(
    a: jnp.ndarray, *, bs: int = 128, interpret: bool | None = None
) -> jnp.ndarray:
    """Lower Cholesky factor of a local SPD (n, n) f32 buffer via the
    panel-fused kernel (one VMEM residency for factor + trailing update)."""
    if a.ndim != 2 or a.shape[0] != a.shape[1]:
        raise ValueError(f"cholesky_blocked expects a square 2-D buffer, got {a.shape}")
    if a.shape[0] > MAX_FUSED_N:
        raise ValueError(
            f"n={a.shape[0]} exceeds MAX_FUSED_N={MAX_FUSED_N} (matrix must fit VMEM)"
        )
    from ._dispatch import pallas_supported

    if interpret is None:
        interpret = not pallas_supported(CHOL_KERNEL)
    a = a.astype(jnp.float32)
    # keep bs a multiple of 8: tile-unaligned pl.ds slices break Mosaic
    bs = max(8, min(bs, -(-a.shape[0] // 8) * 8))
    return _chol_call(a, bs, interpret)
