"""Pallas TPU kernels for heat_tpu's hot ops.

The reference delegates its inner loops to libtorch kernels (SURVEY §2:
"native under the hood"). On TPU most of those loops compile to optimal
XLA programs already; the kernels here cover the cases XLA cannot reach,
each registered on the :mod:`._dispatch` registry (per-kernel probe,
declared fallback mode, ``KERNEL_STATS`` dispatch counters):

- :func:`nearest_neighbors` — fused pairwise-distance + running top-k that
  never materializes the (n, m) distance matrix (the flash-attention trick
  applied to ``cdist`` + ``top_k``), for kNN on training sets where the
  (n, m) intermediate would not fit in HBM.
- :func:`lloyd_local` / :func:`lloyd_sharded` — fused distance + argmin +
  centroid-update for the Lloyd iteration: one HBM pass per iteration,
  sidestepping the MXU-narrow-output (k×n)@(n×f) update matmul.
- :func:`moments_local` / :func:`moments_sharded` / :func:`chunk_moments`
  — one-pass Welford (count, mean, M2): a single data read where the
  naive ``mean`` + ``std`` sequence takes three.
- :func:`cholesky_blocked` — blocked panel-fused Cholesky: panel factor,
  triangular solve and trailing update in one VMEM residency.
"""
from ._dispatch import (
    KERNEL_STATS,
    KERNELS,
    dispatch_mode,
    forced_mode,
    kernel_spec,
    pallas_supported,
    record_dispatch,
    register_kernel,
    reset_kernel_stats,
)
from .lloyd import LLOYD_KERNEL, lloyd_local, lloyd_sharded
from .moments import MOMENTS_KERNEL, chunk_moments, merge_moments, moments_local, moments_sharded
from .panel_update import CHOL_KERNEL, MAX_FUSED_N, cholesky_blocked
from .topk_distance import TOPK_KERNEL, nearest_neighbors

__all__ = [
    "CHOL_KERNEL",
    "KERNELS",
    "KERNEL_STATS",
    "LLOYD_KERNEL",
    "MAX_FUSED_N",
    "MOMENTS_KERNEL",
    "TOPK_KERNEL",
    "cholesky_blocked",
    "chunk_moments",
    "dispatch_mode",
    "forced_mode",
    "kernel_spec",
    "lloyd_local",
    "lloyd_sharded",
    "merge_moments",
    "moments_local",
    "moments_sharded",
    "nearest_neighbors",
    "pallas_supported",
    "record_dispatch",
    "register_kernel",
    "reset_kernel_stats",
]
