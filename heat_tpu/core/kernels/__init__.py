"""Pallas TPU kernels for heat_tpu's hot ops.

The reference delegates its inner loops to libtorch kernels (SURVEY §2:
"native under the hood"). On TPU most of those loops compile to optimal
XLA programs already (the fused Lloyd step measures at one HBM pass over
the data per iteration — the roofline). The kernels here cover the cases
XLA cannot reach:

- :func:`nearest_neighbors` — fused pairwise-distance + running top-k that
  never materializes the (n, m) distance matrix (the flash-attention trick
  applied to ``cdist`` + ``top_k``), for kNN on training sets where the
  (n, m) intermediate would not fit in HBM.
"""
from .topk_distance import nearest_neighbors, pallas_supported

__all__ = ["nearest_neighbors", "pallas_supported"]
