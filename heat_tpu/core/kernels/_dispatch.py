"""Per-kernel dispatch registry + ``KERNEL_STATS`` counters.

Every fused kernel in this package registers itself here with a *probe*
(can the compiled pallas path run on this backend?) and a declared
fallback mode. Public APIs then ask :func:`dispatch_mode` which
implementation to run and report the decision through
:func:`record_dispatch`, so kernel-vs-fallback dispatch is observable
exactly like LAYOUT/MOVE/COMPILE_STATS:

- ``"pallas"``    — compiled Mosaic kernel (TPU backend);
- ``"interpret"`` — pallas interpreter (CPU test meshes; opt-in only —
  the interpreter is orders of magnitude slower than XLA, so it is for
  parity tests, never the default dispatch);
- ``"xla"``       — a fused raw-jnp twin of the kernel (same one-pass
  dataflow, compiled by XLA; the default fast path off-TPU);
- ``"fallback"``  — the pre-kernel legacy path (two-pass reduce,
  unfused update matmul, separate XLA factorization ops).

One module-level observer folds ``kernel.dispatch`` events into
:data:`KERNEL_STATS` (exported as ``ht.KERNEL_STATS``); events from
other families pass through untouched. Dispatch is recorded at the
Python call boundary — once per eager call / fit / chunk — never inside
traced code, so warm cached programs still count.
"""
from __future__ import annotations

import contextlib
from typing import Callable, Dict, Iterator, Optional

import jax

from .. import _hooks

try:  # pallas TPU backend is optional at import time (CPU test meshes)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = [
    "KERNEL_STATS",
    "KERNELS",
    "dispatch_mode",
    "forced_mode",
    "kernel_spec",
    "pallas_supported",
    "record_dispatch",
    "register_kernel",
    "reset_kernel_stats",
]


def _default_probe() -> bool:
    return pltpu is not None and jax.default_backend() == "tpu"


# name -> spec dict: {"probe", "fallback", "comparator", "roofline"}
KERNELS: Dict[str, Dict] = {}


def register_kernel(
    name: str,
    *,
    probe: Optional[Callable[[], bool]] = None,
    fallback: str = "fallback",
    comparator: str = "",
    roofline: str = "",
) -> str:
    """Register a fused kernel with the dispatch layer.

    ``probe`` answers "can the *compiled* pallas path run right now?"
    (default: TPU backend with pltpu importable). ``fallback`` names the
    mode :func:`dispatch_mode` reports when it cannot. ``comparator``
    and ``roofline`` are documentation carried into bench notes and
    docs/PERFORMANCE.md — every kernel lands with a raw-jnp comparator
    row and a roofline statement, so wins stay measured, not asserted.
    """
    KERNELS[name] = {
        "probe": probe or _default_probe,
        "fallback": fallback,
        "comparator": comparator,
        "roofline": roofline,
    }
    return name


def kernel_spec(name: str) -> Dict:
    return KERNELS[name]


def pallas_supported(kernel: Optional[str] = None) -> bool:
    """True when compiled (non-interpreted) pallas kernels can run.

    With a ``kernel`` name, consults that kernel's registered probe
    (kernels may have extra requirements beyond the backend); without
    one, keeps the historical global semantics.
    """
    if kernel is not None and kernel in KERNELS:
        return bool(KERNELS[kernel]["probe"]())
    return _default_probe()


# test-only overrides: kernel name -> forced mode (see forced_mode())
_FORCED: Dict[str, str] = {}


def dispatch_mode(kernel: str) -> str:
    """The mode the public API should dispatch for ``kernel`` right now."""
    forced = _FORCED.get(kernel)
    if forced is not None:
        return forced
    return "pallas" if pallas_supported(kernel) else KERNELS[kernel]["fallback"]


@contextlib.contextmanager
def forced_mode(kernel: str, mode: str) -> Iterator[None]:
    """Force :func:`dispatch_mode` for one kernel inside the block.

    Parity tests use this to drive the *public* APIs through the
    interpret-mode kernels on CPU meshes — dispatch never picks the
    interpreter on its own (it is orders of magnitude slower than XLA).
    """
    prev = _FORCED.get(kernel)
    _FORCED[kernel] = mode
    try:
        yield
    finally:
        if prev is None:
            _FORCED.pop(kernel, None)
        else:
            _FORCED[kernel] = prev


def record_dispatch(kernel: str, mode: str) -> None:
    """Report one public-API dispatch decision (call boundary only)."""
    _hooks.observe("kernel.dispatch", kernel=kernel, mode=mode)


KERNEL_STATS: Dict[str, int] = {"dispatches": 0}


def reset_kernel_stats() -> None:
    """Zero :data:`KERNEL_STATS` (counter-asserting tests bracket with
    this)."""
    KERNEL_STATS.clear()
    KERNEL_STATS["dispatches"] = 0


def _observer(event: str, ctx: dict) -> None:
    if event == "kernel.dispatch":
        KERNEL_STATS["dispatches"] += 1
        key = f"{ctx.get('kernel', '?')}.{ctx.get('mode', '?')}"
        KERNEL_STATS[key] = KERNEL_STATS.get(key, 0) + 1


_hooks.add_observer(_observer)
