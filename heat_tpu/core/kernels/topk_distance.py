"""Fused pairwise-distance + top-k Pallas kernel ("flash kNN").

The reference's kNN predict (``heat/classification/kneighborsclassifier.py:
10-136``) materializes the full (n_query, n_train) distance matrix and then
takes a top-k — HBM traffic and capacity O(n·m). This kernel streams y-tiles
through VMEM, keeps a running per-row top-k carry in the output block, and
never writes the distance matrix: O(n·k) output, one pass over x and y.

Distances are squared euclidean computed with the MXU-friendly quadratic
expansion ``|x|² + |y|² - 2·x@yᵀ`` (same formula as
``spatial.distance._quadratic_expand``), so values — and therefore
neighbor ordering — match the materializing path bit for bit. Ties break
toward the lower index, matching ``jax.lax.top_k``.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._dispatch import pallas_supported, register_kernel

try:  # pallas TPU backend is optional at import time (CPU test meshes)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["nearest_neighbors", "pallas_supported", "TOPK_KERNEL"]

_INT_MAX = 2**31 - 1  # python int: jnp constants would be captured consts in kernels

TOPK_KERNEL = register_kernel(
    "topk_distance",
    fallback="fallback",
    comparator="materializing cdist + jax.lax.top_k ((n, m) distance matrix in HBM)",
    roofline="one pass over x and y, O(n·k) output — never writes the (n, m) matrix",
)


def _merge_topk(cat_d: jnp.ndarray, cat_i: jnp.ndarray, k: int):
    """k smallest (distance, index) lexicographic pairs per row.

    Gather-free (Mosaic-friendly): k rounds of min-reduce + mask-out over
    the (rows, carry+tile) concatenation. Duplicate distances are
    disambiguated by the globally-unique column index, so exactly one entry
    is retired per round and ties break toward the lower index.
    """
    out_d, out_i = [], []
    d = cat_d
    for _ in range(k):
        mval = jnp.min(d, axis=1, keepdims=True)
        is_min = d == mval
        sel = jnp.min(
            jnp.where(is_min, cat_i, jnp.int32(_INT_MAX)), axis=1, keepdims=True
        )
        out_d.append(mval)
        out_i.append(sel)
        d = jnp.where(is_min & (cat_i == sel), jnp.inf, d)
    return jnp.concatenate(out_d, axis=1), jnp.concatenate(out_i, axis=1)


def _knn_kernel(x_ref, y_ref, d_ref, i_ref, *, k: int, m: int, tile_m: int):
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        d_ref[:] = jnp.full(d_ref.shape, jnp.inf, dtype=d_ref.dtype)
        i_ref[:] = jnp.full(i_ref.shape, _INT_MAX, dtype=i_ref.dtype)

    x = x_ref[:]
    y = y_ref[:]
    xy = jnp.dot(x, y.T, preferred_element_type=jnp.float32)
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    y2 = jnp.sum(y * y, axis=1)[None, :]
    tile = jnp.maximum(x2 + y2 - 2.0 * xy, 0.0)
    col = jax.lax.broadcasted_iota(jnp.int32, tile.shape, 1) + j * tile_m
    if m % tile_m:  # mask the ragged last y-tile (m is static: y.shape[0])
        tile = jnp.where(col < m, tile, jnp.inf)
    nd, ni = _merge_topk(
        jnp.concatenate([d_ref[:], tile], axis=1),
        jnp.concatenate([i_ref[:], col], axis=1),
        k,
    )
    d_ref[:] = nd
    i_ref[:] = ni


@functools.partial(jax.jit, static_argnames=("k", "tile_n", "tile_m", "interpret"))
def _knn_local(x, y, k: int, tile_n: int, tile_m: int, interpret: bool):
    n, f = x.shape
    m = y.shape[0]
    xp = jnp.pad(x, ((0, (-n) % tile_n), (0, 0)))
    yp = jnp.pad(y, ((0, (-m) % tile_m), (0, 0)))
    grid = (xp.shape[0] // tile_n, yp.shape[0] // tile_m)
    if pltpu is not None and not interpret:
        vmem = pltpu.VMEM
    else:  # interpreter path (CPU test meshes) has no TPU memory spaces
        vmem = pl.ANY
    # index maps derive their zero components from the grid args (j - j)
    # instead of the literal 0: this Mosaic build mis-legalizes i64 index-map
    # constants mixed with i32 grid indices ("failed to legalize func.return")
    xmap = lambda i, j: (i, j - j)
    ymap = lambda i, j: (j, i - i)
    kwargs = {}
    if pltpu is not None and not interpret:
        # the (tile_n, tile_m) scratch + double-buffered y-tiles exceed the
        # 16MB default scoped-vmem limit at the fastest tile shapes
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024
        )
    d, i = pl.pallas_call(
        functools.partial(_knn_kernel, k=k, m=m, tile_m=tile_m),
        grid=grid,
        **kwargs,
        in_specs=[
            pl.BlockSpec((tile_n, f), xmap, memory_space=vmem),
            pl.BlockSpec((tile_m, f), ymap, memory_space=vmem),
        ],
        out_specs=[
            pl.BlockSpec((tile_n, k), xmap, memory_space=vmem),
            pl.BlockSpec((tile_n, k), xmap, memory_space=vmem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((xp.shape[0], k), jnp.float32),
            jax.ShapeDtypeStruct((xp.shape[0], k), jnp.int32),
        ],
        interpret=interpret,
    )(xp, yp)
    return d[:n], i[:n]


def nearest_neighbors(
    x: jnp.ndarray,
    y: jnp.ndarray,
    k: int,
    *,
    tile_n: int = 256,
    tile_m: int | None = None,
    interpret: bool | None = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """k nearest reference rows for every query row, without the (n, m)
    distance matrix.

    Parameters
    ----------
    x : (n, f) queries; y : (m, f) references — single-device arrays
        (callers shard_map over a mesh for split operands).
    k : neighbors to keep (k <= m). The merge pass costs O(k*(k+tile_m))
        per tile, so the kernel is profitable for small k (<= ~64);
        callers should prefer the materializing cdist+top_k path beyond
        that (see ``KNeighborsClassifier.predict``'s gate).

    Returns
    -------
    (d2, idx) : (n, k) squared distances (ascending) and reference indices.
    """
    if x.ndim != 2 or y.ndim != 2 or x.shape[1] != y.shape[1]:
        raise ValueError(f"bad operand shapes {x.shape} x {y.shape}")
    m = y.shape[0]
    if not 0 < k <= m:
        raise ValueError(f"k={k} must be in [1, {m}]")
    if interpret is None:
        interpret = not pallas_supported(TOPK_KERNEL)
    x = x.astype(jnp.float32)
    y = y.astype(jnp.float32)
    tile_n = min(tile_n, max(8, x.shape[0]))
    if tile_m is None:
        # wide y-tiles amortize the merge passes (measured 2.5x over the
        # materializing path at (256, 8192)); cap the (tile_n, tile_m)
        # scratch at 8MB and the y-tile at 4MB to stay inside VMEM
        f = x.shape[1]
        tile_m = min(8192, (1 << 21) // tile_n, (1 << 20) // max(f, 1))
    tile_m = max(128, min(tile_m, max(128, m)) // 128 * 128)
    return _knn_local(x, y, k, tile_n, tile_m, interpret)
