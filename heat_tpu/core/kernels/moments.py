"""Fused one-pass Welford moments kernel (count / mean / M2 in one read).

The public two-call sequence ``ht.mean(x)`` + ``ht.std(x)`` used to read
the data three times (mean; then std's own mean + centered pass) while
the fused bench probe showed a single-read sweep at the HBM roofline
(VERDICT round 5: 562 GB/s fused vs ~250 through the API). This module
is the single-read path:

- :func:`moments_local` — a pallas kernel that streams row tiles of a
  local (n, f) buffer through VMEM and Chan-merges each tile's
  (count, mean, M2) into a carried accumulator: exactly one HBM pass,
  compiled on TPU, interpreted on CPU test meshes (parity tests only —
  the interpreter is far slower than XLA);
- :func:`chunk_moments` — the raw-jnp twin of the same dataflow
  (shifted one-pass sums, one fused XLA program, still a single read),
  the default fast path off-TPU and the building block
  ``stream.StreamingMoments``' fold and ``ht.mean``/``ht.var``/
  ``ht.std``'s moments panel dispatch through;
- :func:`moments_sharded` — shard_map wrapper combining per-shard
  moments with the parallel Chan formulas (psum of counts and
  count-weighted means, then M2 correction).

Roofline: axis-0 moments of an (n, f) f32 buffer move ``4nf`` bytes and
do O(nf) FLOPs — pure HBM bandwidth. One read is the floor; this kernel
is at it. Comparator: ``jnp.mean`` + ``jnp.std`` (three reads).

Numerics: per-tile/per-chunk sums use the first valid row as a shift
(variance is shift-invariant), so M2 matches the two-pass oracle to
float32 re-association (~1e-6 relative — the documented tolerance in
the parity tests). Merging follows Chan et al., the same formulas as
``stream.estimators``.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._dispatch import register_kernel

try:  # pallas TPU backend is optional at import time (CPU test meshes)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["chunk_moments", "moments_local", "moments_sharded", "MOMENTS_KERNEL"]

MOMENTS_KERNEL = register_kernel(
    "moments_onepass",
    fallback="xla",
    comparator="jnp.mean + jnp.std (three data reads)",
    roofline="one HBM read of the (n, f) buffer; O(nf) FLOPs — bandwidth bound",
)


def chunk_moments(xa: jnp.ndarray, n_valid):
    """(count, mean, M2) per column of a padded (n, f) buffer, one read.

    Traceable raw-jnp twin of the pallas kernel: the shifted one-pass
    sums ``s1 = Σ(x - x₀)`` and ``s2 = Σ(x - x₀)²`` fuse into a single
    XLA loop over the buffer (no dependent second pass — ``jnp.var``'s
    ``mean`` → ``mean((x - mean)²)`` chain cannot fuse). Rows at index
    ``>= n_valid`` are masked out; ``n_valid`` may be a traced scalar.
    """
    row = jax.lax.broadcasted_iota(jnp.int32, (xa.shape[0], 1), 0)
    valid = row < n_valid
    shift = xa[0:1, :]  # first row is always logically valid
    xs = jnp.where(valid, xa - shift, 0.0)
    nb = jnp.sum(valid.astype(xa.dtype))
    nb1 = jnp.maximum(nb, 1.0)
    s1 = jnp.sum(xs, axis=0)
    s2 = jnp.sum(xs * xs, axis=0)
    mean = shift[0] + s1 / nb1
    m2 = jnp.maximum(s2 - s1 * s1 / nb1, 0.0)
    return nb, mean, m2


def merge_moments(na, mean_a, m2_a, nb, mean_b, m2_b):
    """Chan pairwise combine of two (count, mean, M2) states (traceable)."""
    n = na + nb
    n1 = jnp.maximum(n, 1.0)
    delta = mean_b - mean_a
    mean = mean_a + delta * (nb / n1)
    m2 = m2_a + m2_b + delta * delta * (na * nb / n1)
    return n, mean, m2


def _moments_kernel(nv_ref, x_ref, cnt_ref, mean_ref, m2_ref, *, tile_n: int):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        cnt_ref[:] = jnp.zeros(cnt_ref.shape, cnt_ref.dtype)
        mean_ref[:] = jnp.zeros(mean_ref.shape, mean_ref.dtype)
        m2_ref[:] = jnp.zeros(m2_ref.shape, m2_ref.dtype)

    x = x_ref[:]
    row = jax.lax.broadcasted_iota(jnp.int32, (x.shape[0], 1), 0) + i * tile_n
    valid = row < nv_ref[0, 0]
    xs = jnp.where(valid, x, 0.0)
    nb = jnp.sum(valid.astype(x.dtype))
    nb1 = jnp.maximum(nb, 1.0)
    mean_b = jnp.sum(xs, axis=0, keepdims=True) / nb1
    d = jnp.where(valid, x - mean_b, 0.0)  # tile stays in VMEM: still one HBM read
    m2_b = jnp.sum(d * d, axis=0, keepdims=True)
    na = cnt_ref[0, 0]
    n = na + nb
    n1 = jnp.maximum(n, 1.0)
    delta = mean_b - mean_ref[:]
    mean_ref[:] = mean_ref[:] + delta * (nb / n1)
    m2_ref[:] = m2_ref[:] + m2_b + delta * delta * (na * nb / n1)
    cnt_ref[0, 0] = n


@functools.partial(jax.jit, static_argnames=("tile_n", "interpret"))
def _moments_call(xa, n_valid, tile_n: int, interpret: bool):
    n, f = xa.shape
    fp = -f % 128  # lane-pad: padded columns carry zeros, sliced off below
    xp = jnp.pad(xa, ((0, (-n) % tile_n), (0, fp)))
    grid = (xp.shape[0] // tile_n,)
    if pltpu is not None and not interpret:
        vmem = pltpu.VMEM
    else:  # interpreter path (CPU test meshes) has no TPU memory spaces
        vmem = pl.ANY
    # zero index-map components derive from the grid arg (i - i): this
    # Mosaic build mis-legalizes i64 index-map constants (see topk_distance)
    amap = lambda i: (i - i, i - i)
    kwargs = {}
    if pltpu is not None and not interpret:
        kwargs["compiler_params"] = pltpu.CompilerParams(
            vmem_limit_bytes=64 * 1024 * 1024
        )
    cnt, mean, m2 = pl.pallas_call(
        functools.partial(_moments_kernel, tile_n=tile_n),
        grid=grid,
        **kwargs,
        in_specs=[
            pl.BlockSpec((1, 1), amap, memory_space=vmem),
            pl.BlockSpec((tile_n, xp.shape[1]), lambda i: (i, i - i), memory_space=vmem),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), amap, memory_space=vmem),
            pl.BlockSpec((1, xp.shape[1]), amap, memory_space=vmem),
            pl.BlockSpec((1, xp.shape[1]), amap, memory_space=vmem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, xp.shape[1]), jnp.float32),
            jax.ShapeDtypeStruct((1, xp.shape[1]), jnp.float32),
        ],
        interpret=interpret,
    )(jnp.asarray(n_valid, jnp.int32).reshape(1, 1), xp)
    return cnt[0, 0], mean[0, :f], m2[0, :f]


def moments_local(
    xa: jnp.ndarray,
    n_valid=None,
    *,
    tile_n: int = 1024,
    interpret: bool | None = None,
):
    """(count, mean, M2) per column of a local (n, f) buffer via the
    pallas kernel: row tiles stream through VMEM, each tile's moments
    Chan-merge into the carried accumulator — one HBM pass total.

    ``n_valid`` masks buffer tail padding (defaults to all rows).
    """
    if xa.ndim != 2:
        raise ValueError(f"moments_local expects a 2-D buffer, got {xa.shape}")
    from ._dispatch import pallas_supported

    if interpret is None:
        interpret = not pallas_supported(MOMENTS_KERNEL)
    xa = xa.astype(jnp.float32)
    if n_valid is None:
        n_valid = xa.shape[0]
    # keep the tile a multiple of 8: unaligned block shapes break Mosaic
    tile_n = max(8, min(tile_n, -(-xa.shape[0] // 8) * 8))
    return _moments_call(xa, n_valid, tile_n, interpret)


def moments_sharded(xa, n_valid, mesh, *, tile_n: int = 1024, interpret: bool | None = None):
    """Global (count, mean, M2) of a split-0 sharded (n, f) buffer.

    Each shard runs :func:`moments_local`; the parallel Chan combine
    (psum counts and count-weighted means, then correct each shard's M2
    by its mean's distance to the global mean) runs over the mesh axis.
    ``n_valid`` is the GLOBAL logical row count; each shard derives its
    local validity window from its position.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..communication import SPLIT_AXIS

    p = mesh.devices.size
    mi = xa.shape[0] // p

    def local(xs, nv_g):
        r = jax.lax.axis_index(SPLIT_AXIS)
        nv = jnp.clip(nv_g - r * mi, 0, mi)
        cnt, mean, m2 = moments_local(xs, nv, tile_n=tile_n, interpret=interpret)
        gcnt = jax.lax.psum(cnt, SPLIT_AXIS)
        gcnt1 = jnp.maximum(gcnt, 1.0)
        gmean = jax.lax.psum(cnt * mean, SPLIT_AXIS) / gcnt1
        dm = mean - gmean
        gm2 = jax.lax.psum(m2 + cnt * dm * dm, SPLIT_AXIS)
        return gcnt, gmean, gm2

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(SPLIT_AXIS, None), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,  # pallas_call out_shapes carry no vma info
    )(xa, jnp.asarray(n_valid, jnp.int32))
