"""Parallel I/O (reference ``heat/core/io.py``).

The reference reads per-rank byte/row ranges through parallel HDF5
(``mpio`` driver) / netCDF4 / CSV splitting (``io.py:57-1111``). Under
single-controller JAX the controller reads and shards via ``device_put``;
under multi-host each process reads only its ``comm.chunk`` slice and the
global array is assembled with ``jax.make_array_from_single_device_arrays``.
netCDF uses the netCDF4 library when installed, else an h5py fallback for
the netCDF-4/HDF5 data model (netCDF-4 files ARE HDF5 files).
"""
from __future__ import annotations

import csv as csv_module
import os
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from . import _hooks, devices, types
from ._atomic import atomic_write, tmp_path_for
from ._retry import NO_RETRY, RetryPolicy
from .communication import _assemble_from_chunks, sanitize_comm
from .dndarray import DNDarray

try:
    import h5py

    __HDF5_EXTENSIONS = [".h5", ".hdf5"]
    __HAS_HDF5 = True
except ImportError:  # pragma: no cover
    __HDF5_EXTENSIONS = []
    __HAS_HDF5 = False

try:  # pragma: no cover - not in this image
    import netCDF4 as nc

    __NETCDF_EXTENSIONS = [".nc", ".nc4", ".netcdf"]
    __HAS_NETCDF = True
except ImportError:
    __NETCDF_EXTENSIONS = [".nc", ".nc4", ".netcdf"]
    __HAS_NETCDF = False

__CSV_EXTENSION = ".csv"

__all__ = [
    "load",
    "load_csv",
    "load_hdf5",
    "load_netcdf",
    "save",
    "save_csv",
    "save_hdf5",
    "save_netcdf",
    "supports_hdf5",
    "supports_netcdf",
]


def _row_window(n_rows: int, start: Optional[int], stop: Optional[int]) -> tuple:
    """Clamp an axis-0 row window to ``[0, n_rows]`` with Python-slice
    semantics (``None`` endpoints, negatives count from the end). All
    three loaders resolve their uniform ``start``/``stop`` arguments
    through this one helper so a window means the same thing for
    HDF5, netCDF and CSV — the contract ``stream.ChunkIterator`` reads
    chunks through."""
    r0, r1, _ = slice(start, stop).indices(int(n_rows))
    return r0, max(r0, r1)


def _offset_row_slices(slices: tuple, r0: int, w_rows: int) -> tuple:
    """Rebase assembly slices (relative to a row window) onto absolute
    file rows: axis 0 shifts by ``r0``; other axes pass through."""
    s0 = slices[0]
    lo = r0 + (s0.start or 0)
    hi = r0 + (w_rows if s0.stop is None else s0.stop)
    return (slice(lo, hi),) + tuple(slices[1:])


def _check_path_visible(path: str) -> None:
    """Divergence-proof existence check for multi-process loads.

    ``os.path.exists`` is a per-host answer: when a path exists on one
    host but not another, the host that sees it proceeds into a backend
    read (and its collectives) while the other raises — the survivors
    then hang at the next collective waiting for a process that already
    left. The allgather makes the verdict REPLICATED: all processes
    raise together (``FileNotFoundError`` when nobody sees the path, a
    clear cross-host visibility ``OSError`` when only some do), or all
    proceed together.
    """
    visible = os.path.exists(path)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        vis = np.asarray(
            multihost_utils.process_allgather(
                np.asarray([1 if visible else 0], dtype=np.int32)
            )
        ).ravel()
        if not vis.any():
            raise FileNotFoundError(
                f"no such file: {path!r} (missing on all {vis.size} processes)"
            )
        if not vis.all():
            raise OSError(
                f"{path!r} is visible on process(es) "
                f"{np.nonzero(vis)[0].tolist()} but missing on "
                f"{np.nonzero(vis == 0)[0].tolist()} — every process must see "
                "the same path (shared filesystem or identical per-host "
                "copies); refusing the divergent read that would hang the "
                "next collective"
            )
    elif not visible:
        raise FileNotFoundError(f"no such file: {path!r}")


def _single_writer_commit(label: str, write) -> None:
    """Single-writer + barrier pattern for whole-array saves.

    Process 0 runs ``write()`` (which must itself be atomic: temp file +
    ``os.replace``); every other process blocks at the barrier until the
    commit happened, so a reader on another process can never observe the
    pre-rename state. The status gather makes failure symmetric: a
    writer-side error raises on ALL processes instead of stranding the
    non-writers one collective later.
    """
    err = None
    try:
        if jax.process_index() == 0:
            write()
    except BaseException as e:  # noqa: BLE001 - re-raised after the barrier
        err = e
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"heat_tpu_{label}")
        statuses = np.asarray(
            multihost_utils.process_allgather(np.asarray([0 if err is None else 1]))
        ).ravel()
        if err is None and statuses.any():
            raise OSError(
                f"{label} failed on process(es) {np.nonzero(statuses)[0].tolist()}"
            )
    if err is not None:
        raise err


def _h5_read_open(path: str):
    """Open an HDF5 file read-only WITHOUT taking the HDF5 file lock.

    At ws>1 every process opens the same file, and two processes can
    legitimately hold read handles concurrently (streaming chunk reads,
    overlapped prefetch). libhdf5's default file locking makes that a
    race: a reader fails with ``BlockingIOError: unable to lock file``
    while a sibling's handle is open on storage where POSIX locks are
    per-file, not per-handle. Lock-free reads are safe here because no
    reader ever races a writer's bytes — every write in this module
    stages into a temp file and commits by atomic rename, so an open
    path always names a fully-written file. ``locking=False`` needs
    h5py >= 3.5 (HDF5 >= 1.12.1); older stacks fall back to the default
    locked open.
    """
    try:
        return h5py.File(path, "r", locking=False)
    except TypeError:  # pragma: no cover - old h5py without the kwarg
        return h5py.File(path, "r")


def supports_hdf5() -> bool:
    """Whether h5py is available (reference ``io.py``)."""
    return __HAS_HDF5


def supports_netcdf() -> bool:
    """Whether netCDF I/O is available (reference ``io.py``): the netCDF4
    library, or the h5py fallback for the netCDF-4/HDF5 data model."""
    return __HAS_NETCDF or __HAS_HDF5


def load(path: str, *args, retry: Optional[RetryPolicy] = None, **kwargs) -> DNDarray:
    """Load by file extension (reference ``io.py:662``).

    A missing file raises ``FileNotFoundError`` naming the path *before*
    extension dispatch — the backends otherwise surface inconsistent
    ``OSError``/``KeyError`` texts for the same mistake. ``retry`` (a
    :class:`~heat_tpu.resilience.retry.RetryPolicy`) reruns the whole
    backend read on transient OSError/TimeoutError with backoff; the
    default is a single attempt.
    """
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    _check_path_visible(path)
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in (".h5", ".hdf5"):
        backend = load_hdf5
    elif extension in __NETCDF_EXTENSIONS:
        backend = load_netcdf
    elif extension == __CSV_EXTENSION:
        backend = load_csv
    else:
        raise ValueError(f"Unsupported file extension {extension}")

    def attempt():
        _hooks.fault_point("io.open", path=path)
        return backend(path, *args, **kwargs)

    return (retry or NO_RETRY).call(attempt, label=f"load({path!r})")


def load_hdf5(
    path: str,
    dataset: str,
    dtype=types.float32,
    split: Optional[int] = None,
    device=None,
    comm=None,
    start: Optional[int] = None,
    stop: Optional[int] = None,
) -> DNDarray:
    """Load an HDF5 dataset, each process reading only its chunk (reference
    ``io.py:57``).

    ``start``/``stop`` select an axis-0 row window ``[start, stop)``
    (Python-slice semantics) BEFORE the split: only the window's rows are
    read from disk, and the returned array's shape-0 is the window
    length. This is the chunked-read contract ``stream.ChunkIterator``
    iterates over; the same arguments exist on :func:`load_netcdf` and
    :func:`load_csv`.

    Host-boundary audit (VERDICT round 5): EVERY process opens ``path``
    and reads its own devices' slices — there is no host-0-only read or
    scatter. The path must therefore resolve on all hosts (shared
    filesystem or identical per-host copies), and the file contents must
    be identical everywhere; a per-host divergent file silently produces
    divergent shards.
    """
    if not __HAS_HDF5:
        raise ImportError("h5py is required for HDF5 support")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(dataset, str):
        raise TypeError(f"dataset must be str, not {type(dataset)}")
    comm = sanitize_comm(comm)
    dtype = types.canonical_heat_type(dtype)
    with _h5_read_open(path) as handle:
        data = handle[dataset]
        fshape = tuple(data.shape)
        r0, r1 = _row_window(fshape[0] if fshape else 0, start, stop)
        gshape = ((r1 - r0,) + fshape[1:]) if fshape else fshape
        if split is not None:
            from .stride_tricks import sanitize_axis

            split = sanitize_axis(gshape, split)
        if split is not None and comm.size > 1:
            # chunked path (reference io.py:57-147's per-rank slice reads):
            # each PROCESS reads only its devices' slices from the file and
            # the global padded buffer is assembled shard-by-shard — no
            # device and no host ever holds the full array.
            garr = _assemble_from_chunks(
                lambda slices: np.asarray(
                    data[_offset_row_slices(slices, r0, r1 - r0)],
                    dtype=np.dtype(dtype.jax_type()),
                ),
                gshape,
                split,
                comm,
                np.dtype(dtype.jax_type()),
            )
            return DNDarray._from_buffer(
                garr, gshape, dtype, split, devices.sanitize_device(device), comm
            )
        window = data[r0:r1] if fshape else data[...]
        arr = np.asarray(window, dtype=np.dtype(dtype.jax_type()))
    return DNDarray(jnp.asarray(arr), dtype=dtype, split=split, device=device, comm=comm)




def save_hdf5(data: DNDarray, path: str, dataset: str, mode: str = "w", **kwargs) -> None:
    """Save to HDF5 (reference ``io.py:149``: parallel ``mpio`` driver or
    rank-serialized writes; rank-serialized here — each process writes only
    its local shards' regions, coordinated by a global barrier).

    Writes are ATOMIC: all bytes land in a temp file next to ``path``
    (append modes first copy the existing file there) and ``os.replace``
    commits only on success — a crash or injected mid-write fault can
    never corrupt a previously-saved file. Multi-host, every process
    stages into the SAME deterministic temp name and process 0 renames
    after the success barrier.
    """
    if not __HAS_HDF5:
        raise ImportError("h5py is required for HDF5 support")
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    nproc = jax.process_count()
    comm_spans_processes = (
        len({d.process_index for d in data.comm.mesh.devices.ravel()}) > 1
    )
    if nproc > 1 and data.split is not None and comm_spans_processes:
        from jax.experimental import multihost_utils

        pid = jax.process_index()
        gshape = data.gshape
        # each addressable shard's global placement comes straight from
        # jax (shard.index on the padded buffer), clipped to the logical
        # extent — no hand-rolled device->rank bookkeeping
        local = []  # (clipped global slices, trimmed chunk)
        for shard in data.larray.addressable_shards:
            sl, trim = [], []
            for d, s in enumerate(shard.index):
                start = 0 if s.start is None else min(s.start, gshape[d])
                stop = gshape[d] if s.stop is None else min(s.stop, gshape[d])
                sl.append(slice(start, stop))
                trim.append(slice(0, stop - start))
            if all(s.stop > s.start for s in sl):
                local.append((tuple(sl), np.asarray(shard.data)[tuple(trim)]))
        # all processes stage into the SAME temp file (deterministic
        # suffix, NOT the pid); the destination is touched only by the
        # final rename, so a failure at any round leaves it intact
        tmp = tmp_path_for(path, suffix="mh")
        err = None
        try:
            _hooks.fault_point("io.open", path=path)
            if pid == 0 and mode != "w" and os.path.exists(path):
                import shutil

                shutil.copy2(path, tmp)  # append modes extend a copy
        except BaseException as e:  # noqa: BLE001 - re-raised below
            err = e
        multihost_utils.sync_global_devices("heat_tpu_save_hdf5_prep")
        # a failed write must not desert the remaining barriers (the other
        # processes would hang forever) — carry the error through every
        # round, then let ALL processes fail together via a status gather
        for p in range(nproc):
            try:
                if pid == p and err is None:
                    # process 0 truncates (unless appending to the staged
                    # copy — a stale temp from a crashed run must not leak
                    # in); later ranks extend what round 0 created
                    p0_mode = "a" if (mode != "w" and os.path.exists(tmp)) else "w"
                    with h5py.File(tmp, p0_mode if p == 0 else "a") as handle:
                        if p == 0:
                            handle.create_dataset(
                                dataset, shape=gshape, dtype=np.dtype(data.dtype.jax_type()), **kwargs
                            )
                        dset = handle[dataset]
                        for slices, chunk in local:
                            dset[slices] = chunk
            except BaseException as e:  # noqa: BLE001 - re-raised below;
                # even KeyboardInterrupt must reach the barrier first or
                # every other process hangs in sync forever
                err = e
            multihost_utils.sync_global_devices(f"heat_tpu_save_hdf5_{p}")
        statuses = np.asarray(
            multihost_utils.process_allgather(np.asarray([0 if err is None else 1]))
        ).ravel()
        if err is None and not statuses.any() and pid == 0:
            try:
                _hooks.fault_point("io.commit", path=path, tmp_path=tmp)
                os.replace(tmp, path)
            except BaseException as e:  # noqa: BLE001
                err = e
        if (err is not None or statuses.any()) and pid == 0:
            import contextlib

            with contextlib.suppress(OSError):
                os.remove(tmp)
        # second gather: the commit itself may have failed on process 0
        commit = np.asarray(
            multihost_utils.process_allgather(np.asarray([0 if err is None else 1]))
        ).ravel()
        if err is not None:
            raise err
        if statuses.any() or commit.any():
            raise OSError(
                f"save_hdf5 failed on process(es) "
                f"{np.nonzero(statuses | commit)[0].tolist()}"
            )
        return
    arr = data.numpy()

    def write():
        with atomic_write(path) as tmp:
            if mode != "w" and os.path.exists(path):
                import shutil

                shutil.copy2(path, tmp)  # append modes extend a copy
            with h5py.File(tmp, mode) as handle:
                handle.create_dataset(dataset, data=arr, **kwargs)

    _single_writer_commit("save_hdf5_commit", write)


def load_netcdf(
    path: str,
    variable: str,
    dtype=types.float32,
    split=None,
    device=None,
    comm=None,
    start: Optional[int] = None,
    stop: Optional[int] = None,
) -> DNDarray:
    """Load a netCDF variable (reference ``io.py:268``).

    Uses the ``netCDF4`` library when installed; otherwise falls back to
    reading the file through h5py — netCDF-4 files ARE HDF5 files
    (variables are datasets, dimensions are HDF5 dimension scales), so the
    fallback covers the standard netCDF-4 data model and reuses the
    parallel chunked-read path. Classic (netCDF-3) files need the real
    library.

    ``start``/``stop`` select an axis-0 row window ``[start, stop)``
    before the split — the same uniform window contract as
    :func:`load_hdf5` / :func:`load_csv` (only the window's rows are read
    on every backend, including the classic-format byte-range reader).

    Host-boundary audit: all backends open ``path`` on EVERY process (no
    host-0-only read); the path and its contents must be identical on all
    hosts. See :func:`load_hdf5`.
    """
    comm = sanitize_comm(comm)
    dtype = types.canonical_heat_type(dtype)
    if __HAS_NETCDF:  # pragma: no cover - not in this image
        with nc.Dataset(path, "r") as handle:
            try:  # __getitem__ resolves group-qualified names ('g/v') too
                var = handle[variable]
            except (KeyError, IndexError) as e:
                raise KeyError(f"variable {variable!r} not found in {path}") from e
            if var.shape and (start is not None or stop is not None):
                r0, r1 = _row_window(var.shape[0], start, stop)
                arr = np.asarray(var[r0:r1], dtype=np.dtype(dtype.jax_type()))
            else:
                arr = np.asarray(var[...], dtype=np.dtype(dtype.jax_type()))
        return DNDarray(jnp.asarray(arr), dtype=dtype, split=split, device=device, comm=comm)
    if _is_classic_netcdf(path):
        return _load_netcdf3(path, variable, dtype, split, device, comm, start, stop)
    if not __HAS_HDF5:
        raise ImportError("netCDF support needs netCDF4 or h5py installed")
    with _h5_read_open(path) as probe:
        if variable not in probe:
            raise KeyError(f"variable {variable!r} not found in {path}")
        # netCDF convention: a PURE dimension (no data) is a dimension
        # scale whose NAME attr says so; coordinate variables are scales
        # too but carry real data and must load fine
        name_attr = probe[variable].attrs.get("NAME", b"")
        if isinstance(name_attr, bytes) and name_attr.startswith(
            b"This is a netCDF dimension but not a netCDF variable"
        ):
            raise KeyError(f"{variable!r} is a dimension, not a data variable")
    return load_hdf5(
        path, variable, dtype=dtype, split=split, device=device, comm=comm,
        start=start, stop=stop,
    )


def _is_classic_netcdf(path: str) -> bool:
    from ._netcdf3 import is_classic_netcdf

    try:
        return is_classic_netcdf(path)
    except OSError:
        return False


def _load_netcdf3(path, variable, dtype, split, device, comm, start=None, stop=None):
    """Classic (CDF-1/2) load through the dependency-free parser
    (:mod:`heat_tpu.core._netcdf3`), chunked on the first dimension into
    the shared multi-host assembly — the reference's parallel
    ``nc.Dataset`` read of the same files (``io.py:268``). Classic files
    are row-major with row-granular byte ranges, so a ``split != 0``
    load reads row stripes (bounded memory) and slices columns in
    memory — the same IO the netCDF4 C library performs for column
    hyperslabs of classic files. ``start``/``stop`` window the first
    dimension: all reads below are rebased onto absolute file rows."""
    from ._netcdf3 import NetCDF3File

    reader = NetCDF3File(path)
    if variable not in reader.vars:
        raise KeyError(f"variable {variable!r} not found in {path}")
    fshape = reader.shape(variable)
    if fshape:
        w0, w1 = _row_window(fshape[0], start, stop)
        gshape = (w1 - w0,) + tuple(fshape[1:])
    else:
        w0, w1 = 0, 0
        gshape = fshape
    np_dtype = np.dtype(dtype.jax_type())
    if split is not None and gshape:
        from .stride_tricks import sanitize_axis

        split = sanitize_axis(gshape, split)
    if split is None or not gshape or comm.size == 1:
        if gshape:
            arr = np.asarray(reader.read(variable, w0, w1)).astype(np_dtype)
        else:
            arr = np.asarray(reader.read(variable)).astype(np_dtype)
        return DNDarray(
            jnp.asarray(arr), dtype=dtype, split=split, device=device, comm=comm
        )
    row_bytes = max(
        1,
        int(np.prod(gshape[1:], dtype=np.int64)) * reader.vars[variable].dtype.itemsize,
    )
    stripe = max(1, (4 << 20) // row_bytes)

    def read_chunk(slices):
        r0 = w0 + (slices[0].start or 0)
        r1 = w0 + (slices[0].stop if slices[0].stop is not None else gshape[0])
        rest = tuple(slices[1:])
        parts = []
        for s in range(r0, r1, stripe):
            rows = reader.read(variable, s, min(s + stripe, r1))
            parts.append(np.asarray(rows)[(slice(None),) + rest].astype(np_dtype))
        return parts[0] if len(parts) == 1 else np.concatenate(parts, axis=0)

    buf = _assemble_from_chunks(read_chunk, gshape, split, comm, np_dtype)
    return DNDarray._from_buffer(buf, gshape, dtype, split, device, comm)


def save_netcdf(
    data: DNDarray, path: str, variable: str, mode: str = "w", format: str = "NETCDF4", **kwargs
) -> None:
    """Save to netCDF (reference ``io.py:351``).

    With ``netCDF4`` installed the real library writes; otherwise a
    netCDF-4-compatible HDF5 file is produced directly with h5py:
    per-dimension datasets registered as HDF5 dimension scales and
    attached to the variable — the structure the netCDF-4 data model
    stores on disk, readable by netCDF tooling. ``format`` beginning
    with ``"NETCDF3"`` writes the classic CDF format through the
    dependency-free writer (:mod:`heat_tpu.core._netcdf3`) — CDF-2
    (64-bit offsets) for ``"NETCDF3_64BIT"``, else CDF-1.
    """
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    if __HAS_NETCDF:  # pragma: no cover - not in this image
        # the real library handles every format (incl. classic) with full
        # attribute/mode support; the pure writer below is the fallback
        arr = data.numpy()
        with nc.Dataset(path, mode, format=format) as handle:
            dims = []
            for i, s in enumerate(arr.shape):
                name = f"dim_{i}"
                handle.createDimension(name, s)
                dims.append(name)
            var = handle.createVariable(variable, arr.dtype, tuple(dims), **kwargs)
            var[...] = arr
        return
    if format.upper().startswith("NETCDF3"):
        from ._netcdf3 import write_netcdf3

        if mode != "w":
            raise ValueError("classic netCDF-3 save supports mode='w' only")
        err = None
        try:
            if jax.process_index() == 0:
                version = 2 if "64BIT" in format.upper() else 1
                arr = data.numpy()
                with atomic_write(path) as tmp:
                    write_netcdf3(tmp, variable, arr, version=version)
            else:
                data.numpy()  # participate in the gather collectives
        except BaseException as e:  # noqa: BLE001 - re-raised after the barrier
            err = e
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("heat_tpu_save_netcdf3")
            statuses = np.asarray(
                multihost_utils.process_allgather(np.asarray([0 if err is None else 1]))
            ).ravel()
            if err is None and statuses.any():
                raise OSError(
                    f"save_netcdf failed on process(es) {np.nonzero(statuses)[0].tolist()}"
                )
        if err is not None:
            raise err
        return
    if not __HAS_HDF5:
        raise ImportError("netCDF support needs netCDF4 or h5py installed")
    if mode not in ("w", "a", "r+"):
        raise ValueError(f"unsupported mode {mode!r}")
    if jax.process_count() == 1:
        # single-controller: variable AND dimension scales are staged in
        # one temp file and committed with a single rename — fully atomic
        arr = data.numpy()
        with atomic_write(path) as tmp:
            if mode != "w" and os.path.exists(path):
                import shutil

                shutil.copy2(path, tmp)
            with h5py.File(tmp, "a" if (mode != "w" and os.path.exists(tmp)) else "w") as handle:
                handle.create_dataset(variable, data=arr, **kwargs)
                _attach_netcdf_scales(handle, variable, data.gshape)
        return
    # multi-host: the variable write reuses save_hdf5 — including its
    # rank-serialized, barrier-coordinated, temp-staged atomic path — then
    # process 0 attaches the netCDF-4 dimension-scale structure (a second
    # phase on the committed file; a failure there leaves the data intact
    # but scale-less)
    save_hdf5(data, path, variable, mode=mode, **kwargs)
    err = None
    try:
        if jax.process_index() == 0:
            with h5py.File(path, "r+") as handle:
                _attach_netcdf_scales(handle, variable, data.gshape)
    except BaseException as e:  # noqa: BLE001 - re-raised after the barrier
        err = e
    if jax.process_count() > 1:
        # reach the barrier even on failure, then fail ALL processes
        # together — the full save_hdf5 discipline, not just the hang fix
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("heat_tpu_save_netcdf")
        statuses = np.asarray(
            multihost_utils.process_allgather(np.asarray([0 if err is None else 1]))
        ).ravel()
        if err is None and statuses.any():
            raise OSError(
                f"save_netcdf failed on process(es) {np.nonzero(statuses)[0].tolist()}"
            )
    if err is not None:
        raise err


def _attach_netcdf_scales(handle, variable: str, gshape) -> None:
    """Register per-dimension datasets as HDF5 dimension scales and attach
    them to ``variable`` — the on-disk structure of the netCDF-4 data model."""
    var = handle[variable]
    for i, n_i in enumerate(gshape):
        dname = f"dim_{i}_{variable}" if f"dim_{i}" in handle else f"dim_{i}"
        # shape-only dataset: netCDF4's own phony dimensions never
        # materialize their fill storage either
        scale = handle.create_dataset(dname, shape=(n_i,), dtype=np.float32)
        scale.make_scale(dname)
        # netCDF4's phony-dimension marker: these are dimensions, not data
        # variables (load_netcdf refuses to load them)
        scale.attrs["NAME"] = np.bytes_(
            b"This is a netCDF dimension but not a netCDF variable. %10d" % n_i
        )
        var.dims[i].attach_scale(scale)


def _py_csv_range(path, offset, length, header_lines, sep, encoding):
    """Rows owned by byte range [offset, offset+length): pure-Python
    fallback for :func:`heat_tpu.native.csv_parse_range`, reading only its
    range (plus the straddling tail line) — the reference's per-rank seek/
    readline convention (``io.py:713-924``)."""
    import io as _io

    with open(path, "rb") as f:
        for _ in range(header_lines):
            if not f.readline():
                break
        data_start = f.tell()
        f.seek(0, os.SEEK_END)
        fsize = f.tell()
        lo = max(offset, data_start)
        hi = min(offset + length, fsize) if length >= 0 else fsize
        if lo > data_start:
            # a line starting before lo belongs to the previous range
            f.seek(lo - 1)
            f.readline()
        else:
            f.seek(data_start)
        chunks = []
        while f.tell() < hi:
            line = f.readline()
            if not line:
                break
            chunks.append(line)
    text = b"".join(chunks).decode(encoding)
    if not text.strip():
        return np.empty((0, 0), dtype=np.float64)
    return np.loadtxt(
        _io.StringIO(text), delimiter=sep, dtype=np.float64, ndmin=2
    )


def _rebalance_csv_rows(local: np.ndarray, comm) -> tuple:
    """Move byte-range-parsed rows to their canonical-chunk owners.

    Byte ranges almost never split exactly at the canonical per-device row
    boundaries, so each process exchanges only its BOUNDARY SURPLUS (rows
    it parsed that belong to another process's devices) via one padded
    allgather — O(max surplus) extra memory, not O(n) — and returns
    ``(rows_for_my_devices, t_lo, n_rows)`` with this process holding
    exactly the global row range its devices' chunks cover.
    """
    from jax.experimental import multihost_utils

    from .communication import _split_ranks

    nproc = jax.process_count()
    pid = jax.process_index()
    counts = np.asarray(
        multihost_utils.process_allgather(np.asarray([local.shape[0]], np.int64))
    ).reshape(-1)
    offs = np.concatenate([[0], np.cumsum(counts)])
    n = int(offs[-1])
    cr = comm.padded_dim(n) // comm.size
    mine = sorted({r for r, d in _split_ranks(comm) if d.process_index == pid})
    t_lo = min(r * cr for r in mine)
    t_hi = min(n, max((r + 1) * cr for r in mine))
    t_lo = min(t_lo, t_hi)
    o_lo = int(offs[pid])
    own_idx = np.arange(o_lo, o_lo + local.shape[0])
    keep = (own_idx >= t_lo) & (own_idx < t_hi)
    surplus, surplus_idx = local[~keep], own_idx[~keep]
    caps = np.asarray(
        multihost_utils.process_allgather(np.asarray([len(surplus)], np.int64))
    ).reshape(-1)
    out = np.empty((t_hi - t_lo,) + local.shape[1:], dtype=local.dtype)
    out[own_idx[keep] - t_lo] = local[keep]
    cap = int(caps.max())
    if cap > 0:
        # rows travel in their NATIVE dtype (an f64 round-trip would
        # silently round int64 values above 2^53); indices ride a second
        # small int64 gather
        pad_rows = cap - len(surplus)
        sp = np.pad(surplus, [(0, pad_rows)] + [(0, 0)] * (local.ndim - 1))
        si = np.pad(surplus_idx.astype(np.int64), (0, pad_rows), constant_values=-1)
        all_sp = np.asarray(multihost_utils.process_allgather(sp))
        all_si = np.asarray(multihost_utils.process_allgather(si))
        for q in range(nproc):
            sel = (all_si[q] >= t_lo) & (all_si[q] < t_hi)
            out[all_si[q][sel] - t_lo] = all_sp[q][sel]
    return out, t_lo, n


def _float_fields_parse(path, header_lines, sep, encoding, dtype, start=0, max_rows=None):
    """Reference-exact CSV row parse: ``line.split(sep)`` + Python
    ``float()`` per field (``/root/reference/heat/core/io.py:800-806``) —
    the ONE implementation both the loadtxt-rejected fallback and the
    multi-character-separator path share. ``start``/``max_rows`` window
    the non-blank data rows (the loaders' uniform row-window contract)."""
    with open(path, "r", encoding=encoding) as f:
        lines = f.read().splitlines()[header_lines:]
    data_lines = [line for line in lines if line.strip()]
    stop = None if max_rows is None else start + max_rows
    rows = [
        [float(field) for field in line.split(sep)] for line in data_lines[start:stop]
    ]
    return np.array(rows, dtype=np.float64, ndmin=2).astype(np.dtype(dtype.jax_type()))


def load_csv(
    path: str,
    header_lines: int = 0,
    sep: str = ",",
    dtype=types.float32,
    encoding: str = "utf-8",
    split: Optional[int] = None,
    device=None,
    comm=None,
    start: Optional[int] = None,
    stop: Optional[int] = None,
) -> DNDarray:
    """Load a CSV file (reference ``io.py:713``).

    Multi-host with ``split=0``: each process parses only its own byte
    range of the file (native ``csv_parse_range`` or the Python seek
    fallback) — row boundaries resolved by first-byte ownership exactly
    like the reference's per-rank reads — and the global padded buffer is
    assembled from the per-process shards; no process reads the whole
    file. Single-host (all devices process-local): one parse, sharded by
    the constructor.

    ``start``/``stop`` select a data-row window ``[start, stop)`` (rows
    counted after ``header_lines``, blank lines excluded) — the same
    uniform window contract as :func:`load_hdf5` / :func:`load_netcdf`,
    read via ``skiprows``/``max_rows`` so only the window is parsed.
    Because a CSV's row count is unknown without a full scan, windowed
    reads require ``start >= 0`` and ``stop >= 0`` (no negative
    indices), and a windowed read takes the whole-file-per-process parse
    path (each window is chunk-sized, so the per-process cost stays
    bounded); the multi-host byte-range split is for full-file loads.

    Host-boundary audit: both paths open ``path`` on every process — a
    shared (or identically replicated) filesystem is assumed; there is
    no host-0-read-and-scatter mode. See :func:`load_hdf5`.
    """
    if not isinstance(path, str):
        raise TypeError(f"path must be str, not {type(path)}")
    if not isinstance(sep, str):
        raise TypeError(f"separator must be str, not {type(sep)}")
    if not isinstance(header_lines, int):
        raise TypeError(f"header_lines must be int, not {type(header_lines)}")
    windowed = start is not None or stop is not None
    if windowed and ((start or 0) < 0 or (stop is not None and stop < 0)):
        raise ValueError(
            "CSV row windows do not support negative indices (the row count "
            f"is unknown without a full scan): start={start}, stop={stop}"
        )
    w0 = int(start or 0)
    w_max = None if stop is None else max(0, int(stop) - w0)
    dtype = types.canonical_heat_type(dtype)
    comm_s = sanitize_comm(comm)
    nproc = jax.process_count()
    # byte-range ownership needs a single-byte separator and an encoding
    # whose newline is the 0x0A byte; other inputs take the whole-file
    # path below (every process parses the file — the pre-round-3 cost)
    rangeable = len(sep) == 1 and encoding in ("utf-8", "ascii", "latin-1")
    if nproc > 1 and split == 0 and rangeable and not windowed:
        from jax.experimental import multihost_utils

        np_dtype = np.dtype(dtype.jax_type())
        fsize = os.path.getsize(path)
        per = -(-fsize // nproc)
        off = jax.process_index() * per
        from .. import native

        local = native.csv_parse_range(path, off, per, header_lines, sep, np_dtype)
        if local is None:
            local = _py_csv_range(path, off, per, header_lines, sep, encoding).astype(np_dtype)
        # empty ranges parse to (0, 0); they need the global column count
        # before shard assembly (non-split dims must agree)
        cols = int(
            np.asarray(
                multihost_utils.process_allgather(np.asarray([local.shape[1]], np.int64))
            ).max()
        )
        if local.shape[0] == 0:
            local = local.reshape(0, cols)
        # The boundary-surplus exchange assumes every split rank lives on
        # exactly one process and each process's ranks are contiguous —
        # true for the standard 1-D world mesh. Replicated or interleaved
        # layouts (hierarchical meshes) take the safe allgather assembly.
        from .communication import _split_ranks, assemble_local_shards

        rank_owners: dict = {}
        proc_ranks: dict = {}
        for r, d in _split_ranks(comm_s):
            rank_owners.setdefault(r, set()).add(d.process_index)
            proc_ranks.setdefault(d.process_index, set()).add(r)
        clean = all(len(o) == 1 for o in rank_owners.values()) and all(
            sorted(rs) == list(range(min(rs), max(rs) + 1))
            for rs in proc_ranks.values()
        )
        if not clean:
            buf, gshape = assemble_local_shards(local, 0, comm_s)
            return DNDarray._from_buffer(
                buf, gshape, dtype, 0, devices.sanitize_device(device), comm_s
            )
        # exchange only boundary surplus rows, then stitch each process's
        # devices' chunks directly — O(local) memory per process (the
        # uneven assemble_local_shards path would allgather the whole set)
        rows, t_lo, n_rows = _rebalance_csv_rows(local, comm_s)
        gshape = (n_rows, cols)
        garr = _assemble_from_chunks(
            lambda slices: rows[
                slices[0].start - t_lo : slices[0].stop - t_lo, slices[1]
            ],
            gshape,
            0,
            comm_s,
            np_dtype,
        )
        return DNDarray._from_buffer(
            garr, gshape, dtype, 0, devices.sanitize_device(device), comm_s
        )
    data = None
    if not windowed and encoding in ("utf-8", "ascii", "latin-1") and len(sep) == 1:
        # the native parser reads the whole file; a windowed read goes
        # through loadtxt's skiprows/max_rows so only the window is parsed
        from .. import native

        data = native.csv_parse(path, header_lines, sep, np.dtype(dtype.jax_type()))
    if data is None and len(sep) == 1:
        # reference semantics (io.py:800-806): every field parsed with
        # float(), rows of fields -> always 2-D, then cast to the requested
        # dtype. loadtxt(ndmin=2) matches that almost exactly; the rare
        # float()-isms loadtxt rejects (underscore numerals like "1_5")
        # get a last-resort pass through the reference-exact parser.
        try:
            data = np.loadtxt(
                path, delimiter=sep, skiprows=header_lines + w0, dtype=np.float64,
                encoding=encoding, ndmin=2, max_rows=w_max,
            ).astype(np.dtype(dtype.jax_type()))
        except ValueError:
            data = _float_fields_parse(
                path, header_lines, sep, encoding, dtype, start=w0, max_rows=w_max
            )
    elif data is None:
        # multi-character separators: loadtxt rejects them (numpy >= 1.23)
        data = _float_fields_parse(
            path, header_lines, sep, encoding, dtype, start=w0, max_rows=w_max
        )
    return DNDarray(jnp.asarray(data), dtype=dtype, split=split, device=device, comm=comm)


def save_csv(
    data: DNDarray,
    path: str,
    header_lines=None,
    sep: str = ",",
    decimals: int = -1,
    encoding: str = "utf-8",
    comm=None,
    truncate: bool = True,
    **kwargs,
) -> None:
    """Save to CSV (reference ``io.py:926``). ``truncate=False`` overwrites
    an existing file from offset 0 without shortening it (the reference's
    semantics — stale trailing rows survive); ``comm`` is accepted for
    signature parity (the controller writes once here)."""
    if not isinstance(data, DNDarray):
        raise TypeError(f"data must be a DNDarray, not {type(data)}")
    arr = data.numpy()
    if arr.ndim == 1:
        arr = arr[:, None]
    fmt = "%s"
    if types.heat_type_is_exact(data.dtype):
        fmt = "%d"
    elif decimals >= 0:
        fmt = f"%.{decimals}f"
    else:
        fmt = "%f"
    def write():
        header = None
        if header_lines is not None:
            header = "\n".join(header_lines) if not isinstance(header_lines, str) else header_lines
        if truncate or not os.path.exists(path):
            # full overwrite: render to bytes, then one atomic staged
            # write — a mid-write crash (or injected torn write) can never
            # corrupt an existing file
            import io as _io_module

            buf = _io_module.StringIO()
            np.savetxt(buf, arr, fmt=fmt, delimiter=sep, header=header or "", comments="")
            from ._atomic import atomic_write_bytes

            atomic_write_bytes(path, buf.getvalue().encode(encoding))
        else:
            # reference semantics (io.py:926): without truncation the file
            # is overwritten from offset 0 but never shortened — stale
            # trailing rows must survive, so this path is inherently
            # in-place (copy to temp first to keep the crash guarantee)
            with atomic_write(path) as tmp:
                import shutil

                shutil.copy2(path, tmp)
                with open(tmp, "r+", encoding=encoding) as fh:
                    fh.seek(0)
                    np.savetxt(fh, arr, fmt=fmt, delimiter=sep, header=header or "", comments="")

    _single_writer_commit("save_csv_commit", write)


def save(data: DNDarray, path: str, *args, retry: Optional[RetryPolicy] = None, **kwargs) -> None:
    """Save by file extension (reference ``io.py:1060``).

    All backends write atomically (temp file + ``os.replace``), so a
    failed attempt never corrupts an existing file and ``retry`` (a
    :class:`~heat_tpu.resilience.retry.RetryPolicy`) can safely rerun the
    whole save on transient OSError/TimeoutError.
    """
    if not isinstance(path, str):
        raise TypeError(f"Expected path to be str, but was {type(path)}")
    extension = os.path.splitext(path)[-1].strip().lower()
    if extension in (".h5", ".hdf5"):
        backend = save_hdf5
    elif extension in __NETCDF_EXTENSIONS:
        backend = save_netcdf
    elif extension == __CSV_EXTENSION:
        backend = save_csv
    else:
        raise ValueError(f"Unsupported file extension {extension}")
    return (retry or NO_RETRY).call(
        backend, data, path, *args, label=f"save({path!r})", **kwargs
    )
