"""Device abstraction (reference ``heat/core/devices.py``).

The reference pins one CUDA device per MPI rank round-robin
(``devices.py:98-102``). Under single-controller JAX the mesh owns device
placement, so :class:`Device` is a light label selecting the JAX platform
("cpu" or "tpu"); all arrays on a given platform are sharded across that
platform's devices via the mesh.
"""
from __future__ import annotations

from typing import Optional, Union

import jax

__all__ = ["Device", "cpu", "get_device", "use_device", "sanitize_device"]


class Device:
    """A compute platform label (reference ``devices.py:17``).

    Parameters
    ----------
    device_type : str
        "cpu", "tpu" (or "gpu" where available).
    device_id : int
        Kept for reference-API parity; under a mesh, placement is collective
        so this is informational only.
    """

    def __init__(self, device_type: str, device_id: int = 0):
        self.__device_type = str(device_type)
        self.__device_id = int(device_id)

    @property
    def device_type(self) -> str:
        return self.__device_type

    @property
    def device_id(self) -> int:
        return self.__device_id

    @property
    def jax_platform(self) -> str:
        return self.__device_type

    def __repr__(self) -> str:
        return f"device({self.__str__()!r})"

    def __str__(self) -> str:
        return f"{self.device_type}:{self.device_id}"

    def __eq__(self, other) -> bool:
        if isinstance(other, Device):
            return self.device_type == other.device_type and self.device_id == other.device_id
        if isinstance(other, str):
            return str(self) == other or self.device_type == other
        return NotImplemented

    def __hash__(self):
        return hash(str(self))


cpu = Device("cpu")
"""The CPU device singleton (reference ``devices.py:79``)."""

# Accelerator detection is lazy: probing the platform initializes the XLA
# backend, which must not happen at import time (init_distributed must be
# callable first — see communication.init_distributed).
_accel: Optional[Device] = None
_accel_probed = False
__default_device: Optional[Device] = None


def _detect_accel() -> Optional[Device]:
    global _accel, _accel_probed
    if not _accel_probed:
        _accel_probed = True
        try:  # pragma: no cover - depends on runtime platform
            platform = jax.default_backend()
            if platform not in ("cpu",):
                _accel = Device(platform)
        except (RuntimeError, ValueError):
            # backend probe failures only; anything else (incl. the
            # ResilienceError hierarchy) must propagate
            pass
    return _accel


# names that may lazily probe the backend (shared by the package-level
# __getattr__ forwarders and sanitize_device); cuda/rocm alias 'gpu'
ACCEL_NAMES = ("tpu", "gpu", "cuda", "rocm", "axon")
_GPU_ALIASES = ("gpu", "cuda", "rocm")


def _accel_matches(name: str, accel: Optional[Device], strict: bool = False) -> bool:
    """Single source of truth for accelerator-name matching.

    ``strict`` (attribute access, e.g. ``ht.gpu``): exact platform name or
    a cuda/rocm<->gpu alias — hasattr-based feature detection must not see
    a TPU as a GPU. Non-strict (``sanitize_device``): additionally accepts
    'gpu' as a generic accelerator request and 'axon' as a TPU alias."""
    if accel is None:
        return False
    if name == accel.device_type or (
        name in _GPU_ALIASES and accel.device_type in _GPU_ALIASES
    ):
        return True
    if strict:
        return False
    return name == "gpu" or (name == "axon" and accel.device_type == "tpu")


def __getattr__(name: str):
    # expose the accelerator singleton by platform name (ht.tpu / ht.gpu);
    # only ACCEL_NAMES may probe the backend — anything else must raise
    # without initializing XLA (import machinery getattrs freely)
    if name in ACCEL_NAMES:
        accel = _detect_accel()
        if _accel_matches(name, accel, strict=True):
            return accel
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def get_device() -> Device:
    """The currently globally-set default device (reference ``devices.py:121``)."""
    global __default_device
    if __default_device is None:
        accel = _detect_accel()
        __default_device = accel if accel is not None else cpu
    return __default_device


def use_device(device: Optional[Union[str, Device]] = None) -> None:
    """Set the global default device (reference ``devices.py:135``)."""
    global __default_device
    __default_device = sanitize_device(device)


def sanitize_device(device: Optional[Union[str, Device]]) -> Device:
    """Default-or-validate a device argument (reference ``devices.py:157``)."""
    if device is None:
        return get_device()
    if isinstance(device, Device):
        return device
    if isinstance(device, str):
        name = device.lower().split(":")[0]
        if name == "cpu":
            # must not probe the backend: sanitizing "cpu" is valid before
            # init_distributed()
            return cpu
        if name in ACCEL_NAMES:
            accel = _detect_accel()
            if _accel_matches(name, accel):
                return accel
    raise ValueError(f"Unknown device, must be 'cpu' or an available accelerator, got {device}")
