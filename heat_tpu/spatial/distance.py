"""Pairwise distances (reference ``heat/spatial/distance.py``).

The reference's ``_dist`` (``distance.py:209-486``) hand-implements a ring
pipeline: the moving shard rotates with Send/Probe/Recv and symmetric tiles
are mailed back. On TPU there are two native schedules:

- **GSPMD path** (default): the quadratic expansion
  ``|x|^2 + |y|^2 - 2 x y^T`` is one sharded matmul on the MXU; XLA
  all-gathers the smaller operand over ICI. Fastest when a y-shard fits
  in HBM alongside x.
- **Ring path** (``heat_tpu.parallel.ring.ring_map``): rotates y-shards
  with ``ppermute`` computing one output tile per step — the reference's
  schedule, for when M·N tiles must not be materialized against a
  replicated y.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from ..core import sanitation, types
from ..core.dndarray import DNDarray
from ..core.linalg.basics import _wrap_result

__all__ = ["cdist", "manhattan", "nearest_neighbors", "rbf"]


def _quadratic_expand(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    """||x_i - y_j||^2 via the MXU-friendly expansion (reference
    ``_quadratic_expand``, ``distance.py:16-133``)."""
    x_norm = jnp.sum(x * x, axis=1, keepdims=True)
    y_norm = jnp.sum(y * y, axis=1)
    d2 = x_norm + y_norm[None, :] - 2.0 * (x @ y.T)
    return jnp.maximum(d2, 0.0)


# cap on the (n, chunk, f) broadcast temporary for exact metrics, in elements
_EXACT_TEMP_ELEMS = 1 << 26


def _chunked_pairwise(x: jnp.ndarray, y: jnp.ndarray, tile_fn) -> jnp.ndarray:
    """Exact pairwise metric without materializing (n, m, f): loop over
    y-chunks on device, writing (n, chunk) tiles into the output. The
    reference's non-expanded path got the same memory bound from its ring
    (``distance.py:209``); here the x axis stays sharded and the chunk loop
    is a ``fori_loop`` inside the program."""

    n, f = x.shape
    m = y.shape[0]
    # memory bound applies to the PER-DEVICE shard of the broadcast temp
    sharding = getattr(x, "sharding", None)
    n_local = sharding.shard_shape(x.shape)[0] if sharding is not None else n
    if n_local * m * f <= _EXACT_TEMP_ELEMS:
        return tile_fn(x, y)
    chunk = max(16, min(m, _EXACT_TEMP_ELEMS // max(1, n_local * f)))
    pad = (-m) % chunk
    yp = jnp.pad(y, ((0, pad), (0, 0))) if pad else y
    nb = yp.shape[0] // chunk

    def body(i, out):
        yc = jax.lax.dynamic_slice_in_dim(yp, i * chunk, chunk, axis=0)
        tile = tile_fn(x, yc)
        return jax.lax.dynamic_update_slice_in_dim(out, tile, i * chunk, axis=1)

    out = jnp.zeros((n, nb * chunk), dtype=x.dtype)
    out = jax.lax.fori_loop(0, nb, body, out)
    return out[:, :m]


def _euclid_tile(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    diff = x[:, None, :] - y[None, :, :]
    return jnp.sqrt(jnp.sum(diff * diff, axis=-1))


def _euclidian(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return _chunked_pairwise(x, y, _euclid_tile)


def _manhattan_tile(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    diff = jnp.abs(x[:, None, :] - y[None, :, :])
    return jnp.sum(diff, axis=-1)


def _manhattan(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return _chunked_pairwise(x, y, _manhattan_tile)


def _gaussian(x: jnp.ndarray, y: jnp.ndarray, sigma: float) -> jnp.ndarray:
    d2 = _quadratic_expand(x, y)
    return jnp.exp(-d2 / (2.0 * sigma * sigma))


def _sqrt_quadratic_expand(x: jnp.ndarray, y: jnp.ndarray) -> jnp.ndarray:
    return jnp.sqrt(_quadratic_expand(x, y))


# Module-level jitted metrics: the public entry points dispatch ONE fused
# XLA program per call instead of eager per-primitive programs — eager
# composition materializes every (n, m) intermediate (d2, the sqrt, the
# norm broadcasts) as separate HBM round-trips, a 3-5x traffic hit on the
# output-bound distance matrix. sigma rides as a traced argument so rbf
# does not recompile per bandwidth value.
_sqrt_qe_jit = jax.jit(_sqrt_quadratic_expand)
_qe_jit = jax.jit(_quadratic_expand)
_gaussian_jit = jax.jit(_gaussian)


def _dist(x: DNDarray, y: Optional[DNDarray], metric: Callable, use_ring: bool = False) -> DNDarray:
    """Dispatch over distributions (reference ``distance.py:209``)."""
    if x.ndim != 2:
        raise NotImplementedError(f"Input x must be a 2D DNDarray, got {x.ndim}-D")
    self_dist = y is None
    if self_dist:
        y = x
    if y.ndim != 2:
        raise NotImplementedError(f"Input y must be a 2D DNDarray, got {y.ndim}-D")
    if x.shape[1] != y.shape[1]:
        raise ValueError(f"feature dimensions differ: {x.shape[1]} != {y.shape[1]}")
    if x.split == 1 or y.split == 1:
        raise NotImplementedError("cdist with split=1 operands: resplit to 0 or None first")

    promoted = types.promote_types(x.dtype, types.float32)
    jt = promoted.jax_type()
    # padded tail rows produce tiles that land in the (trimmed) output
    # padding, so the buffers can be consumed directly
    xa = x.larray.astype(jt)
    ya = y.larray.astype(jt)
    out_gshape = (x.gshape[0], y.gshape[0])
    out_split = 0 if x.split is not None else (1 if y.split is not None else None)

    if use_ring and x.split == 0 and y.split == 0 and x.comm.size > 1:
        from ..parallel.ring import ring_map

        result = ring_map(metric, xa, ya, x.comm)
        return _wrap_result(result, out_gshape, 0, promoted, x.device, x.comm)

    # GSPMD path: one global expression; XLA inserts the collectives
    result = metric(xa, ya)
    return _wrap_result(result, out_gshape, out_split, promoted, x.device, x.comm)


def cdist(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    quadratic_expansion: bool = False,
    use_ring: bool = False,
) -> DNDarray:
    """Euclidean distance matrix (reference ``distance.py:136``).

    ``quadratic_expansion=True`` uses the matmul form (one MXU op); the
    default exact form is used otherwise. ``use_ring=True`` selects the
    ``ppermute`` ring schedule when both operands are split.
    """
    # ring path wants the un-jitted metric (it runs inside shard_map);
    # the GSPMD path gets the fused jitted program
    if quadratic_expansion:
        metric = _sqrt_quadratic_expand if use_ring else _sqrt_qe_jit
    else:
        metric = _euclidian
    return _dist(X, Y, metric, use_ring=use_ring)


def manhattan(X: DNDarray, Y: Optional[DNDarray] = None, expand: bool = False, use_ring: bool = False) -> DNDarray:
    """Manhattan (L1) distance matrix (reference ``distance.py:186``).

    ``expand`` selected a broadcast-vs-loop implementation in the reference
    with identical results; XLA fuses the broadcast form either way, so the
    flag is accepted for API parity and has no effect here.
    """
    if expand:
        sanitation.warn_parity_noop(
            "manhattan", "expand", "XLA fuses the broadcast form either way"
        )
    return _dist(X, Y, _manhattan, use_ring=use_ring)


def rbf(
    X: DNDarray,
    Y: Optional[DNDarray] = None,
    sigma: float = 1.0,
    quadratic_expansion: bool = False,
    use_ring: bool = False,
) -> DNDarray:
    """Gaussian RBF kernel matrix (reference ``distance.py:159``)."""
    if use_ring:
        return _dist(X, Y, lambda a, b: _gaussian(a, b, sigma), use_ring=True)
    return _dist(X, Y, lambda a, b: _gaussian_jit(a, b, sigma), use_ring=False)


def nearest_neighbors(x: DNDarray, y: DNDarray, k: int):
    """k nearest rows of ``y`` for every row of ``x`` — without the (n, m)
    distance matrix.

    TPU-native extension beyond the reference (whose kNN materializes the
    full ``cdist`` then ``topk``, ``kneighborsclassifier.py:10-136``): a
    fused pallas kernel streams y-tiles through VMEM keeping a per-row
    running top-k, so the (n, m) intermediate never exists. Supports
    ``x.split in (0, None)`` with replicated ``y``; x-shards are processed
    independently per device (``shard_map``), indices are global.

    Returns ``(d2, idx)``: (n, k) squared distances (ascending) and row
    indices into ``y``, both with ``x``'s split.
    """
    from ..core.kernels import nearest_neighbors as _nn_local
    from ..core.kernels import pallas_supported, record_dispatch

    if x.ndim != 2 or y.ndim != 2:
        raise NotImplementedError("nearest_neighbors expects 2-D operands")
    # this entry always runs the kernel (interpreted off-TPU) — record the
    # decision at the call boundary, outside any traced code
    record_dispatch(
        "topk_distance",
        "pallas" if pallas_supported("topk_distance") else "interpret",
    )
    if y.split is not None:
        y = y.resplit(None)
    if x.split not in (None, 0):
        raise NotImplementedError("nearest_neighbors: x must be split=0 or replicated")

    # the kernel computes in f32 (MXU precision); cast once here.
    # y must be its logical extent: the kernel's indices are global rows
    xa = x.larray.astype(jnp.float32)
    ya = y._logical().astype(jnp.float32)

    p = x.comm.size
    if x.split == 0 and p > 1 and xa.shape[0] % p == 0:
        from jax import shard_map
        from jax.sharding import PartitionSpec as P

        from ..core.communication import SPLIT_AXIS

        d, idx = shard_map(
            lambda xs, ys: _nn_local(xs, ys, k),
            mesh=x.comm.mesh,
            in_specs=(P(SPLIT_AXIS, None), P(None, None)),
            out_specs=(P(SPLIT_AXIS, None), P(SPLIT_AXIS, None)),
            check_vma=False,  # pallas_call out_shapes carry no vma info
        )(xa, ya)
    else:
        d, idx = _nn_local(xa, ya, k)
    out_gshape = (x.gshape[0], k)
    dist = _wrap_result(d, out_gshape, x.split, types.float32, x.device, x.comm)
    indices = _wrap_result(idx, out_gshape, x.split, types.int32, x.device, x.comm)
    return dist, indices
