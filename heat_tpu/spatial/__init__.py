"""Spatial distance functions (reference ``heat/spatial/``)."""
from . import distance
from .distance import cdist, manhattan, rbf
