"""Spatial distance functions (reference ``heat/spatial/``)."""
from . import distance
from .distance import cdist, manhattan, nearest_neighbors, rbf
