"""Utilities (reference ``heat/utils/``)."""
from . import data
