"""Utilities (reference ``heat/utils/``)."""
from . import checkpointing, data, profiling
from .checkpointing import load_checkpoint, save_checkpoint
