"""Checkpoint / resume.

The reference has no training checkpoint system (SURVEY §5): persistence is
``ht.save``/``ht.load`` plus RNG ``get_state``/``set_state``. This module
goes beyond parity with a consolidated checkpoint for training state:
parameter pytrees (DNDarrays, jax arrays, optax states), the global RNG
state, and user metadata — written once by the controller, restorable with
shardings reapplied.
"""
from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from ..core import random as ht_random
from ..core.dndarray import DNDarray

__all__ = ["save_checkpoint", "load_checkpoint"]

_META = "meta.json"
_ARRAYS = "arrays.npz"
_TREEDEF = "treedef.pkl"


def _flatten(tree) -> Tuple[list, Any]:
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def save_checkpoint(path: str, state: Any, step: Optional[int] = None, metadata: Optional[Dict] = None) -> None:
    """Write a checkpoint directory.

    ``state`` is any pytree of jax arrays / DNDarrays / numpy arrays /
    scalars. DNDarray leaves are recorded with their split so restore can
    reapply the sharding. Includes the heat RNG state (reference
    ``random.get_state:203``).
    """
    os.makedirs(path, exist_ok=True)
    splits = {}

    def to_host(leaf, idx):
        if isinstance(leaf, DNDarray):
            splits[str(idx)] = leaf.split
            return leaf.numpy()
        return np.asarray(jax.device_get(leaf))

    leaves, treedef = _flatten(state)
    arrays = {str(i): to_host(leaf, i) for i, leaf in enumerate(leaves)}
    if jax.process_index() == 0:
        np.savez(os.path.join(path, _ARRAYS), **arrays)
        with open(os.path.join(path, _TREEDEF), "wb") as f:
            pickle.dump(treedef, f)
        meta = {
            "step": step,
            "metadata": metadata or {},
            "treedef": str(treedef),
            "n_leaves": len(leaves),
            "splits": splits,
            "rng_state": list(ht_random.get_state()),
        }
        with open(os.path.join(path, _META), "w") as f:
            json.dump(meta, f)


def load_checkpoint(path: str, like: Any = None, restore_rng: bool = True):
    """Restore a checkpoint.

    ``like`` is a pytree with the same structure as the saved state (e.g.
    freshly-initialized params); leaves are replaced with the stored
    values, DNDarray leaves with their recorded splits reapplied. Returns
    ``(state, step, metadata)``.
    """
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    data = np.load(os.path.join(path, _ARRAYS))
    n = meta["n_leaves"]
    stored = [data[str(i)] for i in range(n)]
    if restore_rng and meta.get("rng_state"):
        s = meta["rng_state"]
        ht_random.set_state((s[0], int(s[1]), int(s[2]), int(s[3]), float(s[4])))

    if like is None:
        # rebuild the saved structure from the pickled treedef
        tpath = os.path.join(path, _TREEDEF)
        if os.path.exists(tpath):
            with open(tpath, "rb") as f:
                treedef = pickle.load(f)
            state = jax.tree_util.tree_unflatten(treedef, stored)
        else:
            state = stored if n != 1 else stored[0]
    else:
        leaves, treedef = _flatten(like)
        if len(leaves) != n:
            raise ValueError(f"checkpoint has {n} leaves, 'like' tree has {len(leaves)}")
        new_leaves = []
        for i, (old, new) in enumerate(zip(leaves, stored)):
            if isinstance(old, DNDarray):
                new_leaves.append(
                    DNDarray(
                        new,
                        dtype=old.dtype,
                        split=meta["splits"].get(str(i), old.split),
                        device=old.device,
                        comm=old.comm,
                    )
                )
            else:
                import jax.numpy as jnp

                new_leaves.append(jnp.asarray(new, dtype=getattr(old, "dtype", None)))
        state = jax.tree_util.tree_unflatten(treedef, new_leaves)
    return state, meta.get("step"), meta.get("metadata", {})
