"""Data-preparation utilities (reference ``heat/utils/data/_utils.py``).

The reference ships two standalone ImageNet helpers it explicitly marks
"not tested, nor actively supported": DALI TFRecord index generation
(``_utils.py:13``) and a TFRecord->HDF5 merger (``_utils.py:47``) that
needs TensorFlow. The TPU-native equivalents here are dependency-free
(the TFRecord wire format is parsed directly) and tested:

- :func:`tfrecord_index` / :func:`write_tfrecord_indexes` — byte-offset
  indexes in the DALI text format, built by walking the record framing
  (uint64 length + masked crc32 + payload + crc32) without TensorFlow.
- :func:`merge_shards_to_hdf5` — stack per-shard ``.npy``/``.npz``
  preprocessing outputs into one chunked HDF5 file consumable by the
  parallel loader (``load_hdf5`` split reads, ``PartialH5Dataset``
  streaming), the analogue of ``merge_files_imagenet_tfrecord``.
- :func:`encode_image_bytes` / :func:`decode_image_bytes` — the
  reference's base64-ASCII image string convention (its HDF5 stores
  images as ``a2b_base64``-decodable strings; ``_utils.py:75-77``).
"""
from __future__ import annotations

import binascii
import os
import struct
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _build_crc32c_table():
    table = []
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
        table.append(crc)
    return tuple(table)


_CRC32C_TABLE = _build_crc32c_table()


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), the TFRecord framing checksum (table-driven:
    one lookup per byte, matches the 0xE3069283 test vector)."""
    crc = 0xFFFFFFFF
    for byte in data:
        crc = (crc >> 8) ^ _CRC32C_TABLE[(crc ^ byte) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc32c(data: bytes) -> int:
    """TFRecord's masked crc: rot15(crc32c) + magic constant."""
    crc = _crc32c(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF

__all__ = [
    "tfrecord_index",
    "write_tfrecord_indexes",
    "merge_shards_to_hdf5",
    "encode_image_bytes",
    "decode_image_bytes",
]


def tfrecord_index(path: str) -> List[Tuple[int, int]]:
    """(offset, size) of every record in a TFRecord file.

    Walks the standard framing — ``uint64 length``, ``uint32`` masked
    crc32 of the length, ``length`` payload bytes, ``uint32`` payload
    crc — exactly like the reference's index loop (``_utils.py:24-44``),
    no TensorFlow required. Truncated trailing records raise.
    """
    entries: List[Tuple[int, int]] = []
    file_size = os.path.getsize(path)
    with open(path, "rb") as f:
        while True:
            start = f.tell()
            header = f.read(8)
            if not header:
                return entries
            if len(header) < 8:
                if start == 0:  # can't even hold one header: not a TFRecord
                    raise ValueError(f"not a TFRecord: {path} is too short")
                raise ValueError(f"truncated record header at byte {start} of {path}")
            (length,) = struct.unpack("<Q", header)
            # the FIRST header's masked crc32c distinguishes a genuine
            # (possibly truncated) TFRecord from an arbitrary file whose
            # bytes decode as an absurd length; past the first record the
            # same failure means in-file corruption and must surface
            crc_bytes = f.read(4)
            if len(crc_bytes) < 4 or struct.unpack("<I", crc_bytes)[0] != _masked_crc32c(header):
                if start == 0:
                    raise ValueError(
                        f"not a TFRecord: bad header checksum at byte 0 of {path}"
                    )
                raise ValueError(f"corrupt record header at byte {start} of {path}")
            # validate BEFORE seeking past the payload: a truncated shard
            # must surface as an error, never as a silent short index
            if start + 8 + 4 + length + 4 > file_size:
                raise ValueError(f"truncated record payload at byte {start} of {path}")
            f.seek(length + 4, os.SEEK_CUR)  # payload + payload-crc
            entries.append((start, 8 + 4 + length + 4))


def write_tfrecord_indexes(data_dir: str, idx_dir: str) -> List[str]:
    """Write a DALI-style text index (``"offset size"`` per line) for every
    file in ``data_dir`` (reference ``dali_tfrecord2idx``, ``_utils.py:13``).
    Returns the written index paths."""
    os.makedirs(idx_dir, exist_ok=True)
    written = []
    for name in sorted(os.listdir(data_dir)):
        src = os.path.join(data_dir, name)
        if not os.path.isfile(src):
            continue
        try:
            entries = tfrecord_index(src)
        except ValueError as e:
            # non-TFRecord files (README, checksums, ...) are skipped — the
            # header-crc check identifies them; TRUNCATED TFRecords raise
            if "not a TFRecord" in str(e):
                continue
            raise
        dst = os.path.join(idx_dir, name + ".idx")
        with open(dst, "w") as out:
            for offset, size in entries:
                out.write(f"{offset} {size}\n")
        written.append(dst)
    return written


def merge_shards_to_hdf5(
    shard_files: Sequence[str],
    output_path: str,
    dataset: str = "images",
    labels_dataset: Optional[str] = "labels",
    chunk_rows: int = 64,
) -> Tuple[int, Tuple[int, ...]]:
    """Stack per-shard arrays into one chunked HDF5 file.

    Each shard is a ``.npy`` (images only) or ``.npz`` with ``images`` and
    optionally ``labels`` arrays; shards are appended along dim 0 in the
    given order, writing directly into a resizable chunked dataset — one
    shard in memory at a time, like the reference's incremental
    ``__write_datasets`` (``_utils.py:217``). Returns
    ``(total_rows, row_shape)``.
    """
    import h5py

    if not shard_files:
        raise ValueError("no shard files given")
    total = 0
    label_rows = 0
    row_shape: Optional[Tuple[int, ...]] = None
    with h5py.File(output_path, "w") as out:
        img_ds = lab_ds = None
        for path in shard_files:
            if path.endswith(".npz"):
                with np.load(path) as z:
                    images = z["images"]
                    labels = z["labels"] if labels_dataset and "labels" in z else None
            else:
                images, labels = np.load(path), None
            if row_shape is None:
                row_shape = tuple(images.shape[1:])
                img_ds = out.create_dataset(
                    dataset,
                    shape=(0,) + row_shape,
                    maxshape=(None,) + row_shape,
                    dtype=images.dtype,
                    chunks=(chunk_rows,) + row_shape,
                )
            elif tuple(images.shape[1:]) != row_shape:
                raise ValueError(
                    f"shard {path} rows {tuple(images.shape[1:])} != {row_shape}"
                )
            if images.dtype != img_ds.dtype:
                raise ValueError(
                    f"shard {path} image dtype {images.dtype} != {img_ds.dtype}; "
                    "h5py would silently cast and corrupt the merged data"
                )
            if labels is not None and lab_ds is not None and labels.dtype != lab_ds.dtype:
                raise ValueError(
                    f"shard {path} label dtype {labels.dtype} != {lab_ds.dtype}"
                )
            n = images.shape[0]
            if labels is not None and labels.shape[0] != n:
                raise ValueError(
                    f"shard {path} has {labels.shape[0]} labels for {n} images; "
                    "a short shard would misalign every subsequent label row"
                )
            img_ds.resize(total + n, axis=0)
            img_ds[total : total + n] = images
            if labels is not None:
                if lab_ds is None and total > 0:
                    raise ValueError(
                        f"shard {path} has labels but earlier shards did not; "
                        "mixed labeled/unlabeled shards would silently "
                        "misalign the label rows"
                    )
                if lab_ds is None:
                    lab_ds = out.create_dataset(
                        labels_dataset,
                        shape=(0,),
                        maxshape=(None,),
                        dtype=labels.dtype,
                        chunks=(max(chunk_rows, 256),),
                    )
                lab_ds.resize(label_rows + n, axis=0)
                lab_ds[label_rows : label_rows + n] = labels
                label_rows += n
            elif lab_ds is not None:
                raise ValueError(
                    f"shard {path} lacks labels but earlier shards had them"
                )
            total += n
    return total, row_shape or ()


def encode_image_bytes(image: np.ndarray) -> str:
    """uint8 image array -> base64 ASCII string (the reference's HDF5
    image storage convention, ``_utils.py:75-77``)."""
    image = np.ascontiguousarray(image, dtype=np.uint8)
    return binascii.b2a_base64(image.tobytes()).decode("ascii")


def decode_image_bytes(payload: str, shape: Sequence[int]) -> np.ndarray:
    """Inverse of :func:`encode_image_bytes` (the reference documents the
    torch decode incantation; numpy equivalent here)."""
    raw = binascii.a2b_base64(payload.encode("ascii"))
    # copy: frombuffer views are read-only, augmentation pipelines mutate
    return np.frombuffer(raw, dtype=np.uint8).reshape(tuple(shape)).copy()
