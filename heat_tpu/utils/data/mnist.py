"""MNIST dataset (reference ``heat/utils/data/mnist.py``).

The reference subclasses ``torchvision.datasets.MNIST`` and slices the
images across ranks (``mnist.py:16``). torchvision is not in this image,
so the raw IDX files are parsed directly; samples end sharded over the
mesh like any split=0 DNDarray.
"""
from __future__ import annotations

import gzip
import os
import struct
from typing import Optional

import numpy as np

from ...core import factories, types
from ...core.dndarray import DNDarray

__all__ = ["MNISTDataset"]


def _read_idx(path: str) -> np.ndarray:
    if not path.endswith(".gz"):
        from ... import native

        arr = native.idx_read(path)
        if arr is not None:
            return arr
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        zero, dtype_code, ndim = struct.unpack(">HBB", f.read(4))
        shape = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)


class MNISTDataset:
    """MNIST over DNDarrays (reference ``mnist.py:16``).

    Parameters
    ----------
    root : str
        Directory containing the raw IDX files
        (train-images-idx3-ubyte[.gz] etc.).
    train : bool
    transform : callable, optional
        Per-image transform.
    split : int or None
        DNDarray split of the sample axis (the reference always splits 0).
    """

    _FILES = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root: str, train: bool = True, transform=None, target_transform=None, split: Optional[int] = 0):
        img_name, lbl_name = self._FILES[train]
        images = labels = None
        for suffix in ("", ".gz"):
            ipath = os.path.join(root, img_name + suffix)
            lpath = os.path.join(root, lbl_name + suffix)
            if os.path.exists(ipath) and os.path.exists(lpath):
                images = _read_idx(ipath)
                labels = _read_idx(lpath)
                break
        if images is None:
            raise FileNotFoundError(f"MNIST idx files not found under {root}")
        self.transform = transform
        self.target_transform = target_transform
        imgs = images.astype(np.float32) / 255.0
        self.htdata = factories.array(imgs, split=split)
        self.httargets = factories.array(labels.astype(np.int64), split=split)

    @property
    def data(self) -> DNDarray:
        return self.htdata

    @property
    def targets(self) -> DNDarray:
        return self.httargets

    def __len__(self) -> int:
        return self.htdata.shape[0]

    def __getitem__(self, index):
        img = self.htdata.larray[index]
        target = self.httargets.larray[index]
        if self.transform is not None:
            img = self.transform(img)
        if self.target_transform is not None:
            target = self.target_transform(target)
        return img, target
