"""Data tooling (reference ``heat/utils/data/``)."""
from . import _utils, datatools, matrixgallery, mnist, partial_dataset
from ._utils import (
    decode_image_bytes,
    encode_image_bytes,
    merge_shards_to_hdf5,
    tfrecord_index,
    write_tfrecord_indexes,
)
from .datatools import DataLoader, Dataset, dataset_ishuffle, dataset_shuffle
from .mnist import MNISTDataset
from .partial_dataset import PartialH5DataLoaderIter, PartialH5Dataset
