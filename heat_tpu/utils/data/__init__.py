"""Data tooling (reference ``heat/utils/data/``)."""
from . import matrixgallery
