"""Data tooling (reference ``heat/utils/data/``)."""
from . import datatools, matrixgallery, mnist, partial_dataset
from .datatools import DataLoader, Dataset, dataset_ishuffle, dataset_shuffle
from .mnist import MNISTDataset
from .partial_dataset import PartialH5DataLoaderIter, PartialH5Dataset
