"""Streaming dataset for larger-than-memory HDF5 files (reference
``heat/utils/data/partial_dataset.py``).

The reference streams slabs of an H5 file with background convert/load
threads (``PartialH5Dataset:32``, ``queue_thread:20``,
``PartialH5DataLoaderIter:224``). Same structure here: a producer thread
reads the next slab from disk while the device consumes the current one;
slabs are device_put asynchronously so host reads overlap device compute.
"""
from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ...core.communication import sanitize_comm

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter", "queue_thread"]


def queue_thread(q: "queue.Queue", fn, *args) -> threading.Thread:
    """Run ``fn(*args)`` pushing results into ``q`` on a daemon thread
    (reference ``partial_dataset.py:20``)."""
    t = threading.Thread(target=fn, args=args, daemon=True)
    t.start()
    return t


class PartialH5Dataset:
    """Iterate an HDF5 dataset in slabs without loading it fully (reference
    ``partial_dataset.py:32``).

    Parameters
    ----------
    file : str
        Path to the HDF5 file.
    dataset_names : list of str
        Datasets to read in lock-step (e.g. ["data", "labels"]).
    initial_load : int
        Rows per slab held in memory at once.
    transforms : callable(s), optional
    use_gpu : bool
        Kept for reference parity; slabs are placed on the default devices.
    """

    def __init__(
        self,
        file: str,
        comm=None,
        dataset_names="data",
        transforms=None,
        use_gpu: bool = True,
        validate_set: bool = False,
        initial_load: int = 7000,
        load_length: Optional[int] = None,
    ):
        import h5py

        self.file = file
        self.comm = sanitize_comm(comm)
        self.dataset_names = [dataset_names] if isinstance(dataset_names, str) else list(dataset_names)
        self.transforms = transforms if isinstance(transforms, (list, tuple)) else [transforms] * len(
            self.dataset_names
        )
        self.load_len = int(load_length or initial_load)
        self.validate_set = validate_set
        with h5py.File(file, "r") as handle:
            self.total_size = handle[self.dataset_names[0]].shape[0]

    def __len__(self) -> int:
        return self.total_size

    def _read_slab(self, start: int, stop: int) -> List[np.ndarray]:
        import h5py

        with h5py.File(self.file, "r") as handle:
            return [np.asarray(handle[name][start:stop]) for name in self.dataset_names]

    def __iter__(self) -> "PartialH5DataLoaderIter":
        return PartialH5DataLoaderIter(self)


class PartialH5DataLoaderIter:
    """Background-prefetching slab iterator (reference
    ``partial_dataset.py:224``).

    Hardened against the classic producer-thread leaks: the bounded queue
    is fed with interruptible timed puts (never a blocking ``put`` into a
    full queue the consumer has abandoned), reader exceptions travel
    through the queue and re-raise in the consumer's ``__next__`` (the
    ``None`` sentinel still follows, so iteration can never hang on a dead
    producer), and :meth:`close` — also run by ``__del__`` and the context
    manager — stops the producer, drains the queue, and joins the thread
    on early teardown (``break`` out of a loop mid-epoch).
    """

    def __init__(self, dataset: PartialH5Dataset):
        self.dataset = dataset
        # maxsize bounds staging to 2 slabs beyond the one being consumed
        self._q: "queue.Queue" = queue.Queue(maxsize=2)
        self._offsets = list(range(0, dataset.total_size, dataset.load_len))
        self._stop = threading.Event()
        self._closed = False
        self._thread = queue_thread(self._q, self._producer)

    def _put(self, item) -> bool:
        """Timed-put loop: blocks only until the queue drains OR the
        consumer signals stop — the producer can always exit."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        try:
            for start in self._offsets:
                if self._stop.is_set():
                    return
                stop = min(start + self.dataset.load_len, self.dataset.total_size)
                slab = self.dataset._read_slab(start, stop)
                out = []
                for arr, t in zip(slab, self.dataset.transforms):
                    j = jnp.asarray(arr)
                    if t is not None:
                        j = t(j)
                    out.append(jax.device_put(j))  # async H2D, overlaps next read
                if not self._put(out[0] if len(out) == 1 else tuple(out)):
                    return
        except BaseException as exc:  # noqa: BLE001 - surfaced to the consumer
            self._put(exc)
        finally:
            self._put(None)

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if not self._thread.is_alive():
                    # producer died without delivering its sentinel (e.g.
                    # interpreter teardown killed the daemon) — never hang
                    raise StopIteration
        if item is None:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def close(self) -> None:
        """Stop the producer and join its thread; safe to call twice."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        while self._thread.is_alive():
            try:  # drain so a blocked timed put can complete and exit
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.05)

    def __enter__(self) -> "PartialH5DataLoaderIter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        # graftlint: G006 - interpreter teardown: modules may already be gone
        except Exception:
            pass
