"""Dataset / DataLoader tooling (reference ``heat/utils/data/datatools.py``).

The reference wraps a split DNDarray's *local* torch shard as a torch
dataset and implements an epoch-end cross-rank shuffle with Isend blocks
(``dataset_shuffle:246``, ``dataset_ishuffle:301``). On TPU the dataset
holds the global sharded array; batching slices the global batch (each
device reads only its shard — no host loop), and the global shuffle is a
single sharded ``take`` with a permutation — one all-to-all on ICI instead
of point-to-point block mailing.
"""
from __future__ import annotations

import math
from typing import Iterator, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as ht_random
from ...core.dndarray import DNDarray

__all__ = ["DataLoader", "Dataset", "dataset_shuffle", "dataset_ishuffle"]


# At ws>1 a per-batch op on a global sharded array is a trap: each rank
# dispatches its own tiny cross-process program per batch, the ranks
# drift apart over an epoch (one rank can be eight launches ahead), and
# the collective rendezvous deadlocks maybe one run in three. Batching
# must therefore cost ONE well-aligned collective per epoch — the same
# shard-assembling allgather ``DNDarray.numpy()`` uses everywhere else —
# and slice the replicated host snapshot locally after that.
_TAKE_FNS: dict = {}


def _sharded_take(arr, perm):
    """Permute rows of a sharded array, keeping its sharding — one jitted
    program shared by every rank instead of an eager per-rank gather."""
    fn = _TAKE_FNS.get(arr.sharding)
    if fn is None:
        fn = _TAKE_FNS[arr.sharding] = jax.jit(
            lambda a, p: jnp.take(a, p, axis=0),
            out_shardings=arr.sharding,
        )
    return fn(arr, perm)


class Dataset:
    """Dataset over one or more (sharded) DNDarrays (reference
    ``datatools.py:143``).

    Parameters
    ----------
    array : DNDarray or sequence of DNDarrays
        Sample axis is axis 0.
    transform : callable, optional
        Applied per batch at load time.
    shuffle : bool
        Whether :func:`dataset_shuffle` reshuffles at epoch end.
    """

    def __init__(self, array, transforms=None, shuffle: bool = True, test_set: bool = False):
        if isinstance(array, DNDarray):
            arrays = [array]
        else:
            arrays = list(array)
        n = arrays[0].shape[0]
        for a in arrays:
            if a.shape[0] != n:
                raise ValueError("all arrays must share the sample axis length")
        self.arrays = arrays
        self.transforms = transforms if isinstance(transforms, (list, tuple)) else [transforms] * len(arrays)
        self.shuffle_flag = shuffle
        self.test_set = test_set
        # per-array (larray, host snapshot) pairs for multi-process reads;
        # a shuffle swaps larray, which invalidates the matching snapshot
        self._snapshots: list = [None] * len(arrays)

    def __len__(self) -> int:
        return self.arrays[0].shape[0]

    def __getitem__(self, index):
        out = []
        for i, (a, t) in enumerate(zip(self.arrays, self.transforms)):
            if a.larray.is_fully_addressable:
                item = a.larray[index]
            else:
                # multi-process: slice a replicated host snapshot (one
                # collective allgather per epoch, refreshed when a
                # shuffle swaps the backing buffer) — every rank must
                # reach this read in lockstep, which the SPMD batch loop
                # guarantees
                cached = self._snapshots[i]
                if cached is None or cached[0] is not a.larray:
                    cached = (a.larray, a.numpy())
                    self._snapshots[i] = cached
                item = jnp.asarray(cached[1][index])
            if t is not None:
                item = t(item)
            out.append(item)
        return out[0] if len(out) == 1 else tuple(out)

    def shuffle(self) -> None:
        """Epoch-end global shuffle (reference ``dataset_shuffle:246``)."""
        dataset_shuffle(self)

    def ishuffle(self) -> None:
        """Async shuffle; on TPU the collective is already non-blocking
        (XLA schedules it), so this is the same one-program shuffle
        (reference ``dataset_ishuffle:301``)."""
        dataset_ishuffle(self)


class DataLoader:
    """Batch iterator over a Dataset (reference ``datatools.py:16``).

    Yields per-batch jnp arrays (sharded like the source); batches are
    global slices so every device reads its own shard.
    """

    def __init__(
        self,
        dataset: Union[Dataset, DNDarray],
        batch_size: int = 1,
        drop_last: bool = True,
        shuffle: bool = True,
    ):
        if isinstance(dataset, DNDarray):
            dataset = Dataset(dataset, shuffle=shuffle)
        if not isinstance(dataset, Dataset):
            raise TypeError(f"dataset must be a Dataset or DNDarray, got {type(dataset)}")
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.drop_last = drop_last
        self.shuffle = shuffle
        self._first_epoch = True

    def __len__(self) -> int:
        n = len(self.dataset)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)

    def __iter__(self) -> Iterator:
        do_shuffle = self.shuffle and self.dataset.shuffle_flag
        if do_shuffle and not self.dataset.test_set and not self._first_epoch:
            self.dataset.shuffle()
        self._first_epoch = False
        n = len(self.dataset)
        nb = len(self)
        for b in range(nb):
            start = b * self.batch_size
            stop = min(start + self.batch_size, n)
            yield self.dataset[slice(start, stop)]


def dataset_shuffle(dataset: Dataset, attrs: Optional[List] = None) -> None:
    """Globally shuffle the dataset's arrays in place (reference
    ``dataset_shuffle:246`` — Isend blocks of samples between ranks; one
    permuted sharded gather here)."""
    n = len(dataset)
    key = ht_random._next_key(n)
    perm = jax.random.permutation(key, n)
    for i, a in enumerate(dataset.arrays):
        if a.larray.is_fully_addressable:
            a.larray = jnp.take(a.larray, perm, axis=0)
        else:
            a.larray = _sharded_take(a.larray, perm)


def dataset_ishuffle(dataset: Dataset, attrs: Optional[List] = None) -> None:
    """Non-blocking variant (reference ``dataset_ishuffle:301``): the XLA
    collective is asynchronous by construction, so identical to
    :func:`dataset_shuffle`."""
    dataset_shuffle(dataset, attrs)
