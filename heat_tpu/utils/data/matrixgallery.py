"""Test-matrix gallery (reference ``heat/utils/data/matrixgallery.py``)."""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ...core import types
from ...core.communication import sanitize_comm
from ...core.dndarray import DNDarray

__all__ = ["parter", "hermitian"]


def parter(n: int, split: Optional[int] = None, device=None, comm=None, dtype=types.float32) -> DNDarray:
    """Parter matrix A[i,j] = 1 / (j - i + 0.5) (reference
    ``matrixgallery.py:15`` builds ``1/(II - JJ + 0.5)`` with II varying
    along columns)."""
    dtype = types.canonical_heat_type(dtype)
    i = jnp.arange(n, dtype=dtype.jax_type())
    a = 1.0 / (i[None, :] - i[:, None] + 0.5)
    return DNDarray(a, dtype=dtype, split=split, device=device, comm=sanitize_comm(comm))


def hermitian(n: int, split: Optional[int] = None, device=None, comm=None, dtype=types.complex64) -> DNDarray:
    """Random Hermitian matrix (reference ``matrixgallery.py``)."""
    from ...core import random as ht_random

    dtype = types.canonical_heat_type(dtype)
    if types.heat_type_is_complexfloating(dtype):
        re = ht_random.rand(n, n).larray
        im = ht_random.rand(n, n).larray
        a = re + 1j * im
        h = (a + a.conj().T) / 2
    else:
        a = ht_random.rand(n, n).larray
        h = (a + a.T) / 2
    return DNDarray(h.astype(dtype.jax_type()), dtype=dtype, split=split, device=device, comm=sanitize_comm(comm))
