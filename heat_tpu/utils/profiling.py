"""Profiling hooks.

The reference has none (SURVEY §5: benchmarks use bare
``time.perf_counter``). On TPU the XLA profiler is nearly free to wire in:
``trace`` captures a TensorBoard-viewable device trace, ``annotate`` names
regions inside it, and ``Timer`` reproduces the reference's benchmark
timing pattern with proper device synchronization.
"""
from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax

__all__ = ["trace", "annotate", "force_sync", "Timer"]


def force_sync(*arrays) -> None:
    """Block until the computations producing ``arrays`` have really run.

    ``block_until_ready`` is not sufficient on tunneled/async TPU platforms
    (the axon transport acknowledges dispatch, not completion); fetching a
    scalar to the host is. Used by the benchmark harnesses.
    """
    import numpy as np

    for x in arrays:
        for leaf in jax.tree_util.tree_leaves(getattr(x, "larray", x)):
            a = getattr(leaf, "larray", leaf)
            if hasattr(a, "ravel"):
                np.asarray(jax.device_get(a.ravel()[-1:]))


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Capture an XLA device trace viewable in TensorBoard/Perfetto."""
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up inside a :func:`trace` capture."""
    return jax.profiler.TraceAnnotation(name)


class Timer:
    """Wall-clock timer that blocks on device completion.

    The reference timed with bare ``perf_counter`` around eager torch+MPI
    (``benchmarks/kmeans/heat-cpu.py:23-26``); under async JAX dispatch a
    correct timer must synchronize, so ``stop(x)`` blocks on ``x`` (or on
    all devices when given nothing).
    """

    def __init__(self):
        self._t0: Optional[float] = None
        self.elapsed: Optional[float] = None

    def start(self) -> "Timer":
        self._t0 = time.perf_counter()
        return self

    def stop(self, *block_on) -> float:
        import numpy as np

        if block_on:
            force_sync(*block_on)
        else:
            # round-trip a sentinel per device: block_until_ready only
            # acknowledges dispatch on tunneled TPU transports
            for d in jax.devices():
                np.asarray(jax.device_get(jax.device_put(0.0, d)))
        self.elapsed = time.perf_counter() - self._t0
        return self.elapsed

    def __enter__(self) -> "Timer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
