"""Gaussian Naive Bayes (reference ``heat/naive_bayes/gaussianNB.py``).

Per-class moments are masked reductions over the sharded sample axis (the
reference's incremental ``__update_mean_variance``, ``gaussianNB.py:131``,
merged by psum); prediction is a fused joint-log-likelihood + argmax.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray

__all__ = ["GaussianNB"]


class GaussianNB(BaseEstimator, ClassificationMixin):
    """reference ``gaussianNB.py:12``

    Parameters: ``priors`` (class priors, optional), ``var_smoothing``.
    Attributes after fit: ``classes_``, ``theta_`` (means), ``sigma_``
    (variances), ``class_prior_``, ``class_count_``, ``epsilon_``.
    """

    def __init__(self, priors=None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing

    def fit(self, x: DNDarray, y: DNDarray, sample_weight=None) -> "GaussianNB":
        """reference ``gaussianNB.py:fit``"""
        self.classes_ = None
        self.theta_ = None
        self.sigma_ = None
        self.class_count_ = None
        self.class_prior_ = None
        return self.partial_fit(x, y, classes=None, sample_weight=sample_weight, _refit=True)

    def partial_fit(self, x: DNDarray, y: DNDarray, classes=None, sample_weight=None, _refit: bool = False) -> "GaussianNB":
        """Incremental fit (reference ``gaussianNB.py:200``)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError(f"input needs to be DNDarrays, but were {type(x)}, {type(y)}")
        X = x._logical().astype(jnp.promote_types(x.larray.dtype, jnp.float32))
        Y = y._logical().ravel()
        if classes is not None:
            class_vals = jnp.asarray(classes if not isinstance(classes, DNDarray) else classes._logical())
        elif not _refit and getattr(self, "classes_", None) is not None:
            class_vals = self.classes_._logical()
        elif _refit:
            class_vals = jnp.unique(Y)
        else:
            # reference ``gaussianNB.py:113``
            raise ValueError("classes must be passed on the first call to partial_fit.")
        unseen = ~jnp.isin(jnp.unique(Y), class_vals)
        if bool(jnp.any(unseen)):
            bad = np.asarray(jnp.unique(Y))[np.asarray(unseen)]
            raise ValueError(
                f"The target label(s) {bad} in y do not exist in the initial classes {np.asarray(class_vals)}"
            )
        k = class_vals.shape[0]
        f = X.shape[1]

        member = (Y[:, None] == class_vals[None, :]).astype(X.dtype)  # (n, k)
        if sample_weight is not None:
            w = sample_weight._logical() if isinstance(sample_weight, DNDarray) else jnp.asarray(sample_weight)
            member = member * w[:, None]
        counts = jnp.sum(member, axis=0)  # (k,)
        sums = member.T @ X  # (k, f)
        means = sums / jnp.maximum(counts, 1.0)[:, None]
        sq = member.T @ (X * X)
        varis = sq / jnp.maximum(counts, 1.0)[:, None] - means**2

        eps = self.var_smoothing * float(jnp.max(jnp.var(X, axis=0)))
        if _refit or getattr(self, "theta_", None) is None:
            new_counts, new_means, new_vars = counts, means, varis
        else:
            # merge with previous moments (parallel Welford, reference
            # ``__update_mean_variance`` gaussianNB.py:131)
            old_counts = self.class_count_._logical()
            old_means = self.theta_._logical()
            old_vars = self.sigma_._logical() - self.epsilon_
            tot = old_counts + counts
            delta = means - old_means
            new_means = old_means + delta * (counts / jnp.maximum(tot, 1.0))[:, None]
            m_a = old_vars * old_counts[:, None]
            m_b = varis * counts[:, None]
            m2 = m_a + m_b + (delta**2) * ((old_counts * counts) / jnp.maximum(tot, 1.0))[:, None]
            new_vars = m2 / jnp.maximum(tot, 1.0)[:, None]
            new_counts = tot

        self.epsilon_ = eps
        self.classes_ = DNDarray(class_vals, split=None, device=x.device, comm=x.comm)
        self.class_count_ = DNDarray(new_counts, split=None, device=x.device, comm=x.comm)
        self.theta_ = DNDarray(new_means, split=None, device=x.device, comm=x.comm)
        self.sigma_ = DNDarray(new_vars + eps, split=None, device=x.device, comm=x.comm)
        if self.priors is not None:
            pr = self.priors._logical() if isinstance(self.priors, DNDarray) else jnp.asarray(self.priors)
            self.class_prior_ = DNDarray(pr, split=None, device=x.device, comm=x.comm)
        else:
            self.class_prior_ = DNDarray(
                new_counts / jnp.sum(new_counts), split=None, device=x.device, comm=x.comm
            )
        return self

    def __joint_log_likelihood(self, X: jnp.ndarray) -> jnp.ndarray:
        """reference ``gaussianNB.py:391``"""
        theta = self.theta_._logical()  # (k, f)
        sigma = self.sigma_._logical()
        prior = self.class_prior_._logical()
        log_prior = jnp.log(jnp.maximum(prior, 1e-300))
        # (n, k): -0.5 * sum(log(2 pi sigma)) - 0.5 * sum((x-mu)^2/sigma)
        n_ij = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * sigma), axis=1)  # (k,)
        quad = -0.5 * jnp.sum(
            ((X[:, None, :] - theta[None, :, :]) ** 2) / sigma[None, :, :], axis=2
        )  # (n, k)
        return log_prior[None, :] + n_ij[None, :] + quad

    def logsumexp(self, a: DNDarray, axis=None) -> DNDarray:
        """reference ``gaussianNB.py:407``"""
        from jax.scipy.special import logsumexp as lse

        out = lse(a._logical(), axis=axis)
        return DNDarray(out, split=None, device=a.device, comm=a.comm)

    def predict(self, x: DNDarray) -> DNDarray:
        """reference ``gaussianNB.py:480``"""
        if getattr(self, "theta_", None) is None:
            raise RuntimeError("fit needs to be called before predict")
        X = x._logical().astype(self.theta_.larray.dtype)
        jll = self.__joint_log_likelihood(X)
        idx = jnp.argmax(jll, axis=1)
        pred = jnp.take(self.classes_._logical(), idx)
        return DNDarray(pred, split=x.split, device=x.device, comm=x.comm)

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Posterior probabilities (reference ``gaussianNB.py``)."""
        from jax.scipy.special import logsumexp as lse

        X = x._logical().astype(self.theta_.larray.dtype)
        jll = self.__joint_log_likelihood(X)
        log_prob = jll - lse(jll, axis=1, keepdims=True)
        return DNDarray(jnp.exp(log_prob), split=x.split, device=x.device, comm=x.comm)

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        from jax.scipy.special import logsumexp as lse

        X = x._logical().astype(self.theta_.larray.dtype)
        jll = self.__joint_log_likelihood(X)
        return DNDarray(jll - lse(jll, axis=1, keepdims=True), split=x.split, device=x.device, comm=x.comm)
