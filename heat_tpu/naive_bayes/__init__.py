"""Naive Bayes (reference ``heat/naive_bayes/``)."""
from .gaussianNB import GaussianNB
