"""Regression (reference ``heat/regression/``)."""
from . import lasso
from .lasso import Lasso
